"""Round-boundary staleness guard, shared by bench.py and
benchmarks/word2vec_profile.py.

Deliberately SIDE-EFFECT-FREE (no env mutation, no jax import): the w2v
profiler used to import bench just for this check and thereby inherited
bench's import-time environment setup (os.environ.setdefault et al.) —
ADVICE r5. The only module-level state captured here is the import
timestamp, which both scripts take at process start, so it approximates
the process birth time the staleness signals need.

The guard itself (two signals, see round_is_stale): a bench/profile child
spawned by a watcher whose round is over — or running across a round
boundary — must abort rather than write a prior round's rows into the new
round's artifacts (scripts/bench_watch.sh round hygiene; CLAUDE.md).
"""

import os
import time

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))

# first-import time ~= process birth time (both consumers import this
# module at the top of the file, before any slow work)
START_TS = time.time()
ROUND_MARKER = os.path.join(_REPO_ROOT, ".bench_round_start")


def round_is_stale(marker: str = None, start_ts: float = None) -> bool:
    """True when the current round (the .bench_round_start marker) is newer
    than this process or than the watcher that spawned it."""
    if marker is None:
        marker = ROUND_MARKER
    if start_ts is None:
        start_ts = START_TS
    # Signal 1 — spawner identity: the watcher exports BENCH_WATCH_ROUND
    # (the marker's mtime at ITS start). A zombie watcher from a prior
    # round hands its children the OLD identity; any mismatch with the
    # current marker means the spawning watcher's round is over. This is
    # the check that catches freshly spawned children (whose own birth
    # time is always newer than the marker, blinding signal 2).
    # "0"/empty = no identity (a failed stat at watcher start must not
    # doom every child of an otherwise healthy watcher to stale-abort)
    spawner_round = os.environ.get("BENCH_WATCH_ROUND")
    if spawner_round and spawner_round != "0":
        try:
            if int(os.path.getmtime(marker)) != int(spawner_round):
                return True
        except (OSError, ValueError):
            return True  # marker vanished mid-boundary / garbled id
    # Signal 2 — own birth time: covers a round boundary that happens
    # WHILE this process is running (marker re-touched after we started).
    try:
        return os.path.getmtime(marker) > start_ts
    except OSError:
        return False  # no marker yet: round hygiene hasn't run — write ok
