#!/bin/bash
# Tunnel watcher: poll the remote-TPU tunnel; whenever it's alive, run
# bench passes until the artifact is complete. Round-2 lesson: the tunnel
# can be down for hours and die mid-bench — capture the proof the moment
# it's possible. Round-4 lesson (the 03:47 contact lasted ~3 minutes): one
# quick+full shot is not enough; RE-ARM after every outage and keep
# filling the gaps until BENCH_PARTIAL.json is clean. bench.py merges
# per-leg results across passes, so each contact window only has to add
# the legs still missing.
cd /root/repo || exit 1
# axon plugin registration needs /root/.axon_site on PYTHONPATH (CLAUDE.md);
# without it jax silently falls back to CPU and the probe would loop forever
export PYTHONPATH="/root/repo:/root/.axon_site${PYTHONPATH:+:$PYTHONPATH}"
PROBE='
import threading, sys
res = {}
def work():
    try:
        import jax, jax.numpy as jnp
        dev = jax.devices()[0]
        if dev.platform == "cpu":
            # CPU fallback must not masquerade as a live TPU tunnel
            res["err"] = f"cpu fallback: {dev}"; return
        res["ok"] = float(jnp.ones((2,)).sum())
    except Exception as e:
        res["err"] = str(e)
t = threading.Thread(target=work, daemon=True); t.start(); t.join(150)
if "err" in res:
    print("probe error:", res["err"], file=sys.stderr)
sys.exit(0 if "ok" in res else 1)
'
log() { echo "$(date -Is) $*" >> bench_watch.log; }

full_passes=0
while true; do
  if ! timeout 180 python -c "$PROBE" 2>>bench_watch.log; then
    log "tunnel down; sleeping 600s"
    sleep 600
    continue
  fi
  if ! python scripts/bench_state.py BENCH_PARTIAL.json >> bench_watch.log 2>&1; then
    # --quick until every leg has a measured row: a short window must
    # yield a COMPLETE (if reduced-step) 5-config artifact before any
    # full-length pass hogs the tunnel.
    log "tunnel ALIVE -> quick pass (filling gaps)"
    touch .quick_pass_start
    python bench.py --quick > BENCH_WATCH_QUICK.json 2>> bench_watch.log
    log "quick pass exit=$?"
    # snapshot iff THIS pass updated the artifact (mtime check): a
    # startup failure must not relabel a prior pass's data as quick
    if [ BENCH_PARTIAL.json -nt .quick_pass_start ]; then
      cp -f BENCH_PARTIAL.json BENCH_PARTIAL_QUICK.json 2>> bench_watch.log
    fi
    rm -f .quick_pass_start
    continue  # re-probe, re-check state before going full-length
  fi
  if [ "$full_passes" -lt 3 ] && ! python scripts/bench_state.py BENCH_WATCH.json >> bench_watch.log 2>&1; then
    # Quick artifact is clean; upgrade to full-length numbers. Cap at 3
    # attempts so a leg that legitimately fails at full length can't
    # hold the tunnel forever (the merged quick rows remain the record).
    log "-> full bench (attempt $((full_passes + 1)))"
    python bench.py > BENCH_WATCH.json 2>> bench_watch.log
    log "full bench exit=$?"
    full_passes=$((full_passes + 1))
    continue
  fi
  # Complete capture: run the word2vec device profile (VERDICT r03 #5,
  # open since round 1) while the tunnel is still warm, then stop. The
  # script writes W2V_PROFILE.json itself — stdout goes to a scratch
  # file, NOT the artifact (two fds on one path garble it).
  if [ ! -f W2V_PROFILE.json ]; then
    log "-> word2vec device profile"
    timeout 1800 python benchmarks/word2vec_profile.py > w2v_profile.out 2>> bench_watch.log \
      || { log "w2v profile failed"; rm -f W2V_PROFILE.json; }
  fi
  log "capture complete (full_passes=$full_passes); watcher exiting"
  break
done
