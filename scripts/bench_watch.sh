#!/bin/bash
# Tunnel watcher: poll the remote-TPU tunnel; the moment it's alive, run
# the full bench (which persists BENCH_PARTIAL.json after every leg) and
# capture the final JSON line. Round-2 lesson: the tunnel can be down for
# hours and die mid-round — capture the proof the moment it's possible.
cd /root/repo || exit 1
# axon plugin registration needs /root/.axon_site on PYTHONPATH (CLAUDE.md);
# without it jax silently falls back to CPU and the probe would loop forever
export PYTHONPATH="/root/repo:/root/.axon_site${PYTHONPATH:+:$PYTHONPATH}"
PROBE='
import threading, sys
res = {}
def work():
    try:
        import jax, jax.numpy as jnp
        dev = jax.devices()[0]
        if dev.platform == "cpu":
            # CPU fallback must not masquerade as a live TPU tunnel
            res["err"] = f"cpu fallback: {dev}"; return
        res["ok"] = float(jnp.ones((2,)).sum())
    except Exception as e:
        res["err"] = str(e)
t = threading.Thread(target=work, daemon=True); t.start(); t.join(150)
if "err" in res:
    print("probe error:", res["err"], file=sys.stderr)
sys.exit(0 if "ok" in res else 1)
'
while true; do
  if timeout 180 python -c "$PROBE" 2>>bench_watch.log; then
    # Two-pass capture (round-3 lesson): a short tunnel window must still
    # yield ALL legs. Pass 1 = --quick (reduced steps, ~minutes/leg),
    # persisted per-leg; pass 2 = full-length for quality numbers.
    echo "$(date -Is) tunnel ALIVE -> quick pass" >> bench_watch.log
    touch .quick_pass_start
    python bench.py --quick > BENCH_WATCH_QUICK.json 2>> bench_watch.log
    rc=$?  # capture BEFORE any $(...) substitution can clobber $?
    echo "$(date -Is) quick pass done exit=$rc; snapshotting" >> bench_watch.log
    # snapshot iff THIS quick pass wrote it (mtime check, not exit code):
    # a startup failure must not relabel a PRIOR round's data as quick,
    # but a mid-run kill must still save the legs that DID persist before
    # the full bench restarts and rewrites BENCH_PARTIAL.json from empty
    if [ BENCH_PARTIAL.json -nt .quick_pass_start ]; then
      cp -f BENCH_PARTIAL.json BENCH_PARTIAL_QUICK.json 2>> bench_watch.log
    fi
    rm -f .quick_pass_start
    echo "$(date -Is) -> full bench" >> bench_watch.log
    python bench.py > BENCH_WATCH.json 2>> bench_watch.log
    rc=$?
    echo "$(date -Is) bench done exit=$rc" >> bench_watch.log
    break
  fi
  echo "$(date -Is) tunnel down; sleeping 600s" >> bench_watch.log
  sleep 600
done
