#!/bin/bash
# Tunnel watcher: poll the remote-TPU tunnel; the moment it's alive, run
# the full bench (which persists BENCH_PARTIAL.json after every leg) and
# capture the final JSON line. Round-2 lesson: the tunnel can be down for
# hours and die mid-round — capture the proof the moment it's possible.
cd /root/repo || exit 1
# axon plugin registration needs /root/.axon_site on PYTHONPATH (CLAUDE.md);
# without it jax silently falls back to CPU and the probe would loop forever
export PYTHONPATH="/root/repo:/root/.axon_site${PYTHONPATH:+:$PYTHONPATH}"
PROBE='
import threading, sys
res = {}
def work():
    try:
        import jax, jax.numpy as jnp
        dev = jax.devices()[0]
        if dev.platform == "cpu":
            # CPU fallback must not masquerade as a live TPU tunnel
            res["err"] = f"cpu fallback: {dev}"; return
        res["ok"] = float(jnp.ones((2,)).sum())
    except Exception as e:
        res["err"] = str(e)
t = threading.Thread(target=work, daemon=True); t.start(); t.join(150)
if "err" in res:
    print("probe error:", res["err"], file=sys.stderr)
sys.exit(0 if "ok" in res else 1)
'
while true; do
  if timeout 180 python -c "$PROBE" 2>>bench_watch.log; then
    echo "$(date -Is) tunnel ALIVE -> running full bench" >> bench_watch.log
    python bench.py > BENCH_WATCH.json 2>> bench_watch.log
    echo "$(date -Is) bench done exit=$?" >> bench_watch.log
    break
  fi
  echo "$(date -Is) tunnel down; sleeping 600s" >> bench_watch.log
  sleep 600
done
