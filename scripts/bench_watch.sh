#!/bin/bash
# Tunnel watcher: poll the remote-TPU tunnel; whenever it's alive, run
# bench passes until the artifact is complete. Round-2 lesson: the tunnel
# can be down for hours and die mid-bench — capture the proof the moment
# it's possible. Round-4 lesson (the 03:47 contact lasted ~3 minutes): one
# quick+full shot is not enough; RE-ARM after every outage and keep
# filling the gaps until BENCH_PARTIAL.json is clean. bench.py merges
# per-leg results across passes, so each contact window only has to add
# the legs still missing.
# BENCH_WATCH_DIR / BENCH_WATCH_AXON_SITE exist so the state machine can
# run under the shell-harness test (tests/test_bench_watch_sh.py) with a
# stub repo + stub jax; production uses the defaults
cd "${BENCH_WATCH_DIR:-/root/repo}" || exit 1
# pidfile so restarts can kill the exact process (grep/pkill patterns
# match the restarting shell's own args and kill the wrong process)
echo $$ > .bench_watch.pid
# axon plugin registration needs /root/.axon_site on PYTHONPATH (CLAUDE.md);
# without it jax silently falls back to CPU and the probe would loop forever
export PYTHONPATH="$PWD:${BENCH_WATCH_AXON_SITE-/root/.axon_site}${PYTHONPATH:+:$PYTHONPATH}"
PROBE='
import threading, sys
res = {}
def work():
    try:
        import jax, jax.numpy as jnp
        dev = jax.devices()[0]
        if dev.platform == "cpu":
            # CPU fallback must not masquerade as a live TPU tunnel
            res["err"] = f"cpu fallback: {dev}"; return
        res["ok"] = float(jnp.ones((2,)).sum())
    except Exception as e:
        res["err"] = str(e)
t = threading.Thread(target=work, daemon=True); t.start(); t.join(150)
if "err" in res:
    print("probe error:", res["err"], file=sys.stderr)
sys.exit(0 if "ok" in res else 1)
'
log() { echo "$(date -Is) $*" >> bench_watch.log; }

# Round-start artifact hygiene: the merged artifacts must not carry a
# PRIOR round's rows into this round's proof (a stale-but-clean
# BENCH_PARTIAL.json would make --fill skip every leg and the watcher
# declare capture complete without measuring anything). The round's
# FIRST watcher launch creates .bench_round_start (CLAUDE.md: rm it at
# round start before launching); artifacts older than the marker are
# archived to *_prev.json. Mid-round watcher restarts keep the marker,
# so the round's own rows survive.
if [ ! -f .bench_round_start ]; then
  touch .bench_round_start
  # unconditional archive: every listed artifact predates the round by
  # definition here (the marker is only absent at round start)
  for f in BENCH_PARTIAL.json BENCH_PARTIAL_QUICK.json BENCH_WATCH.json \
           BENCH_WATCH_QUICK.json W2V_PROFILE.json; do
    if [ -f "$f" ]; then
      mv -f "$f" "${f%.json}_prev.json"
      log "archived stale $f -> ${f%.json}_prev.json (predates round start)"
    fi
  done
fi

full_passes=0
quick_passes=0
w2v_attempts=0
while true; do
  if ! timeout 180 python -c "$PROBE" 2>>bench_watch.log; then
    # short windows are real (03:47 contact lasted ~3 min): poll fast
    # enough that one can't fall entirely inside a sleep (a dead-tunnel
    # probe itself burns up to 180s, so the full cycle is ~8 min)
    log "tunnel down; sleeping 300s"
    sleep 300
    continue
  fi
  if [ "$quick_passes" -lt 5 ] && ! python scripts/bench_state.py BENCH_PARTIAL.json >> bench_watch.log 2>&1; then
    # --quick until every leg has a measured row: a short window must
    # yield a COMPLETE (if reduced-step) 5-config artifact before any
    # full-length pass hogs the tunnel.
    # --fill re-runs only the legs still missing a measured row; capped
    # at 5 so one deterministically-failing quick leg can't loop the
    # watcher forever and never reach the full bench
    log "tunnel ALIVE -> quick pass $((quick_passes + 1)) (filling gaps)"
    touch .quick_pass_start
    python bench.py --quick --fill > BENCH_WATCH_QUICK.json 2>> bench_watch.log
    log "quick pass exit=$?"
    quick_passes=$((quick_passes + 1))
    # snapshot iff THIS pass updated the artifact (mtime check): a
    # startup failure must not relabel a prior pass's data as quick
    if [ BENCH_PARTIAL.json -nt .quick_pass_start ]; then
      cp -f BENCH_PARTIAL.json BENCH_PARTIAL_QUICK.json 2>> bench_watch.log
    fi
    rm -f .quick_pass_start
    continue  # re-probe, re-check state before going full-length
  fi
  if [ "$full_passes" -lt 3 ] && ! python scripts/bench_state.py BENCH_WATCH.json >> bench_watch.log 2>&1; then
    # Quick artifact is clean; upgrade to full-length numbers. Cap at 3
    # attempts so a leg that legitimately fails at full length can't
    # hold the tunnel forever (the merged quick rows remain the record).
    log "-> full bench (attempt $((full_passes + 1)))"
    # --fill at full length: skips rows already measured FULL-length,
    # re-measures rows that only have --quick numbers
    python bench.py --fill > BENCH_WATCH.json 2>> bench_watch.log
    log "full bench exit=$?"
    full_passes=$((full_passes + 1))
    continue
  fi
  # Complete capture: run the word2vec device profile (VERDICT r03 #5,
  # open since round 1) while the tunnel is still warm, then stop. The
  # script writes W2V_PROFILE.json itself — stdout goes to a scratch
  # file, NOT the artifact (two fds on one path garble it).
  if [ ! -f W2V_PROFILE.json ] && [ "$w2v_attempts" -lt 3 ]; then
    log "-> word2vec device profile (attempt $((w2v_attempts + 1)))"
    w2v_attempts=$((w2v_attempts + 1))
    timeout 1800 python benchmarks/word2vec_profile.py > w2v_profile.out 2>> bench_watch.log || true
    # success test is the ARTIFACT, not the exit code: a 0-exit that
    # wrote no file must also retry
    if [ ! -f W2V_PROFILE.json ]; then
      log "w2v profile failed; re-arming"
      continue  # back to the probe — the tunnel may have died mid-profile
    fi
  fi
  if [ -f W2V_PROFILE.json ]; then
    log "capture complete (full_passes=$full_passes quick=$quick_passes w2v=$w2v_attempts); watcher exiting"
  else
    log "capture ended WITHOUT w2v profile ($w2v_attempts attempts exhausted); watcher exiting"
  fi
  break
done
