#!/bin/bash
# Tunnel watcher: poll the remote-TPU tunnel; whenever it's alive, run
# bench passes until the artifact is complete. Round-2 lesson: the tunnel
# can be down for hours and die mid-bench — capture the proof the moment
# it's possible. Round-4 lesson (the 03:47 contact lasted ~3 minutes): one
# quick+full shot is not enough; RE-ARM after every outage and keep
# filling the gaps until BENCH_PARTIAL.json is clean. bench.py merges
# per-leg results across passes, so each contact window only has to add
# the legs still missing.
# Round-5 lessons (VERDICT r4 weak #3 + ADVICE #1):
#   - pass caps are per-CONTACT-WINDOW, not per-lifetime: a flapping
#     tunnel must not burn the whole budget on five 3-minute windows and
#     leave the rest of the round unwatched. Counters reset on every
#     down->up transition and after every slow re-arm sleep.
#   - the watcher NEVER exits. Complete capture degrades to an idle
#     re-verify loop; cap exhaustion degrades to a slow re-arm. The only
#     way to stop it is the pidfile group kill below.
#   - the watcher runs as its own process-group leader (self-setsid), and
#     the pidfile kill is `kill -- -$(cat .bench_watch.pid)`: a plain kill
#     of the shell left an in-flight `python bench.py` child alive to
#     re-pollute the next round's artifact.
# BENCH_WATCH_DIR / BENCH_WATCH_AXON_SITE / BENCH_WATCH_POLL /
# BENCH_WATCH_REARM exist so the state machine can run under the
# shell-harness test (tests/test_bench_watch_sh.py) with a stub repo +
# stub jax + sub-second sleeps; production uses the defaults
# absolute self-path BEFORE any cd: a relative $0 would resolve against
# the post-cd directory and the re-exec below would die at startup
SELF="$(cd "$(dirname "$0")" && pwd)/$(basename "$0")"
cd "${BENCH_WATCH_DIR:-/root/repo}" || exit 1
# self-setsid: become a process-group leader so `kill -- -PID` takes down
# any in-flight bench.py/w2v child with the watcher (ADVICE r4 #1)
if [ -z "$BENCH_WATCH_NO_SETSID" ] \
   && [ "$(ps -o pgid= -p $$ | tr -d ' ')" != "$$" ] \
   && command -v setsid > /dev/null; then
  exec setsid bash "$SELF" "$@"
fi
# pidfile so restarts can kill the exact process group (grep/pkill
# patterns match the restarting shell's own args and kill the wrong
# process). Before claiming it: take over from a live incumbent — under
# the never-exit contract a duplicate watcher would otherwise run
# forever, double-loading the 1-core host and racing on the artifacts,
# with its pid lost the moment we overwrite the file. The /proc cmdline
# check keeps a recycled pid (now some unrelated process) safe from the
# takeover kill.
if [ -f .bench_watch.pid ]; then
  old="$(cat .bench_watch.pid)"
  # identity grep is the SCRIPT PATH, not the bare 'bench_watch' substring
  # (ADVICE r5): a recycled pid landing on the restart wrapper shell —
  # whose argv contains 'bench_watch', the exact pkill trap CLAUDE.md
  # warns about — must not pass as the incumbent
  if [ -n "$old" ] && [ "$old" != "$$" ] \
     && grep -aq scripts/bench_watch.sh "/proc/$old/cmdline" 2>/dev/null; then
    echo "$(date -Is) killing incumbent watcher pid $old (group) before takeover" >> bench_watch.log
    # a LEGACY incumbent (pre-setsid, or setsid-less host) is not a group
    # leader: the group kills below no-op on it, and a plain kill of the
    # shell would orphan an in-flight bench.py (and ITS --only children)
    # to keep racing us on BENCH_PARTIAL.json for up to an hour — collect
    # two generations of descendants BEFORE the TERM (afterwards they
    # reparent to init and become unfindable without forbidden pgrep)
    kids="$(ps -o pid= --ppid "$old" 2>/dev/null)"
    for k in $kids; do
      kids="$kids $(ps -o pid= --ppid "$k" 2>/dev/null)"
    done
    kill -TERM -- "-$old" 2>/dev/null || kill -TERM "$old" 2>/dev/null
    # per-kid TERMs carry the SAME ppid/cmdline identity gate as the -9s
    # below (ADVICE r5): a pid collected from ps and recycled in the
    # interim must not get TERMed just for having been in the list
    for k in $kids; do
      pp="$(ps -o ppid= -p "$k" 2>/dev/null | tr -d ' ')"
      if [ "$pp" = "$old" ] || { [ "$pp" = "1" ] \
           && grep -aq -e bench -e word2vec "/proc/$k/cmdline" 2>/dev/null; }; then
        kill -TERM "$k" 2>/dev/null
      fi
    done
    sleep 2
    # identity re-checks before EVERY -9: the 2s window is enough for a
    # killed process to exit and its pid to be recycled to an innocent
    # process — possibly even a new group leader (the TERMs above were
    # identity-gated; the KILLs must be too). An incumbent the TERM
    # already reaped simply skips this; surviving kids are handled below.
    if grep -aq scripts/bench_watch.sh "/proc/$old/cmdline" 2>/dev/null; then
      kill -KILL -- "-$old" 2>/dev/null || kill -KILL "$old" 2>/dev/null
    fi
    for k in $kids; do
      # a kid still parented to the incumbent is certainly ours; one
      # reparented to init must ALSO look like something the watcher
      # spawns (bench.py / the probe / the w2v profile) before -9
      pp="$(ps -o ppid= -p "$k" 2>/dev/null | tr -d ' ')"
      if [ "$pp" = "$old" ] || { [ "$pp" = "1" ] \
           && grep -aq -e bench -e word2vec "/proc/$k/cmdline" 2>/dev/null; }; then
        kill -KILL "$k" 2>/dev/null
      fi
    done
  fi
fi
echo $$ > .bench_watch.pid
# axon plugin registration needs /root/.axon_site on PYTHONPATH (CLAUDE.md);
# without it jax silently falls back to CPU and the probe would loop forever
export PYTHONPATH="$PWD:${BENCH_WATCH_AXON_SITE-/root/.axon_site}${PYTHONPATH:+:$PYTHONPATH}"
# persistent XLA compile cache (VERDICT r4 #2b): a compile paid in one
# 3-minute tunnel window is FREE in the next. jax reads this env var
# directly, so every child — bench legs, subprocess-isolated legs, the
# w2v profile — inherits it with no per-script wiring.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/root/.jax_compile_cache}"
# 150s sleep + ~150s dead-probe hang ≈ 5-min detection cycle (was 8 min
# at 300s): a ~3-minute contact window (round-4's norm) is marginal at
# 8 but catchable at 5. CPU cost per cycle is only the ~10-15s jax
# import — the 150s hang itself is ~0 CPU.
POLL="${BENCH_WATCH_POLL:-150}"
REARM="${BENCH_WATCH_REARM:-3600}"
PROBE='
import threading, sys
res = {}
def work():
    try:
        import jax, jax.numpy as jnp
        dev = jax.devices()[0]
        if dev.platform == "cpu":
            # CPU fallback must not masquerade as a live TPU tunnel
            res["err"] = f"cpu fallback: {dev}"; return
        res["ok"] = float(jnp.ones((2,)).sum())
    except Exception as e:
        res["err"] = str(e)
t = threading.Thread(target=work, daemon=True); t.start(); t.join(150)
if "err" in res:
    print("probe error:", res["err"], file=sys.stderr)
sys.exit(0 if "ok" in res else 1)
'
log() { echo "$(date -Is) $*" >> bench_watch.log; }
# single probe path for BOTH the main poll and the re-arm wait: timeout /
# stderr routing tweaks must apply to every detection site at once
probe() { timeout 180 python -c "$PROBE" 2>>bench_watch.log; }
# single promotion rule for BOTH pass kinds: run bench, promote stdout to
# the artifact only when the pass FINISHED (exit 0) with output — a
# killed/crashed/stale-aborted pass must not replace the last good
# artifact with emptiness or a truncated JSON line
run_pass() {  # run_pass <artifact> <bench flags...>
  local art="$1"; shift
  python bench.py "$@" > "$art.tmp" 2>> bench_watch.log
  local rc=$?
  log "bench pass ($*) exit=$rc"
  if [ "$rc" -eq 0 ] && [ -s "$art.tmp" ]; then
    mv -f "$art.tmp" "$art"
  fi
  rm -f "$art.tmp"
  return "$rc"
}
reset_caps() { quick_passes=0; full_passes=0; w2v_attempts=0; }
# one evaluation of both artifact states, shared by the idle branch and
# the quick/full gates; pass /dev/null to keep the pre-probe check from
# appending gap listings to bench_watch.log every outage poll cycle
compute_state() {  # compute_state [gap-listing sink]
  local out="${1:-bench_watch.log}"
  is_clean=1
  python scripts/bench_state.py BENCH_PARTIAL.json >> "$out" 2>&1 || is_clean=0
  watch_clean=1
  python scripts/bench_state.py BENCH_WATCH.json >> "$out" 2>&1 || watch_clean=0
}

# Round-start artifact hygiene: the merged artifacts must not carry a
# PRIOR round's rows into this round's proof (a stale-but-clean
# BENCH_PARTIAL.json would make --fill skip every leg and the watcher
# declare capture complete without measuring anything). The round's
# FIRST watcher launch creates .bench_round_start (CLAUDE.md: rm it at
# round start before launching); artifacts older than the marker are
# archived to *_prev.json. Mid-round watcher restarts keep the marker,
# so the round's own rows survive.
if [ ! -f .bench_round_start ]; then
  touch .bench_round_start
  # unconditional archive: every listed artifact predates the round by
  # definition here (the marker is only absent at round start)
  for f in BENCH_PARTIAL.json BENCH_PARTIAL_QUICK.json BENCH_WATCH.json \
           BENCH_WATCH_QUICK.json W2V_PROFILE.json; do
    if [ -f "$f" ]; then
      mv -f "$f" "${f%.json}_prev.json"
      log "archived stale $f -> ${f%.json}_prev.json (predates round start)"
    fi
  done
fi

# Round identity for children: the marker's mtime at THIS watcher's
# start. A zombie watcher surviving a round boundary spawns children
# whose env carries the OLD identity; bench.py/_round_is_stale compares
# it to the CURRENT marker mtime and aborts at process start — the
# birth-time check alone can't catch this (a freshly spawned child is
# always younger than the marker). Exported ONLY when stat succeeds: a
# bogus fallback id would doom every child to the stale-abort path for
# the whole round.
round_id="$(stat -c %Y .bench_round_start 2>/dev/null)"
[ -n "$round_id" ] && export BENCH_WATCH_ROUND="$round_id"

reset_caps
was_down=1
while true; do
  # Completeness FIRST, probe second: once the capture is complete there
  # is nothing a live tunnel could trigger, so the idle loop must not
  # burn a heavyweight jax probe (up to 180s on a dead tunnel) every
  # cycle on the 1-core host. W2V_PROFILE.json is the LAST gap a window
  # fills, so its absence (the dominant state while the tunnel is down)
  # proves incompleteness with a free [ -f ]. When it IS present but the
  # capture is still incomplete (full-length cap exhausted), the two
  # ~100ms bench_state spawns recur per outage cycle — accepted: they're
  # noise next to the 150s dead-tunnel probe they sit in front of, and
  # their gap listings go to /dev/null here, not the log.
  is_clean=-1; watch_clean=-1
  if [ -f W2V_PROFILE.json ]; then
    compute_state /dev/null
    if [ "$is_clean" -eq 1 ] && [ "$watch_clean" -eq 1 ]; then
      log "capture complete; idling ${REARM}s (no probe needed; watcher stays alive)"
      sleep "$REARM"
      continue
    fi
  fi
  if ! probe; then
    # short windows are real (03:47 contact lasted ~3 min): poll fast
    # enough that one can't fall entirely inside a sleep (a dead-tunnel
    # probe itself burns ~150s, so the full cycle is ~5 min)
    was_down=1
    log "tunnel down; sleeping ${POLL}s"
    sleep "$POLL"
    continue
  fi
  if [ "$was_down" -eq 1 ]; then
    # new contact window: the caps exist to stop a deterministically
    # failing leg from looping one window forever, NOT to ration the
    # round — reset them so every fresh contact gets the full budget
    reset_caps
    log "tunnel contact: new window, pass counters reset"
  fi
  was_down=0
  if [ "$is_clean" -lt 0 ]; then
    # tunnel is alive and the pre-probe short-circuit skipped the state
    # spawns — the gates below need real values
    compute_state
  fi
  if [ "$quick_passes" -lt 5 ] && [ "$is_clean" -eq 0 ]; then
    # --quick until every leg has a measured row: a short window must
    # yield a COMPLETE (if reduced-step) 5-config artifact before any
    # full-length pass hogs the tunnel.
    # --fill re-runs only the legs still missing a measured row; capped
    # at 5 per contact window so one deterministically-failing quick leg
    # can't loop the window forever and never reach the full bench
    log "tunnel ALIVE -> quick pass $((quick_passes + 1)) (filling gaps)"
    touch .quick_pass_start
    run_pass BENCH_WATCH_QUICK.json --quick --fill
    quick_passes=$((quick_passes + 1))
    # snapshot iff THIS pass updated the artifact (mtime check): a
    # startup failure must not relabel a prior pass's data as quick
    if [ BENCH_PARTIAL.json -nt .quick_pass_start ]; then
      cp -f BENCH_PARTIAL.json BENCH_PARTIAL_QUICK.json 2>> bench_watch.log
    fi
    rm -f .quick_pass_start
    continue  # re-probe, re-check state before going full-length
  fi
  if [ "$full_passes" -lt 3 ] && [ "$watch_clean" -eq 0 ]; then
    # Quick artifact is clean; upgrade to full-length numbers. Cap at 3
    # per contact window so a leg that legitimately fails at full length
    # can't hold the tunnel forever (the merged quick rows remain the
    # record).
    log "-> full bench (attempt $((full_passes + 1)))"
    # --fill at full length: skips rows already measured FULL-length,
    # re-measures rows that only have --quick numbers
    run_pass BENCH_WATCH.json --fill
    full_passes=$((full_passes + 1))
    continue
  fi
  # Quick+full artifacts are as good as this window allows: run the
  # word2vec device profile (VERDICT r03 #5, open since round 1) while
  # the tunnel is still warm. The script writes W2V_PROFILE.json itself —
  # stdout goes to a scratch file, NOT the artifact (two fds on one path
  # garble it).
  if [ ! -f W2V_PROFILE.json ] && [ "$w2v_attempts" -lt 3 ]; then
    log "-> word2vec device profile (attempt $((w2v_attempts + 1)))"
    w2v_attempts=$((w2v_attempts + 1))
    timeout 1800 python benchmarks/word2vec_profile.py > w2v_profile.out 2>> bench_watch.log || true
    # success test is the ARTIFACT, not the exit code: a 0-exit that
    # wrote no file must also retry
    if [ ! -f W2V_PROFILE.json ]; then
      log "w2v profile failed; re-arming"
      continue  # back to the probe — the tunnel may have died mid-profile
    fi
  fi
  # Terminal state of THIS window — but never of the watcher (VERDICT r4
  # weak #3: exiting left the rest of the round unwatched). Either the
  # capture JUST completed this iteration (the w2v write above was the
  # last gap — the top-of-loop idle branch takes over from here on) or
  # this window's caps are exhausted on something deterministic (slow
  # re-arm with fresh caps — an hourly retry is cheap and a changed
  # tunnel/chip state may unstick the leg).
  # "capture complete" requires the FULL-length artifact too: quick-only
  # rows satisfying BENCH_PARTIAL must not masquerade as a finished
  # capture when all 3 full-length attempts failed (that state is an
  # exhausted window, reported honestly below)
  if [ "$is_clean" -eq 1 ] && [ "$watch_clean" -eq 1 ] && [ -f W2V_PROFILE.json ]; then
    log "capture complete (full=$full_passes quick=$quick_passes w2v=$w2v_attempts); idling ${REARM}s (watcher stays alive)"
    sleep "$REARM"
  else
    log "window caps exhausted with incomplete artifact (full=$full_passes quick=$quick_passes w2v=$w2v_attempts); slow re-arm in ${REARM}s"
    # Chunked re-arm wait: an uninterruptible hour-long sleep could eat
    # an entire short contact window (round-4's was ~3 min). Wake every
    # POLL, probe, and end the wait the moment the tunnel DROPS — the
    # main loop's fast poll then catches the next revival, which gets a
    # fresh budget. Only a tunnel that stays up (the deterministic-
    # failure case this cooldown exists to ration) waits out the REARM.
    waited=0
    while [ "$waited" -lt "$REARM" ]; do
      sleep "$POLL"
      waited=$((waited + POLL))
      if ! probe; then
        was_down=1
        log "tunnel dropped during re-arm wait; resuming fast poll"
        break
      fi
    done
  fi
  reset_caps
done
