#!/usr/bin/env python
"""Bench-artifact state checker for the tunnel watcher.

`python scripts/bench_state.py <artifact.json>` exits 0 iff every expected
bench leg has a measured (non-error) row in the artifact, else exits 1 and
prints the gaps. Reads either schema:
  BENCH_PARTIAL.json  -> {"updated": ..., "legs": {...}}
  BENCH_WATCH*.json   -> {"metric": ..., "extras": {...}}

The watcher uses this to decide whether another pass is still needed after
a tunnel outage ate part of a run (round-4: the 03:47 contact lasted ~3
minutes and the single-shot watcher would have stopped watching after one
all-error pass).

It also WARNS (without failing) when the merged artifact mixes
measurement conditions (VERDICT r5 weak #9 — the Spark stats timeline
role, dl4j-spark/.../stats/StatsUtils.java:65): rows spanning more than
MAX_SPAN_HOURS (a multi-window capture under different tunnel/host
states), or rows whose recorded 1-minute load averages (`load1`, stamped
by bench.py per leg) differ by more than MAX_LOAD_SPREAD — a quiet-host
row and a contended-host row must not be read as one regime.
"""
import json
import os
import re
import sys

# fallback only — expected_legs() derives the live list from bench.py's
# run() calls so a new leg can't silently escape the completeness check
EXPECTED = [
    "mxu_calibration", "lenet5", "lenet5_fused", "dispatch_overhead",
    "remat_memory", "char_rnn", "word2vec_sgns", "transformer_lm",
    "resnet50", "resnet50_bf16", "transformer_lm_big", "flash_attention",
    "ring_attention", "lstm_kernel", "north_star", "serving_throughput",
    "serving_resilience", "serving_decode", "serving_fleet", "autoscale",
    "decode_amortize", "serving_mesh", "checkpoint_overhead",
    "input_pipeline",
    "elastic_dp", "online_loop", "lowprec", "retrieval", "obs_overhead",
    "paged_kernel", "sgns_kernel",
    "reference_cpu_lenet5_torch", "lenet5_cpu",
    "char_rnn_cpu", "native_feed", "scaling_virtual8",
]

MAX_SPAN_HOURS = 6.0
MAX_LOAD_SPREAD = 1.5

_BENCH_PY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def expected_legs() -> list:
    """The single source of truth is bench.py's run("<leg>", ...) calls;
    EXPECTED is only the fallback if bench.py is unreadable."""
    try:
        with open(_BENCH_PY) as f:
            legs = re.findall(r'^\s*run\("([a-z0-9_]+)"', f.read(), re.M)
        return legs or EXPECTED
    except OSError:
        return EXPECTED


def load_artifact(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def legs_of(path: str) -> dict:
    data = load_artifact(path)
    return data.get("legs") or data.get("extras") or {}


def gaps(legs: dict) -> list:
    out = []
    for name in expected_legs():
        row = legs.get(name)
        if not isinstance(row, dict) or "error" in row:
            out.append(name)
    return out


def _parse_ts(s):
    import time

    try:
        return time.mktime(time.strptime(s, "%Y-%m-%dT%H:%M:%S"))
    except (TypeError, ValueError):
        return None


def warnings(legs: dict) -> list:
    """Cross-row condition-skew flags for a merged multi-pass artifact.
    Warnings never change the exit code — a complete artifact is complete
    — but a summarizer quoting rows hours (or load regimes) apart should
    say so."""
    out = []
    stamped = [(name, row) for name, row in legs.items()
               if isinstance(row, dict) and "error" not in row]
    times = [(name, _parse_ts(row.get("ts"))) for name, row in stamped]
    times = [(n, t) for n, t in times if t is not None]
    if len(times) >= 2:
        lo = min(times, key=lambda p: p[1])
        hi = max(times, key=lambda p: p[1])
        span_h = (hi[1] - lo[1]) / 3600.0
        if span_h > MAX_SPAN_HOURS:
            out.append(
                f"rows span {span_h:.1f}h (oldest {lo[0]}, newest {hi[0]})"
                f" > {MAX_SPAN_HOURS:.0f}h — mixed capture windows; treat"
                " cross-leg comparisons with care")
    loads = [(name, row.get("load1")) for name, row in stamped]
    loads = [(n, float(l)) for n, l in loads if isinstance(l, (int, float))]
    if len(loads) >= 2:
        lo = min(loads, key=lambda p: p[1])
        hi = max(loads, key=lambda p: p[1])
        if hi[1] - lo[1] > MAX_LOAD_SPREAD:
            out.append(
                f"host-load regimes differ: load1 {lo[1]:.2f} ({lo[0]}) vs"
                f" {hi[1]:.2f} ({hi[0]}), spread > {MAX_LOAD_SPREAD} — "
                "rows were measured under different contention")
    return out


_PALLAS_BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "PALLAS_BENCH.json")


def kernel_gate_warnings(path: str = None) -> list:
    """Provenance check on the measured-win artifact (ISSUE 13): a
    default-on kernel decision must come from a real-chip row. measured_win
    already IGNORES backend=="cpu"/interpret rows, but their presence in a
    group means the honest answer for that kernel is still 'unproven' —
    a summarizer (or a human eyeballing speedup numbers) must not read an
    interpret-mode timing as chip evidence."""
    out = []
    try:
        with open(path or _PALLAS_BENCH) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return out
    for group, rows in data.items():
        if group == "verdicts" or not isinstance(rows, dict):
            continue
        for name, row in rows.items():
            if not isinstance(row, dict) or "speedup" not in row:
                continue
            if row.get("backend") == "cpu" or row.get("interpret"):
                out.append(
                    f"PALLAS_BENCH {group}.{name}: speedup "
                    f"{row['speedup']} is a CPU/interpret-mode row — NOT "
                    "chip evidence; the measured-win gate ignores it and "
                    f"the {group} kernel stays default-off until a real-"
                    "chip row lands")
    return out


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_PARTIAL.json"
    try:
        data = load_artifact(path)
        legs = data.get("legs") or data.get("extras") or {}
        missing = gaps(legs)
    except (OSError, ValueError) as e:
        print(f"unreadable {path}: {e}")
        return 1
    # lint provenance (ISSUE 10): an artifact stamped from a graftlint-
    # DIRTY tree is still a measurement, but a summarizer quoting it as
    # this round's proof should say so (None = linter unavailable; no
    # warning — absence of the bit is not evidence of dirt)
    if data.get("graftlint_clean") is False:
        print("WARN: artifact was produced from a graftlint-DIRTY tree "
              "(run `python -m deeplearning4j_tpu.analysis`)")
    for w in warnings(legs):
        print("WARN:", w)
    for w in kernel_gate_warnings():
        print("WARN:", w)
    if missing:
        print("missing/errored legs:", ", ".join(missing))
        return 1
    print("clean: all", len(expected_legs()), "legs measured")
    return 0


if __name__ == "__main__":
    sys.exit(main())
