#!/usr/bin/env python
"""Bench-artifact state checker for the tunnel watcher.

`python scripts/bench_state.py <artifact.json>` exits 0 iff every expected
bench leg has a measured (non-error) row in the artifact, else exits 1 and
prints the gaps. Reads either schema:
  BENCH_PARTIAL.json  -> {"updated": ..., "legs": {...}}
  BENCH_WATCH*.json   -> {"metric": ..., "extras": {...}}

The watcher uses this to decide whether another pass is still needed after
a tunnel outage ate part of a run (round-4: the 03:47 contact lasted ~3
minutes and the single-shot watcher would have stopped watching after one
all-error pass).
"""
import json
import os
import re
import sys

# fallback only — expected_legs() derives the live list from bench.py's
# run() calls so a new leg can't silently escape the completeness check
EXPECTED = [
    "mxu_calibration", "lenet5", "lenet5_fused", "dispatch_overhead",
    "char_rnn", "word2vec_sgns", "transformer_lm", "resnet50",
    "resnet50_bf16", "transformer_lm_big", "flash_attention",
    "ring_attention", "lstm_kernel", "north_star",
    "reference_cpu_lenet5_torch", "native_feed", "scaling_virtual8",
]

_BENCH_PY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def expected_legs() -> list:
    """The single source of truth is bench.py's run("<leg>", ...) calls;
    EXPECTED is only the fallback if bench.py is unreadable."""
    try:
        with open(_BENCH_PY) as f:
            legs = re.findall(r'^\s*run\("([a-z0-9_]+)"', f.read(), re.M)
        return legs or EXPECTED
    except OSError:
        return EXPECTED


def legs_of(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    return data.get("legs") or data.get("extras") or {}


def gaps(legs: dict) -> list:
    out = []
    for name in expected_legs():
        row = legs.get(name)
        if not isinstance(row, dict) or "error" in row:
            out.append(name)
    return out


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_PARTIAL.json"
    try:
        missing = gaps(legs_of(path))
    except (OSError, ValueError) as e:
        print(f"unreadable {path}: {e}")
        return 1
    if missing:
        print("missing/errored legs:", ", ".join(missing))
        return 1
    print("clean: all", len(expected_legs()), "legs measured")
    return 0


if __name__ == "__main__":
    sys.exit(main())
