#!/usr/bin/env bash
# graftlint wrapper: run the project-invariant static analysis over the
# repo surface (or the given paths). Exit 0 clean, 1 findings.
#
#   scripts/lint.sh            # full sweep (DEFAULT_TARGETS)
#   scripts/lint.sh --json     # machine-readable report
#   scripts/lint.sh deeplearning4j_tpu/serving
#
# jax-free and fast (~2s): safe to run any time, tunnel up or down.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m deeplearning4j_tpu.analysis "$@"
