#!/usr/bin/env bash
# Install the opt-in pre-commit hook: graftlint must be clean before a
# commit lands. The hook is a thin shim to scripts/lint.sh so hook
# behavior updates with the repo, not with re-installation.
#
#   scripts/install_hooks.sh
#
# Bypass for a genuinely exceptional commit: git commit --no-verify
# (prefer a per-site `# graftlint: disable=<rule> -- <why>` instead —
# the suppression inventory is the documented-exceptions list).
set -euo pipefail
cd "$(dirname "$0")/.."
hook_dir=$(git rev-parse --git-path hooks)
mkdir -p "$hook_dir"
cat > "$hook_dir/pre-commit" <<'HOOK'
#!/usr/bin/env bash
# installed by scripts/install_hooks.sh — graftlint gate
exec bash "$(git rev-parse --show-toplevel)/scripts/lint.sh"
HOOK
chmod +x "$hook_dir/pre-commit"
echo "installed $hook_dir/pre-commit -> scripts/lint.sh (graftlint gate)"
