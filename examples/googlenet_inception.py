"""GoogLeNet / Inception-v1 — the DAG the graph machinery exists for:
nine four-tower inception modules merged on the channel axis, plus the
paper's auxiliary softmax heads as extra graph OUTPUTS (multi-output
training: one label array per head). Runs a tiny 64px smoke train on the
virtual CPU mesh; identical code drives a TPU at 224px."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.models.googlenet import build_googlenet  # noqa: E402
from deeplearning4j_tpu.ops import env as envknob


# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")


def main():
    rng = np.random.default_rng(0)
    size, batch = (32, 4) if SMOKE else (64, 8)
    net = build_googlenet(input_size=size, num_classes=10, aux_heads=True)
    print(f"GoogLeNet (aux heads): {net.num_params()/1e6:.2f}M params, "
          f"{len(net.conf.outputs)} outputs")
    x = rng.random((batch, size, size, 3)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    for step in range(2 if SMOKE else 5):
        loss = float(net.fit(x, [y, y, y]))  # main + two aux heads
        print(f"step {step}: summed 3-head loss {loss:.3f}")
    main_out = net.output(x)[0]
    print(f"main head output: {main_out.shape}, "
          f"row sums {np.asarray(main_out).sum(1)[:3].round(3)}")


if __name__ == "__main__":
    main()
