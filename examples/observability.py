"""Unified observability plane: trace a training run, scrape it, read the
flight recorder.

The obs plane (deeplearning4j_tpu/obs/ — the TPU-native growth of the
reference's IterationListener chain + UI/stats plane,
deeplearning4j-ui-parent) around a plain MLP fit:

  1. ``DL4J_TPU_OBS=1`` turns the span tracer on: every jit dispatch,
     checkpoint phase and staging wait becomes a monotonic-clock span
     with ids + parent ids (host-side events only — no device syncs);
  2. the five telemetry ledgers (dispatch/memory/pipeline/resilience/
     serving) register in ONE MetricsRegistry; a standalone stdlib-HTTP
     exporter serves it as Prometheus text exposition during the fit;
  3. the flight-recorder journal keeps the last-N-events timeline and
     flushes crash-safely — a dead run leaves a readable JSONL file.

Run from the repo root:  python examples/observability.py
"""

import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# obs on for this process BEFORE the framework imports; journal into a
# scratch dir so repeated runs don't collide
os.environ["DL4J_TPU_OBS"] = "1"
os.environ.setdefault(
    "DL4J_TPU_OBS_JOURNAL",
    os.path.join(tempfile.mkdtemp(prefix="obs_example_"), "journal.jsonl"))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu import obs  # noqa: E402
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator  # noqa: E402
from deeplearning4j_tpu.nn.conf import (  # noqa: E402
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.optimize.listeners import (  # noqa: E402
    DispatchStatsListener,
)
from deeplearning4j_tpu.ops import env as envknob

# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")

N_EXAMPLES = 128 if SMOKE else 1024
HIDDEN = 16 if SMOKE else 128
EPOCHS = 1 if SMOKE else 3
BATCH = 16


def build() -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder().seed(42).learning_rate(0.05)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=8, n_out=HIDDEN, activation="relu"))
        .layer(1, OutputLayer(n_in=HIDDEN, n_out=4, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf)


def make_iterator() -> ListDataSetIterator:
    rng = np.random.default_rng(1)
    centers = rng.standard_normal((4, 8)) * 2.0
    labels = rng.integers(0, 4, N_EXAMPLES)
    x = (centers[labels] + rng.standard_normal((N_EXAMPLES, 8))).astype(
        np.float32)
    y = np.eye(4, dtype=np.float32)[labels]
    return ListDataSetIterator(x, y, batch=BATCH)


def main() -> None:
    exporter = obs.MetricsExporter().start()
    print(f"=== metrics exporter live at {exporter.url}/metrics ===")

    net = build()
    net.set_listeners(DispatchStatsListener(frequency=4))
    net.fit_iterator(make_iterator(), num_epochs=EPOCHS)

    # -- spans: the per-dispatch timeline the ledgers can't show ----------
    steps = obs.tracer().spans("dispatch.train_step")
    traced = [s for s in steps if s["attrs"].get("traced")]
    assert steps, "tracing was on but no dispatch spans were recorded"
    print(f"=== {len(steps)} train-step dispatch spans "
          f"({len(traced)} traced/compiled, {len(steps) - len(traced)} "
          "compiled-cache hits) ===")
    for s in steps[:3]:
        print(f"    span {s['span_id']} {s['name']} "
              f"{s['duration_s'] * 1e3:.2f}ms attrs={s['attrs']}")

    # -- one Prometheus scrape over every registered ledger ---------------
    with urllib.request.urlopen(exporter.url + "/metrics",
                                timeout=10) as r:
        page = r.read().decode()
    samples = [ln for ln in page.splitlines()
               if ln and not ln.startswith("#")]
    assert any(ln.startswith("dl4j_dispatch_") for ln in samples), \
        "dispatch ledger missing from the scrape"
    print(f"=== /metrics: {len(samples)} Prometheus samples; a taste: ===")
    for ln in samples[:5]:
        print("    " + ln)

    # -- the flight recorder: what a post-mortem would read ---------------
    path = obs.default_journal().flush(fsync=True)
    assert path, "journal flush failed (journal path unwritable?)"
    events = obs.FlightRecorder.load(path)
    assert events, "journal flushed empty — the flight recorder saw nothing"
    print(f"=== flight recorder: {len(events)} events at {path} ===")
    print(f"    last event: {events[-1]['kind']} seq={events[-1]['seq']}")

    exporter.stop()
    print("OK")


if __name__ == "__main__":
    main()
