"""Data-parallel training — the dl4j-spark ParameterAveraging role on a
TPU mesh. Runs on a virtual 8-device CPU mesh here (same code drives a
real slice): GSPMD gradient DP via ParallelWrapper, plus the reference's
parameter-averaging-every-k-steps compatibility mode."""


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# virtual 8-device CPU mesh (same pattern as tests/conftest.py); on a real
# TPU slice, delete this call and the mesh uses the chips
from deeplearning4j_tpu.parallel.mesh import virtual_cpu_devices

virtual_cpu_devices(8)

from deeplearning4j_tpu.models.lenet import build_lenet5  # noqa: E402
from deeplearning4j_tpu.parallel.data_parallel import (  # noqa: E402
    ParallelWrapper,
    ParameterAveragingTrainer,
)
from deeplearning4j_tpu.ops import env as envknob


# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")


def main():
    rng = np.random.default_rng(0)
    n = 64 if SMOKE else 256
    dp_steps, pa_steps = (2, 2) if SMOKE else (5, 4)
    x = rng.random((n, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]

    # GSPMD gradient DP: batch sharded over 8 devices, XLA inserts the psum
    pw = ParallelWrapper(build_lenet5(), num_devices=8)
    for step in range(dp_steps):
        loss = float(pw.fit(x, y))
    print(f"gradient-DP loss after {dp_steps} steps: {loss:.4f}")

    # reference-compatible parameter averaging (Spark master semantics:
    # local steps then params+updater pmean every averaging_frequency)
    pat = ParameterAveragingTrainer(build_lenet5(), num_workers=8,
                                    averaging_frequency=2)
    for step in range(pa_steps):
        loss = float(pat.fit(x, y))
    print(f"param-averaging loss after {pa_steps} rounds: {loss:.4f}")


if __name__ == "__main__":
    main()
