"""Data-parallel training — the dl4j-spark ParameterAveraging role on a
TPU mesh. Runs on a virtual 8-device CPU mesh here (same code drives a
real slice): GSPMD gradient DP via ParallelWrapper, plus the reference's
parameter-averaging-every-k-steps compatibility mode."""


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

# virtual 8-device CPU mesh (same pattern as tests/conftest.py); on a real
# TPU slice, delete these two lines and the mesh uses the chips
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

from deeplearning4j_tpu.models.lenet import build_lenet5  # noqa: E402
from deeplearning4j_tpu.parallel.data_parallel import (  # noqa: E402
    ParallelWrapper,
    ParameterAveragingTrainer,
)


def main():
    rng = np.random.default_rng(0)
    x = rng.random((256, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 256)]

    # GSPMD gradient DP: batch sharded over 8 devices, XLA inserts the psum
    pw = ParallelWrapper(build_lenet5(), num_devices=8)
    for step in range(5):
        loss = float(pw.fit(x, y))
    print(f"gradient-DP loss after 5 steps: {loss:.4f}")

    # reference-compatible parameter averaging (Spark master semantics:
    # local steps then params+updater pmean every averaging_frequency)
    pat = ParameterAveragingTrainer(build_lenet5(), num_workers=8,
                                    averaging_frequency=2)
    for step in range(4):
        loss = float(pat.fit(x, y))
    print(f"param-averaging loss after 4 rounds: {loss:.4f}")


if __name__ == "__main__":
    main()
