"""Word2Vec skip-gram negative sampling — the reference's
Word2VecRawTextExample: build vocab, train embeddings, query nearest words."""


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# force CPU BEFORE first backend use: the axon TPU plugin hangs
# forever initializing a dead remote tunnel (CLAUDE.md); demos run
# in seconds on CPU and scale to TPU unchanged via this one line
jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.ops import env as envknob

SENTENCES = [
    "the king rules the kingdom with the queen",
    "the queen rules beside the king",
    "a dog chases the cat around the yard",
    "the cat sleeps while the dog barks",
    "day follows night and night follows day",
    "the sun shines during the day",
    "the moon glows at night",
    "kings and queens live in castles",
    "dogs and cats are animals",
] * 30


# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")


def main():
    w2v = Word2Vec(layer_size=48, window=4, negative=5, min_word_frequency=3,
                   epochs=2 if SMOKE else 8, seed=42)
    w2v.fit(SENTENCES)
    for word in ("king", "dog", "day"):
        print(f"nearest to '{word}':", w2v.words_nearest(word, 4))
    print("similarity(king, queen) =",
          round(w2v.similarity("king", "queen"), 3))
    print("similarity(king, cat)   =",
          round(w2v.similarity("king", "cat"), 3))


if __name__ == "__main__":
    main()
