"""Stacked-RBM DBN on MNIST — the reference's DBNMnistFullExample flow:
layerwise contrastive-divergence pretraining, then supervised fine-tune."""


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# force CPU BEFORE first backend use: the axon TPU plugin hangs
# forever initializing a dead remote tunnel (CLAUDE.md); demos run
# in seconds on CPU and scale to TPU unchanged via this one line
jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.datasets.fetchers import load_mnist_info
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.models.dbn import build_dbn
from deeplearning4j_tpu.ops import env as envknob


# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")


def main():
    x, y, provenance = load_mnist_info(train=True,
                                       num_examples=256 if SMOKE else 1024,
                                       binarize=True)
    xt, yt, _ = load_mnist_info(train=False, num_examples=256, binarize=True)
    x, xt = x.reshape(len(x), -1), xt.reshape(len(xt), -1)
    print(f"data: {provenance}")

    net = build_dbn(n_in=784, hidden=(256, 128), num_classes=10,
                    learning_rate=0.05)
    print("pretraining (layerwise CD-1)...")
    net.pretrain(x, num_epochs=1)

    print("fine-tuning...")
    batch = 128
    for epoch in range(1 if SMOKE else 3):
        losses = [float(net.fit(x[i:i + batch], y[i:i + batch]))
                  for i in range(0, len(x), batch)]
        print(f"epoch {epoch}: mean loss {np.mean(losses):.4f}")

    ev = Evaluation(num_classes=10)
    ev.eval(yt, np.asarray(net.output(xt)))
    print(f"test accuracy: {ev.accuracy():.3f}")


if __name__ == "__main__":
    main()
