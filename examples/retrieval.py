"""Embedding & retrieval serving: /embed + device-resident ANN /search.

The retrieval plane (deeplearning4j_tpu/retrieval/ — the serving half
the reference's scaleout-nlp module never grew: its InMemoryLookupTable
answers wordsNearest with a host-side full scan, here the arena lives
on device and top-k is one batched matmul) around a plain MLP encoder:

  1. register a trained net with a ``ServingEngine``; ``/embed`` routes
     its last HIDDEN layer through the same dynamic batcher + bucket
     ladder as ``/predict`` (byte-identical to a direct feed_forward);
  2. embed a corpus, upsert it into a ``VectorStore`` and publish —
     an immutable generation snapshot behind ``/search`` (exact top-k
     oracle + an IVF probe whose recall is MEASURED, never assumed);
  3. mutate the index ONLINE: upserts land in a staging arena, a
     publish swaps generations atomically under live search traffic —
     zero failed requests by construction;
  4. a drifted feed (``online/drift.DriftMonitor``) VETOES the publish
     — the serving generation never moves under a distribution shift.

Run from the repo root:  python examples/retrieval.py
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.nn.conf import (  # noqa: E402
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.online import DriftMonitor  # noqa: E402
from deeplearning4j_tpu.ops import env as envknob  # noqa: E402
from deeplearning4j_tpu.retrieval import (  # noqa: E402
    PublishVetoed,
    VectorStore,
)
from deeplearning4j_tpu.serving.engine import ServingEngine  # noqa: E402

SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")

N_CORPUS = 256 if SMOKE else 4096
N_CLUSTERS = 8 if SMOKE else 32
FEATURES = 16
HIDDEN = 12 if SMOKE else 32


def build_encoder(seed: int = 7) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(0, DenseLayer(n_in=FEATURES, n_out=HIDDEN,
                                 activation="relu"))
            .layer(1, OutputLayer(n_in=HIDDEN, n_out=N_CLUSTERS,
                                  activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def clustered_rows(rng, n):
    centers = rng.normal(size=(N_CLUSTERS, FEATURES)).astype(np.float32)
    assign = rng.integers(0, N_CLUSTERS, size=n)
    rows = centers[assign] + 0.1 * rng.normal(size=(n, FEATURES))
    return rows.astype(np.float32), assign


def main():
    rng = np.random.default_rng(0)
    net = build_encoder()
    engine = ServingEngine(model=net, input_shape=(FEATURES,)).start()
    try:
        # -- 1. /embed through the serving batcher ------------------------
        corpus_rows, _ = clustered_rows(rng, N_CORPUS)
        emb = engine.embed(corpus_rows)
        direct = np.asarray(net.feed_forward(corpus_rows, train=False)[-2],
                            np.float32).reshape(N_CORPUS, -1)
        assert np.array_equal(emb, direct), "batcher != direct embed"
        print(f"=== /embed: {emb.shape[0]} rows -> dim {emb.shape[1]} "
              "(byte-identical to direct feed_forward) ===")

        # -- 2. index + publish + measured recall -------------------------
        store = VectorStore(emb.shape[1], capacity=N_CORPUS + 64,
                            kind="ivf", clusters=N_CLUSTERS, nprobe=4,
                            min_ivf_rows=32, name="corpus")
        store.upsert(np.arange(N_CORPUS), emb)
        store.publish()
        engine.register_index("corpus", store)
        probe_rows, _ = clustered_rows(rng, 32)
        recall = store.probe_recall(engine.embed(probe_rows), k=10)
        rep = store.report()
        print(f"=== index: {rep['rows']} rows, generation "
              f"{rep['generation']}, ivf_built={rep['ivf_built']}, "
              f"measured recall@10 {recall:.3f} ===")

        ids, scores = engine.search("corpus", emb[:2], k=3)
        assert ids[0][0] == 0 and ids[1][0] == 1, "self-hit failed"
        print(f"=== /search self-hit: ids {ids.tolist()} ===")

        # -- 3. online mutation under live search traffic -----------------
        stop = threading.Event()
        answered, failed = [0], [0]

        def searcher():
            while not stop.is_set():
                try:
                    engine.search("corpus", emb[:4], k=5)
                    answered[0] += 1
                except Exception:  # noqa: BLE001 — the zero-failure claim
                    failed[0] += 1
                    return

        t = threading.Thread(target=searcher)
        t.start()
        fresh_rows, _ = clustered_rows(rng, 16)
        store.upsert(np.arange(N_CORPUS, N_CORPUS + 16),
                     engine.embed(fresh_rows))
        store.publish()
        stop.set()
        t.join()
        assert failed[0] == 0, "a generation swap failed a live search"
        print(f"=== online publish: generation {store.generation}, "
              f"{answered[0]} live searches answered, {failed[0]} failed ===")

        # -- 4. drift veto -------------------------------------------------
        drift = DriftMonitor((emb.mean(axis=0), emb.std(axis=0) + 1e-6),
                             min_rows=8)
        drift.observe(emb[:16] + 100.0)  # a scripted shift
        store.upsert([N_CORPUS + 63], np.ones((1, emb.shape[1])))
        try:
            store.publish(drift=drift)
            raise AssertionError("drifted publish was not vetoed")
        except PublishVetoed:
            pass
        print(f"=== drift veto: publish blocked, generation still "
              f"{store.generation} ===")
        print("OK")
    finally:
        engine.stop(drain=False)


if __name__ == "__main__":
    main()
