"""Streaming /generate against the paged-KV serving plane.

A client's view of the block-pool decode path (deeplearning4j_tpu/
serving/paged.py — the subsystem the reference's one-record Camel route,
dl4j-streaming/.../routes/DL4jServeRouteBuilder.java, never grew):

  1. a ServingEngine serves a small TransformerLM with the paged KV
     arena (DL4J_TPU_SERVE_KV_BLOCK) and two SLO classes;
  2. several requests SHARE a long system prompt — the prefix cache
     hashes the shared blocks once and later admissions reference them
     instead of recomputing/storing their KV (watch prefix_hits and
     kv capacity at /models);
  3. one request streams: POST /generate with ``"stream": true`` chunks
     NDJSON ``{"token": t}`` events per decode tick and a final
     ``{"done": true, "tokens": [...]}`` record.

Run from the repo root:  python examples/serving_generate.py
"""

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    TransformerLM,
)
from deeplearning4j_tpu.ops import env as envknob  # noqa: E402
from deeplearning4j_tpu.serving import ServingEngine  # noqa: E402

# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")

D_MODEL = 32 if SMOKE else 128
N_LAYERS = 2 if SMOKE else 4
MAX_LEN = 64 if SMOKE else 256
N_NEW = 8 if SMOKE else 32
N_CLIENTS = 3 if SMOKE else 6
VOCAB = 64


def post(url, path, payload, timeout=300):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return json.loads(r.read())


def main() -> None:
    lm = TransformerLM(TransformerConfig(
        vocab_size=VOCAB, d_model=D_MODEL, n_layers=N_LAYERS,
        n_heads=4, d_ff=2 * D_MODEL, max_len=MAX_LEN, use_flash=False))
    eng = ServingEngine(model=lm, kv_block=8,
                        slo_classes="interactive:60,batch:300").start()
    try:
        kv = get(eng.url, "/models")["kv"]["default@v1"]
        print(f"=== paged KV arena: {kv['blocks_total']} blocks x "
              f"{kv['block_tokens']} tokens = {kv['capacity_tokens']} "
              f"tokens across {kv['lanes']} lanes ===")

        # a shared system prompt long enough to span whole KV blocks —
        # the prefix cache dedupes it across the client requests below
        rng = np.random.default_rng(0)
        system = rng.integers(1, VOCAB, MAX_LEN // 2).tolist()

        print(f"--- {N_CLIENTS} clients, one shared system prompt ---")
        for i in range(N_CLIENTS):
            out = post(eng.url, "/generate",
                       {"tokens": system + [i + 1], "n_new": N_NEW,
                        "temperature": 0.0, "slo": "interactive"})
            print(f"client {i}: {out['tokens'][0][:8]}...")

        served = get(eng.url, "/metrics")["serving"]
        print(f"prefix cache: {served['prefix_hits']}/"
              f"{served['prefix_lookups']} block lookups hit "
              f"(shared system prompt stored once)")

        print("--- streaming client (NDJSON chunks per decode tick) ---")
        req = urllib.request.Request(
            eng.url + "/generate",
            data=json.dumps({"tokens": system + [42], "n_new": N_NEW,
                             "temperature": 0.0, "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            for raw in resp:
                event = json.loads(raw)
                if "token" in event:
                    print(f"  token: {event['token']}")
                elif event.get("done"):
                    print(f"  done: {event['tokens']}")

        kv = get(eng.url, "/models")["kv"]["default@v1"]
        print(f"=== arena after traffic: {kv['blocks_in_use']} blocks "
              f"held ({kv['prefix_blocks_cached']} by the prefix cache), "
              f"{kv['blocks_total'] - kv['blocks_in_use']} free ===")
    finally:
        eng.stop()


if __name__ == "__main__":
    main()
