"""Multi-host data parallelism: one training script, N OS processes.

The reference scales out by running one Spark executor per partition that
feeds a ParameterAveragingTrainingMaster
(dl4j-spark SparkDl4jMultiLayer.fit — SURVEY.md section 2.3). The
TPU-native shape of that plane is jax.distributed: one controller process
per host, XLA collectives over ICI/DCN, each process feeding ONLY the
examples it loaded (`multihost.put_batch` assembles the global array with
zero cross-host data movement).

This example launches the 2-process cluster LOCALLY (CPU devices, Gloo
collectives) — the exact same script a TPU pod runs per host, where the
provisioner (provision/tpu_pod.py) injects the same env contract. Run:

    python examples/multihost_dp.py            # parent: spawns 2 workers
    # or launch each worker yourself (the full contract, one process each):
    DL4J_TPU_COORDINATOR=host:port DL4J_TPU_NUM_PROCESSES=2 \
        DL4J_TPU_PROCESS_ID=<0|1> python examples/multihost_dp.py

Each worker trains the same MLP data-parallel over the global mesh and
verifies its parameters track a serial run to float32 tolerance (the
gradient psum reduces in a different order than the serial batch sum;
tests/test_multihost_cpu.py pins BIT-exactness under float64).
"""
import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.parallel import multihost  # noqa: E402

N_PROCESSES = 2


def worker() -> None:
    import jax

    from deeplearning4j_tpu.parallel.mesh import virtual_cpu_devices

    virtual_cpu_devices(2)

    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

    multihost.initialize_multihost()
    info = multihost.process_info()
    print(f"[proc {info['process_index']}] sees "
          f"{info['local_device_count']} local / "
          f"{info['global_device_count']} global devices", flush=True)

    # capability probe (same filter as tests/multihost_worker.py): some
    # jaxlib builds cannot run multi-process computations on the CPU
    # backend — exit cleanly there instead of crashing the stock example;
    # any OTHER collective failure stays loud
    try:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("example_probe")
    except Exception as e:  # noqa: BLE001 — filtered to the capability case
        if "Multiprocess computations" not in str(e):
            raise
        print(f"[proc {info['process_index']}] MH_SKIP multiprocess CPU "
              f"collectives unavailable in this jaxlib: {e}", flush=True)
        return

    def build():
        conf = (
            NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("sgd")
            .list()
            .layer(0, DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(1, OutputLayer(n_in=16, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    X = rng.randn(32, 8)
    Y = np.eye(3)[rng.randint(0, 3, size=32)]

    serial = build()
    for _ in range(10):
        serial.fit(X, Y)

    net = build()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    pw = ParallelWrapper(net, mesh=mesh)
    sl = multihost.local_batch_slice(len(X))  # this process's shard
    for _ in range(10):
        loss = pw.fit(X[sl], Y[sl])

    dev = max(
        float(abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree_util.tree_leaves(serial.params),
                        jax.tree_util.tree_leaves(net.params))
    )
    print(f"[proc {info['process_index']}] final loss {float(loss):.6f}, "
          f"max param deviation vs serial: {dev:.2e}", flush=True)
    assert dev < 1e-5, dev


def parent() -> None:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(N_PROCESSES):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env[multihost.COORDINATOR_ENV] = f"127.0.0.1:{port}"
        env[multihost.NUM_PROCESSES_ENV] = str(N_PROCESSES)
        env[multihost.PROCESS_ID_ENV] = str(pid)
        procs.append(subprocess.Popen([sys.executable, __file__], env=env))
    rcs = [p.wait() for p in procs]
    if any(rcs):
        raise SystemExit(f"worker failures: {rcs}")
    print("both processes trained data-parallel, matching serial")


if __name__ == "__main__":
    if os.environ.get(multihost.COORDINATOR_ENV):
        worker()
    else:
        parent()
