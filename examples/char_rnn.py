"""Char-RNN language modelling — the reference's
GravesLSTMCharModellingExample: 2-layer LSTM, TBPTT training, then
streaming generation through the jitted `rnn_time_step` path."""


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# force CPU BEFORE first backend use: the axon TPU plugin hangs
# forever initializing a dead remote tunnel (CLAUDE.md); demos run
# in seconds on CPU and scale to TPU unchanged via this one line
jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.models.char_rnn import char_rnn_conf
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops import env as envknob

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "she sells sea shells by the sea shore. "
    "peter piper picked a peck of pickled peppers. "
) * 40

# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")


def main():
    chars = sorted(set(CORPUS))
    stoi = {c: i for i, c in enumerate(chars)}
    vocab = len(chars)
    ids = np.array([stoi[c] for c in CORPUS], np.int64)
    eye = np.eye(vocab, dtype=np.float32)

    seq, batch = 60, 16
    net = MultiLayerNetwork(
        char_rnn_conf(vocab, lstm_size=96, num_layers=2, tbptt_length=30)
    ).init(input_shape=(1, vocab))

    rng = np.random.default_rng(0)
    for step in range(8 if SMOKE else 60):
        starts = rng.integers(0, len(ids) - seq - 1, batch)
        x = eye[np.stack([ids[s:s + seq] for s in starts])]
        y = eye[np.stack([ids[s + 1:s + seq + 1] for s in starts])]
        loss = float(net.fit(x, y))
        if step % 20 == 0:
            print(f"step {step}: loss {loss:.3f}")

    # streaming sampling (reference rnnTimeStep :2152)
    net.rnn_clear_previous_state()
    cur = stoi["t"]
    out = ["t"]
    g = np.random.default_rng(1)
    for _ in range(20 if SMOKE else 120):
        probs = np.asarray(net.rnn_time_step(eye[cur][None, None, :]))[0, 0]
        probs = np.maximum(probs, 0)
        probs /= probs.sum()
        cur = int(g.choice(vocab, p=probs))
        out.append(chars[cur])
    print("sample:", "".join(out))


if __name__ == "__main__":
    main()
