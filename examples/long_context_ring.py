"""Long-context inference via sequence parallelism — beyond the reference
(whose only long-sequence tool was truncated BPTT): a sequence too big to
attend on one device is sharded over the mesh's 'seq' axis and attention
runs as an exact RING (K/V shards rotating via ppermute, online softmax) or
via Ulysses all-to-alls. Runs on a virtual 8-device CPU mesh; identical
code drives a TPU slice."""


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.parallel.mesh import virtual_cpu_devices

virtual_cpu_devices(8)

import jax  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from deeplearning4j_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    forward,
    init_params,
    ring_forward,
)
from deeplearning4j_tpu.ops import env as envknob


# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")
SEQ = 128 if SMOKE else 512


def main():
    cfg = TransformerConfig(vocab_size=128, d_model=64, n_layers=2,
                            n_heads=8, d_ff=128, max_len=SEQ)
    params = init_params(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, cfg.max_len)),
                         jnp.int32)
    mesh = Mesh(np.array(jax.devices()), ("seq",))
    print(f"sequence length {cfg.max_len}, sharded over "
          f"{len(jax.devices())} devices "
          f"({cfg.max_len // len(jax.devices())}/device)")

    dense, _ = forward(params, tokens, cfg)
    for strategy in ("ring", "ulysses"):
        out = ring_forward(params, tokens, cfg, mesh, strategy=strategy)
        dev = float(jnp.max(jnp.abs(out - dense)))
        print(f"{strategy:8s}: max deviation vs dense attention {dev:.2e}")

    # round 3: long-context TRAINING — the same ring schedule composed
    # with loss + Adam (make_ring_train_step under TransformerLM's
    # sequence mode); on a ('data','seq') mesh the batch shards too
    from deeplearning4j_tpu.models.transformer import TransformerLM

    cfg_t = TransformerConfig(vocab_size=128, d_model=64, n_layers=2,
                              n_heads=8, d_ff=128, max_len=SEQ,
                              learning_rate=1e-2, use_flash=False)
    mesh2 = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "seq"))
    lm = TransformerLM(cfg_t, mesh=mesh2)
    targets = jnp.asarray(
        rng.integers(0, cfg_t.vocab_size, tokens.shape), jnp.int32)
    losses = [float(lm.fit(tokens, targets))
              for _ in range(2 if SMOKE else 5)]
    print(f"SP TRAINING on DPxSP (2x4): loss {losses[0]:.3f} -> "
          f"{losses[-1]:.3f} over {len(losses)} steps")


if __name__ == "__main__":
    main()
