"""BERT-style masked-LM pretraining over the framework's NLP pipeline.

End-to-end text path: DefaultTokenizerFactory -> VocabCache (the same
vocab plane word2vec uses — reference AbstractCache/VocabConstructor,
SURVEY.md section 2.3) -> id sequences -> BertMLM whole-step-jit
pretraining -> masked-token recovery + contextual embeddings. The corpus
is deterministic synthetic "sentences" with strong local structure, so a
minute of CPU training visibly learns to fill in the blanks.

Run from the repo root:  python examples/bert_mlm.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.models.bert import BertConfig, BertMLM  # noqa: E402
from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory  # noqa: E402
from deeplearning4j_tpu.nlp.vocab import VocabCache  # noqa: E402
from deeplearning4j_tpu.ops import env as envknob

SEQ_LEN = 12
PAD, MASK = "[PAD]", "[MASK]"

SUBJECTS = ["the cat", "a dog", "the bird", "one fish"]
VERBS = ["sat on", "ran past", "looked at", "slept under"]
OBJECTS = ["the mat", "a tree", "the fence", "one rock"]

# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")


def corpus(n: int, rng) -> list:
    return [f"{SUBJECTS[rng.integers(4)]} {VERBS[rng.integers(4)]} "
            f"{OBJECTS[rng.integers(4)]} today" for _ in range(n)]


def main() -> None:
    rng = np.random.default_rng(0)
    sentences = corpus(256, rng)

    tok = DefaultTokenizerFactory()
    vocab = VocabCache()
    # huge counts pin the special tokens to indices 0/1 after finalize
    vocab.add_token(PAD, 1e9)
    vocab.add_token(MASK, 1e8)
    tokenized = [tok.tokenize(s) for s in sentences]
    for words in tokenized:
        for w in words:
            vocab.add_token(w)
    vocab.finalize_vocab()
    print(f"vocab: {vocab.num_words()} words "
          f"(pad={vocab.index_of(PAD)}, mask={vocab.index_of(MASK)})")

    def to_ids(words):
        ids = [vocab.index_of(w) for w in words][:SEQ_LEN]
        return ids + [vocab.index_of(PAD)] * (SEQ_LEN - len(ids))

    data = np.asarray([to_ids(w) for w in tokenized])

    cfg = BertConfig(vocab_size=vocab.num_words(), d_model=48, n_layers=2,
                     n_heads=4, d_ff=96, max_len=SEQ_LEN,
                     learning_rate=5e-3, mlm_prob=0.2,
                     pad_token_id=vocab.index_of(PAD),
                     mask_token_id=vocab.index_of(MASK), seed=0)
    lm = BertMLM(cfg)
    first = lm.fit(data[:64])
    for epoch in range(4 if SMOKE else 30):
        for i in range(0, len(data), 64):
            loss = lm.fit(data[i:i + 64])
        if epoch % 10 == 0:
            acc = lm.masked_accuracy(data[:64], n_draws=2)
            print(f"epoch {epoch:2d}: loss {loss:.3f}, masked acc {acc:.2f}")
    acc = lm.masked_accuracy(data[:64], n_draws=4)
    print(f"final: loss {first:.3f} -> {loss:.3f}, masked acc {acc:.2f}")

    # fill-in-the-blank: mask the verb of a fresh sentence
    words = tok.tokenize("the cat sat on the mat today")
    ids = np.asarray([to_ids(words)])
    masked = ids.copy()
    masked[0, 2] = cfg.mask_id  # "sat"
    pred = int(lm.predict_logits(masked)[0, 2].argmax())
    print(f"'the cat [MASK] on the mat today' -> {vocab.word_at_index(pred)!r}")

    emb = lm.embed_tokens(ids)
    print(f"contextual embeddings: {emb.shape}")


if __name__ == "__main__":
    main()
