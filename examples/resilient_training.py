"""Fault-tolerant training: kill a run mid-fit, resume it bit-exactly.

The resilience runtime (deeplearning4j_tpu/resilience/) around a plain
MLP classification fit:

  1. an UNINTERRUPTED run — the ground truth;
  2. the same run under ResilientTrainer + async CheckpointManager,
     KILLED mid-training by the deterministic chaos harness;
  3. a resumed run pointed at the same checkpoint directory — it
     restores params, updater state, step counters, RNG key and the
     data-iterator cursor, replays the exact remaining batch stream, and
     finishes bit-identical to run 1 (max |param delta| printed — it is
     exactly 0.0, and the stitched loss curve matches element-for-element).

The reference survives worker loss through Spark lineage recomputation;
this shows the TPU-native answer: checkpoint-and-replay with full
training-state capture, so nothing is recomputed and nothing drifts.

Run from the repo root:  python examples/resilient_training.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator  # noqa: E402
from deeplearning4j_tpu.nn.conf import (  # noqa: E402
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.resilience import (  # noqa: E402
    ChaosConfig,
    ChaosMonkey,
    CheckpointManager,
    InjectedKill,
    ResilientTrainer,
)
from deeplearning4j_tpu.ops import env as envknob

# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")

N_EXAMPLES = 128 if SMOKE else 512
HIDDEN = 16 if SMOKE else 64
EPOCHS = 2 if SMOKE else 4
BATCH = 16


def build() -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder().seed(42).learning_rate(0.05)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=8, n_out=HIDDEN, activation="relu"))
        .layer(1, OutputLayer(n_in=HIDDEN, n_out=4, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf)


def make_iterator() -> ListDataSetIterator:
    rng = np.random.default_rng(1)
    centers = rng.standard_normal((4, 8)) * 2.0
    labels = rng.integers(0, 4, N_EXAMPLES)
    x = (centers[labels] + rng.standard_normal((N_EXAMPLES, 8))).astype(
        np.float32)
    y = np.eye(4, dtype=np.float32)[labels]
    return ListDataSetIterator(x, y, batch=BATCH)


def main() -> None:
    steps_per_epoch = N_EXAMPLES // BATCH
    kill_at = steps_per_epoch + 2  # dies early in epoch 2

    print("=== run 1: uninterrupted (ground truth) ===")
    truth = ResilientTrainer(build())
    truth.fit(make_iterator(), num_epochs=EPOCHS)
    print(f"    {truth.step} steps, final loss {truth.losses[-1]:.4f}")

    with tempfile.TemporaryDirectory() as ckdir:
        print(f"=== run 2: checkpointed (async, every 4 steps), killed "
              f"at step {kill_at} ===")
        mgr = CheckpointManager(ckdir, every_steps=4, keep_last=3)
        chaos = ChaosMonkey(ChaosConfig(kill_at_step=kill_at))
        victim = ResilientTrainer(build(), mgr, chaos=chaos)
        try:
            victim.fit(make_iterator(), num_epochs=EPOCHS)
        except InjectedKill as e:
            print(f"    KILLED: {e}")
        mgr.close()
        kept = [s for s, _ in mgr.checkpoints()]
        print(f"    checkpoints on disk: steps {kept}")

        print("=== run 3: resume from the newest intact checkpoint ===")
        mgr2 = CheckpointManager(ckdir, every_steps=4, keep_last=3)
        survivor = ResilientTrainer(build(), mgr2)
        survivor.fit(make_iterator(), num_epochs=EPOCHS)
        mgr2.close()
        print(f"    resumed at step {survivor.resumed_step}, finished at "
              f"step {survivor.step}")

    stitched = victim.losses[:survivor.resumed_step] + survivor.losses
    curve_ok = stitched == truth.losses
    max_dev = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(jax.tree_util.tree_leaves(truth.net.params),
                        jax.tree_util.tree_leaves(survivor.net.params))
    )
    print("=== verdict ===")
    print(f"    loss curve (pre-kill prefix + resumed) == uninterrupted: "
          f"{curve_ok}")
    print(f"    max |param delta| vs uninterrupted: {max_dev}")
    if not curve_ok or max_dev != 0.0:
        raise SystemExit("resume was not bit-exact")
    print("    interrupted-and-resumed training == uninterrupted training")


if __name__ == "__main__":
    main()
