"""Transformer LM — the beyond-reference flagship: one jitted train step
sharded dp x tp over a mesh (GSPMD inserts every collective), then
sampling. Runs on a virtual 8-device CPU mesh; identical code drives a
TPU slice."""


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deeplearning4j_tpu.parallel.mesh import virtual_cpu_devices

virtual_cpu_devices(8)

import jax.numpy as jnp  # noqa: E402

from deeplearning4j_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    TransformerLM,
)
from deeplearning4j_tpu.parallel.mesh import device_mesh  # noqa: E402
from deeplearning4j_tpu.ops import env as envknob

TEXT = ("to be or not to be that is the question "
        "whether tis nobler in the mind to suffer ") * 60

# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")


def main():
    chars = sorted(set(TEXT))
    stoi = {c: i for i, c in enumerate(chars)}
    ids = np.array([stoi[c] for c in TEXT], np.int32)

    # activation-remat knob (ops/remat.py ladder): DL4J_TPU_REMAT picks
    # none/dots/block; the `-m examples` smoke tier pins "block" so the
    # remat path is exercised end-to-end on every smoke run
    remat = envknob.raw("DL4J_TPU_REMAT") or ("block" if SMOKE else "auto")
    cfg = TransformerConfig(vocab_size=len(chars), d_model=64, n_layers=2,
                            n_heads=4, d_ff=128, max_len=64,
                            learning_rate=3e-3, remat=remat)
    from deeplearning4j_tpu.ops.remat import remat_policy

    print("remat policy:", remat_policy(cfg.remat))
    mesh = device_mesh(shape=(2, 4), axis_names=("data", "model"))
    lm = TransformerLM(cfg, mesh=mesh)

    rng = np.random.default_rng(0)
    batch, seq = 8, cfg.max_len
    for step in range(6 if SMOKE else 40):
        starts = rng.integers(0, len(ids) - seq - 1, batch)
        x = jnp.asarray(np.stack([ids[s:s + seq] for s in starts]))
        y = jnp.asarray(np.stack([ids[s + 1:s + seq + 1] for s in starts]))
        loss = float(lm.fit(x, y))
        if step % 10 == 0:
            print(f"step {step}: loss {loss:.3f}")

    prompt = jnp.asarray([[stoi[c] for c in "to be "]], jnp.int32)
    # KV-cache decoding (default), nucleus sampling: O(max_len) per token
    out = lm.generate(prompt, n_new=8 if SMOKE else 40, temperature=0.8,
                      seed=0, top_k=min(50, cfg.vocab_size), top_p=0.95)
    print("sample:", "to be " + "".join(chars[int(i)] for i in out[0]))


if __name__ == "__main__":
    main()
