"""LeNet-5 on MNIST — the reference's LenetMnistExample, TPU-native.

Builds the conf through the DSL, trains with the single jitted train step,
evaluates, and writes a ModelSerializer checkpoint."""


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# force CPU BEFORE first backend use: the axon TPU plugin hangs
# forever initializing a dead remote tunnel (CLAUDE.md); demos run
# in seconds on CPU and scale to TPU unchanged via this one line
jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.datasets.fetchers import load_mnist_info
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.models.lenet import build_lenet5
from deeplearning4j_tpu.utils.serialization import ModelSerializer
from deeplearning4j_tpu.ops import env as envknob


# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py):
# the stock flow unchanged, just fewer examples/epochs so 11 entrypoints
# finish in minutes on the 1-core CPU host
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")


def main():
    net = build_lenet5()
    x, y, provenance = load_mnist_info(train=True,
                                       num_examples=512 if SMOKE else 2048)
    xt, yt, _ = load_mnist_info(train=False, num_examples=512)
    print(f"data: {provenance}; train {x.shape}, test {xt.shape}")

    batch = 256
    for epoch in range(1 if SMOKE else 3):
        perm = np.random.default_rng(epoch).permutation(len(x))
        losses = []
        for i in range(0, len(x), batch):
            idx = perm[i:i + batch]
            losses.append(float(net.fit(x[idx], y[idx])))
        print(f"epoch {epoch}: mean loss {np.mean(losses):.4f}")

    ev = Evaluation(num_classes=10)
    ev.eval(yt, np.asarray(net.output(xt)))
    print(ev.stats())

    ModelSerializer.write_model(net, "/tmp/lenet_mnist.zip")
    print("checkpoint written to /tmp/lenet_mnist.zip")


if __name__ == "__main__":
    main()
