"""DataVec-parity ETL: schema'd transforms, fitted normalizers, and the
overlapped InputPipeline (deeplearning4j_tpu/etl/).

The 2016 DataVec workflow, end to end, on a real on-disk CSV:

  1. a typed Schema + TransformProcess (drop a column, filter bad rows,
     one-hot a categorical, add a rolling mean) compiled into one record
     function;
  2. a NormalizerStandardize FITTED over the training stream (one pass,
     streaming statistics) — not per-batch statistics;
  3. an InputPipeline: parallel off-thread transform + vectorized batch
     assembly, deterministic batch order (byte-identical to direct
     iteration — asserted below), double-buffered device staging, and
     the pipeline_stats stall ledger;
  4. the fitted statistics ride the ModelSerializer zip, so a reloaded
     model + normalizer predicts identically to the live one.

Run from the repo root:  python examples/etl_pipeline.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.datasets.records import (  # noqa: E402
    CSVRecordReader,
    RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.etl import (  # noqa: E402
    InputPipeline,
    NormalizerStandardize,
    Schema,
    TransformProcess,
)
from deeplearning4j_tpu.etl.transforms import (  # noqa: E402
    TransformProcessRecordReader,
)
from deeplearning4j_tpu.nn.conf import (  # noqa: E402
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.utils.serialization import (  # noqa: E402
    ModelSerializer,
    read_normalizer,
)
from deeplearning4j_tpu.ops import env as envknob

# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")

ROWS = 400 if SMOKE else 4000
BATCH = 32
EPOCHS = 1 if SMOKE else 3
WORKERS = 2
CLASSES = 3
SPECIES = ["setosa", "versicolor", "virginica"]


def write_csv(path: str) -> None:
    """Synthetic iris-shaped CSV: 4 numeric columns, a throwaway id, a
    categorical species column, a label — plus a few deliberately broken
    rows the filter step must drop."""
    rng = np.random.default_rng(42)
    with open(path, "w") as f:
        f.write("id,f0,f1,f2,f3,species,label\n")
        for i in range(ROWS):
            label = int(rng.integers(0, CLASSES))
            feats = rng.standard_normal(4) + label
            if i % 97 == 0:  # corrupt row -> filtered by the transform
                f.write(f"{i},oops,,x,y,{SPECIES[label]},{label}\n")
                continue
            f.write(f"{i}," + ",".join(f"{v:.6f}" for v in feats)
                    + f",{SPECIES[label]},{label}\n")


def build_transform() -> TransformProcess:
    schema = (Schema.builder()
              .add_integer_column("id")
              .add_numeric_column("f0", "f1", "f2", "f3")
              .add_categorical_column("species", SPECIES)
              .add_integer_column("label")
              .build())
    return (TransformProcess(schema)
            .remove_columns("id")
            .filter_invalid(["f0", "f1", "f2", "f3"])   # drop corrupt rows
            .one_hot("species")                          # 3 extra columns
            .rolling_window("f0", 4, "mean"))            # time-window feat


def build_net(n_in: int) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
            .updater("adam").list()
            .layer(0, DenseLayer(n_in=n_in, n_out=16, activation="tanh"))
            .layer(1, OutputLayer(n_in=16, n_out=CLASSES,
                                  activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf)


def main() -> None:
    work = tempfile.mkdtemp(prefix="etl_example_")
    csv = os.path.join(work, "iris_like.csv")
    write_csv(csv)

    tp = build_transform()
    final = tp.final_schema()
    label_idx = final.index_of("label")
    n_features = final.num_columns() - 1
    print(f"transformed schema: {final.names()}")

    reader = lambda: CSVRecordReader(csv, skip_lines=1)

    # fitted statistics: ONE streaming pass over the transformed stream
    norm = NormalizerStandardize().fit(RecordReaderDataSetIterator(
        TransformProcessRecordReader(reader(), tp), BATCH,
        label_index=label_idx, num_possible_labels=CLASSES))

    pipeline = InputPipeline.from_reader(
        reader(), BATCH, label_index=label_idx,
        num_possible_labels=CLASSES, transform=tp, normalizer=norm,
        workers=WORKERS, prefetch=4)

    # pipeline == serial contract (the test suite proves it at byte
    # level; the example spot-checks the first batch)
    direct = RecordReaderDataSetIterator(
        TransformProcessRecordReader(reader(), tp), BATCH,
        label_index=label_idx, num_possible_labels=CLASSES)
    first_direct = next(iter(direct))
    norm.transform(first_direct)
    first_piped = next(iter(pipeline))
    assert (np.asarray(first_piped.features).tobytes()
            == np.asarray(first_direct.features).tobytes()), \
        "pipeline diverged from direct iteration"
    print("pipeline == direct iteration: byte-identical first batch")

    net = build_net(n_features)
    net.fit_iterator(pipeline, num_epochs=EPOCHS)
    stats = net.pipeline_stats.snapshot()
    print(f"trained {EPOCHS} epoch(s): loss {net.score_value:.4f}")
    print(f"pipeline_stats: {stats['batches']} batches, "
          f"{stats['records_per_sec']:.0f} records/s, "
          f"stall {stats['stall_fraction']:.0%} of wall, "
          f"producer stall {stats['producer_stall_seconds']:.3f}s")

    # the statistics ride the checkpoint: reloaded model + normalizer
    # predict identically to the live pair
    zip_path = os.path.join(work, "model.zip")
    ModelSerializer.write_model(net, zip_path, normalizer=norm)
    net2 = ModelSerializer.restore(zip_path)
    norm2 = read_normalizer(zip_path)
    probe = np.asarray(first_direct.features)  # already normalized
    live = np.asarray(net.output(probe))
    loaded = np.asarray(net2.output(probe))
    assert live.tobytes() == loaded.tobytes()
    raw = norm.revert_array(probe)
    assert (norm2.transform_array(raw).tobytes()
            == norm.transform_array(raw).tobytes())
    print(f"normalizer rides the zip: reloaded predictions identical "
          f"({type(norm2).__name__})")


if __name__ == "__main__":
    main()
