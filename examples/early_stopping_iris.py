"""Early stopping on Iris — the reference's EarlyStoppingMNIST pattern:
score calculator + epoch/iteration terminations + best-model saver."""


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# force CPU BEFORE first backend use: the axon TPU plugin hangs
# forever initializing a dead remote tunnel (CLAUDE.md); demos run
# in seconds on CPU and scale to TPU unchanged via this one line
jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.datasets.fetchers import IrisDataSetIterator
from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.savers import InMemoryModelSaver
from deeplearning4j_tpu.earlystopping.scorecalc import DataSetLossCalculator
from deeplearning4j_tpu.earlystopping.terminations import (
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops import env as envknob


# tiny-shape mode for the `-m examples` smoke tier (tests/test_examples.py)
SMOKE = envknob.nonempty("DL4J_TPU_EXAMPLE_SMOKE")


def main():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(42)
        .learning_rate(0.1)
        .updater("adam")
        .weight_init("xavier")
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=16, activation="relu"))
        .layer(1, OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss_function="negativeloglikelihood"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(input_shape=(1, 4))

    train_iter = IrisDataSetIterator(batch=32, num_examples=120)
    val_iter = IrisDataSetIterator(batch=30, num_examples=150)

    es_conf = (
        EarlyStoppingConfiguration.builder()
        .score_calculator(DataSetLossCalculator(val_iter))
        .epoch_termination_conditions(
            MaxEpochsTerminationCondition(6 if SMOKE else 50),
            ScoreImprovementEpochTerminationCondition(2 if SMOKE else 8),
        )
        .iteration_termination_conditions(
            InvalidScoreIterationTerminationCondition())
        .model_saver(InMemoryModelSaver())
        .build()
    )
    result = EarlyStoppingTrainer(es_conf, net, train_iter).fit()
    print(f"terminated: {result.termination_reason} "
          f"({result.termination_details})")
    print(f"best epoch {result.best_model_epoch}, "
          f"best score {result.best_model_score:.4f}, "
          f"epochs run {result.total_epochs}")


if __name__ == "__main__":
    main()
