// Native host-runtime library for deeplearning4j_tpu.
//
// Role: the host-side IO/runtime layer that the reference implements
// natively (SURVEY.md L0: nd4j-native C++ backend; L5 ingest:
// Canova/DataVec record readers feeding AsyncDataSetIterator,
// deeplearning4j-core/.../datasets/iterator/AsyncDataSetIterator.java:30).
// Device compute stays in XLA; this library removes the Python overhead on
// the feed path: idx (MNIST) parsing, bulk CSV parsing, deterministic
// shuffling, and a threaded prefetching CSV batch loader (the
// AsyncDataSetIterator ring buffer, in native code, off the GIL).
//
// Pure C ABI so Python binds via ctypes (no pybind11 in this image).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <chrono>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// idx (MNIST) file parsing — big-endian magic + dims + raw bytes
// ---------------------------------------------------------------------------

// Reads an idx file. On success returns 0 and fills:
//   *out_ndim, dims[0..ndim), and *out_data (malloc'd float32 buffer,
//   caller frees via dl4j_free). Pixel bytes are scaled to [0,1] when
//   normalize != 0.
int dl4j_read_idx(const char* path, int normalize, int* out_ndim,
                  int64_t* dims /* size >= 4 */, float** out_data) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  unsigned char magic[4];
  if (fread(magic, 1, 4, f) != 4) { fclose(f); return -2; }
  int dtype = magic[2];
  int ndim = magic[3];
  if (ndim <= 0 || ndim > 4 || (dtype != 0x08 && dtype != 0x0D)) {
    fclose(f);
    return -3;
  }
  const int64_t kMaxElements = (int64_t)1 << 31;  // 2G elements cap
  int64_t total = 1;
  for (int i = 0; i < ndim; i++) {
    unsigned char b[4];
    if (fread(b, 1, 4, f) != 4) { fclose(f); return -2; }
    dims[i] = ((int64_t)b[0] << 24) | (b[1] << 16) | (b[2] << 8) | b[3];
    if (dims[i] <= 0 || dims[i] > kMaxElements / total) {  // overflow guard
      fclose(f);
      return -3;
    }
    total *= dims[i];
  }
  float* out = (float*)malloc(sizeof(float) * (size_t)total);
  if (!out) { fclose(f); return -4; }
  if (dtype == 0x08) {  // unsigned byte
    std::vector<unsigned char> buf((size_t)total);
    if (fread(buf.data(), 1, (size_t)total, f) != (size_t)total) {
      free(out); fclose(f); return -2;
    }
    const float scale = normalize ? (1.0f / 255.0f) : 1.0f;
    for (int64_t i = 0; i < total; i++) out[i] = buf[(size_t)i] * scale;
  } else {  // 0x0D float32 big-endian
    std::vector<unsigned char> buf((size_t)total * 4);
    if (fread(buf.data(), 1, buf.size(), f) != buf.size()) {
      free(out); fclose(f); return -2;
    }
    for (int64_t i = 0; i < total; i++) {
      unsigned char* p = &buf[(size_t)i * 4];
      uint32_t v = ((uint32_t)p[0] << 24) | (p[1] << 16) | (p[2] << 8) | p[3];
      memcpy(&out[i], &v, 4);
    }
  }
  fclose(f);
  *out_ndim = ndim;
  *out_data = out;
  return 0;
}

void dl4j_free(void* p) { free(p); }

// ---------------------------------------------------------------------------
// Bulk CSV parsing (numeric, single delimiter) — the DataVec
// CSVRecordReader hot path without per-cell Python objects.
// ---------------------------------------------------------------------------

// Fast fixed-notation float parse ([-]ddd[.ddd...]); defers to strtof for
// exponents / inf / nan / overlong digit runs. strtof is locale-aware and
// slow; CSV feeds are overwhelmingly plain fixed notation.
static inline float fast_strtof(char* p, char** end) {
  char* start = p;
  bool neg = false;
  if (*p == '-') { neg = true; p++; }
  else if (*p == '+') { p++; }
  uint64_t mant = 0;
  int digits = 0, frac_digits = 0;
  while (*p >= '0' && *p <= '9') {
    mant = mant * 10 + (uint64_t)(*p - '0');
    digits++;
    p++;
  }
  if (*p == '.') {
    p++;
    while (*p >= '0' && *p <= '9') {
      mant = mant * 10 + (uint64_t)(*p - '0');
      digits++;
      frac_digits++;
      p++;
    }
  }
  if (digits == 0 || digits > 17 || *p == 'e' || *p == 'E' || *p == 'n' ||
      *p == 'N' || *p == 'i' || *p == 'I') {
    return strtof(start, end);  // exotic form — exact library parse
  }
  static const double kPow10[18] = {
      1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11, 1e12,
      1e13, 1e14, 1e15, 1e16, 1e17};
  double v = (double)mant / kPow10[frac_digits];
  *end = p;
  return (float)(neg ? -v : v);
}

static int read_whole_file(const char* path, std::vector<char>* buf) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  if (size < 0) { fclose(f); return -1; }  // non-seekable (FIFO etc.)
  fseek(f, 0, SEEK_SET);
  buf->resize((size_t)size + 1);
  if (size > 0 && fread(buf->data(), 1, (size_t)size, f) != (size_t)size) {
    fclose(f);
    return -2;
  }
  fclose(f);
  (*buf)[(size_t)size] = '\0';
  return 0;
}

// Single read + in-memory scan: rows/cols from the buffer, then parse into
// a malloc'd rows*cols float32 buffer (*out_data, caller frees).
int dl4j_csv_read(const char* path, char delim, int64_t* out_rows,
                  int64_t* out_cols, float** out_data) {
  std::vector<char> buf;
  int rc = read_whole_file(path, &buf);
  if (rc != 0) return rc;
  // shape scan over memory
  int64_t rows = 0, cols = 0, cur_cols = 1;
  int line_has_data = 0;
  for (const char* p = buf.data(); *p; p++) {
    char c = *p;
    if (c == '\n') {
      if (line_has_data) {
        if (cols == 0) cols = cur_cols;
        else if (cur_cols != cols) return -5;  // ragged
        rows++;
      }
      cur_cols = 1;
      line_has_data = 0;
    } else if (c == delim) {
      cur_cols++;
      line_has_data = 1;
    } else if (c != '\r' && c != ' ' && c != '\t') {
      line_has_data = 1;
    }
  }
  if (line_has_data) {  // last line without trailing newline
    if (cols == 0) cols = cur_cols;
    else if (cur_cols != cols) return -5;
    rows++;
  }
  *out_rows = rows;
  *out_cols = cols;
  if (rows == 0) { *out_data = nullptr; return 0; }
  const int64_t total = rows * cols;
  float* out = (float*)malloc(sizeof(float) * (size_t)total);
  if (!out) return -4;
  char* p = buf.data();
  int64_t i = 0;
  while (*p && i < total) {
    while (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n' || *p == delim)
      p++;
    if (!*p) break;
    char* end = nullptr;
    out[i++] = fast_strtof(p, &end);
    if (end == p) { free(out); return -6; }  // not a number
    p = end;
  }
  if (i != total) { free(out); return -7; }
  *out_data = out;
  return 0;
}

// ---------------------------------------------------------------------------
// Deterministic shuffle — Fisher-Yates with splitmix64 (stable across
// platforms; the reference shuffles partitions with a seeded java Random).
// ---------------------------------------------------------------------------

static inline uint64_t splitmix64(uint64_t* state) {
  uint64_t z = (*state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

void dl4j_shuffle_indices(int64_t n, uint64_t seed, int64_t* out) {
  for (int64_t i = 0; i < n; i++) out[i] = i;
  uint64_t st = seed;
  for (int64_t i = n - 1; i > 0; i--) {
    int64_t j = (int64_t)(splitmix64(&st) % (uint64_t)(i + 1));
    int64_t t = out[i];
    out[i] = out[j];
    out[j] = t;
  }
}

// Gather rows: out[i, :] = src[idx[i], :] — batch assembly after shuffle.
void dl4j_gather_rows(const float* src, const int64_t* idx, int64_t n_idx,
                      int64_t row_len, float* out) {
  for (int64_t i = 0; i < n_idx; i++) {
    memcpy(out + i * row_len, src + idx[i] * row_len,
           sizeof(float) * (size_t)row_len);
  }
}

// ---------------------------------------------------------------------------
// Threaded prefetch ring buffer (AsyncDataSetIterator.java:30 equivalent).
// The producer thread assembles shuffled minibatches from an in-memory
// float table; the consumer (Python) pops fully-formed batches.
// ---------------------------------------------------------------------------

struct Prefetcher {
  const float* features;   // [n, f_len] borrowed
  const float* labels;     // [n, l_len] borrowed
  int64_t n, f_len, l_len, batch;
  uint64_t seed;
  int epochs;
  size_t capacity;

  std::deque<std::vector<float>> queue;  // alternating feat/label blocks
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::thread worker;
  std::atomic<bool> done{false};
  std::atomic<bool> stop{false};
  std::atomic<int> consumers{0};  // threads inside dl4j_prefetch_next

  void run() {
    std::vector<int64_t> idx((size_t)n);
    uint64_t st = seed;
    for (int e = 0; e < epochs && !stop; e++) {
      dl4j_shuffle_indices(n, splitmix64(&st), idx.data());
      for (int64_t b = 0; b + batch <= n && !stop; b += batch) {
        std::vector<float> fb((size_t)(batch * f_len));
        std::vector<float> lb((size_t)(batch * l_len));
        dl4j_gather_rows(features, idx.data() + b, batch, f_len, fb.data());
        dl4j_gather_rows(labels, idx.data() + b, batch, l_len, lb.data());
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] { return queue.size() < capacity * 2 || stop; });
        if (stop) return;
        queue.emplace_back(std::move(fb));
        queue.emplace_back(std::move(lb));
        cv_get.notify_one();
      }
    }
    {
      // lock before flipping done: otherwise a consumer that just evaluated
      // the wait predicate misses this notify and sleeps forever
      std::lock_guard<std::mutex> lk(mu);
      done = true;
    }
    cv_get.notify_all();
  }
};

void* dl4j_prefetch_start(const float* features, const float* labels,
                          int64_t n, int64_t f_len, int64_t l_len,
                          int64_t batch, int epochs, uint64_t seed,
                          int capacity) {
  if (batch <= 0 || n < batch) return nullptr;
  Prefetcher* p = new Prefetcher();
  p->features = features;
  p->labels = labels;
  p->n = n;
  p->f_len = f_len;
  p->l_len = l_len;
  p->batch = batch;
  p->seed = seed;
  p->epochs = epochs;
  p->capacity = (size_t)(capacity > 0 ? capacity : 2);
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// Pops one batch into caller buffers. Returns 1 on success, 0 when the
// stream is exhausted.
int dl4j_prefetch_next(void* handle, float* feat_out, float* label_out) {
  Prefetcher* p = (Prefetcher*)handle;
  p->consumers.fetch_add(1);
  int ret = 0;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_get.wait(lk, [&] { return p->queue.size() >= 2 || p->done; });
    if (p->queue.size() >= 2) {
      std::vector<float> fb = std::move(p->queue.front());
      p->queue.pop_front();
      std::vector<float> lb = std::move(p->queue.front());
      p->queue.pop_front();
      lk.unlock();
      p->cv_put.notify_one();
      memcpy(feat_out, fb.data(), fb.size() * sizeof(float));
      memcpy(label_out, lb.data(), lb.size() * sizeof(float));
      ret = 1;
    }
  }
  p->consumers.fetch_sub(1);
  return ret;
}

// ---------------------------------------------------------------------------
// npz (numpy zip) reader + ordered background prefetcher — the native feed
// path for the exported-dataset plane (training_master.export_datasets
// writes one STORED-entry npz per minibatch, the reference's
// RDDTrainingApproach.Export split files; fit(path) then streams them:
// ParameterAveragingTrainingMaster.java:148-168, SparkDl4jMultiLayer:217).
// Parsing + file IO happen on a worker thread, off the GIL.
// Scope: stored (uncompressed) entries, little-endian f4/f8/i4/i8/b1,
// C-order, no ZIP64 — anything else returns null and Python falls back to
// np.load.
// ---------------------------------------------------------------------------

struct NpzMember {
  std::string name;       // member name without the ".npy" suffix
  int dtype;              // 0=f4 1=f8 2=i4 3=i8 4=b1
  int ndim;
  int64_t dims[8];
  int64_t count;          // product of dims
  size_t esize;
  void* data;             // malloc'd, owned by NpzFile
};

struct NpzFile {
  std::vector<NpzMember> members;
  ~NpzFile() {
    for (auto& m : members) free(m.data);
  }
};

static uint32_t rd_u32(const unsigned char* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}
static uint16_t rd_u16(const unsigned char* p) {
  return (uint16_t)p[0] | ((uint16_t)p[1] << 8);
}

// Parses one stored .npy payload (buf/len) into m (fills dtype/dims/data).
// Returns false on any unsupported feature.
static bool parse_npy(const unsigned char* buf, size_t len, NpzMember* m) {
  if (len < 10 || memcmp(buf, "\x93NUMPY", 6) != 0) return false;
  int major = buf[6];
  size_t hlen, hoff;
  if (major == 1) {
    hlen = rd_u16(buf + 8);
    hoff = 10;
  } else if (major == 2 || major == 3) {
    if (len < 12) return false;
    hlen = rd_u32(buf + 8);
    hoff = 12;
  } else {
    return false;
  }
  if (hoff + hlen > len) return false;
  std::string h((const char*)buf + hoff, hlen);
  // descr
  size_t dp = h.find("'descr'");
  if (dp == std::string::npos) return false;
  size_t q1 = h.find('\'', dp + 7);
  size_t q2 = (q1 == std::string::npos) ? q1 : h.find('\'', q1 + 1);
  if (q2 == std::string::npos) return false;
  std::string descr = h.substr(q1 + 1, q2 - q1 - 1);
  static const struct { const char* d; int code; size_t es; } kTypes[] = {
      {"<f4", 0, 4}, {"<f8", 1, 8}, {"<i4", 2, 4}, {"<i8", 3, 8},
      {"|b1", 4, 1},
  };
  m->dtype = -1;
  for (auto& t : kTypes) {
    if (descr == t.d) { m->dtype = t.code; m->esize = t.es; }
  }
  if (m->dtype < 0) return false;
  // fortran_order must be False (C-order)
  size_t fo = h.find("'fortran_order'");
  if (fo == std::string::npos || h.find("False", fo) == std::string::npos ||
      h.find("False", fo) > fo + 24) {
    return false;
  }
  // shape tuple
  size_t sp = h.find("'shape'");
  if (sp == std::string::npos) return false;
  size_t p1 = h.find('(', sp);
  size_t p2 = (p1 == std::string::npos) ? p1 : h.find(')', p1);
  if (p2 == std::string::npos) return false;
  m->ndim = 0;
  m->count = 1;
  // overflow guards: a crafted shape must DECLINE, not wrap int64 (UB)
  // and sneak a tiny `need` past the bounds check below
  const int64_t kMaxCount = (int64_t)1 << 40;  // far above any minibatch
  size_t pos = p1 + 1;
  while (pos < p2) {
    while (pos < p2 && (h[pos] == ' ' || h[pos] == ',')) pos++;
    if (pos >= p2) break;
    if (m->ndim >= 8) return false;
    int64_t v = 0;
    bool any = false;
    while (pos < p2 && h[pos] >= '0' && h[pos] <= '9') {
      if (v > kMaxCount) return false;  // before the *10 can overflow
      v = v * 10 + (h[pos] - '0');
      pos++;
      any = true;
    }
    if (!any || v > kMaxCount) return false;
    m->dims[m->ndim++] = v;
    if (v != 0 && m->count > kMaxCount / (v ? v : 1)) return false;
    m->count *= v;
  }
  // scalar () => ndim 0, count 1; the payload must actually contain the
  // claimed elements (count bounded above, so this product can't wrap)
  if (m->count > (int64_t)(len / m->esize) + 1) return false;
  size_t need = (size_t)m->count * m->esize;
  if (hoff + hlen + need > len) return false;
  m->data = malloc(need ? need : 1);
  if (!m->data) return false;
  memcpy(m->data, buf + hoff + hlen, need);
  return true;
}

static void* npz_open_impl(const char* path);

// Exception wall: a corrupt file (garbage sizes -> bad_alloc, etc.) must
// DECLINE (null -> Python np.load fallback), never unwind across the C
// ABI into ctypes or terminate the prefetch worker.
void* dl4j_npz_open(const char* path) {
  try {
    return npz_open_impl(path);
  } catch (...) {
    return nullptr;
  }
}

static void* npz_open_impl(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return nullptr; }
  long fsize = ftell(f);
  if (fsize < 22) { fclose(f); return nullptr; }
  // find EOCD (sig 0x06054b50) in the last 64K+22
  long tail = fsize < 65558 ? fsize : 65558;
  std::vector<unsigned char> tb((size_t)tail);
  fseek(f, fsize - tail, SEEK_SET);
  if (fread(tb.data(), 1, (size_t)tail, f) != (size_t)tail) {
    fclose(f);
    return nullptr;
  }
  long eocd = -1;
  for (long i = tail - 22; i >= 0; i--) {
    if (tb[i] == 0x50 && tb[i + 1] == 0x4b && tb[i + 2] == 0x05 &&
        tb[i + 3] == 0x06) {
      eocd = i;
      break;
    }
  }
  if (eocd < 0) { fclose(f); return nullptr; }
  uint16_t n_entries = rd_u16(&tb[eocd + 10]);
  uint32_t cd_off = rd_u32(&tb[eocd + 16]);
  if (n_entries == 0xFFFF || cd_off == 0xFFFFFFFFu) {  // ZIP64
    fclose(f);
    return nullptr;
  }
  NpzFile* nf = new NpzFile();
  long pos = (long)cd_off;
  for (int e = 0; e < n_entries; e++) {
    unsigned char ch[46];
    fseek(f, pos, SEEK_SET);
    if (fread(ch, 1, 46, f) != 46 || rd_u32(ch) != 0x02014b50) goto fail;
    {
      uint16_t method = rd_u16(ch + 10);
      uint32_t csize = rd_u32(ch + 20);
      uint32_t usize = rd_u32(ch + 24);
      uint16_t nlen = rd_u16(ch + 28);
      uint16_t xlen = rd_u16(ch + 30);
      uint16_t clen = rd_u16(ch + 32);
      uint32_t lho = rd_u32(ch + 42);
      if (method != 0 || csize != usize) goto fail;  // stored only
      std::string name((size_t)nlen, '\0');
      if (fread(&name[0], 1, nlen, f) != nlen) goto fail;
      // data offset: local header's own name/extra lens (can differ)
      unsigned char lh[30];
      fseek(f, (long)lho, SEEK_SET);
      if (fread(lh, 1, 30, f) != 30 || rd_u32(lh) != 0x04034b50) goto fail;
      long doff = (long)lho + 30 + rd_u16(lh + 26) + rd_u16(lh + 28);
      std::vector<unsigned char> payload((size_t)usize);
      fseek(f, doff, SEEK_SET);
      if (usize && fread(payload.data(), 1, usize, f) != usize) goto fail;
      NpzMember m;
      m.data = nullptr;
      if (name.size() > 4 && name.compare(name.size() - 4, 4, ".npy") == 0) {
        name.resize(name.size() - 4);
      }
      m.name = name;
      if (!parse_npy(payload.data(), payload.size(), &m)) goto fail;
      nf->members.push_back(std::move(m));
      pos += 46 + nlen + xlen + clen;
    }
  }
  fclose(f);
  return nf;
fail:
  fclose(f);
  delete nf;
  return nullptr;
}

int dl4j_npz_count(void* h) {
  return h ? (int)((NpzFile*)h)->members.size() : -1;
}

int dl4j_npz_member_info(void* h, int i, char* name_buf, int name_cap,
                         int* dtype, int* ndim, int64_t* dims) {
  NpzFile* nf = (NpzFile*)h;
  if (!nf || i < 0 || (size_t)i >= nf->members.size()) return -1;
  const NpzMember& m = nf->members[(size_t)i];
  if ((int)m.name.size() + 1 > name_cap) return -2;
  memcpy(name_buf, m.name.c_str(), m.name.size() + 1);
  *dtype = m.dtype;
  *ndim = m.ndim;
  for (int d = 0; d < m.ndim; d++) dims[d] = m.dims[d];
  return 0;
}

int dl4j_npz_member_data(void* h, int i, void* out) {
  NpzFile* nf = (NpzFile*)h;
  if (!nf || i < 0 || (size_t)i >= nf->members.size()) return -1;
  const NpzMember& m = nf->members[(size_t)i];
  memcpy(out, m.data, (size_t)m.count * m.esize);
  return 0;
}

void dl4j_npz_close(void* h) { delete (NpzFile*)h; }

// Ordered background prefetcher over a list of npz paths: the worker
// parses files ahead (bounded queue); the consumer pops them IN ORDER.
// A file that fails to parse yields a null handle (consumer falls back).
struct NpzPrefetcher {
  std::vector<std::string> paths;
  size_t capacity;
  std::deque<NpzFile*> queue;   // parallel to next_idx ordering
  size_t consumed = 0;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::thread worker;
  std::atomic<bool> stop{false};

  void run() {
    for (size_t i = 0; i < paths.size() && !stop; i++) {
      NpzFile* nf = (NpzFile*)dl4j_npz_open(paths[i].c_str());
      std::unique_lock<std::mutex> lk(mu);
      cv_put.wait(lk, [&] { return queue.size() < capacity || stop; });
      if (stop) { delete nf; return; }
      queue.push_back(nf);
      cv_get.notify_one();
    }
  }
};

void* dl4j_npz_prefetch_open(const char* const* paths, int n_paths,
                             int capacity) {
  if (n_paths <= 0) return nullptr;
  NpzPrefetcher* p = new NpzPrefetcher();
  for (int i = 0; i < n_paths; i++) p->paths.emplace_back(paths[i]);
  p->capacity = (size_t)(capacity > 0 ? capacity : 4);
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// Returns the file index whose handle is placed in *out (may be null on
// parse failure — caller falls back for that file), or -1 when the
// stream is exhausted. The handle is owned by the caller: free it with
// dl4j_npz_close.
int dl4j_npz_prefetch_next(void* h, void** out) {
  NpzPrefetcher* p = (NpzPrefetcher*)h;
  std::unique_lock<std::mutex> lk(p->mu);
  if (p->consumed >= p->paths.size()) return -1;
  p->cv_get.wait(lk, [&] { return !p->queue.empty() || p->stop; });
  if (p->queue.empty()) return -1;  // stopped mid-stream
  *out = p->queue.front();
  p->queue.pop_front();
  int idx = (int)p->consumed++;
  lk.unlock();
  p->cv_put.notify_one();
  return idx;
}

void dl4j_npz_prefetch_close(void* h) {
  NpzPrefetcher* p = (NpzPrefetcher*)h;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
    p->cv_put.notify_all();
    p->cv_get.notify_all();
  }
  if (p->worker.joinable()) p->worker.join();
  for (NpzFile* nf : p->queue) delete nf;
  delete p;
}

void dl4j_prefetch_stop(void* handle) {
  Prefetcher* p = (Prefetcher*)handle;
  {
    // done must flip too: a consumer blocked in dl4j_prefetch_next would
    // otherwise re-sleep after the notify and later touch a freed mutex
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
    p->done = true;
    p->cv_put.notify_all();
    p->cv_get.notify_all();
  }
  if (p->worker.joinable()) p->worker.join();
  // drain concurrent consumers: done is set, so any thread inside
  // dl4j_prefetch_next wakes and exits promptly; deleting while one is
  // still unwinding off the condvar would destroy a mutex in use
  while (p->consumers.load() != 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  delete p;
}

}  // extern "C"
