#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extras": {...}}

Covers all five BASELINE.json configs plus the north-star equivalence bar:
  configs[0] LeNet-5 MNIST      -> lenet5 samples/sec/chip; the headline is
                                   the fused training loop (fit_batches — K
                                   steps per lax.scan), the framework's
                                   sustained fit(DataSetIterator) speed;
                                   the per-step number is reported alongside
  configs[1] MLP+LSTM char-RNN  -> char_rnn train samples/sec + tokens/sec
                                   + rnn_time_step generation chars/sec
  configs[2] ResNet-50          -> samples/sec/chip + MFU (XLA-counted step
                                   FLOPs / peak chip FLOPs)
  configs[3] Word2Vec SGNS      -> skip-gram pairs/sec
  configs[4] 1→8 scaling        -> measured on the virtual 8-device CPU mesh
                                   (this host exposes ONE real TPU chip and
                                   ONE cpu core, so the honest number is the
                                   equal-work DP overhead ratio; raw 1→8
                                   speedup on a 1-core host is meaningless
                                   and labeled as such)
  north_star                    -> 100-step CPU-vs-TPU float32-strict loss
                                   curve deviation (written to
                                   NORTHSTAR_r.json artifact)

vs_baseline: measured against a faithful torch-CPU LeNet-5 reimplementation
of the reference's nd4j-native CPU training path (the reference itself is
2016 Java/ND4J and cannot run here; torch-cpu is a GENEROUS stand-in — BLAS
conv + hand-tuned kernels, no per-op JVM dispatch — so the ratio understates
our advantage over real dl4j). Reference comparison path:
MultiLayerNetwork.fit :1017 (see BASELINE.md).

Data provenance is reported per dataset ("local"/"downloaded"/"synthetic");
this host is zero-egress so MNIST falls back to the deterministic synthetic
stand-in unless idx files are provided via DL4J_TPU_DATA_DIR.

Timing policy: batches are device-resident (training throughput, not the
host->device tunnel) and every timed region ends with a one-element host
readback that has a true data dependency on the final step —
jax.block_until_ready is NOT a reliable completion fence through the axon
remote-TPU tunnel (measured ~5x inflation in round 1).
"""

import json
import os
import subprocess
import sys
import time
from typing import Optional

import numpy as np

from deeplearning4j_tpu.ops import env as envknob

os.environ.setdefault("DL4J_TPU_OFFLINE", "")  # downloads attempted once


def _log(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: repeat bench runs (the driver runs
    bench every round) skip the slow first-compile through the TPU tunnel.
    Delegates to the shared wiring in ops/dispatch (the library's training
    stack enables the same cache lazily, so bench legs and ordinary fit()
    users share one on-disk cache; DL4J_TPU_COMPILE_CACHE=0 disables)."""
    from deeplearning4j_tpu.ops import dispatch

    # bench's historical default dir applies only when NEITHER knob is set
    # (DL4J_TPU_COMPILE_CACHE / JAX_COMPILATION_CACHE_DIR) — an explicit
    # knob must win, or in-process and subprocess legs would split into
    # two divergent caches
    cache_dir = None
    if not (envknob.raw(dispatch.ENV_CACHE, "").strip()
            or os.environ.get("JAX_COMPILATION_CACHE_DIR", "").strip()):
        cache_dir = "/root/.jax_compile_cache"
    if dispatch.enable_compile_cache(cache_dir) is None:
        _log("compile cache disabled/unavailable")


def _time_steps(fn, warmup: int, steps: int):
    """Time `steps` calls of fn. fn must RETURN a device value that depends
    on the whole step (e.g. the loss); completion is forced by reading it
    back to host. NOTE: jax.block_until_ready is NOT a reliable fence
    through the axon remote-TPU tunnel (measured: it returns before remote
    execution finishes, inflating throughput ~5x) — a host readback of a
    scalar with a true data dependency is the only sound sync, and its cost
    (one 4-byte RTT per timed region) is amortized over all steps."""
    out = None
    for _ in range(warmup):
        out = fn()
    _force(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn()
    _force(out)
    return time.perf_counter() - t0


def _force(x):
    """Host readback: materializes x and everything it depends on. The
    slice happens BEFORE np.asarray so only one element crosses the
    tunnel, not the whole array."""
    if x is None:
        return
    np.asarray(x.reshape(-1)[:1] if hasattr(x, "reshape") else x)


# ---------------------------------------------------------------------------
# configs[0]: LeNet-5 MNIST
# ---------------------------------------------------------------------------


def bench_lenet(batch=512, steps=30):
    import jax

    from deeplearning4j_tpu.datasets.fetchers import load_mnist_info
    from deeplearning4j_tpu.models.lenet import build_lenet5

    net = build_lenet5()
    x, y, prov = load_mnist_info(train=True, num_examples=batch * 4)
    # device-resident rotating batches: measures training throughput, not
    # the host->device tunnel (input pipelining is the AsyncDataSetIterator's
    # job and is benched by its own tests)
    xs = [jax.device_put(x[i * batch : (i + 1) * batch]) for i in range(4)]
    ys = [jax.device_put(y[i * batch : (i + 1) * batch]) for i in range(4)]
    i = [0]

    def step():
        loss = net.fit(xs[i[0] % 4], ys[i[0] % 4])
        i[0] += 1
        return loss

    dt = _time_steps(step, 3, steps)
    return {
        "samples_per_sec": round(batch * steps / dt, 1),
        "data": prov,
        "batch": batch,
        "sync": "loss readback",
    }


def bench_lenet_fused(batch=512, k=32, reps=3):
    """Sustained training throughput with the fused multi-step path
    (MultiLayerNetwork.fit_batches: K optimizer steps in ONE lax.scan) —
    the framework's answer to per-step dispatch latency; the reference's
    fit(DataSetIterator) loop compiled end-to-end."""
    import jax

    from deeplearning4j_tpu.datasets.fetchers import load_mnist_info
    from deeplearning4j_tpu.models.lenet import build_lenet5

    net = build_lenet5()
    x, y, prov = load_mnist_info(train=True, num_examples=batch * 4)
    xs = np.stack([x[(i % 4) * batch:((i % 4) + 1) * batch] for i in range(k)])
    ys = np.stack([y[(i % 4) * batch:((i % 4) + 1) * batch] for i in range(k)])
    xs, ys = jax.device_put(xs), jax.device_put(ys)

    losses = net.fit_batches(xs, ys)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        losses = net.fit_batches(xs, ys)  # ends in host readback of losses
    dt = time.perf_counter() - t0
    return {
        "samples_per_sec": round(batch * k * reps / dt, 1),
        "steps_fused": k, "batch": batch, "data": prov,
    }


def bench_torch_lenet_cpu(batch=512, steps=8):
    """Reference-CPU baseline: LeNet-5 (same topology as models/lenet.py /
    the dl4j LenetMnistExample) trained on torch-cpu. Stands in for the
    nd4j-native CPU path of MultiLayerNetwork.fit :1017."""
    import torch
    import torch.nn as nn

    torch.manual_seed(0)
    model = nn.Sequential(
        nn.Conv2d(1, 20, 5), nn.MaxPool2d(2),
        nn.Conv2d(20, 50, 5), nn.MaxPool2d(2),
        nn.Flatten(), nn.Linear(50 * 4 * 4, 500), nn.ReLU(),
        nn.Linear(500, 10),
    )
    opt = torch.optim.SGD(model.parameters(), lr=0.01, momentum=0.9)
    lossf = nn.CrossEntropyLoss()
    x = torch.randn(batch, 1, 28, 28)
    y = torch.randint(0, 10, (batch,))

    def step():
        opt.zero_grad()
        loss = lossf(model(x), y)
        loss.backward()
        opt.step()
        return loss.detach().numpy()

    dt = _time_steps(step, 2, steps)
    return {"samples_per_sec": round(batch * steps / dt, 1), "batch": batch}


# ---------------------------------------------------------------------------
# configs[1]: char-RNN (LSTM) train + generation
# ---------------------------------------------------------------------------


def bench_char_rnn(batch=32, seq=100, vocab=80, lstm=200, steps=10):
    import jax

    from deeplearning4j_tpu.models.char_rnn import char_rnn_conf
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(
        char_rnn_conf(vocab, lstm_size=lstm, num_layers=2, tbptt_length=50)
    ).init(input_shape=(1, vocab))
    rng = np.random.default_rng(0)
    eye = np.eye(vocab, dtype=np.float32)
    ids = rng.integers(0, vocab, (batch, seq + 1))
    x = jax.device_put(eye[ids[:, :seq]])
    y = jax.device_put(eye[ids[:, 1:]])

    def step():
        return net.fit(x, y)  # 2 TBPTT windows of 50

    dt = _time_steps(step, 2, steps)
    train_samples = batch * steps / dt
    train_tokens = train_samples * seq

    # streaming generation throughput (reference rnnTimeStep :2152 hot path)
    net.rnn_clear_previous_state()
    x1 = jax.device_put(eye[0][None, None, :])
    gen_steps = 200
    out = None
    for _ in range(3):
        out = net.rnn_time_step(x1)
    _force(out)  # warmup (incl. compile) must finish before the timer starts
    t0 = time.perf_counter()
    for _ in range(gen_steps):
        out = net.rnn_time_step(x1)
    _force(out)
    gen_dt = time.perf_counter() - t0
    return {
        "train_samples_per_sec": round(train_samples, 1),
        "train_tokens_per_sec": round(train_tokens, 1),
        "generation_chars_per_sec": round(gen_steps / gen_dt, 1),
        "batch": batch, "seq": seq, "lstm": lstm,
    }


# ---------------------------------------------------------------------------
# configs[2]: ResNet-50 + MFU
# ---------------------------------------------------------------------------


def _peak_flops_per_chip() -> float:
    """bf16 peak for the local accelerator (MXU rate; f32 inputs hit the MXU
    through bf16 passes under jax's DEFAULT matmul precision)."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind:
        return 918e12
    return 197e12  # conservative default


def bench_resnet50(batch=128, steps=10, input_size=224,
                   dtype_policy="strict"):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.resnet import build_resnet50

    net = build_resnet50(input_size=input_size, num_classes=1000,
                         updater="nesterovs", learning_rate=0.05,
                         dtype_policy=dtype_policy)
    rng = np.random.default_rng(0)
    x = jax.device_put(
        rng.random((batch, input_size, input_size, 3)).astype(np.float32)
    )
    y = jax.device_put(
        np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)]
    )

    def step():
        return net.fit(x, y)

    dt = _time_steps(step, 2, steps)
    samples_per_sec = batch * steps / dt

    # XLA-counted FLOPs of the whole compiled train step (fwd+bwd+update)
    flops = None
    try:
        step_fn = net._get_train_step(1, False)
        inputs = net._as_inputs(jnp.asarray(x))
        labels = [jnp.asarray(y)]
        from deeplearning4j_tpu.ops import rng as rng_mod

        lowered = step_fn.lower(
            net.params, net.states, net.updater_state, inputs, labels,
            jnp.asarray(0, jnp.int32), rng_mod.step_key(net._rng, 0), {}, None,
        )
        cost = lowered.compile().cost_analysis()
        if cost:
            c = cost[0] if isinstance(cost, (list, tuple)) else cost
            flops = float(c.get("flops", 0.0)) or None
    except Exception as e:  # noqa: BLE001 — cost analysis is best-effort
        print(f"cost_analysis unavailable: {e}", file=sys.stderr)
    mfu = None
    if flops:
        # FLOPs per step / (seconds per step * peak FLOPs/sec)
        mfu = (flops / (dt / steps)) / _peak_flops_per_chip()
    return {
        "samples_per_sec": round(samples_per_sec, 2),
        "step_flops": flops,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "batch": batch, "input": input_size,
        "dtype_policy": dtype_policy,
    }


# ---------------------------------------------------------------------------
# beyond-reference flagship: transformer LM (tokens/sec + MFU + flash kernel)
# ---------------------------------------------------------------------------


def bench_mxu_calibration(steps=10):
    """Pure-matmul ceiling of THIS accelerator: nominal v5e bf16 peak is
    197 TFLOPS, but the tunneled chip delivers a fraction of that even on
    ideal 8192^3 matmuls (measured ~119 TFLOPS) with ~5ms per-dispatch
    overhead — the honest denominator context for the MFU numbers."""
    import jax
    import jax.numpy as jnp

    out = {}
    for n in (4096, 8192):
        a = jax.device_put(jnp.ones((n, n), jnp.bfloat16))
        b = jax.device_put(jnp.ones((n, n), jnp.bfloat16))
        f = jax.jit(lambda a, b: a @ b)
        o = f(a, b)
        _force(o)
        t0 = time.perf_counter()
        for _ in range(steps):
            o = f(o, b)
        _force(o)
        dt = time.perf_counter() - t0
        out[f"bf16_{n}cubed_tflops"] = round(2 * n**3 * steps / dt / 1e12, 1)
    out["nominal_peak_tflops"] = round(_peak_flops_per_chip() / 1e12, 1)
    return out


def _transformer_bench_cfg(seq, d_model, n_layers, heads, vocab=8192,
                           dtype_policy="performance", remat="auto"):
    """Single source of truth for the bench transformer's architecture —
    bench_transformer runs it, transformer_hbm_preflight sizes it; sharing
    the builder keeps the OOM guard modeling the exact network it guards."""
    from deeplearning4j_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_layers=n_layers, n_heads=heads,
        d_ff=4 * d_model, max_len=seq, dtype_policy=dtype_policy,
        learning_rate=1e-4, remat=remat,
    )


def bench_transformer(batch=16, seq=1024, d_model=2048, n_layers=4, heads=32,
                      steps=5, dtype_policy="performance", remat="auto"):
    """Decoder-only LM train throughput (models/transformer.py): the model
    family whose scale needs the parallelism stack. Runs the flash-attention
    pallas kernel when on TPU (ops/pallas_attention.py); MFU from
    XLA-counted step FLOPs."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import TransformerLM

    cfg = _transformer_bench_cfg(seq, d_model, n_layers, heads,
                                 dtype_policy=dtype_policy, remat=remat)
    lm = TransformerLM(cfg)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    x = jax.device_put(jnp.asarray(toks[:, :-1], jnp.int32))
    y = jax.device_put(jnp.asarray(toks[:, 1:], jnp.int32))

    dt = _time_steps(lambda: lm.fit(x, y), 2, steps)
    tokens_per_sec = batch * seq * steps / dt

    # fused multi-step (fit_batches: K steps per XLA program) — removes the
    # per-step dispatch round-trip through the tunnel
    xs = jnp.broadcast_to(x, (steps,) + x.shape)
    ys = jnp.broadcast_to(y, (steps,) + y.shape)
    losses = lm.fit_batches(xs, ys)  # compile + warm
    _force(losses)
    t0 = time.perf_counter()
    losses = lm.fit_batches(xs, ys)
    _force(losses)
    fused_tokens_per_sec = batch * seq * steps / (time.perf_counter() - t0)

    flops = None
    try:
        lowered = lm._step.lower(lm.params, lm.opt, x, y)
        cost = lowered.compile().cost_analysis()
        if cost:
            c = cost[0] if isinstance(cost, (list, tuple)) else cost
            flops = float(c.get("flops", 0.0)) or None
    except Exception as e:  # noqa: BLE001 — cost analysis is best-effort
        _log(f"transformer cost_analysis unavailable: {e}")
    mfu = None
    if flops:
        mfu = (flops / (dt / steps)) / _peak_flops_per_chip()
    from deeplearning4j_tpu.ops.pallas_attention import flash_fits, pallas_enabled

    # generation throughput: KV-cache decode (O(T) per token) vs the
    # full-forward sampler (O(T^2) per token) — the rnnTimeStep-style
    # streaming win for the flagship
    gen = {}
    prompt = x[:, :128]
    gen_reps = 3  # mean over repeats — one ~5ms dispatch hiccup must not
    # skew the committed speedup (matches the other legs' methodology)
    for uc, label in ((True, "kv"), (False, "full")):
        out = lm.generate(prompt, n_new=64, temperature=1.0, seed=0,
                          use_cache=uc)  # compile + warm
        _force(out)
        t0 = time.perf_counter()
        for rep in range(gen_reps):
            out = lm.generate(prompt, n_new=64, temperature=1.0,
                              seed=1 + rep, use_cache=uc)
            _force(out)
        gen[label] = batch * 64 * gen_reps / (time.perf_counter() - t0)

    return {
        "gen_tokens_per_sec_kv": round(gen["kv"], 1),
        "gen_tokens_per_sec_full": round(gen["full"], 1),
        "kv_cache_speedup": round(gen["kv"] / gen["full"], 2),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "tokens_per_sec_fused": round(fused_tokens_per_sec, 1),
        # the TPU-first story quantified: K steps per XLA program vs one
        # dispatch per step (~5ms tunnel overhead each — BENCH_NOTES.md)
        "fused_over_per_step": round(fused_tokens_per_sec / tokens_per_sec,
                                     2),
        "samples_per_sec": round(batch * steps / dt, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "step_flops": flops,
        "flash_kernel": bool(pallas_enabled()
                             and flash_fits(seq, d_model // heads)),
        "batch": batch, "seq": seq, "d_model": d_model, "layers": n_layers,
        "dtype_policy": dtype_policy,
        # resolved remat rung (ops/remat.py ladder) — measurement provenance
        "remat": _resolved_remat(remat),
    }


def _resolved_remat(remat) -> str:
    from deeplearning4j_tpu.ops.remat import remat_policy

    return remat_policy(remat)


def transformer_hbm_preflight(batch, seq, d_model, n_layers, heads,
                              vocab=8192, hbm_gb=16.0, remat="none",
                              accum_steps=1):
    """HBM preflight for one transformer training step — the guard that
    keeps the MFU-chase leg (transformer_lm_big) from dying with an OOM
    on first tunnel contact (an untested config must not waste the
    round's one capture window).

    The accounting guts now live in the AOT memory plane
    (ops/memory.transformer_preflight): params/optimizer/grads EXACT via
    jax.eval_shape on the real inits; activations a remat- and
    accum-aware analytic model of the bf16+flash regime (``remat`` picks
    the ladder rung — none/dots/block, ops/remat.py); measured
    memory_analysis numbers merged in when the config is small enough to
    AOT-compile on the CPU substrate. Returns (fits, report_dict)."""
    from deeplearning4j_tpu.ops.memory import transformer_preflight

    # the SAME config builder bench_transformer uses: the estimate must
    # model the exact network the leg will run, or the guard drifts
    cfg = _transformer_bench_cfg(seq, d_model, n_layers, heads, vocab,
                                 dtype_policy="performance", remat=remat)
    return transformer_preflight(cfg, batch, accum_steps=accum_steps,
                                 remat=remat, hbm_gb=hbm_gb)


def bench_transformer_big(steps=3, seq=1024, d_model=2048, n_layers=8,
                          heads=32):
    """The MFU-chase leg with the HBM preflight in front: the auto-fit
    sizer (ops/memory.auto_fit_transformer) picks the largest
    (batch, remat policy) pair whose estimate fits this chip's 16GB —
    largest batch first, weakest remat rung first (each rung down the
    ladder costs backward recompute), so the first on-chip run can't OOM
    on an untested shape (VERDICT r03 weak #8) and the b32 config that
    exceeded HBM un-rematted (BENCH_NOTES round-2 ceiling) is attempted
    WITH remat on the watcher's next contact."""
    from deeplearning4j_tpu.ops.memory import auto_fit_transformer

    hbm_gb = envknob.get_float("DL4J_TPU_HBM_GB", 16.0)
    cfg = _transformer_bench_cfg(seq, d_model, n_layers, heads,
                                 dtype_policy="performance")
    # accum pinned to 1 for the leg: the MFU number must stay a
    # one-dispatch-per-step measurement (accum changes the program shape)
    choice = auto_fit_transformer(cfg, batches=(32, 16, 8, 4),
                                  accum_steps=(1,), hbm_gb=hbm_gb)
    if choice is None:
        # keep the diagnostic: the per-component breakdown of the MOST
        # affordable candidate says WHY nothing fit (triage from the
        # artifact instead of re-running the preflight by hand)
        _, report = transformer_hbm_preflight(
            4, seq, d_model, n_layers, heads, hbm_gb=hbm_gb, remat="block")
        return {"error": "no (batch, remat) candidate fits HBM",
                "preflight": report}
    out = bench_transformer(batch=choice["batch"], seq=seq, d_model=d_model,
                            n_layers=n_layers, heads=heads, steps=steps,
                            remat=choice["remat"])
    out["preflight"] = choice["report"]
    return out


def bench_ring_attention(n=1, t=4096, h=8, d=64, steps=5, interpret=False):
    """Long-context ring attention: local block product through the pallas
    flash kernel (ops/pallas_attention.flash_attention_block) vs the einsum
    body, on a 1-device 'seq' mesh — the only ring THIS host can run (one
    chip); the multi-device collective schedule is validated on the virtual
    mesh (tests + dryrun), and what changes between the two paths is
    exactly the per-device local block compute timed here. The einsum body
    materializes the [N,H,T,T] score block; the kernel streams it through
    VMEM."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from deeplearning4j_tpu.parallel.sequence_parallel import (
        ring_attention_sharded,
    )

    rng = np.random.default_rng(0)
    q, k, v = (
        jax.device_put(jnp.asarray(
            rng.standard_normal((n, t, h, d)), jnp.bfloat16))
        for _ in range(3)
    )
    mesh = Mesh(np.array(jax.devices()[:1]), ("seq",))
    out = {"shape": f"n{n} t{t} h{h} d{d}",
           "note": ("1-device ring (one real chip on this host): times the "
                    "local block product the kernel replaces; collective "
                    "schedule equivalence is proven on the virtual mesh")}
    for name, uf in (("einsum", False), ("flash", True)):
        fn = jax.jit(lambda q, k, v, uf=uf: ring_attention_sharded(
            q, k, v, mesh, causal=True, use_flash=uf,
            interpret=interpret))
        o = fn(q, k, v)
        _force(o)
        t0 = time.perf_counter()
        for _ in range(steps):
            o = fn(q, k, v)
        _force(o)
        out[f"ring_{name}_ms"] = round(
            (time.perf_counter() - t0) / steps * 1000, 3)
    out["flash_speedup"] = round(
        out["ring_einsum_ms"] / out["ring_flash_ms"], 2)
    # feed the measured-win gate: ring_attention_sharded's auto path turns
    # the kernel on only when this committed row proves it (kernel_gate).
    # Record the ACTUAL backend/interpret so a CPU or interpret invocation
    # can never masquerade as an on-chip row (measured_win filters those).
    from deeplearning4j_tpu.ops.kernel_gate import record_win

    record_win("attention", "ring_local_flash", {
        "speedup": out["flash_speedup"], "shape": out["shape"],
        "einsum_ms": out["ring_einsum_ms"],
        "flash_ms": out["ring_flash_ms"],
        "backend": jax.default_backend(), "interpret": bool(interpret),
    })
    return out


def bench_flash_attention(n=4, t=2048, h=8, d=64, steps=10):
    """Flash pallas kernel vs dense XLA attention, same shapes, fwd only."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas_attention import (
        flash_attention,
        flash_fits,
        pallas_enabled,
    )

    rng = np.random.default_rng(0)
    q, k, v = (
        jax.device_put(jnp.asarray(
            rng.standard_normal((n, t, h, d)), jnp.bfloat16))
        for _ in range(3)
    )

    # q/k/v as traced ARGS (a nullary closure would bake them in as
    # jaxpr constants that XLA may fold away, timing nothing)
    from deeplearning4j_tpu.ops.pallas_attention import dense_attention

    dense_j = jax.jit(lambda q, k, v: dense_attention(q, k, v, causal=True))
    dt_dense = _time_steps(lambda: dense_j(q, k, v), 2, steps)
    out = {"dense_ms": round(dt_dense / steps * 1000, 3),
           "shape": f"n{n} t{t} h{h} d{d}"}
    if pallas_enabled() and flash_fits(t, d):
        flash_j = jax.jit(
            lambda q, k, v: flash_attention(q, k, v, causal=True))
        dt_flash = _time_steps(lambda: flash_j(q, k, v), 2, steps)
        out["flash_ms"] = round(dt_flash / steps * 1000, 3)
        out["flash_speedup"] = round(dt_dense / dt_flash, 2)
    else:
        out["flash_ms"] = None
        out["note"] = "pallas off or shape unfit; dense path only"

    # masked variant: extended kernel (key bias) vs dense-masked — the row
    # that gates attention_auto's masked default (kernel_gate rent rule)
    from deeplearning4j_tpu.ops.pallas_attention import (
        _dense_masked,
        ext_fits,
        flash_attention_masked,
    )

    km = jax.device_put(jnp.asarray(rng.random((n, t)) > 0.2))
    dm_j = jax.jit(lambda q, k, v, km: _dense_masked(q, k, v, km,
                                                     causal=True))
    dt_dm = _time_steps(lambda: dm_j(q, k, v, km), 2, steps)
    out["masked_dense_ms"] = round(dt_dm / steps * 1000, 3)
    if pallas_enabled() and ext_fits(t, t, d):
        fm_j = jax.jit(lambda q, k, v, km: flash_attention_masked(
            q, k, v, km, causal=True))
        dt_fm = _time_steps(lambda: fm_j(q, k, v, km), 2, steps)
        out["masked_flash_ms"] = round(dt_fm / steps * 1000, 3)
        out["masked_speedup"] = round(dt_dm / dt_fm, 2)
        from deeplearning4j_tpu.ops.kernel_gate import record_win

        record_win("attention", "masked_flash", {
            "speedup": out["masked_speedup"], "shape": out["shape"],
            "dense_ms": out["masked_dense_ms"],
            "flash_ms": out["masked_flash_ms"],
            "backend": jax.default_backend(), "interpret": False,
        })
    return out


# ---------------------------------------------------------------------------
# kernel-rent legs (ISSUE 13): paged-decode attention + fused SGNS step.
# Each leg probes the tunnel itself (dispatch_overhead pattern): on a chip
# it times the COMPILED kernel vs its XLA twin and records the measured-win
# row (kernel_gate, honest backend/interpret labels); offline it still
# proves interpret-mode equivalence on CPU — an honest non-arming row, so
# the completeness check passes while the tunnel is down and the next
# contact's full pass drops in the chip row without code changes.
# ---------------------------------------------------------------------------

_PAGED_KERNEL_SCRIPT = r"""
import json, sys, time
mode, steps = sys.argv[1], int(sys.argv[2])
if mode == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import pallas_paged

interpret = mode == "cpu"
if interpret:
    S, H, HD, BT, M = 4, 2, 16, 4, 4      # tiny: interpret walltime
else:
    S, H, HD, BT, M = 8, 8, 128, 16, 8    # serving class: paged_fits-true

n_blocks = S * M                          # every table slot distinct
rng = np.random.default_rng(0)
ck = jnp.asarray(rng.standard_normal((n_blocks + 1, BT, H, HD)), jnp.float32)
cv = jnp.asarray(rng.standard_normal((n_blocks + 1, BT, H, HD)), jnp.float32)
# lane tables: allocated prefix, trash-block tail past the write position
tables = np.zeros((S, M), np.int32)
pos = np.zeros((S,), np.int32)
for s in range(S):
    used = 1 + s % M
    tables[s, :used] = 1 + (rng.permutation(n_blocks)[:used])
    pos[s] = used * BT - 1 - (s % BT)
tables = jnp.asarray(tables)
pos = jnp.asarray(pos)
q = jnp.asarray(rng.standard_normal((S, H, HD)), jnp.float32)
scale = 1.0 / float(np.sqrt(HD))
T = M * BT


def gather_ref(q, ck, cv, tables, pos):
    # the serving tick's dense fallback, verbatim (serving/paged.py block())
    kg = ck[tables].reshape(S, T, H, HD)
    vg = cv[tables].reshape(S, T, H, HD)
    sc = jnp.einsum("nhd,nthd->nht", q, kg) * scale
    visible = jnp.arange(T)[None, :] <= pos[:, None]
    sc = jnp.where(visible[:, None, :], sc, -jnp.inf)
    return jnp.einsum("nht,nthd->nhd", jax.nn.softmax(sc, axis=-1), vg)


def force(x):
    np.asarray(x.reshape(-1)[:1])  # data-dependent host readback fence


def timed(fn):
    out = fn(q, ck, cv, tables, pos)
    force(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(q, ck, cv, tables, pos)
    force(out)
    return out, (time.perf_counter() - t0) / steps * 1e3


ref_fn = jax.jit(gather_ref)
kern_fn = jax.jit(lambda *a: pallas_paged.paged_attention(
    *a, interpret=interpret))
ref, gather_ms = timed(ref_fn)
out, kernel_ms = timed(kern_fn)
max_dev = float(jnp.max(jnp.abs(out - ref)))
assert max_dev < 1e-4, f"kernel diverges from gather path: {max_dev}"

backend = jax.default_backend()
row = {
    "speedup": round(gather_ms / kernel_ms, 2),
    "gather_ms": round(gather_ms, 3), "kernel_ms": round(kernel_ms, 3),
    "shape": f"s{S} h{H} hd{HD} bt{BT} m{M}",
    "backend": backend, "interpret": interpret,
}
recorded = backend == "tpu" and not interpret
if recorded:  # CPU/interpret smoke must never overwrite chip evidence
    from deeplearning4j_tpu.ops.kernel_gate import record_win

    record_win("paged", "decode_attention", row)
print(json.dumps({
    "backend": backend, "device": str(jax.devices()[0]),
    "data": "synthetic", "timed_steps": steps,
    "row": row, "max_abs_dev_vs_gather": max_dev,
    "gate_row_recorded": recorded,
    "fits": pallas_paged.paged_fits(BT, H, HD),
    "stat": "per-call ms over the jitted attention body alone "
            "(readback-fenced); equal table/pos workload both paths",
}))
"""


_SGNS_KERNEL_SCRIPT = r"""
import json, sys, time
mode, steps = sys.argv[1], int(sys.argv[2])
if mode == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.word2vec import _neg_body
from deeplearning4j_tpu.ops import pallas_sgns

interpret = mode == "cpu"
if interpret:
    V, D, B, K1 = 200, 32, 16, 6          # tiny: interpret walltime
else:
    V, D, B, K1 = 100_000, 100, 1024, 6   # the W2V profile's hot class

rng = np.random.default_rng(0)
syn0 = jnp.asarray(rng.standard_normal((V, D)) * 0.1, jnp.float32)
syn1 = jnp.asarray(rng.standard_normal((V, D)) * 0.1, jnp.float32)
contexts = jnp.asarray(rng.integers(0, V, (B,)), jnp.int32)
targets = jnp.asarray(rng.integers(0, V, (B, K1)), jnp.int32)
labels = jnp.concatenate(
    [jnp.ones((B, 1)), jnp.zeros((B, K1 - 1))], axis=1).astype(jnp.float32)
live = jnp.asarray(rng.integers(0, 2, (B, K1)), jnp.float32)
alpha = 0.025


def force(x):
    np.asarray(x[0].reshape(-1)[:1])


def timed(fn):
    out = fn(syn0, syn1, contexts, targets, labels, live, alpha)
    force(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(syn0, syn1, contexts, targets, labels, live, alpha)
    force(out)
    return out, (time.perf_counter() - t0) / steps * 1e3


ref_fn = jax.jit(_neg_body)
kern_fn = jax.jit(lambda *a: pallas_sgns.sgns_fused_step(
    *a, interpret=interpret))
(r0, r1), xla_ms = timed(ref_fn)
(o0, o1), kernel_ms = timed(kern_fn)
max_dev = max(float(jnp.max(jnp.abs(o0 - r0))),
              float(jnp.max(jnp.abs(o1 - r1))))
assert max_dev < 1e-4, f"kernel diverges from _neg_body: {max_dev}"

backend = jax.default_backend()
row = {
    "speedup": round(xla_ms / kernel_ms, 2),
    "xla_ms": round(xla_ms, 3), "kernel_ms": round(kernel_ms, 3),
    "shape": f"v{V} d{D} b{B} k{K1}",
    "backend": backend, "interpret": interpret,
}
recorded = backend == "tpu" and not interpret
if recorded:  # CPU/interpret smoke must never overwrite chip evidence
    from deeplearning4j_tpu.ops.kernel_gate import record_win

    record_win("sgns", "fused_step", row)
print(json.dumps({
    "backend": backend, "device": str(jax.devices()[0]),
    "data": "synthetic", "timed_steps": steps,
    "row": row, "max_abs_dev_vs_xla": max_dev,
    "gate_row_recorded": recorded,
    "fits": pallas_sgns.sgns_fits(B, K1, D),
    "stat": "per-call ms over one SGNS minibatch step (readback-fenced); "
            "same tables/indices both paths, stale-gather semantics",
}))
"""


def bench_paged_kernel(steps=10):
    """Paged-decode attention kernel (ops/pallas_paged.py) vs the serving
    tick's dense ``ck[tables]`` gather fallback, attention body alone, at
    equal workload. On a chip: compiled kernel, measured-win row recorded
    under PALLAS_BENCH.json ``paged.decode_attention``; offline: honest
    interpret-mode CPU equivalence row (never recorded as chip proof)."""
    probe_err = _probe_device(timeout_s=90.0)
    mode = "cpu" if probe_err else "auto"
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _PAGED_KERNEL_SCRIPT, mode, str(steps)], 900)
    if parsed is None:
        return {"error": err}
    if probe_err:
        parsed["note"] = (f"accelerator unreachable ({probe_err}); "
                          "interpret-mode equivalence only — the gate row "
                          "needs the chip")
    return parsed


def bench_sgns_kernel(steps=10):
    """Fused SGNS gather-dot-scatter kernel (ops/pallas_sgns.py) vs the
    XLA _neg_body step on the W2V profile's hot shape class. On a chip:
    compiled kernel, measured-win row recorded under PALLAS_BENCH.json
    ``sgns.fused_step``; offline: honest interpret-mode CPU equivalence
    row (never recorded as chip proof)."""
    probe_err = _probe_device(timeout_s=90.0)
    mode = "cpu" if probe_err else "auto"
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _SGNS_KERNEL_SCRIPT, mode, str(steps)], 900)
    if parsed is None:
        return {"error": err}
    if probe_err:
        parsed["note"] = (f"accelerator unreachable ({probe_err}); "
                          "interpret-mode equivalence only — the gate row "
                          "needs the chip")
    return parsed


# ---------------------------------------------------------------------------
# dispatch efficiency: retrace telemetry + buffer-donation win
# ---------------------------------------------------------------------------

_DISPATCH_SCRIPT = r"""
import json, os, sys, time
import numpy as np

mode, steps = sys.argv[1], int(sys.argv[2])
if mode == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax, jax.numpy as jnp
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def build(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.01)
            .updater("adam").list()
            .layer(0, DenseLayer(n_in=256, n_out=256, activation="relu"))
            .layer(1, DenseLayer(n_in=256, n_out=128, activation="relu"))
            .layer(2, OutputLayer(n_in=128, n_out=10, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


rng = np.random.default_rng(0)
x = rng.standard_normal((324, 256)).astype(np.float32)
y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 324)]

# --- retrace telemetry: ragged batch sizes {96, 100, 128} through
# fit_iterator. Bucketed: 100 pads to 128, 96 IS a bucket -> <= 2 traces.
# Unbucketed: one trace per distinct shape (the seed behavior).
def feed(bucketing):
    os.environ["DL4J_TPU_BUCKET_BATCHES"] = "1" if bucketing else "0"
    net = build()
    for b in (96, 100, 128, 100, 96, 128):  # repeats must be cache hits
        i = {96: 0, 100: 96, 128: 196}[b]
        net.fit_iterator(ListDataSetIterator(x[i:i + b], y[i:i + b], b))
    s = net.dispatch_stats
    return {"traces": s.traces.get("train_step", 0),
            "dispatches": s.calls.get("train_step", 0),
            "cache_hits": s.cache_hits("train_step"),
            "padded_batches": s.padded_batches,
            # wall-seconds spent in calls that traced (trace + XLA
            # compile) — the per-program compile budget a short tunnel
            # contact window has to plan around
            "trace_seconds": round(s.trace_seconds.get("train_step", 0.0),
                                   3)}

bucketed = feed(True)
unbucketed = feed(False)
os.environ["DL4J_TPU_BUCKET_BATCHES"] = "1"

# --- donation win: steps/sec of the SAME fixed-shape train step with and
# without params/states/upd_state donation (fresh net per setting — the
# donation decision is read at jit construction). jax implements donation
# on CPU too (buffer reuse instead of copy), but the HBM-copy-per-step
# the chip saves is the point of this leg. INTERLEAVED paired reps with a
# median-pair commit, exactly like the scaling_virtual8 leg: on this
# shared 1-core host a single A-then-B timing swings wildly with
# background load (measured 0.79-1.35 on back-to-back CPU runs).
xb = jax.device_put(jnp.asarray(x[:128]))
yb = jax.device_put(jnp.asarray(y[:128]))

def build_timed(donate):
    os.environ["DL4J_TPU_DONATE"] = "force" if donate else "0"
    net = build()
    np.asarray(net.fit(xb, yb))  # compile + warm
    return net

def timed(net):
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = net.fit(xb, yb)
    np.asarray(loss)  # host readback with a true data dependency (the
    # only sound completion fence through the remote-TPU tunnel)
    return steps / (time.perf_counter() - t0)

net_d, net_c = build_timed(True), build_timed(False)
pairs = [(timed(net_d), timed(net_c)) for _ in range(3)]
donated_n = net_d.dispatch_stats.donated_steps
ratios = [d / c for d, c in pairs]
mi = sorted(range(3), key=lambda i: ratios[i])[1]
sps_donated, sps_copied = pairs[mi]
del os.environ["DL4J_TPU_DONATE"]

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "batch_sizes": [96, 100, 128],
    "bucketed": bucketed,
    "unbucketed": unbucketed,
    "steps_per_sec_donated": round(sps_donated, 2),
    "steps_per_sec_copied": round(sps_copied, 2),
    "donation_speedup": round(ratios[mi], 3),
    "speedup_reps": [round(r, 3) for r in ratios],
    "speedup_stat": "median of 3 interleaved pair ratios; committed "
                    "steps/sec are the median pair's own halves",
    "donated_steps_counted": int(donated_n),
    "train_step_trace_seconds": round(
        net_d.dispatch_stats.trace_seconds.get("train_step", 0.0), 3),
    "timed_steps": steps,
}))
"""


def bench_dispatch_overhead(steps=40):
    """Dispatch-efficiency leg (ops/dispatch.py): proves the retrace count
    stays at one-per-bucket across ragged batch sizes, and measures the
    buffer-donation steps/sec delta on a fixed shape. Runs in a subprocess
    (fresh tunnel, same reasoning as the north-star leg); falls back to an
    honest CPU row (backend labeled, synthetic provenance) when the
    accelerator is unreachable — the retrace telemetry is
    backend-independent, so the leg is still meaningful offline."""
    probe_err = _probe_device(timeout_s=90.0)
    mode = "cpu" if probe_err else "auto"
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _DISPATCH_SCRIPT, mode, str(steps)], 900)
    if parsed is None:
        return {"error": err}
    if probe_err:
        parsed["note"] = (f"accelerator unreachable ({probe_err}); CPU "
                          "dispatch numbers — the retrace counts carry "
                          "over, the donation/steps-sec row needs the chip")
    return parsed


# ---------------------------------------------------------------------------
# remat: AOT memory ladder + step-time overhead (CPU-measurable — the
# tunnel-independent proof of the HBM-lean training PR)
# ---------------------------------------------------------------------------

_REMAT_SCRIPT = r"""
import dataclasses, json, os, sys, time
mode, steps = sys.argv[1], int(sys.argv[2])
if mode == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
import jax.numpy as jnp
import numpy as np

import deeplearning4j_tpu.models.transformer as tfm
from deeplearning4j_tpu.ops import memory as mem

# the d512 L8 evidence config (ISSUE 4 acceptance): big enough that the
# activation ladder dominates temp bytes, small enough that the CPU
# substrate compiles each rung in seconds. Strict f32 on CPU (bf16 is a
# pessimization there); the chip regime (bf16) rides the same ladder.
d, L, heads, seq, batch, vocab = 512, 8, 8, 256, 8, 8192
dtype = "strict" if mode == "cpu" else "performance"
cfg0 = tfm.TransformerConfig(
    vocab_size=vocab, d_model=d, n_layers=L, n_heads=heads, d_ff=4 * d,
    max_len=seq, dtype_policy=dtype, learning_rate=1e-4)

rng = np.random.default_rng(0)
toks = rng.integers(0, vocab, (batch, seq + 1))
x = jax.device_put(jnp.asarray(toks[:, :-1], jnp.int32))
y = jax.device_put(jnp.asarray(toks[:, 1:], jnp.int32))

rows = {}
for pol in ("none", "dots", "block"):
    cfg = dataclasses.replace(cfg0, remat=pol)
    step = tfm.make_train_step(cfg)
    # ONE compile serves both the AOT ledger and the timed run (the
    # ledger comes first: the memory claim must not depend on the timed
    # run surviving)
    p_sh = jax.eval_shape(lambda: tfm.init_params(cfg))
    o_sh = jax.eval_shape(tfm.init_opt_state, p_sh)
    compiled = step.lower(p_sh, o_sh, x, y).compile()
    a = mem.analyze_compiled(compiled)
    params = tfm.init_params(cfg)
    opt = tfm.init_opt_state(params)
    step = compiled  # the AOT executable IS the step from here on
    params, opt, loss = step(params, opt, x, y)  # warm
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss = step(params, opt, x, y)
    final = float(loss)  # host readback with a true data dependency —
    # the only sound completion fence through the remote-TPU tunnel
    rows[pol] = {
        "temp_bytes": None if a is None else a["temp_bytes"],
        "temp_gb": None if a is None else round(a["temp_bytes"] / 2**30, 3),
        "peak_gb": None if a is None else round(a["peak_bytes"] / 2**30, 3),
        "step_ms": round((time.perf_counter() - t0) / steps * 1000, 1),
        "loss": round(final, 4),
    }

def ratio(num, den):
    return None if not num or not den else round(num / den, 2)

out = {
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "config": f"d{d} L{L} h{heads} b{batch} s{seq} v{vocab} {dtype}",
    "timed_steps": steps,
    "policies": rows,
    # the headline: AOT temp bytes (activations + workspace) per rung
    "temp_reduction_dots_x": ratio(rows["none"]["temp_bytes"],
                                   rows["dots"]["temp_bytes"]),
    "temp_reduction_block_x": ratio(rows["none"]["temp_bytes"],
                                    rows["block"]["temp_bytes"]),
    # recompute cost per rung (>1 = slower than none, the expected trade)
    "step_overhead_dots": ratio(rows["dots"]["step_ms"],
                                rows["none"]["step_ms"]),
    "step_overhead_block": ratio(rows["block"]["step_ms"],
                                 rows["none"]["step_ms"]),
    "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
}
# committed artifact (the PALLAS_BENCH.json pattern): the ladder evidence
# survives independently of the merged bench artifact
tmp = "REMAT_MEMORY.json.tmp"
with open(tmp, "w") as f:
    json.dump(out, f, indent=1, sort_keys=True)
os.replace(tmp, "REMAT_MEMORY.json")
print(json.dumps(out))
"""


def bench_remat_memory(steps=2):
    """Remat-ladder leg (ops/remat.py + ops/memory.py): AOT
    ``memory_analysis`` temp bytes and measured step time for the d512 L8
    train step under each remat rung (none/dots/block). CPU-measurable —
    the AOT ledger is exactly as valid on the CPU substrate as on the
    chip (it accounts the program XLA compiled for THAT backend) — with
    an honest backend label either way; on-chip rows additionally report
    real HBM. Writes REMAT_MEMORY.json beside the bench artifact. Runs
    in a subprocess (fresh tunnel, the north-star reasoning)."""
    probe_err = _probe_device(timeout_s=90.0)
    mode = "cpu" if probe_err else "auto"
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _REMAT_SCRIPT, mode, str(steps)], 900)
    if parsed is None:
        return {"error": err}
    if probe_err:
        parsed["note"] = (f"accelerator unreachable ({probe_err}); CPU "
                          "AOT memory ladder — the temp-bytes reductions "
                          "are per-backend-program facts, the on-chip HBM "
                          "row lands at next contact")
    return parsed


# ---------------------------------------------------------------------------
# serving: dynamic batcher vs the naive per-request path under load
# ---------------------------------------------------------------------------

_SERVING_SCRIPT = r"""
import json, os, sys, threading, time
import numpy as np

mode, clients, per_client = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
if mode == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
from concurrent.futures import ThreadPoolExecutor
from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import DynamicBatcher, ServingStats
from deeplearning4j_tpu.serving.registry import bucket_ladder

conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.01)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=256, n_out=256, activation="relu"))
        .layer(1, DenseLayer(n_in=256, n_out=128, activation="relu"))
        .layer(2, OutputLayer(n_in=128, n_out=10, activation="softmax",
                              loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
rows = rng.standard_normal((clients, 256)).astype(np.float32)
n_requests = clients * per_client

# steady-state measurement: pre-compile every program either path can hit
# (batch-1 for naive; the bucket ladder for the batcher) — first-request
# compile latency is warmup's job (serving/registry.py), not this leg's
max_batch = 64
for b in sorted(set(bucket_ladder(max_batch)) | {1}):
    np.asarray(net.output(np.zeros((b, 256), np.float32)))

# naive path: the pre-rewrite ModelServer.predict — one locked batch-1
# output() dispatch per request (streaming/serving.py before this PR)
lock = threading.Lock()

def naive_one(i):
    with lock:
        out = net.output(rows[i % clients][None])
    return np.asarray(out)

def run_naive():
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=clients) as ex:
        list(ex.map(naive_one, range(n_requests)))
    return n_requests / (time.perf_counter() - t0)

def run_batched():
    stats = ServingStats()
    batcher = DynamicBatcher(lambda x: np.asarray(net.output(x)),
                             max_batch=max_batch, max_wait_ms=4,
                             queue_capacity=4096, stats=stats)
    try:
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=clients) as ex:
            list(ex.map(
                lambda i: batcher.predict(rows[i % clients][None]),
                range(n_requests)))
        rps = n_requests / (time.perf_counter() - t0)
    finally:
        batcher.stop()
    return rps, stats

run_naive(); run_batched()  # warm thread pools + any residual compiles

# INTERLEAVED paired reps with a median-pair commit (the scaling_virtual8
# methodology): single A-then-B timings on this shared 1-core host swing
# wildly with background load. The committed latency/fill telemetry is
# the MEDIAN PAIR'S OWN rep — quoting rep-3 percentiles against rep-1
# rps would mix measurement regimes in one row.
pairs = []
for _ in range(3):
    nv = run_naive()
    bt, st = run_batched()
    pairs.append((nv, bt, st))
ratios = [b / n for n, b, _ in pairs]
mi = sorted(range(3), key=lambda i: ratios[i])[1]
naive_rps, batched_rps, stats = pairs[mi]
lat = stats.latency_ms()

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "clients": clients,
    "requests_per_rep": n_requests,
    "naive_rps": round(naive_rps, 1),
    "batched_rps": round(batched_rps, 1),
    "batcher_speedup": round(ratios[mi], 3),
    "speedup_reps": [round(r, 3) for r in ratios],
    "speedup_stat": "median of 3 interleaved pair ratios; committed rps "
                    "are the median pair's own halves",
    "p50_ms": lat["p50"], "p95_ms": lat["p95"], "p99_ms": lat["p99"],
    "batch_fill_ratio": stats.batch_fill_ratio(),
    "batches_last_rep": stats.batches,
    "max_batch": max_batch,
}))
"""


def bench_serving_throughput(clients=32, per_client=16):
    """Serving-engine leg (deeplearning4j_tpu/serving/): requests/sec of
    the dynamic batcher vs the naive per-request path (one locked batch-1
    dispatch per request — the pre-rewrite streaming/serving.py and the
    reference's DL4jServeRouteBuilder granularity) under `clients`
    concurrent clients, plus the batcher's p50/p95/p99 latency and
    batch-fill ratio. Subprocess-isolated like dispatch_overhead; honest
    CPU row (backend labeled) when the accelerator is unreachable — the
    batching win is about dispatch count, which exists on every backend
    and only grows with the chip's ~5ms dispatch cost."""
    probe_err = _probe_device(timeout_s=90.0)
    mode = "cpu" if probe_err else "auto"
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _SERVING_SCRIPT, mode, str(clients),
         str(per_client)], 900)
    if parsed is None:
        return {"error": err}
    if probe_err:
        parsed["note"] = (f"accelerator unreachable ({probe_err}); CPU "
                          "serving numbers — the dispatch-amortization "
                          "ratio carries over, per-dispatch cost on chip "
                          "is ~25x the CPU's")
    return parsed


# ---------------------------------------------------------------------------
# serving_resilience: breaker+watchdog accounting cost on the batcher hot
# path, and time-to-recover after an injected hang (ISSUE 8 —
# serving/resilience.py). CPU-only by design: the plane is host-side
# bookkeeping (a lock-guarded state machine per dispatch and an armed
# deadline per batch), so its cost exists on every backend and is a
# LARGER fraction of a fast CPU dispatch than of a real ~5ms TPU one —
# the CPU row bounds the on-chip overhead from above. Bar: < 3% rps.
# ---------------------------------------------------------------------------

_SERVING_RESILIENCE_SCRIPT = r"""
import json, sys, threading, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from concurrent.futures import ThreadPoolExecutor
from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import ServingChaos, ServingChaosConfig
from deeplearning4j_tpu.serving import (CircuitBreaker, DynamicBatcher,
                                        ServingEngine, ServingStats)
from deeplearning4j_tpu.serving.registry import bucket_ladder

clients, per_client = int(sys.argv[1]), int(sys.argv[2])
conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.01)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=256, n_out=256, activation="relu"))
        .layer(1, DenseLayer(n_in=256, n_out=128, activation="relu"))
        .layer(2, OutputLayer(n_in=128, n_out=10, activation="softmax",
                              loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
rows = rng.standard_normal((clients, 256)).astype(np.float32)
n_requests = clients * per_client
max_batch = 64
for b in sorted(set(bucket_ladder(max_batch)) | {1}):
    np.asarray(net.output(np.zeros((b, 256), np.float32)))


def run_batched(plane_on):
    stats = ServingStats()
    breaker = (CircuitBreaker(fails=5, key="bench", stats=stats)
               if plane_on else None)

    def on_outcome(ok, exc):
        if ok:
            breaker.record_success()
        else:
            breaker.record_failure(str(exc))

    batcher = DynamicBatcher(
        lambda x: np.asarray(net.output(x)), max_batch=max_batch,
        max_wait_ms=4, queue_capacity=4096, stats=stats,
        watchdog_s=(5.0 if plane_on else 0.0),
        on_outcome=(on_outcome if plane_on else None))
    try:
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=clients) as ex:
            list(ex.map(
                lambda i: batcher.predict(rows[i % clients][None]),
                range(n_requests)))
        rps = n_requests / (time.perf_counter() - t0)
    finally:
        batcher.stop()
    assert stats.wedged_batches == 0  # a false positive would taint the row
    return rps


run_batched(False); run_batched(True)  # warm thread pools

# interleaved off/on pairs, median-of-ratios (the serving_throughput /
# obs_overhead methodology: single A-then-B swings with load on this
# shared 1-core host)
pairs = []
for _ in range(3):
    off = run_batched(False)
    on = run_batched(True)
    pairs.append((off, on))
ratios = sorted(off / on for off, on in pairs)
ratio = ratios[len(ratios) // 2]
mi = [i for i, p in enumerate(pairs) if p[0] / p[1] == ratio][0]
rps_off, rps_on = pairs[mi]

# time-to-recover after an injected hang: the engine-level wedge ->
# watchdog verdict -> breaker trip -> cooldown -> half-open probe ->
# serving again, measured end to end through the public predict API
chaos = ServingChaos(ServingChaosConfig(infer_hang_at=1, infer_hang_s=60.0))
eng = ServingEngine(model=net, max_wait_ms=2, watchdog_s=0.3,
                    breaker_fails=3, breaker_cooldown_s=0.2, chaos=chaos)
row = rows[0][None]
t0 = time.monotonic()
wedge_kind = None
try:
    eng.predict(row, timeout_s=30)
except Exception as e:
    wedge_kind = type(e).__name__
wedge_detect_s = time.monotonic() - t0
recover_s = None
t_limit = time.monotonic() + 30
while time.monotonic() < t_limit:
    try:
        eng.predict(row, timeout_s=5)
        recover_s = time.monotonic() - t0
        break
    except Exception:
        time.sleep(0.05)
snap = eng.stats.snapshot()
chaos.release_hangs()
eng.stop(drain=False)

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "clients": clients,
    "requests_per_rep": n_requests,
    "rps_plane_off": round(rps_off, 1),
    "rps_plane_on": round(rps_on, 1),
    "overhead_pct": round((ratio - 1.0) * 100.0, 2),
    "overhead_reps_pct": [round((r - 1.0) * 100.0, 2) for r in ratios],
    "overhead_bar_pct": 3.0,
    "wedge_error": wedge_kind,
    "wedge_detect_s": round(wedge_detect_s, 3),
    "time_to_recover_s": (round(recover_s, 3) if recover_s is not None
                          else None),
    "watchdog_s": 0.3,
    "breaker_cooldown_s": 0.2,
    "wedged_batches": snap["wedged_batches"],
    "watchdog_restarts": snap["watchdog_restarts"],
    "breaker_opens": snap["breaker_opens"],
    "breaker_closes": snap["breaker_closes"],
    "stat": "median of 3 interleaved plane-off/on pair ratios; recovery "
            "timed through the public predict API (wedge -> watchdog -> "
            "breaker cooldown -> probe -> first success)",
    "note": "host-side accounting only (no device sync added); the CPU "
            "dispatch is far cheaper than the chip's ~5ms, so this "
            "overhead fraction upper-bounds the on-chip one",
}))
"""


def bench_serving_resilience(clients=16, per_client=8):
    """Serving resilience leg (serving/resilience.py): steady-state rps
    cost of the breaker+watchdog accounting on the DynamicBatcher hot
    path (bar < 3% vs the plane-off batcher), plus the end-to-end
    time-to-recover after a deterministically injected infer-hang (the
    stale-tunnel wedge): watchdog verdict -> breaker trip -> half-open
    probe -> serving again. Subprocess-isolated, CPU-only by design —
    the plane is host-side bookkeeping on every backend."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _SERVING_RESILIENCE_SCRIPT, str(clients),
         str(per_client)], 900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# serving_fleet: router+replica tier (ISSUE 12 — serving/fleet.py +
# serving/router.py). CPU-only by design: on this 1-core host replicas
# share the core, so the replica-count sweep measures ROUTER overhead
# (proxy hop + breaker/SLO accounting per request) staying flat as the
# tier widens — not parallel speedup — and the kill leg measures the
# failover machinery (connect-failure verdict -> breaker vote ->
# retry-on-survivor -> board expiry -> restart -> re-admission), all of
# which is host-side bookkeeping that exists unchanged on every backend.
# Acceptance bar: ZERO failed admitted requests across the chaos kill,
# with the end-to-end time-to-recover committed in the row.
# ---------------------------------------------------------------------------

_SERVING_FLEET_SCRIPT = r"""
import json, sys, threading, time
import jax
jax.config.update("jax_platforms", "cpu")
import urllib.error, urllib.request
import numpy as np
from concurrent.futures import ThreadPoolExecutor
from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import RouterChaos, RouterChaosConfig
from deeplearning4j_tpu.serving.fleet import ServingFleet
from deeplearning4j_tpu.serving.registry import bucket_ladder

clients, per_client = int(sys.argv[1]), int(sys.argv[2])
conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.01)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=256, n_out=256, activation="relu"))
        .layer(1, DenseLayer(n_in=256, n_out=128, activation="relu"))
        .layer(2, OutputLayer(n_in=128, n_out=10, activation="softmax",
                              loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
rows = rng.standard_normal((clients, 256)).astype(np.float32)
n_requests = clients * per_client
# thread-mode replicas share the model object, so one warm pass fills the
# jit cache for every replica count (the bucket ladder + batch-1)
for b in sorted(set(bucket_ladder(64)) | {1}):
    np.asarray(net.output(np.zeros((b, 256), np.float32)))


def post(url, payload, timeout=60):
    req = urllib.request.Request(
        url + "/predict", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code
    except OSError:
        return -1


def drive(url, n):
    lat, codes, lock = [], [], threading.Lock()

    def one(i):
        t0 = time.perf_counter()
        c = post(url, {"batch": rows[i % clients][None].tolist()})
        dt = time.perf_counter() - t0
        with lock:
            lat.append(dt)
            codes.append(c)

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=clients) as ex:
        list(ex.map(one, range(n)))
    return time.perf_counter() - t0, sorted(lat), codes


replica_rows = {}
for n_rep in (1, 2, 4):
    fleet = ServingFleet(model=net, replicas=n_rep,
                         heartbeat_s=0.5).start()
    try:
        drive(fleet.url, clients * 2)  # warm every replica + the router
        wall, lat, codes = drive(fleet.url, n_requests)
        bad = sum(1 for c in codes if c != 200)
        assert bad == 0, f"{bad} non-200s at {n_rep} replicas"
        replica_rows[str(n_rep)] = {
            "rps": round(n_requests / wall, 1),
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
            "p99_ms": round(
                lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3, 2),
        }
    finally:
        fleet.stop()

# chaos kill mid-stream: r0 hard-dies after `kill_after` proxied requests
# (RouterChaos verdict, enacted by the fleet's kill hook); the bar is
# ZERO failed admitted requests. Recovery is timed end to end through
# the PUBLIC router API: kill instant -> restart_replica -> first
# /health scrape whose routable set includes r0 again.
kill_after = max(4, n_requests // 4)
chaos = RouterChaos(RouterChaosConfig(
    kill_replica={"replica": "r0", "after_proxied": kill_after}))
fleet = ServingFleet(model=net, replicas=2, heartbeat_s=0.25, chaos=chaos,
                     router_kwargs={"poll_s": 0.1})
times = {}
enact = fleet.router.on_kill


def on_kill(rid):
    times["kill"] = time.monotonic()
    enact(rid)


fleet.router.on_kill = on_kill
fleet.start()
result = {}
t = threading.Thread(
    target=lambda: result.update(
        zip(("wall", "lat", "codes"), drive(fleet.url, n_requests))))
t.start()
while "kill" not in times and t.is_alive():
    time.sleep(0.01)
assert "kill" in times, "chaos kill never fired"
recover_s = None
deadline = time.monotonic() + 30
while time.monotonic() < deadline:
    try:
        fleet.restart_replica("r0")
        break
    except ValueError:
        time.sleep(0.005)  # kill() may still be mid-enactment
# recovered == the router's PUBLIC /replicas view shows r0 at the NEW
# incarnation's address, probed ready, breaker serving — the stale
# pre-kill table entry (optimistic ready, unopened breaker) must not
# count as recovery
new_url = fleet.engines()["r0"].url
while time.monotonic() < deadline:
    try:
        with urllib.request.urlopen(fleet.url + "/replicas",
                                    timeout=5) as r:
            body = json.loads(r.read())
        d = body.get("r0")
        if (d and d["url"] == new_url and d["ready"]
                and d["breaker"]["state"] == "serving"):
            recover_s = time.monotonic() - times["kill"]
            break
    except OSError:
        pass
    time.sleep(0.02)
t.join()
failed = sum(1 for c in result["codes"] if c != 200)
snap = fleet.router.stats.snapshot()
fleet.stop()
assert failed == 0, f"{failed} admitted requests failed across the kill"
assert recover_s is not None, "killed replica never re-admitted"

r1 = replica_rows["1"]["rps"]
print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "clients": clients,
    "requests_per_leg": n_requests,
    "replicas": replica_rows,
    "router_rps_ratio_2v1": round(replica_rows["2"]["rps"] / r1, 3),
    "router_rps_ratio_4v1": round(replica_rows["4"]["rps"] / r1, 3),
    "kill": {
        "requests": n_requests,
        "failed": failed,
        "kill_after_proxied": kill_after,
        "retries": snap["retries"],
        "replica_failures": snap["replica_failures"],
        "breaker_opens": snap["breaker_opens"],
        "time_to_recover_s": round(recover_s, 3),
    },
    "stat": "rps + latency through the public router HTTP API per "
            "replica count; recovery = kill instant -> restart -> first "
            "/replicas scrape showing the NEW incarnation's address "
            "ready with a serving breaker",
    "note": "1-core host: replicas share the core, so the sweep bounds "
            "ROUTER overhead (ratios ~1.0 == the proxy hop scales), not "
            "parallel speedup; failover/recover timings are host-side "
            "and backend-independent",
}))
"""


def bench_serving_fleet(clients=8, per_client=12):
    """Serving fleet leg (serving/fleet.py + serving/router.py): rps/p99
    through the public FleetRouter API at 1/2/4 replicas, plus the
    zero-loss chaos-kill contract — a replica hard-killed mid-stream
    must fail ZERO admitted requests (retry-on-survivor) — with the
    end-to-end time-to-recover (kill -> restart -> routable again).
    Subprocess-isolated, CPU-only by design: router accounting and
    failover are host-side on every backend."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _SERVING_FLEET_SCRIPT, str(clients),
         str(per_client)], 900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# autoscale: the ISSUE 20 control loop — scripted load wave -> scale-up
# (time-to-scale measured wave start -> second replica ready), quiet
# ticks -> scale-down draining the victim through the goodbye path while
# live /predict traffic keeps flowing (zero failed admitted requests),
# deterministic decision replay from the recorded signals_log, and the
# per-tenant token-bucket fairness proof (one tenant's burst sheds 429
# while the other's admission is untouched). CPU-only by design: every
# measured quantity is host-side control-plane work.
# ---------------------------------------------------------------------------

_AUTOSCALE_SCRIPT = r"""
import json, sys, threading, time
import jax
jax.config.update("jax_platforms", "cpu")
import urllib.error, urllib.request
import numpy as np
from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import (AutoscaleChaos,
                                           AutoscaleChaosConfig)
from deeplearning4j_tpu.serving.autoscale import FleetAutoscaler, ScaleConfig
from deeplearning4j_tpu.serving.fleet import ServingFleet
from deeplearning4j_tpu.serving.placement import model_footprint
from deeplearning4j_tpu.serving.registry import bucket_ladder
from deeplearning4j_tpu.serving.router import read_replica_addr

hammers, burst_n = int(sys.argv[1]), int(sys.argv[2])
N_IN = 64
conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.01)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=N_IN, n_out=64, activation="relu"))
        .layer(1, OutputLayer(n_in=64, n_out=8, activation="softmax",
                              loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
for b in sorted(set(bucket_ladder(64)) | {1}):
    np.asarray(net.output(np.zeros((b, N_IN), np.float32)))

fleet = ServingFleet(model=net, replicas=1, heartbeat_s=0.5,
                     router_kwargs={
                         "tenant_quotas": "burst:0.001:3,steady:1e9:1e9"})
fleet.start()
cfg = ScaleConfig(min_replicas=1, max_replicas=2, up_queue=10.0,
                  up_shed=0, window=2, down_queue=2.0, cooldown=1)
auto = FleetAutoscaler(fleet, config=cfg, chaos=AutoscaleChaos(
    AutoscaleChaosConfig(load_wave={"at_tick": 0, "ticks": 2,
                                    "queue_depth": 50})))
plan = auto.plan_placement([model_footprint("default", net)])


def wait_ready(n, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(fleet.router.signals()["ready_replicas"]) >= n:
            return
        time.sleep(0.05)
    raise RuntimeError("fleet never reached %d ready replicas" % n)


def post(payload, timeout=60):
    req = urllib.request.Request(
        fleet.url + "/predict", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code
    except OSError:
        return -1


wait_ready(1)
row = [[0.1] * N_IN]
codes, lock, stop = [], threading.Lock(), threading.Event()


def hammer():
    while not stop.is_set():
        c = post({"batch": row})
        with lock:
            codes.append(c)
        time.sleep(0.004)


threads = [threading.Thread(target=hammer) for _ in range(hammers)]
for t in threads:
    t.start()
t_wave = time.perf_counter()
while auto.tick()["action"] != "up":
    time.sleep(0.02)
wait_ready(2)
time_to_scale = time.perf_counter() - t_wave
t_down_start = time.perf_counter()
down = None
for _ in range(30):
    d = auto.tick()
    if d["action"] == "down":
        down = d
        break
    time.sleep(0.05)
assert down is not None and down.get("enacted") == down["victim"]
time_to_drain = time.perf_counter() - t_down_start
time.sleep(0.3)  # a last window of traffic on the survivor
stop.set()
for t in threads:
    t.join(timeout=30)
failed = sum(1 for c in codes if c != 200)
stale_addr = read_replica_addr(fleet.fleet_dir, down["victim"]) is not None

replay = FleetAutoscaler.replay(auto.signals_log, config=cfg)
stripped = [{k: v for k, v in d.items()
             if k not in ("enacted", "enact_error")}
            for d in auto.decisions]
replay_match = stripped == replay

tenant_codes = {"burst": [], "steady": []}
for i in range(burst_n):
    tenant_codes["burst"].append(post({"batch": row, "tenant": "burst"}))
    tenant_codes["steady"].append(post({"batch": row, "tenant": "steady"}))
tsnap = fleet.router.stats.snapshot()
fleet.stop()

snap = auto.stats.snapshot()
print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "decisions": [d["action"] for d in auto.decisions],
    "time_to_scale_s": round(time_to_scale, 3),
    "time_to_drain_s": round(time_to_drain, 3),
    "scale_down": {"requests": len(codes), "failed": failed,
                   "victim": down["victim"],
                   "stale_addr_left": stale_addr},
    "replay_match": replay_match,
    "tenant": {"admitted": tsnap["tenant_admitted"],
               "shed": tsnap["tenant_shed"],
               "burst_429": sum(1 for c in tenant_codes["burst"]
                                if c == 429),
               "steady_429": sum(1 for c in tenant_codes["steady"]
                                 if c == 429)},
    "placement": {"models": plan.models(), "unplaced": plan.unplaced,
                  "utilization": plan.describe()["utilization"]},
    "autoscale_stats": snap,
    "stat": "scripted load wave -> scale-up (wave start -> second "
            "replica ready) -> quiet -> scale-down draining the victim "
            "under live /predict traffic; failed counts every non-200 "
            "answer an admitted client saw; replay_match re-runs the "
            "decision layer over the recorded signals_log",
    "note": "1-core host, CPU-only by design: every measured quantity "
            "is host-side control-plane work (decisions, drain, "
            "routing), identical on every backend",
}))
"""


def bench_autoscale(hammers=3, burst_n=10):
    """Autoscaling control-plane leg (ISSUE 20 — serving/autoscale.py):
    scripted load wave -> scale-up time, zero-loss scale-down under
    live traffic, bit-exact decision replay, tenant-bucket fairness.
    Subprocess-isolated, CPU-only by design (host-side control plane)."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _AUTOSCALE_SCRIPT, str(hammers),
         str(burst_n)], 900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# serving_decode: paged block-pool /generate vs the fixed slot pool at
# EQUAL KV HBM budget (ISSUE 11 — serving/paged.py). CPU-only by design:
# the contested resource is KV capacity and the win is scheduling
# (admission by free blocks + prefix sharing lets ~4x the streams
# co-reside in the same bytes), which exists on every backend; the tick
# arithmetic is the same jitted program either way.
# ---------------------------------------------------------------------------

_SERVING_DECODE_SCRIPT = r"""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

streams, n_new = int(sys.argv[1]), int(sys.argv[2])

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
from deeplearning4j_tpu.ops import env as envknob
from deeplearning4j_tpu.ops import lowprec
from deeplearning4j_tpu.serving.decode import ContinuousDecoder
from deeplearning4j_tpu.serving.engine import ServingEngine
from deeplearning4j_tpu.serving.paged import PagedDecoder, attention_path

SLOTS, BLOCK, PREFIX = 4, 16, 48
cfg = TransformerConfig(vocab_size=64, d_model=64, n_layers=2, n_heads=4,
                        d_ff=128, max_len=128, use_flash=False)
lm = TransformerLM(cfg)
budget_tokens = SLOTS * cfg.max_len   # the fixed 4-slot pool's KV bytes,
n_blocks = budget_tokens // BLOCK     # handed to the paged arena instead

rng = np.random.default_rng(0)
system = rng.integers(1, 64, PREFIX)  # shared system prompt: 3 full blocks
prompts = [np.concatenate([system, rng.integers(1, 64, 8)]).astype(np.int32)
           for _ in range(streams)]


def pooled(make):
    d = make()
    try:
        t0 = time.perf_counter()
        futs = [d.submit(p, n_new, temperature=0.0, timeout_s=600)
                for p in prompts]
        outs = [np.asarray(f.result(timeout=600)) for f in futs]
        wall = time.perf_counter() - t0
        snap = d.stats.snapshot()
        lat = snap["latency_ms"]
        return outs, {
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(streams * n_new / wall, 1),
            "concurrent_streams": d.peak_active,
            "p50_ms": lat["p50"],
            "p99_ms": lat["p99"],
        }, snap
    finally:
        d.stop()


make_paged = lambda: PagedDecoder(lm, block_tokens=BLOCK, n_blocks=n_blocks)
make_fixed = lambda: ContinuousDecoder(lm, slots=SLOTS)

# solo baselines (single-request path — the byte-identity reference)
d = make_paged()
try:
    solo = np.asarray(d.generate(prompts[0][None], n_new,
                                 temperature=0.0)[0])
finally:
    d.stop()
d = make_fixed()
try:
    solo_fixed = np.asarray(d.generate(prompts[0][None], n_new,
                                       temperature=0.0)[0])
finally:
    d.stop()
assert (solo == solo_fixed).all()

# warm pass: compiles the preemption path's re-admission prefill widths
# so the timed pass measures scheduling, not XLA
pooled(make_paged)

outs_p, row_p, snap_p = pooled(make_paged)
outs_f, row_f, snap_f = pooled(make_fixed)

assert (outs_p[0] == solo).all()        # pool-independence, paged
assert (outs_f[0] == solo_fixed).all()  # pool-independence, fixed slot
for a, b in zip(outs_p, outs_f):
    assert (a == b).all()               # cross-decoder identity

hit_rate = (snap_p["prefix_hits"] / snap_p["prefix_lookups"]
            if snap_p["prefix_lookups"] else None)

# span evidence AFTER the timed runs (the tracer never rides the hot
# path): serve.request (engine) parents serve.batch (paged tick)
obs.set_enabled(True)
eng = ServingEngine(model=lm, kv_block=BLOCK, kv_blocks=n_blocks)
try:
    eng.generate(prompts[0][None], 4, temperature=0.0)
finally:
    eng.stop()
reqs = obs.tracer().spans("serve.request")
batches = [s for s in obs.tracer().spans("serve.batch")
           if s["attrs"].get("kind") == "decode.paged"]
assert reqs and batches, "span evidence missing"
obs.set_enabled(None)

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "streams": streams,
    "n_new": n_new,
    "shared_prefix_tokens": PREFIX,
    "kv_budget_tokens": budget_tokens,
    "block_tokens": BLOCK,
    "n_blocks": n_blocks,
    "paged": row_p,
    "fixed_slot": row_f,
    "stream_ratio": round(row_p["concurrent_streams"]
                          / max(1, row_f["concurrent_streams"]), 2),
    "stream_ratio_bar": 4.0,
    "tokens_per_sec_ratio": round(row_p["tokens_per_sec"]
                                  / max(1e-9, row_f["tokens_per_sec"]), 2),
    "prefix_hit_rate": (round(hit_rate, 3) if hit_rate is not None
                        else None),
    "preemptions": snap_p["preemptions"],
    "attention_path": attention_path(cfg, BLOCK),
    "tick_k": envknob.get_int("DL4J_TPU_SERVE_TICK_K", 1),
    "spec": lowprec.spec_mode() or None,
    "byte_identical": True,
    "span_evidence": {"serve_request": len(reqs),
                      "serve_batch_paged": len(batches)},
    "stat": "one timed pass per pool over the same prompts (greedy), "
            "after a warm pass; latency percentiles from the decoder's "
            "own enqueue-to-completion ledger",
    "note": "equal KV budget: the fixed pool's slots*max_len tokens "
            "re-housed as a block arena (+1 trash block); the stream "
            "win is admission-by-free-blocks + prefix sharing, the "
            "byte-identity asserts are the independence contract",
}))
"""


def bench_serving_decode(streams=16, n_new=24):
    """Paged-KV decode leg (serving/paged.py): concurrent streams,
    aggregate tokens/s, and p50/p99 latency of the block-pool /generate
    plane vs the fixed 4-slot pool at EQUAL KV HBM budget, on a
    shared-system-prompt workload (prefix-cache hit rate and preemption
    count stamped). Asserts greedy outputs byte-identical to the
    single-request path on both pools, and serve.request -> serve.batch
    span evidence through the engine. Subprocess-isolated, CPU-only by
    design — the win is scheduling, not arithmetic."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _SERVING_DECODE_SCRIPT, str(streams),
         str(n_new)], 900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# decode_amortize: multi-token ticks + self-speculative decoding (ISSUE 16
# — serving/speculate.py). CPU-only by design: the claim provable off-chip
# is DISPATCH-COUNT reduction at byte-identical transcripts (the ~5ms
# fixed per-dispatch overhead this amortizes is a chip number —
# BENCH_NOTES; the CPU tokens/s rows are honest CPU arithmetic, and the
# chip single-stream tokens/s row lands at tunnel contact, never faked).
# ---------------------------------------------------------------------------

_DECODE_AMORTIZE_SCRIPT = r"""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

k, n_new = int(sys.argv[1]), int(sys.argv[2])

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
from deeplearning4j_tpu.ops import lowprec
from deeplearning4j_tpu.serving.paged import PagedDecoder
from deeplearning4j_tpu.serving.speculate import SpeculativeDecoder

BLOCK, STREAMS = 8, 4
cfg = TransformerConfig(vocab_size=64, d_model=64, n_layers=2, n_heads=4,
                        d_ff=128, max_len=128, use_flash=False)
lm = TransformerLM(cfg)
n_blocks = STREAMS * cfg.max_len // BLOCK
rng = np.random.default_rng(0)
prompts = [rng.integers(1, 64, 12).astype(np.int32) for _ in range(STREAMS)]
draft = lowprec.draft_lm(lm, "int8")


def run(make):
    # warm pass on a throwaway decoder compiles every program (the jit
    # caches are module-level), then a fresh decoder for the timed pass
    # so tick counters cover exactly the measured work
    for timed in (False, True):
        d = make()
        try:
            t0 = time.perf_counter()
            futs = [d.submit(p, n_new, temperature=0.0, timeout_s=600)
                    for p in prompts]
            outs = [np.asarray(f.result(timeout=600)).tolist()
                    for f in futs]
            wall = time.perf_counter() - t0
            # single-stream pass: the latency shape the dispatch
            # amortization actually targets
            t0 = time.perf_counter()
            solo = np.asarray(d.submit(prompts[0], n_new, temperature=0.0,
                                       timeout_s=600).result(timeout=600))
            solo_wall = time.perf_counter() - t0
            if timed:
                ds = d.dispatch_stats.snapshot()
                return outs, solo.tolist(), {
                    "wall_s": round(wall, 3),
                    "tokens_per_sec": round(STREAMS * n_new / wall, 1),
                    "solo_tokens_per_sec": round(n_new / solo_wall, 1),
                    "decode_ticks": ds["decode_ticks"],
                    "decode_tokens": ds["decode_tokens"],
                    "tokens_per_dispatch": ds["tokens_per_dispatch"],
                }, d.stats.snapshot()
        finally:
            d.stop()


base_o, base_solo, base_row, _ = run(lambda: PagedDecoder(
    lm, block_tokens=BLOCK, n_blocks=n_blocks, tick_k=1))
tick_o, tick_solo, tick_row, _ = run(lambda: PagedDecoder(
    lm, block_tokens=BLOCK, n_blocks=n_blocks, tick_k=k))
spec_o, spec_solo, spec_row, spec_snap = run(lambda: SpeculativeDecoder(
    lm, draft=draft, spec_k=k, block_tokens=BLOCK, n_blocks=n_blocks))

# equal transcripts are the contract the dispatch reduction rides on
assert tick_o == base_o and tick_solo == base_solo
assert spec_o == base_o and spec_solo == base_solo

tick_ratio = round(base_row["decode_ticks"]
                   / max(1, tick_row["decode_ticks"]), 2)
spec_ratio = round(base_row["decode_ticks"]
                   / max(1, spec_row["decode_ticks"]), 2)

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "streams": STREAMS,
    "n_new": n_new,
    "tick_k": k,
    "spec_k": k,
    "draft": "int8",
    "k1": base_row,
    "tick": tick_row,
    "spec": spec_row,
    "tick_dispatch_ratio": tick_ratio,
    "tick_dispatch_ratio_bar": round(k / 2, 2),
    "spec_dispatch_ratio": spec_ratio,
    "acceptance_rate": spec_snap.get("acceptance_rate"),
    "byte_identical": True,
    "stat": "one timed pass per decoder (greedy, pooled then "
            "single-stream) after a warm pass; tick counters from the "
            "decoder's own dispatch ledger",
    "note": "CPU proof is the dispatch-count reduction at equal "
            "transcripts; per-dispatch overhead here is XLA:CPU's, so "
            "tokens/s gains are muted — the ~5ms-amortization chip row "
            "lands at tunnel contact (spec counts draft+verify as 2 "
            "dispatches, honest about the draft's cost)",
}))
"""


def bench_decode_amortize(k=4, n_new=24):
    """Multi-token tick + self-speculative decode leg
    (serving/speculate.py): dispatch-count reduction of the k-scanned
    paged tick and the int8 draft-verify round vs k=1 ticking, at
    byte-identical greedy transcripts (pooled AND single-stream), plus
    honest CPU tokens/s and the acceptance-rate ledger. Subprocess-
    isolated, CPU-only by design — the amortized ~5ms dispatch overhead
    is a chip number; the reduction ratio is backend-invariant."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _DECODE_AMORTIZE_SCRIPT, str(k),
         str(n_new)], 900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# serving_mesh: mesh-sharded decode + prefill/decode disaggregation
# (ISSUE 18 — serving/mesh.py). CPU-only by design: the byte-identity
# claim and the per-device capacity closed form are backend-invariant,
# and the virtual 8-device mesh exercises the real shard_map programs.
# ---------------------------------------------------------------------------

_SERVING_MESH_SCRIPT = r"""
import json, os, sys, time

# the sharded tick needs the virtual multi-device CPU platform BEFORE
# jax initializes (same discipline as tests/conftest.py)
os.environ["XLA_FLAGS"] = " ".join(
    [f for f in os.environ.get("XLA_FLAGS", "").split()
     if "xla_force_host_platform_device_count" not in f]
    + ["--xla_force_host_platform_device_count=8"])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

mesh_d, n_new = int(sys.argv[1]), int(sys.argv[2])

import urllib.request

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
from deeplearning4j_tpu.ops import memory as opsmem
from deeplearning4j_tpu.serving.engine import ServingEngine
from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder
from deeplearning4j_tpu.serving.paged import PagedDecoder
from deeplearning4j_tpu.serving.router import FleetRouter

BLOCK, STREAMS = 8, 4
cfg = TransformerConfig(vocab_size=64, d_model=64, n_layers=2,
                        n_heads=mesh_d, d_ff=128, max_len=128,
                        use_flash=False)
lm = TransformerLM(cfg)
n_blocks = STREAMS * cfg.max_len // BLOCK
rng = np.random.default_rng(0)
prompts = [rng.integers(1, 64, 12).astype(np.int32) for _ in range(STREAMS)]


def run(make):
    # warm pass compiles every program, then a fresh decoder for the
    # timed pass (the decode_amortize methodology)
    for timed in (False, True):
        d = make()
        try:
            t0 = time.perf_counter()
            futs = [d.submit(p, n_new, temperature=0.0, timeout_s=600)
                    for p in prompts]
            futs.append(d.submit(prompts[0], n_new, temperature=0.8,
                                 seed=11, timeout_s=600))
            outs = [np.asarray(f.result(timeout=600)).tolist()
                    for f in futs]
            wall = time.perf_counter() - t0
            if timed:
                return outs, {
                    "wall_s": round(wall, 3),
                    "tokens_per_sec": round(
                        (STREAMS + 1) * n_new / wall, 1),
                }
        finally:
            d.stop()


dense_o, dense_row = run(lambda: PagedDecoder(
    lm, block_tokens=BLOCK, n_blocks=n_blocks))
mesh_o, mesh_row = run(lambda: MeshPagedDecoder(
    lm, devices=mesh_d, block_tokens=BLOCK, n_blocks=n_blocks))
# the contract everything rides on: sharded tick == solo tick, bitwise,
# greedy AND sampled lanes co-resident
assert mesh_o == dense_o

# per-device arena accounting: same per-device HBM budget admits ~d x
# the global blocks (ops/memory closed form, tunnel-free; budget small
# enough that neither side clamps at max_blocks)
blocks_1 = opsmem.kv_arena_blocks(cfg, BLOCK, hbm_gb=0.002)
blocks_d = opsmem.kv_arena_blocks(cfg, BLOCK, hbm_gb=0.002,
                                  devices=mesh_d)

# disaggregation: prefill-role + decode-role engines behind the
# role-aware router; every admitted /generate answered, byte-equal to
# a solo engine
prompt = [int(t) for t in prompts[0]]


def post(url, path, payload):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=600) as r:
        return json.loads(r.read())


solo = ServingEngine(model=lm, kv_block=BLOCK,
                     kv_blocks=n_blocks).start()
try:
    want = post(solo.url, "/generate",
                {"tokens": prompt, "n_new": n_new,
                 "temperature": 0.0})["tokens"][0]
finally:
    solo.stop()

pre = ServingEngine(model=lm, kv_block=BLOCK, kv_blocks=n_blocks,
                    role="prefill").start()
dec = ServingEngine(model=lm, kv_block=BLOCK, kv_blocks=n_blocks,
                    role="decode").start()
router = FleetRouter(replicas={
    "p0": {"url": pre.url, "role": "prefill"},
    "d0": {"url": dec.url, "role": "decode"},
}).start()
n_req, walls = 8, []
try:
    for _ in range(n_req):
        t0 = time.perf_counter()
        got = post(router.url, "/generate",
                   {"tokens": prompt, "n_new": n_new,
                    "temperature": 0.0})["tokens"][0]
        walls.append(time.perf_counter() - t0)
        assert got == want
    rsnap = router.stats.snapshot()
    dsnap = dec.stats.snapshot()
    psnap = pre.stats.snapshot()
finally:
    router.stop()
    pre.stop()
    dec.stop()

walls.sort()
print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "mesh_devices": mesh_d,
    "streams": STREAMS + 1,
    "n_new": n_new,
    "dense": dense_row,
    "mesh": mesh_row,
    "byte_identical": True,
    "kv_blocks_1dev": blocks_1,
    "kv_blocks_mesh": blocks_d,
    "kv_capacity_ratio": round(blocks_d / max(1, blocks_1), 2),
    "disagg_requests": n_req,
    "disagg_failed": n_req - dsnap["completed"],
    "prefill_handoffs": rsnap["prefill_handoffs"],
    "prefill_fallbacks": rsnap["prefill_fallbacks"],
    "prefix_imports": dsnap["prefix_imports"],
    "prefill_decode_tokens": psnap["generated_tokens"],
    "disagg_p50_ms": round(walls[len(walls) // 2] * 1e3, 1),
    "disagg_p99_ms": round(walls[-1] * 1e3, 1),
    "stat": "one timed pass per decoder after a warm pass (4 greedy + "
            "1 sampled co-resident lanes); handoff counters from the "
            "router/serving ledgers",
    "note": "CPU row — the virtual mesh shards over one physical core, "
            "so mesh tokens/s bounds program overhead, not the TP win; "
            "byte-identity and the capacity closed form are the "
            "backend-invariant proof, chip tokens/s lands at tunnel "
            "contact",
}))
"""


def bench_serving_mesh(mesh_devices=4, n_new=16):
    """Mesh-sharded inference leg (serving/mesh.py): sharded-tick ==
    solo-tick byte-identity with greedy + sampled lanes co-resident,
    the per-device KV capacity closed form (capacity scales with the
    mesh at a fixed per-device budget), and the prefill/decode
    disaggregated fleet answering every admitted /generate byte-equal
    to a solo engine (handoff counters as evidence). Subprocess-
    isolated, CPU-only by design — the virtual 8-device mesh runs the
    real shard_map programs; chip tokens/s lands at tunnel contact."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _SERVING_MESH_SCRIPT, str(mesh_devices),
         str(n_new)], 900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# checkpoint_overhead: sync vs async checkpointing cost (resilience/)
# ---------------------------------------------------------------------------

_CKPT_SCRIPT = r"""
import json, os, shutil, sys, tempfile, time

mode, steps = sys.argv[1], int(sys.argv[2])
if mode == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
import numpy as np

from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import CheckpointManager

conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.01)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=784, n_out=256, activation="relu"))
        .layer(1, DenseLayer(n_in=256, n_out=256, activation="relu"))
        .layer(2, OutputLayer(n_in=256, n_out=10, activation="softmax",
                              loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
x = rng.standard_normal((256, 784)).astype(np.float32)
y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 256)]
net.fit(x, y)  # compile outside the timed region
cadence = max(1, steps // 6)
work = tempfile.mkdtemp(prefix="ckpt_bench_")

def run(m):
    mgr, blocks = None, []
    if m != "none":
        mgr = CheckpointManager(tempfile.mkdtemp(dir=work),
                                async_save=(m == "async"), keep_last=2)
    t0 = time.perf_counter()
    for s in range(1, steps + 1):
        net.fit(x, y)
        if mgr is not None and s % cadence == 0:
            tb = time.perf_counter()
            mgr.save(net, step=s, block=(m == "sync"))
            blocks.append(time.perf_counter() - tb)
    if mgr is not None:
        mgr.flush()  # async wall honestly includes the deferred IO drain
    wall = time.perf_counter() - t0
    stats = dict(mgr.stats) if mgr is not None else {}
    if mgr is not None:
        mgr.close()
    return wall, blocks, stats

for m in ("none", "sync", "async"):
    run(m)  # warm fs caches + writer thread

# interleaved reps + per-metric median (the scaling_virtual8 methodology:
# single A-then-B timings swing with background load on this shared host)
reps = [{m: run(m) for m in ("none", "sync", "async")} for _ in range(3)]
med = lambda vals: sorted(vals)[len(vals) // 2]
wall = {m: med([r[m][0] for r in reps]) for m in ("none", "sync", "async")}
block_ms = {
    m: med([1e3 * sum(r[m][1]) / max(1, len(r[m][1])) for r in reps])
    for m in ("sync", "async")
}
sync_stats = reps[-1]["sync"][2]
async_stats = reps[-1]["async"][2]
saves = max(1, sync_stats.get("saves", 1))
shutil.rmtree(work, ignore_errors=True)

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "steps": steps,
    "ckpt_every": cadence,
    "ckpt_mb": round(sync_stats.get("bytes", 0) / saves / 1e6, 2),
    # headline (the satellite's "step-time delta"): how long the train
    # loop STALLS per checkpoint — sync pays serialize+write+fsync
    # inline, async pays the host snapshot only
    "overhead_sync_ms_per_ckpt": round(block_ms["sync"], 2),
    "overhead_async_ms_per_ckpt": round(block_ms["async"], 2),
    "async_lt_sync": block_ms["async"] < block_ms["sync"],
    # secondary: whole-run wall overhead per step (async includes its
    # flush; on this 1-core host CPU-bound zip work cannot truly overlap,
    # so the wall delta narrows while the stall delta stays structural)
    "overhead_sync_ms_per_step": round(
        1e3 * (wall["sync"] - wall["none"]) / steps, 3),
    "overhead_async_ms_per_step": round(
        1e3 * (wall["async"] - wall["none"]) / steps, 3),
    "steps_per_sec_baseline": round(steps / wall["none"], 2),
    "writer_mb_per_sec": round(
        sync_stats.get("bytes", 0) / 1e6 / max(1e-9,
                                               sync_stats.get("write_s", 0)),
        1),
    "async_saves": async_stats.get("saves", 0),
    "async_skipped_busy": async_stats.get("skipped_busy", 0),
    "stat": "per-metric median of 3 interleaved none/sync/async reps",
}))
"""


def bench_checkpoint_overhead(steps=30):
    """Resilience leg (deeplearning4j_tpu/resilience/): the train-loop
    cost of checkpointing — per-checkpoint stall (sync = inline
    serialize+write+fsync, async = host snapshot only), whole-run wall
    overhead, checkpoint size and writer throughput. Subprocess-isolated
    like dispatch_overhead; honest CPU row (backend labeled) when the
    accelerator is unreachable — the sync-vs-async stall structure exists
    on every backend; on chip the snapshot adds the device->host
    readback, which this leg then measures for real."""
    probe_err = _probe_device(timeout_s=90.0)
    mode = "cpu" if probe_err else "auto"
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _CKPT_SCRIPT, mode, str(steps)], 900)
    if parsed is None:
        return {"error": err}
    if probe_err:
        parsed["note"] = (f"accelerator unreachable ({probe_err}); CPU "
                          "checkpoint numbers — the async-vs-sync stall "
                          "structure carries over, the device->host "
                          "snapshot cost needs the chip")
    return parsed


# ---------------------------------------------------------------------------
# input_pipeline: naive single-thread feed vs the overlapped InputPipeline
# (deeplearning4j_tpu/etl/ — ISSUE 5). CPU-measurable by design: ingest
# throughput is host-side work, so this proof never needs the tunnel.
# ---------------------------------------------------------------------------

_INPUT_PIPELINE_SCRIPT = r"""
import json, os, shutil, sys, tempfile, time

mode, batches = sys.argv[1], int(sys.argv[2])
if mode == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
import numpy as np

from deeplearning4j_tpu.datasets.records import (CSVRecordReader,
                                                 RecordReaderDataSetIterator)
from deeplearning4j_tpu.etl import InputPipeline, NormalizerStandardize
from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

# ETL-heavy regime ON PURPOSE: the leg measures the INPUT plane, so the
# per-batch host work (CSV decode + one-hot + normalize) must be a real
# fraction of the step — exactly the regime where fit_iterator starves
# without staging. The model is a small MLP; the data is a real on-disk
# CSV parsed for real every pass.
F, C, batch = 96, 10, 256
work = tempfile.mkdtemp(prefix="etl_bench_")
path = os.path.join(work, "data.csv")
rng = np.random.default_rng(0)
with open(path, "w") as f:
    for _ in range(batch * batches):
        f.write(",".join(f"{v:.6f}" for v in rng.standard_normal(F))
                + f",{int(rng.integers(0, C))}\n")

conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.01)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=F, n_out=32, activation="relu"))
        .layer(1, OutputLayer(n_in=32, n_out=C, activation="softmax",
                              loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
norm = NormalizerStandardize().fit(RecordReaderDataSetIterator(
    CSVRecordReader(path), batch, label_index=F, num_possible_labels=C))
workers, prefetch = 2, 4


def run_naive():
    # today's single-thread feed: reader -> per-record float() assembly
    # -> normalizer -> fit, ALL on the training thread
    t0 = time.perf_counter()
    it = RecordReaderDataSetIterator(CSVRecordReader(path), batch,
                                     label_index=F, num_possible_labels=C)
    for ds in it:
        norm.transform(ds)
        net.fit(ds.features, ds.labels)
    np.asarray(net._score_dev)  # true data-dependent completion fence
    return time.perf_counter() - t0, None


def run_pipeline():
    t0 = time.perf_counter()
    pipe = InputPipeline.from_reader(
        CSVRecordReader(path), batch, label_index=F, num_possible_labels=C,
        normalizer=norm, workers=workers, prefetch=prefetch)
    for ds in pipe:
        net.fit(ds.features, ds.labels)
    np.asarray(net._score_dev)
    return time.perf_counter() - t0, pipe.pipeline_stats.snapshot()


run_naive(); run_pipeline()  # compile + warm page cache + threads
# interleaved pair reps, median-of-ratios (the serving_throughput
# methodology: single A-then-B timings swing with background load)
reps = [(run_naive(), run_pipeline()) for _ in range(3)]
ratios = sorted(((n[0] / p[0]), n, p) for n, p in reps)
ratio, n_med, p_med = ratios[len(ratios) // 2]
samples = batch * batches
stats = p_med[1]
shutil.rmtree(work, ignore_errors=True)

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "rows": samples, "features": F, "batch": batch,
    "workers": workers, "prefetch": prefetch,
    "naive_samples_per_sec": round(samples / n_med[0], 1),
    "pipeline_samples_per_sec": round(samples / p_med[0], 1),
    "pipeline_speedup": round(ratio, 3),
    "speedup_reps": [round(r[0], 3) for r in ratios],
    # the stall ledger (etl/stats.py): how much of the pass the TRAINING
    # thread still waited on input, and how long producers blocked on
    # full buffers — the two numbers that say who the bottleneck is
    "stall_fraction": stats["stall_fraction"],
    "producer_stall_seconds": stats["producer_stall_seconds"],
    "pipeline_batches_per_sec": stats["batches_per_sec"],
    "pipeline_mb_per_sec": stats["mb_per_sec"],
    "stat": "median of 3 interleaved naive/pipeline pair ratios; "
            "committed sps are the median pair's own halves",
    "note": "1-core host: the win is the pipeline's vectorized off-thread "
            "assembly (byte-identical C-level parse), not overlap — "
            "parse/compute overlap needs a second core and is structural "
            "on real hosts; stall_fraction shows the feed is still the "
            "bottleneck at this ETL weight",
}))
"""


def bench_input_pipeline(batches=20):
    """ETL subsystem leg (deeplearning4j_tpu/etl/): samples/sec of the
    naive single-thread feed (reader -> per-record assembly -> fit on ONE
    thread — the pre-ISSUE-5 ingest plane) vs the overlapped
    InputPipeline (parallel vectorized assembly + reorder + staged
    device_put), plus the pipeline_stats stall ledger. Subprocess-
    isolated like dispatch_overhead; honest CPU row (backend labeled)
    when the accelerator is unreachable — ingest is host-side work, so
    the number is real on every backend."""
    probe_err = _probe_device(timeout_s=90.0)
    mode = "cpu" if probe_err else "auto"
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _INPUT_PIPELINE_SCRIPT, mode, str(batches)],
        900)
    if parsed is None:
        return {"error": err}
    if probe_err:
        parsed["note"] = (f"accelerator unreachable ({probe_err}); CPU "
                          "ingest numbers — host-side feed throughput "
                          "is backend-independent; on chip the step "
                          "compute leaves the host core entirely free "
                          "for the workers. " + parsed.get("note", ""))
    return parsed


# ---------------------------------------------------------------------------
# elastic_dp: averaging-round overhead of the elastic fleet runtime
# (deeplearning4j_tpu/parallel/fleet.py — ISSUE 6). CPU-measurable by
# design: the fleet's control plane (membership, split dispatch, reclaim,
# host-side averaging) is host work, so this proof never needs the tunnel.
# ---------------------------------------------------------------------------

_ELASTIC_DP_SCRIPT = r"""
import json, sys, time

mode, rounds, workers = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
if mode == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
import numpy as np

from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.fleet import ElasticParameterAveragingTrainer
from deeplearning4j_tpu.resilience import FleetChaos, FleetChaosConfig

F, H, C = 32, 64, 10
# the faulted run shrinks to workers-1 members: the round batch must
# divide BOTH sizes (the loud-ValueError divisibility contract)
gb = workers * (workers - 1) * 4 if workers > 1 else 16


def build():
    conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
            .list()
            .layer(0, DenseLayer(n_in=F, n_out=H, activation="tanh"))
            .layer(1, OutputLayer(n_in=H, n_out=C, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf)


rng = np.random.default_rng(0)
x = rng.standard_normal(((rounds + 2) * gb, F)).astype(np.float32)
y = np.eye(C, dtype=np.float32)[rng.integers(0, C, (rounds + 2) * gb)]
batch = lambda r: (x[r * gb:(r + 1) * gb], y[r * gb:(r + 1) * gb])

# serial big-batch baseline (the denominator: what a round costs with no
# fleet control plane at all)
serial = build()
serial.fit(*batch(0)); serial.fit(*batch(1))  # compile + warm
t0 = time.perf_counter()
for r in range(rounds):
    serial.fit(*batch(r))
np.asarray(serial._score_dev)
serial_s = time.perf_counter() - t0

# elastic fleet, steady membership
fleet = ElasticParameterAveragingTrainer(build(), num_workers=workers,
                                         averaging_frequency=1,
                                         heartbeat_s=1.0)
fleet.fit(*batch(0)); fleet.fit(*batch(1))  # compile + warm
t0 = time.perf_counter()
for r in range(rounds):
    fleet.fit(*batch(r))
fleet_s = time.perf_counter() - t0
fleet.close()

# same run WITH one worker lost mid-round (detection + reclaim +
# re-execution + re-formed smaller rounds afterwards)
chaos = FleetChaos(FleetChaosConfig(kill_split={"round": 3, "split": 1}))
faulted = ElasticParameterAveragingTrainer(build(), num_workers=workers,
                                           averaging_frequency=1,
                                           heartbeat_s=0.5, chaos=chaos)
faulted.fit(*batch(0)); faulted.fit(*batch(1))
t0 = time.perf_counter()
for r in range(rounds):
    faulted.fit(*batch(r))
faulted_s = time.perf_counter() - t0
stats = dict(faulted.resilience_stats)
faulted.close()

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "workers": workers, "rounds": rounds, "global_batch": gb,
    "serial_rounds_per_sec": round(rounds / serial_s, 2),
    "fleet_rounds_per_sec": round(rounds / fleet_s, 2),
    # headline: what the elastic control plane (membership poll, split
    # dispatch over the tracker, host-side averaging) costs per round
    "fleet_overhead_ms_per_round": round(
        1e3 * (fleet_s - serial_s) / rounds, 2),
    "faulted_rounds_per_sec": round(rounds / faulted_s, 2),
    # one-time price of losing a worker: heartbeat-expiry detection +
    # split reclaim + re-execution, amortized into the faulted run
    "worker_loss_extra_s": round(faulted_s - fleet_s, 3),
    "reclaims": stats["reclaims"],
    "membership_epochs": stats["epoch"],
    "stat": "single timed run per condition after a 2-round warm "
            "(control-plane overhead, not chip throughput)",
    "note": "1-core host: worker threads serialize on the core, so "
            "fleet vs serial also pays thread scheduling; on a real pod "
            "each member owns its chip and the overhead is the control "
            "plane alone",
}))
"""


def bench_elastic_dp(rounds=10, workers=4):
    """Elastic fleet leg (parallel/fleet.py): averaging-round overhead of
    the fleet control plane at N workers vs the serial big-batch round,
    and the one-time cost of losing a worker mid-round (heartbeat
    detection + split reclaim + re-formed rounds). Subprocess-isolated;
    honest CPU row when the accelerator is unreachable — the control
    plane is host-side work on every backend."""
    probe_err = _probe_device(timeout_s=90.0)
    mode = "cpu" if probe_err else "auto"
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _ELASTIC_DP_SCRIPT, mode, str(rounds),
         str(workers)], 900)
    if parsed is None:
        return {"error": err}
    if probe_err:
        parsed["note"] = (f"accelerator unreachable ({probe_err}); CPU "
                          "control-plane numbers — membership/reclaim/"
                          "averaging costs are host-side on every "
                          "backend. " + parsed.get("note", ""))
    return parsed


# ---------------------------------------------------------------------------
# online_loop: the full online-learning cycle (ISSUE 14 —
# deeplearning4j_tpu/online/): streaming ingest -> continuous fit ->
# candidate export -> shadow stage -> gated promotion, timed per phase,
# plus the shadow-mirror cost on the /predict answer path (bar < 3%).
# CPU-only by design: every phase is host-side orchestration (stream
# buffering, checkpoint commits, registry lifecycle, the offer-path
# stride) around tiny-model dispatches that exist unchanged on every
# backend.
# ---------------------------------------------------------------------------

_ONLINE_LOOP_SCRIPT = r"""
import json, os, sys, tempfile, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from deeplearning4j_tpu.datasets.iterator import DataSet
from deeplearning4j_tpu.etl.normalize import NormalizerStandardize
from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.online import (ContinuousTrainer, DriftMonitor,
                                       ShadowPromoter, StreamSource)
from deeplearning4j_tpu.resilience import CheckpointManager
from deeplearning4j_tpu.serving.engine import ServingEngine

batches, predicts = int(sys.argv[1]), int(sys.argv[2])
F, B, C = 16, 32, 3
rng = np.random.default_rng(0)
X = rng.standard_normal((batches * B, F)).astype(np.float32)
Y = np.eye(C, dtype=np.float32)[rng.integers(0, C, batches * B)]
norm = NormalizerStandardize().fit(X)


def net(seed):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
            .updater("adam").list()
            .layer(0, DenseLayer(n_in=F, n_out=32, activation="tanh"))
            .layer(1, OutputLayer(n_in=32, n_out=C, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf)


tmp = tempfile.mkdtemp(prefix="bench_online_")
cand_zip = os.path.join(tmp, "candidate.zip")

# -- phase 1: ingest + fit round + candidate export ------------------------
mgr = CheckpointManager(os.path.join(tmp, "ckpt"), every_steps=0,
                        keep_last=2)
src = StreamSource(watermark=batches + 1, idle_s=0.05)
drift = DriftMonitor(norm, min_rows=B)
ct = ContinuousTrainer(net(7), src, manager=mgr, drift=drift,
                       normalizer=norm, workers=1, shard=None,
                       candidate_path=cand_zip, snapshot_rounds=1,
                       handle_signals=False)
ct.fit_round()  # warm round: jit compiles + checkpoint machinery
for i in range(batches):
    src.push(DataSet(X[i * B:(i + 1) * B], Y[i * B:(i + 1) * B]))
t0 = time.perf_counter()
losses = ct.fit_round()
fit_s = time.perf_counter() - t0
assert len(losses) == batches and os.path.exists(cand_zip)
drift_verdict = drift.check()["verdict"]
src.close()
mgr.close()

# -- phase 2/3: serve a prior default, stage the candidate, measure the
# shadow-mirror cost on the answered /predict path ------------------------
eng = ServingEngine(model=net(3).init(), input_shape=(F,), max_batch=16)
rows = X[:8]
for _ in range(4):
    eng.predict(rows)  # warm the primary's ladder

promoter = ShadowPromoter(eng, drift=drift, min_mirrored=1, fraction=1.0)
t0 = time.perf_counter()
rec = promoter.stage("candidate", model_path=cand_zip, input_shape=(F,),
                     max_batch=16)
stage_s = time.perf_counter() - t0
mirror = promoter.mirror
eng.predict(rows); mirror.wait_idle()  # warm the candidate dispatch too


def median_predict_s(mirror_on):
    # interleave-friendly single pass; the mirror worker is drained
    # OUTSIDE the timer after every predict (1-core host: leaving the
    # shadow dispatch in flight would time core contention, not the
    # offer-path stride the client actually pays)
    ts = []
    for _ in range(predicts):
        t0 = time.perf_counter()
        eng.predict(rows)
        ts.append(time.perf_counter() - t0)
        if mirror_on:
            mirror.wait_idle()
    ts.sort()
    return ts[len(ts) // 2]


pairs = []
for _ in range(3):
    eng.detach_shadow(mirror)
    off = median_predict_s(False)
    eng.attach_shadow(mirror)
    on = median_predict_s(True)
    pairs.append((off, on))
ratios = sorted(on / off for off, on in pairs)
ratio = ratios[len(ratios) // 2]
mirror.wait_idle()

# -- phase 4: gated promotion (atomic default swap) ------------------------
t0 = time.perf_counter()
report = promoter.promote()
promote_s = time.perf_counter() - t0
assert eng.registry.default().key == rec.key
snap = promoter.online_stats.snapshot()
eng.stop(drain=False)

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "stream_batches": batches, "batch_rows": B, "features": F,
    "ingest_fit_round_s": round(fit_s, 4),
    "batches_per_sec": round(batches / fit_s, 2),
    "stage_s": round(stage_s, 4),
    "promote_s": round(promote_s, 4),
    "cycle_s": round(fit_s + stage_s + promote_s, 4),
    "drift_verdict": drift_verdict,
    "mirrored": report["mirrored"],
    "agreement": report["agreement"],
    "prior_default": report["prior_default"],
    "shadow_overhead_pct": round((ratio - 1.0) * 100.0, 2),
    "shadow_overhead_reps_pct": [round((r - 1.0) * 100.0, 2)
                                 for r in ratios],
    "overhead_bar_pct": 3.0,
    "overhead_ok": bool(ratio - 1.0 < 0.03),
    "promotions": snap["promotions"],
    "stat": "single timed pass per phase after a warm round; shadow "
            "overhead = median of 3 interleaved mirror-off/on "
            "median-predict ratios, mirror drained outside the timer",
    "note": "1-core host: phase times are host-side orchestration around "
            "tiny CPU dispatches; the offer-path overhead fraction "
            "upper-bounds the on-chip one (chip dispatches are ~5ms)",
}))
"""


def bench_online_loop(batches=12, predicts=24):
    """Online learning loop leg (online/): end-to-end cycle time of
    streaming ingest -> fit round -> candidate export -> shadow stage ->
    gated promotion, and the shadow-mirror overhead on the answered
    /predict path (bar < 3% — the mirror must be invisible to clients in
    time as well as bytes). Subprocess-isolated, CPU-only by design —
    the loop is host-side orchestration on every backend."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _ONLINE_LOOP_SCRIPT, str(batches),
         str(predicts)], 900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# lowprec: the low-precision plane (ISSUE 15 — ops/lowprec.py +
# etl/calibrate.py). CPU-only leg: every row is MEASURED on the XLA:CPU
# program with an honest backend label; the chip rows (real HBM halving,
# int8 MXU throughput) are ARMED for the next tunnel contact, never faked.
# ---------------------------------------------------------------------------

_LOWPREC_SCRIPT = r"""
import json, os, sys, time
steps, reps = int(sys.argv[1]), int(sys.argv[2])
os.environ.pop("DL4J_TPU_BF16", None)
os.environ.pop("DL4J_TPU_QUANT", None)
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

import deeplearning4j_tpu.models.transformer as tfm
from deeplearning4j_tpu.ops import memory as mem

# ---- bf16 train step: measured CPU AOT rows + the dtype-aware analytic
# accounting. XLA:CPU float-normalizes bf16 compute back to f32 (the
# flash-leg class of CPU-vs-chip program differences), so the MEASURED
# CPU temp bytes do NOT shrink — reported honestly; the byte claim the
# chip cashes is the analytic activations estimate (ib 2 vs 4), which is
# what transformer_preflight budgets HBM with.
d, L, heads, seq, batch, vocab = 256, 4, 4, 128, 8, 4096
cfg = tfm.TransformerConfig(
    vocab_size=vocab, d_model=d, n_layers=L, n_heads=heads, d_ff=4 * d,
    max_len=seq, learning_rate=1e-4)
rng = np.random.default_rng(0)
toks = rng.integers(0, vocab, (batch, seq + 1))
x = jnp.asarray(toks[:, :-1], jnp.int32)
y = jnp.asarray(toks[:, 1:], jnp.int32)

train = {}
for tmode in ("f32", "bf16"):
    if tmode == "bf16":
        os.environ["DL4J_TPU_BF16"] = "1"
    step = tfm.make_train_step(cfg)
    # fresh lambdas: jax.eval_shape caches on (fun identity, avals), and
    # init_opt_state's tree CHANGES with the env knob
    p_sh = jax.eval_shape(lambda: tfm.init_params(cfg))
    o_sh = jax.eval_shape(lambda p: tfm.init_opt_state(p), p_sh)
    compiled = step.lower(p_sh, o_sh, x, y).compile()
    a = mem.analyze_compiled(compiled)
    _, pre = mem.transformer_preflight(cfg, batch, hbm_gb=16.0,
                                       measure_aot=False)
    params = tfm.init_params(cfg)
    opt = tfm.init_opt_state(params)
    params, opt, loss = compiled(params, opt, x, y)  # warm
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss = compiled(params, opt, x, y)
    final = float(loss)  # data-dependent host readback = the fence
    train[tmode] = {
        "measured_temp_bytes": None if a is None else a["temp_bytes"],
        "measured_peak_bytes": None if a is None else a["peak_bytes"],
        "analytic_act_gb": pre["activations_gb_est"],
        "train_dtype": pre["train_dtype"],
        "step_ms": round((time.perf_counter() - t0) / steps * 1000, 1),
        "loss": round(final, 4),
    }

def ratio(num, den):
    return None if not num or not den else round(num / den, 2)

# ---- calibrated int8 serving: a dense stack big enough that the matmul
# dominates; value delta measured on the SAME batch the rps rows time
from deeplearning4j_tpu.etl.calibrate import QuantCalibrator
from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops import lowprec

F, H, C, b8 = 256, 512, 10, 256
srng = np.random.default_rng(1)
SX = srng.standard_normal((512, F)).astype(np.float32)
SY = np.eye(C, dtype=np.float32)[srng.integers(0, C, 512)]
conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.01)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=F, n_out=H, activation="relu"))
        .layer(1, DenseLayer(n_in=H, n_out=H, activation="relu"))
        .layer(2, OutputLayer(n_in=H, n_out=C, activation="softmax",
                              loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
for i in range(0, 512, 128):
    net.fit(SX[i:i + 128], SY[i:i + 128])
spec = QuantCalibrator().fit(net, SX[:256]).spec(net)
qnet = lowprec.QuantizedNet(net, spec)
xb = SX[:b8]
delta = float(np.max(np.abs(np.asarray(net.output(xb))
                            - np.asarray(qnet.output(xb)))))
serving = {"delta": round(delta, 6),
           "gate_bar": lowprec.quant_max_delta(),
           "quantized_layers": qnet.quantized_layers()}
for pname, m in (("f32", net), ("int8", qnet)):
    np.asarray(m.output(xb))  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(m.output(xb))
    dt = time.perf_counter() - t0
    serving[pname + "_rps"] = round(reps * b8 / dt, 1)
serving["int8_speedup"] = ratio(serving["int8_rps"], serving["f32_rps"])

# ---- bf16 KV arena: pure accounting (AOT by construction, tunnel-free)
kcfg = tfm.TransformerConfig(vocab_size=vocab, d_model=512, n_layers=8,
                             n_heads=8, d_ff=2048, max_len=1024)
kv = {
    "block_bytes_f32": mem.kv_block_bytes(kcfg, 16, dtype=jnp.float32),
    "block_bytes_bf16": mem.kv_block_bytes(kcfg, 16, dtype=jnp.bfloat16),
    "blocks_f32": mem.kv_arena_blocks(kcfg, 16, hbm_gb=2.0,
                                      dtype=jnp.float32),
    "blocks_bf16": mem.kv_arena_blocks(kcfg, 16, hbm_gb=2.0,
                                       dtype=jnp.bfloat16),
}
kv["tokens_ratio"] = ratio(kv["blocks_bf16"], kv["blocks_f32"])

out = {
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "train_config": f"d{d} L{L} h{heads} b{batch} s{seq} v{vocab}",
    "timed_steps": steps,
    "train": train,
    # the accounting-plane headline: activation bytes halve under bf16
    "analytic_act_reduction_x": ratio(train["f32"]["analytic_act_gb"],
                                      train["bf16"]["analytic_act_gb"]),
    # the honest CPU fact: XLA:CPU float-normalization keeps f32 buffers
    "measured_cpu_temp_ratio_x": ratio(
        train["f32"]["measured_temp_bytes"],
        train["bf16"]["measured_temp_bytes"]),
    "bf16_step_overhead_cpu": ratio(train["bf16"]["step_ms"],
                                    train["f32"]["step_ms"]),
    "serving_int8": serving,
    "kv_arena": kv,
    "note": ("CPU rows measure the XLA:CPU program (bf16 is "
             "float-normalized to f32 and int8 dot_general has no MXU): "
             "the byte/throughput wins are chip claims — the HBM AOT row "
             "and the int8 rps row land at the next tunnel contact; the "
             "delta/equivalence rows are backend-independent facts"),
    "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
}
tmp = "LOWPREC_BENCH.json.tmp"
with open(tmp, "w") as f:
    json.dump(out, f, indent=1, sort_keys=True)
os.replace(tmp, "LOWPREC_BENCH.json")
print(json.dumps(out))
"""


def bench_lowprec(steps=2, reps=20):
    """Low-precision plane leg (ISSUE 15): (a) the f32-vs-bf16 train
    step — measured CPU AOT bytes (honest: XLA:CPU float-normalizes
    bf16, no byte win on this substrate) beside the dtype-aware analytic
    accounting whose activation estimate halves (the claim the chip
    budgetes HBM with); (b) calibrated int8 serving rps vs f32 with the
    MEASURED accuracy delta against the gate bar; (c) the bf16 KV-arena
    sizing (2x tokens per budget). Subprocess-isolated, CPU-only by
    design; writes LOWPREC_BENCH.json beside the bench artifact."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _LOWPREC_SCRIPT, str(steps), str(reps)], 900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# retrieval: the embedding & ANN serving plane (ISSUE 17 —
# deeplearning4j_tpu/retrieval/). CPU-only leg: recall and the
# IVF-vs-exact qps win are MEASURED on XLA:CPU at the serving batch
# size (small batches — the /search latency regime, where the probe's
# candidate traffic beats streaming the whole corpus per batch); the
# chip row (MXU-batched exact scan, DMA'd block gathers) is ARMED for
# the next tunnel contact, never faked.
# ---------------------------------------------------------------------------

_RETRIEVAL_SCRIPT = r"""
import json, sys, threading, time
rows, queries = int(sys.argv[1]), int(sys.argv[2])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.retrieval import VectorStore
from deeplearning4j_tpu.serving.engine import ServingEngine

dim, B, k, nprobe = 64, 8, 10, 8
K = max(16, int(np.sqrt(rows)))
rng = np.random.default_rng(0)
# clustered synthetic corpus — the regime IVF probing exists for
# (uniform random vectors would make any recall number meaningless)
centers = rng.normal(size=(K, dim)).astype(np.float32)
assign = rng.integers(0, K, size=rows)
corpus = (centers[assign]
          + 0.05 * rng.normal(size=(rows, dim))).astype(np.float32)
q = (centers[rng.integers(0, K, queries)]
     + 0.05 * rng.normal(size=(queries, dim))).astype(np.float32)

# -- phase 1: build + publish (kmeans cost measured, not hidden) ----------
ex = VectorStore(dim, capacity=rows + 1, kind="exact", name="exact")
iv = VectorStore(dim, capacity=rows + 1, kind="ivf", clusters=K,
                 nprobe=nprobe, ivf_iters=5, name="ivf")
t0 = time.perf_counter()
ex.upsert(np.arange(rows), corpus)
ex.publish()
exact_build_s = time.perf_counter() - t0
t0 = time.perf_counter()
iv.upsert(np.arange(rows), corpus)
iv.publish()
ivf_build_s = time.perf_counter() - t0

recall = iv.probe_recall(q[:64], k=k)

# -- phase 2: qps at the serving batch size (median of reps) --------------
for s in (ex, iv):
    s.search(q[:B], k=k)  # warm the bucket's program


def qps(store, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        done = 0
        for i in range(0, queries, B):
            store.search(q[i:i + B], k=k)
            done += min(B, queries - i)
        ts.append(done / (time.perf_counter() - t0))
    ts.sort()
    return ts[len(ts) // 2]


exact_qps = qps(ex)
ivf_qps = qps(iv)

# -- phase 3: /embed latency through the engine (batcher path) ------------
F, H = 16, dim
conf = (NeuralNetConfiguration.builder().seed(7).list()
        .layer(0, DenseLayer(n_in=F, n_out=H, activation="relu"))
        .layer(1, OutputLayer(n_in=H, n_out=4, activation="softmax",
                              loss_function="mcxent"))
        .build())
net = MultiLayerNetwork(conf)
net.init()
eng = ServingEngine(model=net, input_shape=(F,)).start()
xs = rng.normal(size=(128, F)).astype(np.float32)
for i in range(4):
    eng.embed(xs[i:i + 1])  # warm
lat = []
for i in range(128):
    t0 = time.perf_counter()
    eng.embed(xs[i:i + 1])
    lat.append(time.perf_counter() - t0)
lat.sort()
p50_ms = lat[len(lat) // 2] * 1e3
p99_ms = lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3
eng.stop(drain=False)

# -- phase 4: generation swaps under live search load ---------------------
stop = threading.Event()
answered = [0]
failed = [0]


def searcher():
    while not stop.is_set():
        try:
            ids, _ = ex.search(q[:B], k=k)
            assert ids.shape == (B, k)
            answered[0] += 1
        except Exception:
            failed[0] += 1
            return


threads = [threading.Thread(target=searcher) for _ in range(2)]
for t in threads:
    t.start()
swaps = 12
t0 = time.perf_counter()
for i in range(swaps):
    ex.upsert(np.arange(rows - 64, rows), corpus[rows - 64:])
    ex.publish()
swap_s = (time.perf_counter() - t0) / swaps
stop.set()
for t in threads:
    t.join()

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "rows": rows, "dim": dim, "clusters": K, "nprobe": nprobe,
    "query_batch": B, "k": k,
    "recall_at_10": round(recall, 4), "recall_bar": 0.95,
    "recall_ok": bool(recall >= 0.95),
    "exact_qps": round(exact_qps, 1), "ivf_qps": round(ivf_qps, 1),
    "ivf_speedup": round(ivf_qps / exact_qps, 2), "speedup_bar": 2.0,
    "speedup_ok": bool(ivf_qps >= 2.0 * exact_qps),
    "exact_build_s": round(exact_build_s, 2),
    "ivf_build_s": round(ivf_build_s, 2),
    "embed_p50_ms": round(p50_ms, 3), "embed_p99_ms": round(p99_ms, 3),
    "swap_publish_s": round(swap_s, 3),
    "swap_searches_answered": answered[0],
    "swap_searches_failed": failed[0],
    "stat": "qps = median of 5 full query sweeps at batch %d after one "
            "warm call; recall measured vs the exact oracle on the SAME "
            "snapshot; swap phase overlaps %d publishes with 2 live "
            "search threads" % (B, swaps),
    "note": "CPU substrate: the IVF win is the serving-batch regime "
            "(per-query candidate traffic < streaming the corpus once "
            "per batch); the chip row (MXU exact scan vs DMA block "
            "gathers) lands at tunnel contact",
}))
"""


def bench_retrieval(rows=65536, queries=64):
    """Retrieval plane leg (ISSUE 17): MEASURED IVF recall@10 against
    the exact oracle on the same published snapshot (bar 0.95), the
    IVF-vs-exact qps win at the serving batch size (bar 2x), /embed
    p50/p99 through the engine batcher, and zero-failed-searches across
    generation swaps under live load. Subprocess-isolated, CPU-only by
    design — the chip row is armed for tunnel contact."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _RETRIEVAL_SCRIPT, str(rows), str(queries)],
        900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# obs_overhead: per-step cost of the observability plane (ISSUE 7 —
# deeplearning4j_tpu/obs/). CPU-measurable by design: spans/journal/
# registry are HOST-side events only (never a device sync), so the
# overhead they add to a step is host work on every backend.
# ---------------------------------------------------------------------------

_OBS_SCRIPT = r"""
import json, os, sys, tempfile, time, urllib.request

steps = int(sys.argv[1])
os.environ["DL4J_TPU_OBS"] = "0"
os.environ["DL4J_TPU_OBS_JOURNAL"] = os.path.join(
    tempfile.mkdtemp(prefix="obs_bench_"), "journal.jsonl")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from deeplearning4j_tpu.nn.conf import (DenseLayer, NeuralNetConfiguration,
                                        OutputLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu import obs

F, C, batch = 128, 10, 128
rng = np.random.default_rng(0)
x = rng.standard_normal((batch, F)).astype(np.float32)
y = np.eye(C, dtype=np.float32)[rng.integers(0, C, batch)]


def build():
    conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.01)
            .updater("adam").list()
            .layer(0, DenseLayer(n_in=F, n_out=256, activation="relu"))
            .layer(1, DenseLayer(n_in=256, n_out=128, activation="relu"))
            .layer(2, OutputLayer(n_in=128, n_out=C, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def timed(net):
    # warm: compile + first dispatches outside the timed window (the
    # overhead question is about the steady state, not the retrace)
    for _ in range(5):
        net.fit(x, y)
    np.asarray(net._score_dev)
    t0 = time.perf_counter()
    for _ in range(steps):
        net.fit(x, y)
    np.asarray(net._score_dev)  # data-dependent completion fence
    return (time.perf_counter() - t0) / steps


# interleaved off/on pairs on FRESH nets, median-of-ratios (the
# input_pipeline methodology: single A-then-B swings with load). The env
# flips between halves of a pair — obs_enabled() reads it per span.
pairs = []
net_on = None
for _ in range(5):
    os.environ["DL4J_TPU_OBS"] = "0"
    t_off = timed(build())
    os.environ["DL4J_TPU_OBS"] = "1"
    # keep the last obs-on net ALIVE through the scrape below: the
    # registry holds ledger owners weakly, so a dead net's ledgers are
    # pruned and the families evidence would always read empty
    net_on = build()
    t_on = timed(net_on)
    pairs.append((t_off, t_on))
os.environ["DL4J_TPU_OBS"] = "1"
ratios = sorted((on / off, off, on) for off, on in pairs)
ratio, t_off, t_on = ratios[len(ratios) // 2]

# evidence the plane actually ran: spans in the ring, a scrapeable
# exporter, a non-empty journal
span_count = len(obs.tracer().spans("dispatch.train_step"))
exp = obs.MetricsExporter().start()
with urllib.request.urlopen(exp.url + "/metrics", timeout=10) as r:
    page = r.read().decode()
exp.stop()
jpath = obs.default_journal().flush(fsync=True)
# flush() returns None when the journal path is unwritable — fail the
# leg with the REAL cause, not a TypeError out of load(None)
assert jpath, "journal flush failed (journal path unwritable?)"
journal_events = len(obs.FlightRecorder.load(jpath))

print(json.dumps({
    "backend": jax.default_backend(),
    "device": str(jax.devices()[0]),
    "data": "synthetic",
    "steps": steps, "batch": batch,
    "step_ms_obs_off": round(t_off * 1e3, 4),
    "step_ms_obs_on": round(t_on * 1e3, 4),
    "overhead_pct": round((ratio - 1.0) * 100.0, 2),
    "overhead_reps_pct": [round((r[0] - 1.0) * 100.0, 2) for r in ratios],
    "spans_recorded": span_count,
    "prometheus_sample_lines": sum(
        1 for line in page.splitlines() if line and not line.startswith("#")),
    "ledger_families_in_scrape": sorted({
        line.split("{")[0].split(" ")[0].split("_")[1]
        for line in page.splitlines()
        if line.startswith("dl4j_") and not line.startswith("dl4j_span")}),
    "journal_events": journal_events,
    "stat": "median of 5 interleaved off/on pair ratios, fresh net per "
            "half, steady-state steps only (5-step warmup excluded)",
    "note": "spans are host-side events only (no device sync added); "
            "CPU row — host-side span cost is a LARGER fraction of a "
            "fast CPU step than of a real TPU step, so this bounds the "
            "on-chip overhead from above",
}))
"""


def bench_obs_overhead(steps=150):
    """Observability leg (deeplearning4j_tpu/obs/): per-step wall cost of
    DL4J_TPU_OBS=1 (span tracer + journal + registry histograms) vs the
    default-off baseline on the MLP hot path, plus proof the plane ran
    (span counts, a live Prometheus scrape, journal events).
    Subprocess-isolated; CPU-only by design — spans are host-side, so
    the CPU number upper-bounds the on-chip fraction (acceptance bar:
    < 5% step-time delta)."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _OBS_SCRIPT, str(steps)], 900)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# CPU-for-CPU baseline: OUR framework on jax-CPU vs the torch-CPU rows
# (VERDICT r5 ask #2 — vs_baseline must not be hostage to the tunnel)
# ---------------------------------------------------------------------------

_LENET_CPU_SCRIPT = r"""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from deeplearning4j_tpu.datasets.fetchers import load_mnist_info
from deeplearning4j_tpu.models.lenet import build_lenet5

batch, steps = int(sys.argv[1]), int(sys.argv[2])
net = build_lenet5()
x, y, prov = load_mnist_info(train=True, num_examples=batch)
xb, yb = jax.device_put(x), jax.device_put(y)

out = None
for _ in range(2):
    out = net.fit(xb, yb)
np.asarray(out)
t0 = time.perf_counter()
for _ in range(steps):
    out = net.fit(xb, yb)
np.asarray(out)  # host readback with a true data dependency
per_step = batch * steps / (time.perf_counter() - t0)

# the fused loop (fit_batches) measured for the record, NOT for the
# ratio: XLA-CPU pessimizes the scanned conv program badly (~15x slower
# per step than the unfused fit on this host — measured during PR 2),
# while on TPU the same program is the headline. The honest CPU-for-CPU
# ratio is per-step vs per-step (the torch baseline is a per-step loop).
# DL4J_TPU_FUSE=force: fit_batches now auto-falls back to per-step fits
# for scanned conv on the CPU backend (dispatch.fusion_enabled — the
# guard this measurement motivated); this row deliberately measures the
# pessimized fused program itself, so it must force past the guard.
import os
os.environ["DL4J_TPU_FUSE"] = "force"
k = 4
xs = jax.device_put(np.broadcast_to(x, (k,) + x.shape).copy())
ys = jax.device_put(np.broadcast_to(y, (k,) + y.shape).copy())
losses = net.fit_batches(xs, ys)
np.asarray(losses)
t0 = time.perf_counter()
losses = net.fit_batches(xs, ys)
np.asarray(losses)
fused = batch * k / (time.perf_counter() - t0)

print(json.dumps({
    "backend": jax.default_backend(),
    "samples_per_sec": round(per_step, 1),
    "samples_per_sec_fused": round(fused, 1),
    "fused_note": "XLA-CPU scan-of-conv pessimization: the fused path is "
                  "the TPU headline, not the CPU one; ratio uses per-step",
    "batch": batch, "steps": steps, "data": prov,
    "label": "cpu_for_cpu",
}))
"""

_CHAR_RNN_CPU_SCRIPT = r"""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

batch, seq, vocab, lstm, steps = (int(a) for a in sys.argv[1:6])

from deeplearning4j_tpu.models.char_rnn import char_rnn_conf
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

net = MultiLayerNetwork(
    char_rnn_conf(vocab, lstm_size=lstm, num_layers=2, tbptt_length=50)
).init(input_shape=(1, vocab))
rng = np.random.default_rng(0)
eye = np.eye(vocab, dtype=np.float32)
ids = rng.integers(0, vocab, (batch, seq + 1))
x = jax.device_put(eye[ids[:, :seq]])
y = jax.device_put(eye[ids[:, 1:]])

out = None
for _ in range(2):
    out = net.fit(x, y)
np.asarray(out)
t0 = time.perf_counter()
for _ in range(steps):
    out = net.fit(x, y)
np.asarray(out)
ours = batch * seq * steps / (time.perf_counter() - t0)

# torch-CPU stand-in for the reference's nd4j-native LSTM path (same
# batch/seq/width; full-sequence BPTT — torch has no TBPTT, which HELPS
# torch here: one backward per step instead of two 50-step windows)
import torch
import torch.nn as tnn

torch.manual_seed(0)
lstm_mod = tnn.LSTM(vocab, lstm, num_layers=2, batch_first=True)
head = tnn.Linear(lstm, vocab)
opt = torch.optim.RMSprop(list(lstm_mod.parameters())
                          + list(head.parameters()), lr=0.1)
lossf = tnn.CrossEntropyLoss()
xt = torch.randn(batch, seq, vocab)
yt = torch.randint(0, vocab, (batch, seq))

def tstep():
    opt.zero_grad()
    h, _ = lstm_mod(xt)
    loss = lossf(head(h).reshape(-1, vocab), yt.reshape(-1))
    loss.backward()
    opt.step()
    return float(loss)

for _ in range(2):
    tstep()
t0 = time.perf_counter()
for _ in range(steps):
    tstep()
theirs = batch * seq * steps / (time.perf_counter() - t0)

print(json.dumps({
    "backend": jax.default_backend(),
    "train_tokens_per_sec": round(ours, 1),
    "torch_cpu_tokens_per_sec": round(theirs, 1),
    "vs_torch_cpu": round(ours / theirs, 3),
    "batch": batch, "seq": seq, "lstm": lstm, "steps": steps,
    "data": "synthetic",
    "label": "cpu_for_cpu",
    "note": "ours runs TBPTT(50) = 2 backward windows per step; torch "
            "runs one full-sequence backward — a generous baseline",
}))
"""


def bench_lenet_cpu(batch=512, steps=8, quick=False):
    """OUR LeNet-5 on jax-CPU, same topology/batch/step protocol as the
    committed torch-CPU row (bench_torch_lenet_cpu) — the first measured
    vs_baseline of any kind (VERDICT r5 weak #2: the perf story was
    hostage to the tunnel only because this leg didn't exist). The ratio
    lands in the one-line JSON as `vs_baseline_cpu`."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _LENET_CPU_SCRIPT, str(batch),
         str(2 if quick else steps)], 1800)
    if parsed is None:
        return {"error": err}
    return parsed


def bench_char_rnn_cpu(batch=32, seq=100, vocab=80, lstm=200, steps=6,
                       quick=False):
    """OUR char-RNN (2x GravesLSTM-200, TBPTT 50) on jax-CPU vs an inline
    torch-CPU LSTM of the same width — the configs[1] CPU-for-CPU row."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _CHAR_RNN_CPU_SCRIPT, str(batch), str(seq),
         str(vocab), str(lstm), str(2 if quick else steps)], 1800)
    if parsed is None:
        return {"error": err}
    return parsed


# ---------------------------------------------------------------------------
# configs[3]: Word2Vec skip-gram negative sampling
# ---------------------------------------------------------------------------


def bench_word2vec(vocab=2000, sentences=800, sent_len=40):
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    corpus, provenance = _w2v_corpus(vocab, sentences, sent_len)
    w2v = Word2Vec(layer_size=128, window=5, negative=5, min_word_frequency=1,
                   epochs=1, iterations=1, batch_size=2048, seed=1)
    w2v.build_vocab(corpus)
    seqs = w2v._sequences_as_indices(corpus)
    centers, _ = w2v._make_pairs(seqs, np.random.default_rng(1))
    pairs = len(centers)
    t0 = time.perf_counter()
    w2v.fit_tokens(corpus)  # includes XLA compile
    cold_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    w2v.fit_tokens(corpus)  # steady state (the number that scales to real corpora)
    warm_dt = time.perf_counter() - t0
    return {
        "pairs_per_sec": round(pairs / warm_dt, 1),
        "pairs_per_sec_incl_compile": round(pairs / cold_dt, 1),
        "pairs": int(pairs), "vocab": int(len(w2v.vocab)),
        "data": provenance,
    }


def _w2v_corpus(vocab, sentences, sent_len):
    """Bench corpus: a REAL local text file when DL4J_TPU_W2V_CORPUS
    points at one (tokenized by the framework tokenizer, provenance
    'local' — this zero-egress host cannot download text8), else the
    deterministic zipf-ish synthetic corpus, labeled as such."""
    path = envknob.get_str("DL4J_TPU_W2V_CORPUS")
    if path and os.path.isfile(path):
        from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory

        tf = DefaultTokenizerFactory()
        corpus, line_count = [], 0
        with open(path, errors="ignore") as f:
            for line in f:
                toks = tf.create(line).get_tokens()
                if len(toks) >= 5:
                    corpus.append(toks[:512])
                    line_count += 1
                if line_count >= sentences * 4:
                    break
        if corpus:
            return corpus, f"local:{os.path.basename(path)}"
        _log(f"W2V corpus {path} yielded no usable lines; falling back")
    rng = np.random.default_rng(0)
    # zipf-ish corpus over a synthetic vocab
    probs = 1.0 / np.arange(1, vocab + 1)
    probs /= probs.sum()
    words = [f"w{i}" for i in range(vocab)]
    return (
        [[words[i] for i in rng.choice(vocab, size=sent_len, p=probs)]
         for _ in range(sentences)],
        "synthetic",
    )


# ---------------------------------------------------------------------------
# configs[4]: DP scaling on the virtual 8-device mesh (subprocess, CPU)
# ---------------------------------------------------------------------------

_SCALING_SCRIPT = r"""
import json, time
import numpy as np
# virtual 8-device CPU mesh with the version-portable fallback: a bare
# jax_num_cpu_devices update dies at line one on this image's jax 0.4.x
# (the same rot the `-m examples` tier caught in four examples — this
# script had it too, discovered by the PR-2 quick bench pass)
from deeplearning4j_tpu.parallel.mesh import virtual_cpu_devices
virtual_cpu_devices(8)
import jax
from deeplearning4j_tpu.models.resnet import build_resnet50
from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

# global batch big enough that each of the 8 shards still carries real
# work (256/8 = 32/device); both configs do the SAME total work
batch, steps = 256, 3
rng = np.random.default_rng(0)
x = rng.random((batch, 32, 32, 3)).astype(np.float32)
y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]

def setup(n_dev):
    net = build_resnet50(input_size=32, num_classes=10)
    pw = ParallelWrapper(net, num_devices=n_dev)
    float(pw.fit(x, y))  # compile + warm once; reps below are all timed
    return pw

def timed(pw):
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = pw.fit(x, y)
    float(loss)  # host readback: sound completion fence
    return batch * steps / (time.perf_counter() - t0)

# INTERLEAVED paired reps (1,8),(1,8),(1,8): on this shared 1-core host a
# single rep's ratio swings 0.61-0.83 with background load (round-4
# measurement); interleaving means a load burst must span both halves of
# a pair to bias that pair's ratio. The committed number is the MEDIAN
# pair ratio, and the row carries every rep + the spread so the reader
# sees the noise floor instead of mistaking one draw for a stable
# measurement (VERDICT r4 weak #5).
pw1, pw8 = setup(1), setup(8)
t1s, t8s, ratios = [], [], []
for _ in range(3):
    a, b = timed(pw1), timed(pw8)
    t1s.append(a); t8s.append(b); ratios.append(b / a)
# the committed throughputs are the MEDIAN PAIR'S OWN halves, so the row
# is internally consistent: throughput_8dev / throughput_1dev equals
# dp_overhead_ratio exactly (mixing max-of-reps throughputs with a
# median ratio would let the quoted numbers disagree with each other)
mi = sorted(range(3), key=lambda i: ratios[i])[1]
print(json.dumps({
    "throughput_1dev": round(t1s[mi], 2),
    "throughput_8dev": round(t8s[mi], 2),
    "dp_overhead_ratio": round(ratios[mi], 4),
    "ratio_reps": [round(r, 4) for r in ratios],
    "ratio_spread": round(max(ratios) - min(ratios), 4),
    "reps": 3,
    "ratio_stat": "median of 3 interleaved pair ratios; throughputs are "
                  "the median pair's own halves",
}))
"""


def bench_native_feed(n_files=24, batch=256, feat=784, classes=10,
                      reps=3):
    """CPU-only: exported-dataset feed throughput — the native npz
    ordered prefetcher (C worker thread parsing ahead, off the GIL) vs
    the plain np.load loop it replaces. The reference's analogous edge is
    AsyncDataSetIterator vs synchronous iteration
    (deeplearning4j-core/.../AsyncDataSetIterator.java:30). Writes real
    stored-entry npz minibatches (training_master.export_datasets format)
    to a temp dir, then times streaming them back both ways."""
    import shutil
    import tempfile

    from deeplearning4j_tpu.native import NATIVE_AVAILABLE, iter_npz

    rng = np.random.default_rng(0)
    d = tempfile.mkdtemp(prefix="dl4j_feedbench_")
    try:
        paths = []
        for i in range(n_files):
            p = os.path.join(d, f"dataset_{i:05d}.npz")
            np.savez(p, features=rng.standard_normal(
                (batch, feat)).astype(np.float32),
                labels=np.eye(classes, dtype=np.float32)[
                    rng.integers(0, classes, batch)])
            paths.append(p)
        mb = sum(os.path.getsize(p) for p in paths) / 1e6

        def drain_native(work_s=0.0):
            n = 0
            for z in iter_npz(paths):
                n += z["features"].shape[0]
                if work_s:
                    time.sleep(work_s)  # device-bound consumer: the GIL
                    # is released, the C worker parses ahead
            return n

        def drain_numpy(work_s=0.0):
            n = 0
            for p in paths:
                with np.load(p) as z:
                    n += z["features"].shape[0]
                if work_s:
                    time.sleep(work_s)
            return n

        drain_native(), drain_numpy()  # warm page cache both ways
        t = {}
        # two scenarios: `drain` is the CPU-bound worst case (consumer
        # wants every batch NOW — on a 1-core host the async copy is pure
        # overhead and np.load should win); `overlap` models the real
        # fit(path) loop where the consumer waits ~10ms on the device per
        # minibatch and the prefetcher's parse-ahead hides the file IO
        # (the AsyncDataSetIterator rationale)
        for name, fn in (("native", drain_native), ("numpy", drain_numpy)):
            for label, work in ((name, 0.0), (name + "_overlap", 0.010)):
                t0 = time.perf_counter()
                for _ in range(reps):
                    assert fn(work) == n_files * batch
                t[label] = (time.perf_counter() - t0) / reps
        return {
            "native_mb_per_s": round(mb / t["native"], 1),
            "numpy_mb_per_s": round(mb / t["numpy"], 1),
            "native_over_numpy_drain": round(t["numpy"] / t["native"], 2),
            "overlap_native_s": round(t["native_overlap"], 4),
            "overlap_numpy_s": round(t["numpy_overlap"], 4),
            "native_over_numpy_overlap": round(
                t["numpy_overlap"] / t["native_overlap"], 2),
            "native_available": bool(NATIVE_AVAILABLE),
            "files": n_files, "batch": batch,
            "payload_mb": round(mb, 1),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_scaling():
    repo_root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.environ.get("JAX_COMPILATION_CACHE_DIR", "/root/.jax_compile_cache"),
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", _SCALING_SCRIPT],
            capture_output=True, text=True, timeout=1200, env=env,
        )
        line = out.stdout.strip().splitlines()[-1]
        res = json.loads(line)
    except Exception as e:  # noqa: BLE001 — report, don't kill the bench
        return {"error": f"{type(e).__name__}: {e}"}
    res["note"] = (
        "equal-work DP overhead on the virtual 8-device CPU mesh of a "
        "1-core host: ratio of 8-way-sharded to single-device throughput "
        "at the SAME global batch (1.0 = zero partitioning/collective "
        "overhead). Raw 1-to-8 scaling needs 8 real chips."
    )
    return res


# ---------------------------------------------------------------------------
# north star: 100-step CPU vs accelerator f32-strict curves
# ---------------------------------------------------------------------------


_NORTH_STAR_SCRIPT = r"""
import json, os, sys
if os.environ.get("DL4J_TPU_FORCE_CPU"):
    # offline/test mode: don't touch the accelerator tunnel (the axon
    # sitecustomize overrides the JAX_PLATFORMS env var, so this must be
    # a config update inside the child)
    import jax
    jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.utils.equivalence import run_north_star
res = run_north_star(steps=int(sys.argv[1]), artifact_path="NORTHSTAR_r.json")
print(json.dumps({
    k: {
        "max_abs_deviation": v["max_abs_deviation"],
        "max_rel_deviation": v["max_rel_deviation"],
        "final_loss_cpu": v["final_loss_cpu"],
        "final_loss_accel": v["final_loss_accel"],
        "backends": f"{v['backend_cpu']} vs {v['backend_accel']}",
    }
    for k, v in res.items()
}))
"""


def _run_subprocess_json(args, timeout_s: int):
    """Shared child-process scaffolding (north-star + per-leg isolation):
    repo PYTHONPATH + persistent compile cache env, stderr tail on failure,
    last-stdout-line JSON on success. Returns (parsed_or_None, err_or_None)."""
    repo_root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + ":" + env.get("PYTHONPATH", "")
    # the parent enables the persistent compile cache via jax.config (not
    # inherited); pass it through the env so children skip re-compiles
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/.jax_compile_cache")
    try:
        out = subprocess.run(args, capture_output=True, text=True,
                             timeout=timeout_s, env=env, cwd=repo_root)
        if out.returncode != 0:
            tail = (out.stderr or "").strip().splitlines()[-3:]
            return None, f"exit {out.returncode}: {' | '.join(tail)}"
        return json.loads(out.stdout.strip().splitlines()[-1]), None
    except subprocess.TimeoutExpired:
        return None, f"timed out after {timeout_s}s (tunnel hang?)"
    except Exception as e:  # noqa: BLE001
        return None, f"{type(e).__name__}: {e}"


def bench_north_star(steps=100, timeout=1800):
    """Runs in a SUBPROCESS: the remote-TPU tunnel can go stale inside a
    long-lived process (observed: the accel curve hangs forever in a remote
    call after the slow CPU leg) — a fresh process re-establishes the
    tunnel, and the timeout makes a hang a reported error instead of a
    wedged bench."""
    parsed, err = _run_subprocess_json(
        [sys.executable, "-c", _NORTH_STAR_SCRIPT, str(steps)], timeout)
    return parsed if parsed is not None else {"error": err}


def bench_lstm_kernel(timeout=2400):
    """Fused pallas LSTM fwd AND fwd+bwd vs lax.scan on chip
    (benchmarks/pallas_lstm_bench.py) — writes the PALLAS_BENCH.json
    win-table rows that gate the kernel per shape class. Runs as its own
    subprocess (fresh tunnel, same reasoning as the north-star leg)."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "benchmarks", "pallas_lstm_bench.py")
    parsed, err = _run_subprocess_json([sys.executable, script], timeout)
    if parsed is None:
        return {"error": err}
    return {"cases": parsed.get("cases"), "verdict": parsed.get("verdict")}


def _probe_device(timeout_s: float = 180.0) -> Optional[str]:
    """Liveness probe: run a tiny op with a hard deadline in a worker
    thread. A dead remote-TPU tunnel HANGS (no error), which would wedge
    the whole bench — better to report and exit."""
    import threading

    result: dict = {}

    def work():
        try:
            import jax
            import jax.numpy as jnp

            dev = jax.devices()[0]
            if dev.platform == "cpu":
                # jax_platforms='axon,cpu' silently falls back to CPU if the
                # plugin errors at init — CPU numbers must NEVER be published
                # as per-chip TPU throughput (provenance rule, CLAUDE.md)
                result["err"] = (
                    f"accelerator plugin fell back to CPU ({dev}); refusing "
                    "to bench CPU as if it were the chip")
                return
            result["ok"] = float(jnp.ones((2,)).sum())
            result["device"] = str(dev)
        except Exception as e:  # noqa: BLE001
            result["err"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(timeout_s)
    if "ok" in result:
        _log(f"device probe ok: {result.get('device')}")
        return None
    return result.get("err", f"device probe hung for {timeout_s:.0f}s "
                             "(remote-TPU tunnel down?)")


def _run_isolated(name: str, quick: bool, timeout_s: int = 0,
                  retries: int = 1):
    """Run one bench leg as `bench.py --only=name` in a FRESH subprocess.

    The axon remote-TPU tunnel goes stale inside long-lived processes
    (observed: char_rnn wedged >20min with ~0 CPU mid-RPC after the lenet
    legs finished; same failure mode the north-star harness already guards
    against). A child process re-establishes the tunnel, the persistent
    compile cache keeps re-compiles cheap, and a timeout turns a wedge
    into a reported error + one retry instead of a dead bench run.

    Quick mode uses a tighter deadline: a quick leg finishes in ~2-5 min
    when the tunnel is healthy, so 2x900s on a wedged leg would burn a
    short tunnel window (the round-4 03:47 contact lasted ~3 minutes and
    the full 900s went to one wedged lenet5 attempt)."""
    if not timeout_s:
        timeout_s = 480 if quick else 900
    args = [sys.executable, os.path.abspath(__file__), f"--only={name}"]
    if quick:
        args.append("--quick")
    last_err = None
    for attempt in range(retries + 1):
        parsed, err = _run_subprocess_json(args, timeout_s)
        if parsed is not None:
            if name in parsed:
                return parsed[name]
            # child exited 0 without the leg's key — its own probe failed
            # and it printed the accelerator-unavailable JSON; surface the
            # REAL cause, not a KeyError
            last_err = parsed.get("error", f"child output missing '{name}'")
        else:
            last_err = err
        _log(f"{name} attempt {attempt}: {last_err}")
    return {"error": last_err}


# legs that never touch the accelerator — they must not be gated on (or
# failed by) the remote-TPU probe. dispatch_overhead and
# serving_throughput are listed because they degrade to an honest CPU row
# on their own (internal probe + forced-cpu child) instead of erroring
# out with the tunnel down; lenet5_cpu / char_rnn_cpu are the
# CPU-for-CPU baseline pair (forced jax-CPU by design).
_CPU_ONLY_LEGS = {"reference_cpu_lenet5_torch", "scaling_virtual8",
                  "native_feed", "dispatch_overhead", "serving_throughput",
                  "serving_resilience", "serving_decode", "serving_fleet",
                  "decode_amortize", "checkpoint_overhead",
                  "lenet5_cpu", "char_rnn_cpu",
                  "remat_memory", "input_pipeline", "elastic_dp",
                  "obs_overhead", "paged_kernel", "sgns_kernel",
                  "online_loop", "lowprec", "retrieval", "serving_mesh",
                  "autoscale"}

_PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_PARTIAL.json")

# process birth time, against the round-start marker: a bench pass that
# outlives its round (the watcher that launched it was killed at a round
# boundary but the pass survived) must never write stale rows into the
# NEW round's artifact (ADVICE r4 #1 — the group kill is the first line
# of defense; this guard is the second). The implementation lives in the
# side-effect-free round_guard module (shared with
# benchmarks/word2vec_profile.py, which must not inherit this file's
# import-time env setup — ADVICE r5); the module-level names here remain
# the monkeypatch surface the watcher tests use.
import round_guard  # noqa: E402

_START_TS = round_guard.START_TS
_ROUND_MARKER = round_guard.ROUND_MARKER


def _round_is_stale() -> bool:
    return round_guard.round_is_stale(_ROUND_MARKER, _START_TS)


def _persist_partial(extras: dict) -> None:
    """Append-as-you-go artifact: update BENCH_PARTIAL.json after EVERY
    completed leg so a mid-run tunnel outage preserves finished legs (the
    round-2 failure mode: the tunnel died mid-bench and the whole round's
    on-chip proof was lost). Atomic rename so a crash never leaves a
    truncated artifact.

    MERGES across passes instead of rewriting: a leg that errored this
    pass must never clobber a measured row from an earlier pass (round-4
    incident: the tunnel died mid-quick-pass and a timed-out lenet5
    retry overwrote the measured CPU legs at 04:08). A measured row
    always replaces an older row; an error row only annotates a measured
    row with last_error/last_error_ts."""
    if _round_is_stale():
        # a NEW round started after this process did: these rows belong to
        # the previous round and must not pollute the fresh artifact. The
        # pass itself is pointless now — stop it.
        _log("round marker is newer than this bench process; aborting "
             "stale pass without writing")
        raise SystemExit(3)
    try:
        with open(_PARTIAL_PATH) as f:
            legs = json.load(f).get("legs", {})
    except (OSError, ValueError):
        legs = {}
    for name, row in extras.items():
        old = legs.get(name)
        if (isinstance(row, dict) and "error" in row
                and isinstance(old, dict) and "error" not in old):
            old = dict(old)
            old["last_error"] = row["error"]
            old["last_error_ts"] = row.get("ts",
                                           time.strftime("%Y-%m-%dT%H:%M:%S"))
            legs[name] = old
        else:
            legs[name] = row
    tmp = _PARTIAL_PATH + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({"updated": time.strftime("%Y-%m-%dT%H:%M:%S"),
                       "graftlint_clean": _GRAFTLINT_CLEAN,
                       "legs": legs}, f, indent=1, sort_keys=True)
        os.replace(tmp, _PARTIAL_PATH)
    except OSError as e:
        _log(f"partial artifact write failed: {e}")


#: graftlint verdict for THIS bench process's tree, stamped into every
#: artifact it writes — true/false from the sweep, None when the linter
#: itself failed (a provenance bit like the data labels: never fabricated)
_GRAFTLINT_CLEAN = None


def _graftlint_sweep():
    global _GRAFTLINT_CLEAN
    try:
        from deeplearning4j_tpu.analysis import repo_clean
        _GRAFTLINT_CLEAN = bool(repo_clean())
    except Exception as e:  # the stamp must never take the bench down
        _log(f"graftlint sweep failed: {e}")
        _GRAFTLINT_CLEAN = None
    if _GRAFTLINT_CLEAN is False:
        _log("graftlint: tree is DIRTY — artifact rows will carry "
             "graftlint_clean=false (scripts/bench_state.py will warn)")
    return _GRAFTLINT_CLEAN


def _load_partial_legs() -> dict:
    try:
        with open(_PARTIAL_PATH) as f:
            return json.load(f).get("legs", {})
    except (OSError, ValueError):
        return {}


def _fill_skip(prev, quick: bool) -> bool:
    """--fill decision: skip a leg whose existing row is measured (no
    error) — except a FULL pass re-runs rows measured only at --quick
    settings (3-step numbers must not stand in for 30-step numbers)."""
    return (isinstance(prev, dict) and "error" not in prev
            and (quick or not prev.get("quick")))


def main():
    # fast-abort for zombie-watcher children (same rationale as the
    # startup guard in benchmarks/word2vec_profile.py): a pass spawned by
    # a watcher whose round is over must die HERE, before burning up to
    # three 180s tunnel probes and the 1-core host, not at its first
    # _persist_partial
    if _round_is_stale():
        _log("spawning watcher's round is over; stale bench pass "
             "aborting at startup")
        raise SystemExit(3)
    # lint provenance: stamp whether this tree passes graftlint so an
    # artifact produced from a dirty tree says so (AST-only, ~2s, no jax)
    _graftlint_sweep()
    quick = "--quick" in sys.argv
    # --fill: gap-filling mode for the tunnel watcher — skip legs that
    # already have a measured (non-error) row in BENCH_PARTIAL.json so a
    # short contact window is spent only on what's still missing
    fill = "--fill" in sys.argv
    only = [a.split("=", 1)[1] for a in sys.argv if a.startswith("--only=")]
    # --trace[=DIR]: capture an xplane trace per leg (children inherit the
    # env; SURVEY section 5 profiling mapping — utils/profiling.py)
    for a in sys.argv:
        if a == "--trace":
            os.environ["DL4J_TPU_XPLANE_TRACE"] = "xplane_traces"
        elif a.startswith("--trace="):
            os.environ["DL4J_TPU_XPLANE_TRACE"] = a.split("=", 1)[1]
    trace_dir = envknob.get_str("DL4J_TPU_XPLANE_TRACE")
    if only and all(name in _CPU_ONLY_LEGS for name in only):
        probe_err = None
    else:
        probe_err = _probe_device()
    if probe_err and not only:
        # the tunnel can be transiently down; give it two more chances
        # before declaring the whole bench dead
        for wait in (60, 120):
            _log(f"probe failed ({probe_err}); retrying in {wait}s")
            time.sleep(wait)
            probe_err = _probe_device()
            if not probe_err:
                break
    accel_down = bool(probe_err)
    if not accel_down:
        _enable_compile_cache()
    extras = {}
    if accel_down:
        extras["accelerator"] = {"error": f"unavailable: {probe_err}"}
    elif not only:
        # a healthy probe must CLEAR a stale outage row in the merged
        # artifact (measured replaces error) — otherwise a fully-measured
        # artifact would forever claim "accelerator unavailable"
        extras["accelerator"] = {"ok": True,
                                 "ts": time.strftime("%Y-%m-%dT%H:%M:%S")}
        _persist_partial(extras)

    def run(name, fn, *a, **kw):
        if only and name not in only:
            return
        if fill and not only:
            prev = _load_partial_legs().get(name)
            if _fill_skip(prev, quick):
                extras[name] = prev  # already measured this round — keep
                return
        if accel_down and name not in _CPU_ONLY_LEGS:
            # still record the outage per-leg, and still run (and persist)
            # every CPU-only leg — a dead tunnel must not erase the parts
            # of the proof that don't need it
            extras[name] = {"error": f"accelerator unavailable: {probe_err}"}
            if not only:  # a --only child must never clobber the artifact
                _persist_partial(extras)
            return
        _log(f"start {name}")
        t0 = time.perf_counter()
        try:
            if only:
                # child mode (--only=...): run in THIS process, under an
                # xplane trace when --trace/DL4J_TPU_XPLANE_TRACE is set
                if trace_dir:
                    from deeplearning4j_tpu.utils.profiling import (
                        xplane_trace,
                    )

                    with xplane_trace(os.path.join(trace_dir, name)):
                        extras[name] = fn(*a, **kw)
                    extras[name]["xplane_trace"] = os.path.join(
                        trace_dir, name)
                else:
                    extras[name] = fn(*a, **kw)
            elif name in ("scaling_virtual8", "north_star", "lstm_kernel",
                          "dispatch_overhead", "serving_throughput",
                          "serving_resilience", "serving_decode",
                          "serving_fleet", "autoscale", "decode_amortize",
                          "checkpoint_overhead",
                          "lenet5_cpu", "char_rnn_cpu", "remat_memory",
                          "input_pipeline", "elastic_dp", "obs_overhead",
                          "paged_kernel", "sgns_kernel"):
                # already subprocess-isolated internally
                extras[name] = fn(*a, **kw)
            else:
                extras[name] = _run_isolated(name, quick)
        except Exception as e:  # noqa: BLE001 — one broken bench must not kill the rest
            _log(f"FAILED {name}: {type(e).__name__}: {e}")
            extras[name] = {"error": f"{type(e).__name__}: {e}"}
        if isinstance(extras.get(name), dict):
            # measurement provenance for the merged multi-pass artifact:
            # when it ran, and whether at reduced --quick settings (a full
            # --fill pass re-measures quick rows; the judge can tell 3-step
            # from 30-step numbers). load1 records the host-load regime so
            # bench_state.py can flag artifacts mixing a quiet-host row
            # with a contended one (VERDICT r5 weak #8).
            extras[name].setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%S"))
            extras[name].setdefault("quick", bool(quick))
            try:
                extras[name].setdefault("load1", round(os.getloadavg()[0], 2))
            except OSError:
                pass
        _log(f"done {name} in {time.perf_counter() - t0:.1f}s")
        if not only:
            _persist_partial(extras)

    # Leg ORDER is tunnel-window triage (round-4 lesson: the 03:47 contact
    # lasted ~3 minutes): cheapest-compile highest-value first, so a short
    # window still yields calibration + the headline config; CPU-only legs
    # last (they don't need the window at all).
    run("mxu_calibration", bench_mxu_calibration, steps=3 if quick else 10)
    run("lenet5", bench_lenet, steps=10 if quick else 30)
    run("lenet5_fused", bench_lenet_fused, reps=1 if quick else 3)
    run("dispatch_overhead", bench_dispatch_overhead,
        steps=10 if quick else 40)
    # remat ladder evidence: CPU-measurable (AOT memory_analysis), so a
    # dead tunnel still yields the HBM-lean proof; early because the
    # transformer_lm_big leg below TRUSTS the ladder it validates
    run("remat_memory", bench_remat_memory, steps=1 if quick else 2)
    run("char_rnn", bench_char_rnn, steps=3 if quick else 10)
    run("word2vec_sgns", bench_word2vec, sentences=200 if quick else 800)
    run("transformer_lm", bench_transformer, steps=2 if quick else 5)
    run("resnet50", bench_resnet50, steps=3 if quick else 10)
    run("resnet50_bf16", bench_resnet50, steps=3 if quick else 10,
        dtype_policy="performance")
    # MFU chase (VERDICT round-2 #7): the largest (d_model, batch) that
    # fits HBM with the blocked-flash backward — depth doubled vs the
    # round-2 best-MFU config (d2048 L4 b16 -> 0.110)
    # the preflight inside bench_transformer_big makes this safe to run in
    # the quick pass too — a short tunnel window must still yield the
    # MFU-chase number
    run("transformer_lm_big", bench_transformer_big,
        steps=2 if quick else 3)
    run("flash_attention", bench_flash_attention, steps=3 if quick else 10)
    run("ring_attention", bench_ring_attention, steps=2 if quick else 5)
    run("lstm_kernel", bench_lstm_kernel)
    run("paged_kernel", bench_paged_kernel, steps=3 if quick else 10)
    run("sgns_kernel", bench_sgns_kernel, steps=3 if quick else 10)
    run("north_star", bench_north_star, steps=10 if quick else 100)
    run("serving_throughput", bench_serving_throughput,
        per_client=4 if quick else 16)
    run("serving_decode", bench_serving_decode,
        streams=16, n_new=12 if quick else 24)
    run("decode_amortize", bench_decode_amortize,
        k=4, n_new=12 if quick else 24)
    run("serving_mesh", bench_serving_mesh,
        mesh_devices=4, n_new=10 if quick else 16)
    run("serving_resilience", bench_serving_resilience,
        per_client=4 if quick else 8)
    run("serving_fleet", bench_serving_fleet,
        per_client=4 if quick else 12)
    run("autoscale", bench_autoscale,
        hammers=2 if quick else 3, burst_n=6 if quick else 10)
    run("checkpoint_overhead", bench_checkpoint_overhead,
        steps=12 if quick else 30)
    run("input_pipeline", bench_input_pipeline,
        batches=8 if quick else 20)
    run("elastic_dp", bench_elastic_dp, rounds=6 if quick else 10)
    run("online_loop", bench_online_loop,
        batches=6 if quick else 12, predicts=12 if quick else 24)
    run("lowprec", bench_lowprec, steps=1 if quick else 2,
        reps=8 if quick else 20)
    run("retrieval", bench_retrieval, rows=32768 if quick else 65536,
        queries=64)
    run("obs_overhead", bench_obs_overhead, steps=50 if quick else 150)
    run("reference_cpu_lenet5_torch", bench_torch_lenet_cpu,
        steps=3 if quick else 8)
    run("lenet5_cpu", bench_lenet_cpu, quick=quick)
    run("char_rnn_cpu", bench_char_rnn_cpu, quick=quick)
    run("native_feed", bench_native_feed, n_files=8 if quick else 24,
        reps=1 if quick else 3)
    run("scaling_virtual8", bench_scaling)
    if only:
        print(json.dumps(dict(extras, graftlint_clean=_GRAFTLINT_CLEAN)))
        return

    # headline: the fused training loop (fit_batches == the reference's
    # fit(DataSetIterator) semantics compiled end-to-end); falls back to the
    # per-step number if the fused bench failed
    headline = extras.get("lenet5_fused", {}).get(
        "samples_per_sec",
        extras.get("lenet5", {}).get("samples_per_sec", 0.0),
    )
    ref = extras.get("reference_cpu_lenet5_torch", {}).get("samples_per_sec")
    # CPU-for-CPU tier (VERDICT r5 ask #2): OUR framework on jax-CPU
    # against the torch-CPU row, both on this host's one core — the
    # baseline ratio that exists even when the tunnel never answers.
    # Protocol-matched per-step vs per-step (the torch baseline is a
    # per-step python loop); the fused number rides in the lenet5_cpu row
    # with its XLA-CPU caveat.
    ours_cpu = extras.get("lenet5_cpu", {}).get("samples_per_sec")
    result = {
        "metric": "lenet5_mnist_train_throughput",
        "value": headline,
        "unit": "samples/sec/chip",
        # null (not a fabricated 1.0) when the baseline leg failed
        "vs_baseline": (round(headline / ref, 3) if ref and headline
                        else None),
        "baseline_impl": "torch-cpu LeNet-5 (nd4j-native CPU stand-in)",
        "vs_baseline_cpu": (round(ours_cpu / ref, 3) if ref and ours_cpu
                            else None),
        "baseline_cpu_impl": ("jax-CPU LeNet-5 per-step fit vs torch-cpu "
                              "per-step, same host/core (cpu_for_cpu tier)"),
        "graftlint_clean": _GRAFTLINT_CLEAN,
        "extras": extras,
    }
    if accel_down:
        result["error"] = f"accelerator unavailable: {probe_err}"
    print(json.dumps(result))


if __name__ == "__main__":
    main()
