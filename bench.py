#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric: LeNet-5 MNIST training throughput (samples/sec/chip) —
BASELINE.json configs[0]. The reference publishes no numbers
(BASELINE.md), so vs_baseline is reported against a self-measured
nd4j-era CPU figure recorded here as REFERENCE_CPU_SAMPLES_PER_SEC once
available; until then vs_baseline = 1.0 and the absolute number is the
tracked quantity.
"""

import json
import time

import numpy as np


# Self-baselined: no published reference numbers exist (BASELINE.md). This
# constant tracks OUR first-round measurement so later rounds report progress.
REFERENCE_CPU_SAMPLES_PER_SEC = None  # filled once a reference-side run exists
FIRST_ROUND_SAMPLES_PER_SEC = None  # set after round 1 records BENCH_r1.json


def main():
    import jax

    from deeplearning4j_tpu.models.lenet import build_lenet5
    from deeplearning4j_tpu.datasets.fetchers import load_mnist

    batch = 512
    warmup_steps = 3
    bench_steps = 30

    net = build_lenet5()
    x, y = load_mnist(train=True, num_examples=batch * 4)
    xs = [x[i * batch : (i + 1) * batch] for i in range(4)]
    ys = [y[i * batch : (i + 1) * batch] for i in range(4)]

    # warmup (compile)
    for i in range(warmup_steps):
        net.fit(xs[i % 4], ys[i % 4])
    jax.block_until_ready(net.params)

    t0 = time.perf_counter()
    for i in range(bench_steps):
        net.fit(xs[i % 4], ys[i % 4])
    jax.block_until_ready(net.params)
    dt = time.perf_counter() - t0

    samples_per_sec = batch * bench_steps / dt
    vs = (
        samples_per_sec / REFERENCE_CPU_SAMPLES_PER_SEC
        if REFERENCE_CPU_SAMPLES_PER_SEC
        else 1.0
    )
    print(
        json.dumps(
            {
                "metric": "lenet5_mnist_train_throughput",
                "value": round(samples_per_sec, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
