"""Helpers shared by the two network containers (MultiLayerNetwork and
ComputationGraph) so policy logic lives in one place."""

from __future__ import annotations

from typing import Optional


def tbptt_backprop_window(conf) -> Optional[int]:
    """In-window TBPTT backward truncation length, or None when
    back >= fwd (reference distinct tbpttFwdLength/tbpttBackLength,
    MultiLayerConfiguration.java:55-56; consumed by
    LSTMHelpers.backpropGradientHelper:255)."""
    back = conf.tbptt_back_length
    if back and back < conf.tbptt_fwd_length:
        return back
    return None


def remat_apply(layer, params, state, x, rng, mask, kwargs,
                prevent_cse: bool = True):
    """Apply a layer under jax.checkpoint: store only the layer INPUT and
    recompute its activations in the backward pass (dropout rng keys are
    counter-based, so recomputed masks are identical). prevent_cse=False
    is for callers whose remat sits inside a lax.scan body (fit_batches) —
    the loop boundary already blocks the CSE the barrier guards against,
    so the default barriers would only cost fusion opportunities."""
    import jax

    def _apply(p, s, xx, lr):
        return layer.apply(p, s, xx, train=True, rng=lr, mask=mask, **kwargs)

    return jax.checkpoint(_apply, prevent_cse=prevent_cse)(
        params, state, x, rng
    )


def decay_lr_scale_entry(state, rate: float):
    """One updater-state entry with its 'lr_scale' (the cumulative 'score'
    LR-policy decay, reference Model.applyLearningRateScoreDecay) multiplied
    by `rate`; entries without the key pass through unchanged."""
    if isinstance(state, dict) and "lr_scale" in state:
        return {**state, "lr_scale": state["lr_scale"] * rate}
    return state
