"""Helpers shared by the two network containers (MultiLayerNetwork and
ComputationGraph) so policy logic lives in one place."""

from __future__ import annotations

from typing import Optional


def tbptt_backprop_window(conf) -> Optional[int]:
    """In-window TBPTT backward truncation length, or None when
    back >= fwd (reference distinct tbpttFwdLength/tbpttBackLength,
    MultiLayerConfiguration.java:55-56; consumed by
    LSTMHelpers.backpropGradientHelper:255)."""
    back = conf.tbptt_back_length
    if back and back < conf.tbptt_fwd_length:
        return back
    return None


def compute_dtype_of(conf):
    """jnp dtype for the conf's dtype_policy, or None for strict f32.
    'performance' = bfloat16 compute with float32 master params — the MXU's
    native mode (SURVEY §7: 'bf16 MXU matmuls'). The reference is
    f32-everywhere (2016 ND4J); this is the TPU-first performance mode."""
    import jax.numpy as jnp

    if getattr(conf, "dtype_policy", "strict") == "performance":
        return jnp.bfloat16
    return None


def cast_for_compute(params, x, dtype):
    """Cast the layer input and the layer's float32 param leaves to the
    compute dtype. ONLY f32 is downcast — integer inputs (embedding row
    indices) and f64 (gradient-check mode) pass through untouched. Master
    params stay f32 outside the step — autodiff through the cast yields
    f32 grads on the masters (standard mixed precision)."""
    import jax
    import jax.numpy as jnp

    cast = lambda a: a.astype(dtype) if a.dtype == jnp.float32 else a
    return jax.tree_util.tree_map(cast, params), cast(x)


def apply_layer(layer, conf, params, state, x, rng, mask, kwargs, *,
                train: bool, remat_prevent_cse: bool = True):
    """The shared per-layer application policy for both containers:
    mixed-precision casting (conf.dtype_policy) + remat-vs-plain dispatch
    (conf.gradient_checkpointing). Never downcast: output layers
    (softmax+loss numerics), and normalization layers (BN batch statistics
    / LRN square-sums need f32 accumulations — standard mixed-precision
    practice). Returned recurrent state is cast back to f32 so stored
    states keep ONE dtype regardless of which API path produced them
    (fit_batches' lax.scan carry requires dtype-stable states)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.layers.feedforward import OutputLayerImpl
    from deeplearning4j_tpu.nn.layers.normalization import (
        BatchNormalizationImpl,
        LocalResponseNormalizationImpl,
    )

    compute_dtype = compute_dtype_of(conf)
    cast_active = compute_dtype is not None and not isinstance(
        layer,
        (OutputLayerImpl, BatchNormalizationImpl, LocalResponseNormalizationImpl),
    )
    if cast_active:
        params, x = cast_for_compute(params, x, compute_dtype)
    elif compute_dtype is not None and x.dtype == compute_dtype:
        # excluded layer (output/BN/LRN) fed by a cast layer: UPcast the
        # incoming activations so batch statistics / square-sums really
        # accumulate in f32 — merely skipping the downcast is not enough
        x = x.astype(jnp.float32)
    # per-layer remat is unified under the DL4J_TPU_REMAT policy ladder
    # (ops/remat.py): the conf flag keeps its meaning — full per-layer
    # remat, the ladder's "block" rung — and the env knob can switch any
    # net's policy without a conf change ("dots" = keep matmul outputs,
    # recompute elementwise). Resolved at trace time, like the donation
    # policy.
    from deeplearning4j_tpu.ops.remat import remat_policy

    env_policy = remat_policy("auto")
    effective = env_policy if env_policy != "none" else (
        "block" if conf.gradient_checkpointing else "none")
    if train and effective != "none":
        y, new_state = remat_apply(layer, params, state, x, rng, mask, kwargs,
                                   prevent_cse=remat_prevent_cse,
                                   policy=effective)
    else:
        y, new_state = layer.apply(params, state, x, train=train, rng=rng,
                                   mask=mask, **kwargs)
    if cast_active and new_state:
        new_state = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32)
            if a.dtype == compute_dtype else a,
            new_state,
        )
    return y, new_state


def cast_loss_input(x):
    """Loss math stays >= f32: upcast low-precision activations, leave
    f32/f64 untouched (f64 = gradient-check mode)."""
    import jax.numpy as jnp

    if x.dtype in (jnp.bfloat16, jnp.float16):
        return jnp.asarray(x, jnp.float32)
    return x


def remat_apply(layer, params, state, x, rng, mask, kwargs,
                prevent_cse: bool = True, policy: str = "block"):
    """Apply a layer under jax.checkpoint: store only the layer INPUT and
    recompute its activations in the backward pass (dropout rng keys are
    counter-based, so recomputed masks are identical). prevent_cse=False
    is for callers whose remat sits inside a lax.scan body (fit_batches) —
    the loop boundary already blocks the CSE the barrier guards against,
    so the default barriers would only cost fusion opportunities.
    ``policy``: an active rung of the DL4J_TPU_REMAT ladder ("block" =
    store the layer input only; "dots" = additionally keep this layer's
    matmul outputs, recomputing only elementwise ops — ops/remat.py)."""
    import jax

    from deeplearning4j_tpu.ops.remat import checkpoint_kwargs

    def _apply(p, s, xx, lr):
        return layer.apply(p, s, xx, train=True, rng=lr, mask=mask, **kwargs)

    return jax.checkpoint(_apply, prevent_cse=prevent_cse,
                          **checkpoint_kwargs(policy))(
        params, state, x, rng
    )


def decay_lr_scale_entry(state, rate: float):
    """One updater-state entry with its 'lr_scale' (the cumulative 'score'
    LR-policy decay, reference Model.applyLearningRateScoreDecay) multiplied
    by `rate`; entries without the key pass through unchanged."""
    if isinstance(state, dict) and "lr_scale" in state:
        return {**state, "lr_scale": state["lr_scale"] * rate}
    return state


def fused_iterator_loop(data, k: int, *, can_stack, same_shape, fit_one,
                        fit_fused) -> None:
    """ONE copy of the fused fit(DataSetIterator) buffering state machine,
    shared by MultiLayerNetwork and ComputationGraph (their fit_iterator
    fused_batches paths): buffer up to k stackable same-shape items, flush
    through fit_fused; anything unstackable (or a ragged tail) drains
    through fit_one. On a shape change the buffer drains and the NEW item
    STARTS the next buffer (fusion continues within each shape group)."""
    buf = []

    def drain():
        for d in buf:
            fit_one(d)
        buf.clear()

    for ds in data:
        if not can_stack(ds):
            drain()
            fit_one(ds)
            continue
        if buf and not same_shape(buf[0], ds):
            drain()
        buf.append(ds)
        if len(buf) == k:
            fit_fused(list(buf))
            buf.clear()
    drain()
