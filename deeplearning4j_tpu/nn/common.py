"""Helpers shared by the two network containers (MultiLayerNetwork and
ComputationGraph) so policy logic lives in one place."""

from __future__ import annotations

from typing import Optional


def tbptt_backprop_window(conf) -> Optional[int]:
    """In-window TBPTT backward truncation length, or None when
    back >= fwd (reference distinct tbpttFwdLength/tbpttBackLength,
    MultiLayerConfiguration.java:55-56; consumed by
    LSTMHelpers.backpropGradientHelper:255)."""
    back = conf.tbptt_back_length
    if back and back < conf.tbptt_fwd_length:
        return back
    return None


def decay_lr_scale_entry(state, rate: float):
    """One updater-state entry with its 'lr_scale' (the cumulative 'score'
    LR-policy decay, reference Model.applyLearningRateScoreDecay) multiplied
    by `rate`; entries without the key pass through unchanged."""
    if isinstance(state, dict) and "lr_scale" in state:
        return {**state, "lr_scale": state["lr_scale"] * rate}
    return state
