"""ComputationGraph — the DAG network container.

Functional re-design of the reference's ``ComputationGraph`` (2,025 LoC,
deeplearning4j-core/.../nn/graph/ComputationGraph.java):

  reference mechanism                          -> here
  -------------------------------------------------------------------------
  topologicalSortOrder() (:279,511-540)        -> conf.topological_order()
  feedForward in topo order (:958-1000)        -> _forward over activation dict
  computeGradientAndScore (:884-908), score =
    sum of output-layer scores (:894-907)      -> _loss sums per-output losses
  calcBackpropGradients (:1061)                -> jax autodiff
  fit(MultiDataSet) (:676)                     -> fit(inputs, labels)
  rnnTimeStep (:1601)                          -> rnn_time_step()
  vertex impls (nn/graph/vertex/impl/*)        -> pure jnp vertex functions

The whole step (all vertices forward + backward + updaters) compiles to ONE
XLA program — vertex boundaries vanish under fusion, so DAG generality has
no runtime cost vs the sequential container.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf import layers as conf_layers
from deeplearning4j_tpu.nn.conf.graph import (
    ComputationGraphConfiguration,
    DuplicateToTimeSeriesVertex,
    ElementWiseVertex,
    GraphVertex,
    LastTimeStepVertex,
    MergeVertex,
    PreprocessorVertex,
    ScaleVertex,
    SubsetVertex,
)
from deeplearning4j_tpu.nn.layers.factory import (
    RNN_CONFS,
    STATEFUL_RNN_CONFS,
    create_layer,
)
from deeplearning4j_tpu.nn.layers.feedforward import OutputLayerImpl
from deeplearning4j_tpu.ops import dispatch, lowprec, rng as rng_mod
from deeplearning4j_tpu.optimize.updaters import LayerUpdater, apply_updates

logger = logging.getLogger("deeplearning4j_tpu")

_REG_PARAM_NAMES = ("W", "U")


def _as_list(x) -> List:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class ComputationGraph:
    """DAG of layer vertices and combining vertices over named inputs."""

    def __init__(self, conf: ComputationGraphConfiguration):
        conf.validate()
        self.conf = conf
        self.topo = conf.topological_order()
        self.layer_names = [
            n for n in self.topo if isinstance(conf.vertices[n], conf_layers.Layer)
        ]
        self.layers: Dict[str, Any] = {
            n: create_layer(conf.vertices[n]) for n in self.layer_names
        }
        self.updaters: Dict[str, LayerUpdater] = {
            n: LayerUpdater(conf.vertices[n], conf) for n in self.layer_names
        }
        self.params: Optional[Dict[str, Any]] = None
        self.states: Optional[Dict[str, Any]] = None
        self.updater_state: Optional[Dict[str, Any]] = None
        self.iteration = 0
        self.listeners: List[Any] = []
        self._score_dev = None
        self._rng = rng_mod.key(conf.seed)
        self._jit_cache: Dict[Any, Any] = {}
        # bf16 loss-scaled training state (DL4J_TPU_BF16, ops/lowprec.py)
        self._loss_scale = None
        self._input_shapes: Optional[Dict[str, Tuple[int, ...]]] = None
        self.dispatch_stats = dispatch.DispatchStats()
        from deeplearning4j_tpu.ops.memory import MemoryStats

        # AOT memory ledger beside dispatch_stats (ops/memory.py) —
        # populated on demand via the instrumented jits' .measure_memory
        self.memory_stats = MemoryStats()
        # ingest telemetry (etl/stats.py), adopted by fit_iterator from a
        # staged iterator — see MultiLayerNetwork.pipeline_stats
        self.pipeline_stats = None
        # see MultiLayerNetwork: BN batch statistics would absorb pad rows
        self._bucketing_blocked = any(
            isinstance(v, conf_layers.BatchNormalization)
            for v in conf.vertices.values()
        )
        # True while fit_iterator drives fit() — bucketing's "auto" scope
        self._bucket_scope = False
        # ledgers join the central MetricsRegistry (see MultiLayerNetwork)
        from deeplearning4j_tpu.obs.registry import register_net

        register_net(self)

    # ------------------------------------------------------------------ init
    def _infer_input_shapes(self) -> Dict[str, Tuple[int, ...]]:
        """Infer per-input feature shapes from first consumer layer confs
        (dense/rnn only; CNN-fed inputs need explicit shapes)."""
        shapes: Dict[str, Tuple[int, ...]] = {}
        for inp in self.conf.inputs:
            for name, ins in self.conf.vertex_inputs.items():
                if inp in ins:
                    v = self.conf.vertices[name]
                    if isinstance(v, RNN_CONFS):
                        shapes[inp] = (-1, v.n_in)
                        break
                    if isinstance(v, conf_layers.ConvolutionLayer):
                        raise ValueError(
                            f"input '{inp}' feeds a CNN; pass explicit "
                            "input_shapes to init()"
                        )
                    if isinstance(v, conf_layers.FeedForwardLayer):
                        shapes[inp] = (v.n_in,)
                        break
            if inp not in shapes:
                raise ValueError(
                    f"cannot infer shape for input '{inp}'; pass input_shapes"
                )
        return shapes

    def init(
        self,
        input_shapes: Optional[
            Union[Dict[str, Sequence[int]], Sequence[Sequence[int]]]
        ] = None,
    ) -> "ComputationGraph":
        """Initialize params/states by propagating shapes in topological
        order (role of reference init() + shape validation)."""
        if input_shapes is None:
            shapes = self._infer_input_shapes()
        elif isinstance(input_shapes, dict):
            shapes = {k: tuple(v) for k, v in input_shapes.items()}
        else:
            shapes = {
                n: tuple(s) for n, s in zip(self.conf.inputs, input_shapes)
            }
        self._input_shapes = dict(shapes)
        vshape: Dict[str, Tuple[int, ...]] = dict(shapes)
        params: Dict[str, Any] = {}
        states: Dict[str, Any] = {}
        for i, name in enumerate(self.topo):
            v = self.conf.vertices[name]
            in_shapes = [vshape[i_] for i_ in self.conf.vertex_inputs[name]]
            if isinstance(v, conf_layers.Layer):
                shape = in_shapes[0]
                pp = self.conf.input_preprocessors.get(name)
                if pp is not None:
                    shape = pp.out_shape(shape)
                k = rng_mod.layer_key(self._rng, i, "init")
                p, s, out_shape = self.layers[name].initialize(k, shape)
                params[name] = p
                states[name] = s
                vshape[name] = tuple(out_shape)
            else:
                vshape[name] = self._vertex_out_shape(v, name, in_shapes)
        self.params = params
        self.states = states
        self.updater_state = {
            n: self.updaters[n].init(params[n]) for n in self.layer_names
        }
        return self

    def _vertex_out_shape(self, v: GraphVertex, name: str, in_shapes) -> Tuple[int, ...]:
        if isinstance(v, MergeVertex):
            base = list(in_shapes[0])
            base[-1] = sum(s[-1] for s in in_shapes)
            return tuple(base)
        if isinstance(v, (ElementWiseVertex, ScaleVertex)):
            return tuple(in_shapes[0])
        if isinstance(v, SubsetVertex):
            base = list(in_shapes[0])
            base[-1] = v.to_index - v.from_index + 1
            return tuple(base)
        if isinstance(v, PreprocessorVertex):
            return tuple(v.preprocessor.out_shape(tuple(in_shapes[0])))
        if isinstance(v, LastTimeStepVertex):
            return tuple(in_shapes[0][1:])  # drop time axis -> (F,)
        if isinstance(v, DuplicateToTimeSeriesVertex):
            ref_shape = None
            if v.reference_input in (self._input_shapes or {}):
                ref_shape = self._input_shapes[v.reference_input]
            t = ref_shape[0] if ref_shape and len(ref_shape) >= 2 else -1
            return (t,) + tuple(in_shapes[0])
        raise ValueError(f"unknown vertex type {type(v).__name__} for '{name}'")

    def num_params(self) -> int:
        return sum(
            int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(self.params)
        )

    # --------------------------------------------------------------- forward
    def _apply_vertex(self, v: GraphVertex, xs: List, inputs: Dict, masks: Dict):
        if isinstance(v, MergeVertex):
            return jnp.concatenate(xs, axis=-1)
        if isinstance(v, ElementWiseVertex):
            y = xs[0]
            if v.op == "add":
                for x in xs[1:]:
                    y = y + x
            elif v.op == "subtract":
                for x in xs[1:]:
                    y = y - x
            elif v.op == "product":
                for x in xs[1:]:
                    y = y * x
            elif v.op == "average":
                y = sum(xs) / float(len(xs))
            elif v.op == "max":
                for x in xs[1:]:
                    y = jnp.maximum(y, x)
            return y
        if isinstance(v, SubsetVertex):
            return xs[0][..., v.from_index : v.to_index + 1]
        if isinstance(v, ScaleVertex):
            return xs[0] * v.scale
        if isinstance(v, PreprocessorVertex):
            return v.preprocessor(xs[0])
        if isinstance(v, LastTimeStepVertex):
            x = xs[0]  # [B,T,F]
            mask = masks.get(v.mask_input) if v.mask_input else None
            if mask is None:
                return x[:, -1, :]
            # last unmasked step per example
            idx = jnp.maximum(
                jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0
            )  # [B]
            return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :]
        if isinstance(v, DuplicateToTimeSeriesVertex):
            t = inputs[v.reference_input].shape[1]
            return jnp.broadcast_to(
                xs[0][:, None, :], (xs[0].shape[0], t, xs[0].shape[1])
            )
        raise ValueError(f"unknown vertex type {type(v).__name__}")

    def _forward(
        self,
        params,
        states,
        inputs: Dict[str, jax.Array],
        *,
        train: bool,
        rng=None,
        masks: Optional[Dict[str, jax.Array]] = None,
        carry_state: bool = False,
        backprop_window: Optional[int] = None,
        remat_prevent_cse: bool = True,
    ):
        """Forward all vertices in topo order. Returns (activations dict
        name->array incl. inputs, new states dict).

        Mask propagation: a vertex inherits the mask of its first masked
        input; LastTimeStep drops it (time axis removed) — the simplified
        equivalent of the reference's setLayerMaskArrays flow."""
        from deeplearning4j_tpu.nn.common import apply_layer

        masks = dict(masks or {})
        acts: Dict[str, jax.Array] = dict(inputs)
        new_states = dict(states)
        for i, name in enumerate(self.topo):
            v = self.conf.vertices[name]
            ins = self.conf.vertex_inputs[name]
            xs = [acts[i_] for i_ in ins]
            in_mask = next((masks[i_] for i_ in ins if i_ in masks), None)
            if isinstance(v, conf_layers.Layer):
                x = xs[0]
                pp = self.conf.input_preprocessors.get(name)
                if pp is not None:
                    x = pp(x)
                lrng = (
                    rng_mod.layer_key(rng, i, "dropout") if rng is not None else None
                )
                layer = self.layers[name]
                lmask = in_mask if isinstance(v, RNN_CONFS) else None
                kwargs = {}
                if carry_state and isinstance(v, STATEFUL_RNN_CONFS):
                    kwargs["carry_state"] = True
                if backprop_window is not None and isinstance(
                    v, STATEFUL_RNN_CONFS
                ):
                    kwargs["backprop_window"] = backprop_window
                y, ns = apply_layer(
                    layer, self.conf, params[name], states[name], x, lrng,
                    lmask, kwargs, train=train,
                    remat_prevent_cse=remat_prevent_cse,
                )
                new_states[name] = ns
                if in_mask is not None:
                    masks[name] = in_mask
                acts[name] = y
            else:
                y = self._apply_vertex(v, xs, inputs, masks)
                if in_mask is not None and not isinstance(v, LastTimeStepVertex):
                    masks[name] = in_mask
                acts[name] = y
        return acts, new_states

    def _regularization_penalty(self, params):
        total = jnp.asarray(0.0, jnp.float32)
        for name in self.layer_names:
            lc = self.conf.vertices[name]
            l1 = lc.l1 or 0.0
            l2 = lc.l2 or 0.0
            if l1 == 0.0 and l2 == 0.0:
                continue
            for path, leaf in jax.tree_util.tree_leaves_with_path(params[name]):
                pname = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
                if pname in _REG_PARAM_NAMES:
                    if l2:
                        total = total + 0.5 * l2 * jnp.sum(jnp.square(leaf))
                    if l1:
                        total = total + l1 * jnp.sum(jnp.abs(leaf))
        return total

    def _loss(
        self,
        params,
        states,
        inputs: Dict[str, jax.Array],
        labels: List[jax.Array],
        *,
        train,
        rng,
        masks=None,
        label_masks: Optional[List] = None,
        carry_state: bool = False,
        backprop_window: Optional[int] = None,
        remat_prevent_cse: bool = True,
    ):
        """Sum of output-layer losses (reference computeGradientAndScore
        :894-907 sums per-output scores) + regularization."""
        # run up to (but excluding) output vertices: we need preout for fused
        # softmax-xent. Simplest correct approach: full forward, then redo the
        # loss from each output layer's input activation. The XLA compiler
        # CSEs the duplicated matmul away.
        acts, new_states = self._forward(
            params,
            states,
            inputs,
            train=train,
            rng=rng,
            masks=masks,
            remat_prevent_cse=remat_prevent_cse,
            carry_state=carry_state,
            backprop_window=backprop_window,
        )
        # mask propagated to each output vertex's input (label-mask fallback,
        # mirroring MLN: lmask = label_mask if set else feature mask)
        from deeplearning4j_tpu.nn.common import cast_loss_input

        prop_masks = dict(masks or {})
        for name in self.topo:
            ins = self.conf.vertex_inputs[name]
            m = next((prop_masks[i_] for i_ in ins if i_ in prop_masks), None)
            if m is not None and not isinstance(
                self.conf.vertices[name], LastTimeStepVertex
            ):
                prop_masks[name] = m
        total = jnp.asarray(0.0, jnp.float32)
        for oi, oname in enumerate(self.conf.outputs):
            impl = self.layers[oname]
            if not isinstance(impl, OutputLayerImpl):
                raise ValueError(
                    f"output vertex '{oname}' is not an OutputLayer/RnnOutputLayer"
                )
            in_name = self.conf.vertex_inputs[oname][0]
            x = acts[in_name]
            pp = self.conf.input_preprocessors.get(oname)
            if pp is not None:
                x = pp(x)
            oconf = self.conf.vertices[oname]
            if train and (oconf.dropout or 0.0) > 0 and rng is not None:
                x = impl._dropout_in(
                    x,
                    train,
                    rng_mod.layer_key(rng, self.topo.index(oname), "dropout"),
                )
            lm = label_masks[oi] if label_masks else None
            if lm is None:
                lm = prop_masks.get(in_name)
            x = cast_loss_input(x)
            total = total + impl.loss(params[oname], x, labels[oi], lm)
        return total + self._regularization_penalty(params), new_states

    # ------------------------------------------------------------- jit cache
    def _update_all(self, grads, upd_state, params, iteration):
        updates, new_state = {}, {}
        for n in self.layer_names:
            if not grads[n]:
                updates[n] = grads[n]
                new_state[n] = upd_state[n]
                continue
            u, s = self.updaters[n].update(
                grads[n], upd_state[n], params[n], iteration
            )
            updates[n] = u
            new_state[n] = s
        return updates, new_state

    def _get_train_step(self, n_labels: int, has_label_masks: bool,
                        carry_state=False, backprop_window=None):
        lp = lowprec.train_policy()
        key = ("train_step", n_labels, has_label_masks, carry_state,
               backprop_window, lp)
        if key in self._jit_cache:
            return self._jit_cache[key]

        def train_step(
            params, states, upd_state, inputs, labels, iteration, rng, masks, label_masks
        ):
            def loss_fn(p):
                return self._loss(
                    p,
                    states,
                    inputs,
                    labels,
                    train=True,
                    rng=rng,
                    masks=masks,
                    label_masks=label_masks,
                    carry_state=carry_state,
                    backprop_window=backprop_window,
                )

            (loss, new_states), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params
            )
            updates, upd_state = self._update_all(grads, upd_state, params, iteration)
            params = apply_updates(params, updates, self.conf.minimize)
            return params, new_states, upd_state, loss

        if lp:
            return self._build_lowprec_step(key, carry_state, backprop_window)

        # donation contract as in MultiLayerNetwork._get_train_step: every
        # caller re-binds params/states/upd_state from the returned triple
        fn = dispatch.instrumented_jit(
            train_step, "train_step", self.dispatch_stats,
            donate=(0, 1, 2), step=True, mem_stats=self.memory_stats)
        self._jit_cache[key] = fn
        return fn

    def _ensure_loss_scale(self):
        if self._loss_scale is None:
            self._loss_scale = lowprec.init_scale_state()
        return self._loss_scale

    @property
    def loss_scale(self):
        """Host snapshot of the dynamic loss-scale state (None when bf16
        training never ran); syncs dispatch_stats.loss_scale_skips."""
        snap = lowprec.scale_snapshot(self._loss_scale)
        if snap is not None:
            self.dispatch_stats.loss_scale_skips = snap["skipped"]
        return snap

    def _build_lowprec_step(self, key, carry_state, backprop_window):
        """bf16 master-weight train step for the DAG container — same
        scaled-loss / unscale / halve-and-skip discipline as
        MultiLayerNetwork._build_lowprec_step (Micikevicius et al., ICLR
        2018); the inner jit takes + donates the loss-scale tree, the
        wrapper keeps the original 9-arg signature."""

        def lp_step(params, states, upd_state, ls, inputs, labels,
                    iteration, rng, masks, label_masks):
            scale = ls["scale"]

            def loss_fn(p):
                loss, new_states = self._loss(
                    lowprec.cast_tree(p),
                    states,
                    {k: lowprec.cast_array(v) for k, v in inputs.items()}
                    if isinstance(inputs, dict)
                    else lowprec.cast_array(inputs),
                    labels,
                    train=True,
                    rng=rng,
                    masks=masks,
                    label_masks=label_masks,
                    carry_state=carry_state,
                    backprop_window=backprop_window,
                )
                return loss.astype(jnp.float32) * scale, (loss, new_states)

            (_, (loss, new_states)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = lowprec.unscale(grads, scale)
            finite = lowprec.finite_tree(grads)
            updates, new_upd = self._update_all(
                grads, upd_state, params, iteration)
            new_params = apply_updates(params, updates, self.conf.minimize)
            params = lowprec.select_trees(finite, new_params, params)
            upd_state = lowprec.select_trees(finite, new_upd, upd_state)
            states = lowprec.select_trees(finite, new_states, states)
            ls = lowprec.advance_scale(ls, finite)
            return params, states, upd_state, ls, loss.astype(jnp.float32)

        inner = dispatch.instrumented_jit(
            lp_step, "train_step", self.dispatch_stats,
            donate=(0, 1, 2, 3), step=True, mem_stats=self.memory_stats)
        net = self

        def wrapper(params, states, upd_state, inputs, labels, iteration,
                    rng, masks, label_masks):
            ls = net._ensure_loss_scale()
            params, states, upd_state, ls, loss = inner(
                params, states, upd_state, ls, inputs, labels, iteration,
                rng, masks, label_masks)
            net._loss_scale = ls
            return params, states, upd_state, loss

        def measure_memory(params, states, upd_state, inputs, labels,
                           iteration, rng, masks, label_masks):
            return inner.measure_memory(
                params, states, upd_state, net._ensure_loss_scale(),
                inputs, labels, iteration, rng, masks, label_masks)

        wrapper.measure_memory = measure_memory
        wrapper.lowprec = True
        self._jit_cache[key] = wrapper
        return wrapper

    def _get_fit_batches_fn(self, n_labels: int):
        """K train steps fused into ONE lax.scan (see
        MultiLayerNetwork._get_fit_batches_fn). Mask-free path: masked
        multi-step training uses the per-step fit()."""
        lp = lowprec.train_policy()
        key = ("fit_batches", n_labels, lp)
        if key in self._jit_cache:
            return self._jit_cache[key]

        n_iters = max(1, self.conf.iterations)

        def one_iter(params, states, upd_state, xs_k, ys_k, it, rng):
            def loss_fn(p):
                return self._loss(
                    p, states, xs_k, ys_k, train=True,
                    rng=rng_mod.step_key(rng, it),
                    masks=None, label_masks=None,
                    remat_prevent_cse=False,  # scan boundary blocks CSE
                )

            (loss, states), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, upd_state = self._update_all(
                grads, upd_state, params, it
            )
            params = apply_updates(params, updates, self.conf.minimize)
            return params, states, upd_state, loss

        def one_iter_lp(params, states, upd_state, ls, xs_k, ys_k, it, rng):
            # _build_lowprec_step discipline inlined into the scan body
            scale = ls["scale"]

            def loss_fn(p):
                loss, new_states = self._loss(
                    lowprec.cast_tree(p), states,
                    {k: lowprec.cast_array(v) for k, v in xs_k.items()}
                    if isinstance(xs_k, dict) else lowprec.cast_array(xs_k),
                    ys_k, train=True,
                    rng=rng_mod.step_key(rng, it),
                    masks=None, label_masks=None,
                    remat_prevent_cse=False,
                )
                return loss.astype(jnp.float32) * scale, (loss, new_states)

            (_, (loss, new_states)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = lowprec.unscale(grads, scale)
            finite = lowprec.finite_tree(grads)
            updates, new_upd = self._update_all(
                grads, upd_state, params, it)
            new_params = apply_updates(params, updates, self.conf.minimize)
            params = lowprec.select_trees(finite, new_params, params)
            upd_state = lowprec.select_trees(finite, new_upd, upd_state)
            states = lowprec.select_trees(finite, new_states, states)
            ls = lowprec.advance_scale(ls, finite)
            return params, states, upd_state, ls, loss.astype(jnp.float32)

        def scan_fn(params, states, upd_state, inputs, labels, it0, rng):
            def body(carry, inp):
                params, states, upd_state, it = carry
                xs_k, ys_k = inp

                iter_losses = []
                for _ in range(n_iters):  # conf.iterations, like fit()
                    params, states, upd_state, loss = one_iter(
                        params, states, upd_state, xs_k, ys_k, it, rng)
                    it = it + 1
                    iter_losses.append(loss)
                return (params, states, upd_state, it), jnp.stack(iter_losses)

            (params, states, upd_state, _), losses = jax.lax.scan(
                body, (params, states, upd_state, it0), (inputs, labels)
            )
            return params, states, upd_state, losses.reshape(-1)

        if lp:
            def lp_scan_fn(params, states, upd_state, ls, inputs, labels,
                           it0, rng):
                def body(carry, inp):
                    params, states, upd_state, ls, it = carry
                    xs_k, ys_k = inp
                    iter_losses = []
                    for _ in range(n_iters):
                        params, states, upd_state, ls, loss = one_iter_lp(
                            params, states, upd_state, ls, xs_k, ys_k, it,
                            rng)
                        it = it + 1
                        iter_losses.append(loss)
                    return ((params, states, upd_state, ls, it),
                            jnp.stack(iter_losses))

                (params, states, upd_state, ls, _), losses = jax.lax.scan(
                    body, (params, states, upd_state, ls, it0),
                    (inputs, labels)
                )
                return params, states, upd_state, ls, losses.reshape(-1)

            inner = dispatch.instrumented_jit(
                lp_scan_fn, "fit_batches", self.dispatch_stats,
                donate=(0, 1, 2, 3), step=True,
                mem_stats=self.memory_stats)
            net = self

            def wrapper(params, states, upd_state, inputs, labels, it0,
                        rng):
                ls = net._ensure_loss_scale()
                params, states, upd_state, ls, losses = inner(
                    params, states, upd_state, ls, inputs, labels, it0,
                    rng)
                net._loss_scale = ls
                return params, states, upd_state, losses

            wrapper.lowprec = True
            self._jit_cache[key] = wrapper
            return wrapper

        fn = dispatch.instrumented_jit(
            scan_fn, "fit_batches", self.dispatch_stats,
            donate=(0, 1, 2), step=True, mem_stats=self.memory_stats)
        self._jit_cache[key] = fn
        return fn

    def _has_scanned_conv(self) -> bool:
        return any(isinstance(v, (conf_layers.ConvolutionLayer,
                                  conf_layers.SubsamplingLayer))
                   for v in self.conf.vertices.values())

    def _fit_batches_fallback(self, features, labels):
        """Per-step drain under the fusion policy (dispatch.fusion_enabled:
        the XLA:CPU scan-of-conv ~15x pessimization, BENCH_NOTES round-6);
        recorded in dispatch_stats.fused_fallbacks, DL4J_TPU_FUSE=force
        overrides. Same contract as MultiLayerNetwork's fallback."""
        from deeplearning4j_tpu.optimize.listeners import (
            CollectScoresIterationListener,
        )

        self.dispatch_stats.fused_fallbacks += 1
        feats = [jnp.asarray(f) for f in _as_list(features)]
        labs = [jnp.asarray(l) for l in _as_list(labels)]
        col = CollectScoresIterationListener(frequency=1)
        self.listeners.append(col)
        try:
            for k in range(feats[0].shape[0]):
                self.fit([f[k] for f in feats], [l[k] for l in labs])
        finally:
            self.listeners.remove(col)
        return np.asarray([s for _, s in col.scores], np.float32)

    def fit_batches(self, features, labels):
        """Fit each leading-axis slice ([K, N, ...]) inside a single
        compiled scan — K MultiDataSet fits (each with ``conf.iterations``
        optimizer iterations) without K host round-trips. Returns
        per-iteration losses [K*iterations]. SGD, non-TBPTT, mask-free
        path (same contract as MultiLayerNetwork.fit_batches)."""
        if self.params is None:
            self.init()
        if self.conf.backprop_type == "truncated_bptt":
            raise ValueError("fit_batches: use fit() for TBPTT training")
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            raise ValueError("fit_batches supports SGD-family training only")
        inputs = self._as_inputs(features)  # validates the input count
        labels_l = [jnp.asarray(l) for l in _as_list(labels)]
        if len(labels_l) != len(self.conf.outputs):
            raise ValueError(
                f"expected {len(self.conf.outputs)} label arrays, got {len(labels_l)}"
            )
        if not dispatch.fusion_enabled(scanned_conv=self._has_scanned_conv()):
            return self._fit_batches_fallback(features, labels)
        fn = self._get_fit_batches_fn(len(labels_l))
        self.params, self.states, self.updater_state, losses = fn(
            self.params, self.states, self.updater_state,
            inputs, labels_l,
            jnp.asarray(self.iteration, jnp.int32), self._rng,
        )
        self._score_dev = losses[-1]
        losses_np = np.asarray(losses)  # ONE bulk readback
        for k in range(losses_np.shape[0]):
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration, float(losses_np[k]))
            self.iteration += 1
        return losses_np

    # ------------------------------------------------------------------- fit
    @property
    def score_value(self) -> float:
        return float("nan") if self._score_dev is None else float(self._score_dev)

    def _record_iteration(self, loss):
        self._score_dev = loss
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, float(loss))
        self.iteration += 1

    def _as_inputs(self, features) -> Dict[str, jax.Array]:
        feats = _as_list(features)
        if len(feats) != len(self.conf.inputs):
            raise ValueError(
                f"expected {len(self.conf.inputs)} inputs, got {len(feats)}"
            )
        return {n: jnp.asarray(f) for n, f in zip(self.conf.inputs, feats)}

    def fit(
        self, features, labels, masks=None, label_masks=None
    ) -> float:
        """One MultiDataSet fit (reference fit(MultiDataSet) :676).
        `features`/`labels`: array or list-of-arrays matching conf
        inputs/outputs order."""
        if self.params is None:
            self.init()
        inputs = self._as_inputs(features)
        labels_l = [jnp.asarray(l) for l in _as_list(labels)]
        if len(labels_l) != len(self.conf.outputs):
            raise ValueError(
                f"expected {len(self.conf.outputs)} label arrays, got {len(labels_l)}"
            )
        masks_d = self._as_masks(masks)
        lmasks = (
            [None if m is None else jnp.asarray(m) for m in _as_list(label_masks)]
            if label_masks is not None
            else None
        )
        if self.conf.backprop_type == "truncated_bptt":
            # before solver dispatch, same precedence as MultiLayerNetwork.fit
            return self._fit_tbptt(inputs, labels_l, masks_d, lmasks)
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            from deeplearning4j_tpu.optimize.solvers import Solver

            return Solver(self).optimize_graph(inputs, labels_l, masks_d, lmasks)
        inputs, labels_l, masks_d, lmasks = self._bucket_batch(
            inputs, labels_l, masks_d, lmasks
        )
        step = self._get_train_step(len(labels_l), lmasks is not None)
        loss = None
        for _ in range(max(1, self.conf.iterations)):
            srng = rng_mod.step_key(self._rng, self.iteration)
            self.params, self.states, self.updater_state, loss = step(
                self.params,
                self.states,
                self.updater_state,
                inputs,
                labels_l,
                jnp.asarray(self.iteration, jnp.int32),
                srng,
                masks_d,
                lmasks,
            )
            self._record_iteration(loss)
        return loss

    def _bucket_batch(self, inputs, labels_l, masks_d, lmasks):
        """Shape bucketing for the DAG container (see
        MultiLayerNetwork._bucket_batch): every input/label/mask is padded
        along the example axis up to dispatch.bucket_size, and each output
        gets a label mask that zeroes the pad rows out of its loss.

        Skipped when feature masks are present without a full set of
        explicit label masks: such outputs take their loss mask from
        _loss's mask PROPAGATION, and whether the propagated mask reaches a
        given output is a graph property this hook cannot cheaply verify —
        an unmasked padded output would divide by the padded row count.
        (The MLN container has no such ambiguity: its single output always
        falls back to the feature mask directly.)"""
        mode = dispatch.bucketing_mode()
        if (mode == "off" or (mode == "auto" and not self._bucket_scope)
                or self._bucketing_blocked):
            return inputs, labels_l, masks_d, lmasks
        explicit = (lmasks is not None
                    and all(m is not None for m in lmasks))
        if masks_d and not explicit:
            return inputs, labels_l, masks_d, lmasks
        n = next(iter(inputs.values())).shape[0]
        target = dispatch.bucket_size(n)
        if target != n:
            ik, mk = list(inputs), list(masks_d)
            padded = dispatch.pad_rows(
                self.dispatch_stats, target,
                [inputs[k] for k in ik] + labels_l + [masks_d[k] for k in mk],
            )
            inputs = dict(zip(ik, padded[:len(ik)]))
            labels_l = padded[len(ik):len(ik) + len(labels_l)]
            masks_d = dict(zip(mk, padded[len(ik) + len(labels_l):]))
        new_lmasks = []
        for oi, labels in enumerate(labels_l):
            lm = lmasks[oi] if lmasks is not None else None
            if lm is not None:
                lm = dispatch.pad_axis0(lm, target)
            else:
                # row-validity mask: all-ones for an exact-bucket batch, so
                # every bucket shares one jit signature (see MLN hook)
                lm = dispatch.row_validity_mask(
                    n, target,
                    labels.shape[1] if labels.ndim == 3 else None,
                )
            new_lmasks.append(lm)
        return inputs, labels_l, masks_d, new_lmasks

    def _reset_rnn_states(self, batch_n: int) -> None:
        """Zero recurrent state sized for this batch (sequence start — the
        graph analog of MLN's reset before doTruncatedBPTT :1162)."""
        for n in self.layer_names:
            lc = self.conf.vertices[n]
            if isinstance(lc, STATEFUL_RNN_CONFS):
                self.states[n] = {
                    k: jnp.zeros((batch_n, lc.n_out), jnp.float32)
                    for k in self.states[n]
                }

    def _fit_tbptt(self, inputs, labels_l, masks_d, lmasks,
                   state_placer=None) -> float:
        """Truncated BPTT over a DAG (reference ComputationGraph supports
        BackpropType.TruncatedBPTT the same way MLN does :1162-1233): slice
        the time axis into fwd-length windows, carry recurrent state across
        windows (stop-gradient at the boundary — state enters the next jitted
        step as data).

        A shorter tbptt_back_length truncates the backward pass inside each
        window via stop-gradient segments (reference
        LSTMHelpers.backpropGradientHelper:255)."""
        seq_inputs = {k: v for k, v in inputs.items() if v.ndim == 3}
        if not seq_inputs:
            raise ValueError(
                "backprop_type='truncated_bptt' requires at least one "
                "time-series ([B,T,F]) input"
            )
        first_seq = next(iter(seq_inputs.values()))
        t_total = first_seq.shape[1]
        w = self.conf.tbptt_fwd_length
        batch_n = first_seq.shape[0]
        self._reset_rnn_states(batch_n)
        if state_placer is not None:
            # DP path: place the freshly reset stream state on the mesh's
            # data axis before the first window step (avoids a replicated
            # full-batch state + GSPMD reshard)
            state_placer()
        from deeplearning4j_tpu.nn.common import tbptt_backprop_window

        bw = tbptt_backprop_window(self.conf)
        step = self._get_train_step(
            len(labels_l), lmasks is not None, carry_state=True,
            backprop_window=bw,
        )
        loss = float("nan")
        for window_start in range(0, t_total, w):
            sl = slice(window_start, min(window_start + w, t_total))
            in_w = {k: v[:, sl] if v.ndim == 3 else v for k, v in inputs.items()}
            lb_w = [l[:, sl] if l.ndim == 3 else l for l in labels_l]
            # slice a mask only when it spans the time axis (same guard the
            # labels/inputs get: per-example 2D masks pass through whole)
            mk_w = (
                {
                    k: (m[:, sl] if m.ndim >= 2 and m.shape[1] == t_total else m)
                    for k, m in masks_d.items()
                }
                if masks_d
                else masks_d
            )
            lm_w = (
                [
                    m[:, sl]
                    if m is not None and labels_l[i].ndim == 3
                    else m
                    for i, m in enumerate(lmasks)
                ]
                if lmasks
                else lmasks
            )
            srng = rng_mod.step_key(self._rng, self.iteration)
            self.params, self.states, self.updater_state, loss = step(
                self.params,
                self.states,
                self.updater_state,
                in_w,
                lb_w,
                jnp.asarray(self.iteration, jnp.int32),
                srng,
                mk_w,
                lm_w,
            )
            self._record_iteration(loss)
        return loss

    def fit_iterator(self, iterator, num_epochs: int = 1,
                     fused_batches: int = 1) -> "ComputationGraph":
        """fit over a MultiDataSetIterator (or DataSetIterator for
        single-input/single-output graphs).

        fused_batches=K > 1: stack K consecutive same-shape mask-free
        DataSets/MultiDataSets through fit_batches (one XLA program per K
        optimizer steps — MultiLayerNetwork.fit_iterator's fused path for
        the DAG container). Per-step fallback for masks, shape changes,
        ragged tails, TBPTT and non-SGD solvers.

        Input staging: DL4J_TPU_PIPELINE_WORKERS wraps a plain iterator
        in etl/pipeline.InputPipeline and the staged iterator's telemetry
        is adopted as ``net.pipeline_stats`` (see MultiLayerNetwork)."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.etl.pipeline import maybe_wrap

        iterator = maybe_wrap(iterator)
        if getattr(iterator, "pipeline_stats", None) is not None:
            self.pipeline_stats = iterator.pipeline_stats
            from deeplearning4j_tpu.obs.registry import register_net

            register_net(self)  # the freshly adopted ingest ledger
        fused = (fused_batches > 1
                 and self.conf.backprop_type != "truncated_bptt"
                 and self.conf.optimization_algo
                 == "stochastic_gradient_descent")
        from deeplearning4j_tpu.nn.common import fused_iterator_loop

        # bucketing's "auto" scope (see MultiLayerNetwork.fit_iterator)
        self._bucket_scope = True
        try:
            for _ in range(num_epochs):
                if not fused:
                    for ds in iterator:
                        self._fit_ds(ds)
                else:
                    fused_iterator_loop(
                        iterator, fused_batches,
                        can_stack=self._graph_stackable,  # fit_batches: no masks
                        same_shape=self._same_shapes,
                        fit_one=self._fit_ds,
                        fit_fused=self._fit_fused_graph,
                    )
                if hasattr(iterator, "reset"):
                    iterator.reset()
        finally:
            self._bucket_scope = False
        return self

    @staticmethod
    def _components(ds):
        """(features_list, labels_list, has_masks) for either container."""
        if hasattr(ds, "features_list"):  # MultiDataSet
            masks = any(m is not None for m in (ds.features_masks or [])) \
                or any(m is not None for m in (ds.labels_masks or []))
            return list(ds.features_list), list(ds.labels_list), masks
        return ([ds.features], [ds.labels],
                ds.features_mask is not None or ds.labels_mask is not None)

    def _graph_stackable(self, ds) -> bool:
        return not self._components(ds)[2]  # fit_batches is mask-free

    def _same_shapes(self, a, b) -> bool:
        fa, la, _ = self._components(a)
        fb, lb, _ = self._components(b)
        return (
            len(fa) == len(fb) and len(la) == len(lb)
            and all(np.asarray(x).shape == np.asarray(y).shape
                    for x, y in zip(fa + la, fb + lb))
        )

    def _fit_ds(self, ds) -> None:
        if hasattr(ds, "features_list"):  # MultiDataSet
            self.fit(ds.features_list, ds.labels_list, ds.features_masks,
                     ds.labels_masks)
        else:
            self.fit(ds.features, ds.labels, ds.features_mask,
                     ds.labels_mask)

    def _fit_fused_graph(self, buf) -> None:
        feats0, labs0, _ = self._components(buf[0])
        comps = [self._components(d) for d in buf]
        feats = [np.stack([np.asarray(c[0][i]) for c in comps])
                 for i in range(len(feats0))]
        labs = [np.stack([np.asarray(c[1][i]) for c in comps])
                for i in range(len(labs0))]
        self.fit_batches(feats, labs)

    # ------------------------------------------------------------- inference
    def _get_output_fn(self):
        key = "output"
        if key not in self._jit_cache:

            def out_fn(params, states, inputs):
                acts, _ = self._forward(params, states, inputs, train=False)
                return [acts[o] for o in self.conf.outputs]

            self._jit_cache[key] = dispatch.instrumented_jit(
                out_fn, "output", self.dispatch_stats,
                mem_stats=self.memory_stats)
        return self._jit_cache[key]

    def output(self, *features) -> List[jax.Array]:
        """Inference outputs in conf.outputs order (reference output()/
        feedForward). Ragged batches are bucket-padded and sliced back —
        inference-mode padding is unconditionally safe (BN running stats,
        no dropout), so arbitrary batch sizes compile O(log n) programs."""
        if self.params is None:
            self.init()
        if len(features) == 1 and isinstance(features[0], (list, tuple)):
            features = tuple(features[0])
        inputs = self._as_inputs(list(features))
        n = next(iter(inputs.values())).shape[0]
        target = dispatch.inference_bucket(self.dispatch_stats, n)
        if target is not None:
            inputs = {k: dispatch.pad_axis0(v, target)
                      for k, v in inputs.items()}
            outs = self._get_output_fn()(self.params, self.states, inputs)
            return [o[:n] for o in outs]
        return self._get_output_fn()(self.params, self.states, inputs)

    def feed_forward(self, *features) -> Dict[str, jax.Array]:
        """All vertex activations by name (reference feedForward map)."""
        if self.params is None:
            self.init()
        inputs = self._as_inputs(list(features))
        acts, _ = self._forward(self.params, self.states, inputs, train=False)
        return acts

    def _as_masks(self, masks) -> Dict[str, jax.Array]:
        """Normalize a masks argument (dict by input name, or list in conf
        input order) to the name-keyed dict _forward expects."""
        if masks is None:
            return {}
        if isinstance(masks, dict):
            return {k: jnp.asarray(m) for k, m in masks.items() if m is not None}
        return {
            n: jnp.asarray(m)
            for n, m in zip(self.conf.inputs, _as_list(masks))
            if m is not None
        }

    def _get_score_fn(self, n_labels: int, has_label_masks: bool):
        key = ("score", n_labels, has_label_masks)
        if key not in self._jit_cache:

            def score_fn(params, states, inputs, labels, masks, label_masks):
                loss, _ = self._loss(
                    params,
                    states,
                    inputs,
                    labels,
                    train=False,
                    rng=None,
                    masks=masks,
                    label_masks=label_masks,
                )
                return loss

            self._jit_cache[key] = dispatch.instrumented_jit(
                score_fn, "score", self.dispatch_stats)
        return self._jit_cache[key]

    def score(self, features, labels, masks=None, label_masks=None) -> float:
        if self.params is None:
            self.init()
        inputs = self._as_inputs(features)
        labels_l = [jnp.asarray(l) for l in _as_list(labels)]
        lmasks = (
            [None if m is None else jnp.asarray(m) for m in _as_list(label_masks)]
            if label_masks is not None
            else None
        )
        fn = self._get_score_fn(len(labels_l), lmasks is not None)
        loss = fn(
            self.params,
            self.states,
            inputs,
            labels_l,
            self._as_masks(masks),
            lmasks,
        )
        return float(loss)

    def evaluate(self, iterator):
        """Classification evaluation on the FIRST output (reference
        evaluate(DataSetIterator))."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        ev = Evaluation()
        for ds in iterator:
            feats = getattr(ds, "features_list", None) or ds.features
            labels = getattr(ds, "labels_list", None) or ds.labels
            out = self.output(*_as_list(feats))[0]
            first_labels = _as_list(labels)[0]
            ev.eval(np.asarray(first_labels), np.asarray(out))
        if hasattr(iterator, "reset"):
            iterator.reset()
        return ev

    # ------------------------------------------------------- rnn streaming
    def rnn_clear_previous_state(self):
        for n in self.layer_names:
            if isinstance(self.conf.vertices[n], STATEFUL_RNN_CONFS):
                self.states[n] = {
                    k: jnp.zeros_like(v) for k, v in self.states[n].items()
                }

    def rnn_time_step(self, *features) -> List[jax.Array]:
        """Single-step stateful inference (reference rnnTimeStep :1601):
        feeds one timestep, carries recurrent state across calls."""
        if len(features) == 1 and isinstance(features[0], (list, tuple)):
            features = tuple(features[0])
        feats = []
        for f in features:
            f = jnp.asarray(f)
            if f.ndim == 2:
                f = f[:, None, :]  # [B,F] -> [B,1,F]
            feats.append(f)
        inputs = self._as_inputs(feats)
        batch_n = feats[0].shape[0]
        # size/reset states lazily for this batch
        for n in self.layer_names:
            lc = self.conf.vertices[n]
            if isinstance(lc, STATEFUL_RNN_CONFS):
                st = self.states[n]
                if not st or next(iter(st.values())).shape[0] != batch_n:
                    self.states[n] = {
                        k: jnp.zeros((batch_n, lc.n_out), jnp.float32)
                        for k in (st or {"h": None, "c": None})
                    }
        key = ("rnn_step",)
        if key not in self._jit_cache:

            def step_fn(params, states, inputs):
                acts, new_states = self._forward(
                    params, states, inputs, train=False, carry_state=True
                )
                outs = [acts[o] for o in self.conf.outputs]
                return [
                    o[:, -1, :] if o.ndim == 3 else o for o in outs
                ], new_states

            self._jit_cache[key] = dispatch.instrumented_jit(
                step_fn, "rnn_step", self.dispatch_stats)
        outs, self.states = self._jit_cache[key](
            self.params, self.states, inputs
        )
        return outs

    def apply_lr_score_decay(self) -> None:
        """See MultiLayerNetwork.apply_lr_score_decay (reference
        Model.applyLearningRateScoreDecay for the 'score' LR policy)."""
        from deeplearning4j_tpu.nn.common import decay_lr_scale_entry

        rate = getattr(self.conf, "lr_policy_decay_rate", None)
        if rate is None:
            return
        self.updater_state = {
            n: decay_lr_scale_entry(s, rate)
            for n, s in self.updater_state.items()
        }

    def training_state(self) -> Dict[str, Any]:
        """Exact-resume extras (see MultiLayerNetwork.training_state —
        same contract for the DAG container, loss-scale state included)."""
        st = {
            "iteration": int(self.iteration),
            "rng": np.asarray(self._rng, np.uint32).tolist(),
        }
        snap = self.loss_scale  # property: also syncs loss_scale_skips
        if snap is not None:
            st["loss_scale"] = snap
        return st

    def restore_training_state(self, st: Dict[str, Any]) -> None:
        if st.get("iteration") is not None:
            self.iteration = int(st["iteration"])
        if st.get("rng") is not None:
            self._rng = jnp.asarray(np.asarray(st["rng"], dtype=np.uint32))
        if st.get("loss_scale") is not None:
            self._loss_scale = lowprec.scale_from_snapshot(st["loss_scale"])

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)

    def clone(self) -> "ComputationGraph":
        other = ComputationGraph(self.conf)
        if self.params is not None:
            # real copies (not leaf-sharing): donation would delete shared
            # leaves on the original's next train step
            other.params = jax.tree_util.tree_map(jnp.copy, self.params)
            other.states = jax.tree_util.tree_map(jnp.copy, self.states)
            other.updater_state = jax.tree_util.tree_map(
                jnp.copy, self.updater_state
            )
            other._input_shapes = dict(self._input_shapes or {})
        other.iteration = self.iteration
        return other
