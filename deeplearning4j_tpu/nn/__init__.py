"""Neural-network core: configs, layers, containers.

Maps the reference's nn/* tree (SURVEY.md section 2.1) into a functional,
jit-first design: layer *configs* are serializable dataclasses (the model
identity, like the reference's Jackson configs), layer *implementations* are
pure ``init``/``apply`` functions over param pytrees, and the containers
(MultiLayerNetwork, ComputationGraph) assemble one jittable forward/loss.
"""
