"""Weight initialization schemes.

Mirrors the reference's ``WeightInit`` enum and ``WeightInitUtil`` switch
(deeplearning4j-core/.../nn/weights/WeightInit.java:37,
WeightInitUtil.java:93-123) with identical distributions:

  DISTRIBUTION — sample from a configured distribution
  NORMALIZED   — (U(0,1) - 0.5) / fan_in
  RELU         — N(0, 2/fan_in)
  SIZE         — U(-r, r), r = 4*sqrt(6/(fan_in+fan_out))
  UNIFORM      — U(-1/fan_in, 1/fan_in)
  VI           — U(-r, r), r = sqrt(6)/sqrt(sum(shape)+1)
  XAVIER       — N(0, 1/(fan_in+fan_out))
  ZERO         — zeros

Implemented over jax.random with explicit keys (reference uses the global
Nd4j RNG). Distribution configs are dicts: {"type": "normal", "mean": m,
"std": s} | {"type": "uniform", "lower": a, "upper": b} |
{"type": "binomial", "n": n, "p": p} — matching the reference's
conf/distribution classes.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

WEIGHT_INITS = (
    "distribution",
    "normalized",
    "relu",
    "size",
    "uniform",
    "vi",
    "xavier",
    "zero",
)


def _sample_distribution(key, shape, dist: dict, dtype):
    kind = dist.get("type", "normal").lower()
    if kind == "normal" or kind == "gaussian":
        mean = dist.get("mean", 0.0)
        std = dist.get("std", 1.0)
        return mean + std * jax.random.normal(key, shape, dtype)
    if kind == "uniform":
        lo = dist.get("lower", 0.0)
        hi = dist.get("upper", 1.0)
        return jax.random.uniform(key, shape, dtype, lo, hi)
    if kind == "binomial":
        n = dist.get("n", 1)
        p = dist.get("p", 0.5)
        return jnp.sum(
            jax.random.bernoulli(key, p, (n,) + tuple(shape)).astype(dtype), axis=0
        )
    raise ValueError(f"Unknown distribution type '{kind}'")


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    scheme: str,
    fan_in: int,
    fan_out: int,
    dist: Optional[dict] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Initialize a weight tensor of `shape` with the named scheme.

    `fan_in`/`fan_out` are passed explicitly because conv/recurrent layers
    compute them from receptive fields, not from shape[0]/shape[1].
    """
    shape = tuple(shape)
    s = scheme.lower()
    if s == "distribution":
        if dist is None:
            raise ValueError("WeightInit DISTRIBUTION requires a `dist` config")
        return _sample_distribution(key, shape, dist, dtype)
    if s == "normalized":
        return (jax.random.uniform(key, shape, dtype) - 0.5) / float(fan_in)
    if s == "relu":
        return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / fan_in)
    if s == "size":
        r = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -r, r)
    if s == "uniform":
        a = 1.0 / float(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "vi":
        r = math.sqrt(6.0) / math.sqrt(sum(shape) + 1.0)
        return jax.random.uniform(key, shape, dtype, -r, r)
    if s == "xavier":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in + fan_out)
    if s == "zero":
        return jnp.zeros(shape, dtype)
    raise ValueError(f"Unknown weight init '{scheme}'. Known: {WEIGHT_INITS}")
