"""Input preprocessors — shape adapters between layer families.

Mirrors the reference's ``nn/conf/preprocessor`` package (13 classes,
SURVEY.md section 2.1): CnnToFeedForward, FeedForwardToCnn, RnnToFeedForward,
FeedForwardToRnn, CnnToRnn, RnnToCnn, Reshape. Each reference class has
``preProcess`` + ``backprop``; here only the forward transform is needed
(autodiff provides the backward), plus static shape inference used by the
containers at init time.

Conventions: CNN activations are NHWC; RNN activations are [batch, time,
features] (see nn/conf/layers.py docstring for the deliberate divergence from
the reference's NCHW / [batch, features, time]).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax.numpy as jnp

PREPROCESSOR_REGISTRY: Dict[str, type] = {}


def register_preprocessor(cls):
    PREPROCESSOR_REGISTRY[cls.__name__] = cls
    return cls


def preprocessor_to_dict(p) -> Dict[str, Any]:
    import dataclasses

    d = dataclasses.asdict(p)
    d["type"] = type(p).__name__
    return d


def preprocessor_from_dict(d: Dict[str, Any]):
    d = dict(d)
    cls = PREPROCESSOR_REGISTRY[d.pop("type")]
    kwargs = {k: tuple(v) if isinstance(v, list) else v for k, v in d.items()}
    return cls(**kwargs)


@register_preprocessor
@dataclass
class CnnToFeedForwardPreProcessor:
    """[N,H,W,C] -> [N, H*W*C] (reference: CnnToFeedForwardPreProcessor.java)."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x):
        return x.reshape(x.shape[0], -1)

    def out_shape(self, in_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        h, w, c = in_shape
        return (h * w * c,)


@register_preprocessor
@dataclass
class FeedForwardToCnnPreProcessor:
    """[N, H*W*C] -> [N,H,W,C] (reference: FeedForwardToCnnPreProcessor.java)."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 1

    def __call__(self, x):
        return x.reshape(
            x.shape[0], self.input_height, self.input_width, self.num_channels
        )

    def out_shape(self, in_shape) -> Tuple[int, ...]:
        return (self.input_height, self.input_width, self.num_channels)


@register_preprocessor
@dataclass
class RnnToFeedForwardPreProcessor:
    """[N,T,F] -> [N*T, F] (reference: RnnToFeedForwardPreProcessor.java)."""

    def __call__(self, x):
        return x.reshape(-1, x.shape[-1])

    def out_shape(self, in_shape) -> Tuple[int, ...]:
        t, f = in_shape
        return (f,)


@register_preprocessor
@dataclass
class FeedForwardToRnnPreProcessor:
    """[N*T, F] -> [N,T,F]; time length supplied by the container at apply time
    (reference: FeedForwardToRnnPreProcessor.java)."""

    def __call__(self, x, time_steps: int = -1):
        return x.reshape(-1, time_steps, x.shape[-1]) if time_steps > 0 else x

    def out_shape(self, in_shape) -> Tuple[int, ...]:
        # shape bookkeeping handled by container (needs T)
        return in_shape


@register_preprocessor
@dataclass
class CnnToRnnPreProcessor:
    """[N*T,H,W,C] -> [N,T,H*W*C] (reference: CnnToRnnPreProcessor.java)."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x, time_steps: int = -1):
        flat = x.reshape(x.shape[0], -1)
        if time_steps > 0:
            flat = flat.reshape(-1, time_steps, flat.shape[-1])
        return flat

    def out_shape(self, in_shape) -> Tuple[int, ...]:
        h, w, c = in_shape
        return (h * w * c,)


@register_preprocessor
@dataclass
class RnnToCnnPreProcessor:
    """[N,T,H*W*C] -> [N*T,H,W,C] (reference: RnnToCnnPreProcessor.java)."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x):
        n, t, f = x.shape
        return x.reshape(
            n * t, self.input_height, self.input_width, self.num_channels
        )

    def out_shape(self, in_shape) -> Tuple[int, ...]:
        return (self.input_height, self.input_width, self.num_channels)


@register_preprocessor
@dataclass
class ReshapePreProcessor:
    """Arbitrary reshape keeping the batch axis (reference: ReshapePreProcessor.java)."""

    target_shape: Tuple[int, ...] = ()

    def __call__(self, x):
        return x.reshape((x.shape[0],) + tuple(self.target_shape))

    def out_shape(self, in_shape) -> Tuple[int, ...]:
        return tuple(self.target_shape)


@register_preprocessor
@dataclass
class UnitVarianceProcessor:
    """Normalize each example to unit variance (reference:
    UnitVarianceProcessor.java)."""

    def __call__(self, x):
        flat = x.reshape(x.shape[0], -1)
        std = jnp.std(flat, axis=1).reshape((-1,) + (1,) * (x.ndim - 1))
        return x / jnp.maximum(std, 1e-8)

    def out_shape(self, in_shape) -> Tuple[int, ...]:
        return in_shape
