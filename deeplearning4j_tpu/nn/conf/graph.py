"""ComputationGraph configuration: DAG spec + GraphBuilder + vertex confs.

Mirrors the reference's ``ComputationGraphConfiguration`` (697 LoC;
``GraphBuilder.addInputs/addLayer/addVertex/setOutputs`` —
deeplearning4j-core/.../nn/conf/ComputationGraphConfiguration.java:569-605)
and the vertex conf classes under ``nn/conf/graph/`` (MergeVertex,
ElementWiseVertex, SubsetVertex, PreprocessorVertex; rnn/
LastTimeStepVertex, DuplicateToTimeSeriesVertex).

TPU-first divergence: vertex forward functions are pure jnp ops applied
inside the single jitted train step — there is no per-vertex doForward /
doBackward pair (autodiff provides the backward).

Feature axis is the LAST axis everywhere (NHWC for CNN, [B,T,F] for RNN),
so Merge/Subset act on axis -1 uniformly.
"""

from __future__ import annotations

import copy
import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.nn.conf.layers import (
    Layer,
    layer_from_dict,
    resolve,
)

# ---------------------------------------------------------------------------
# vertex conf registry (role of Jackson subtype registration for vertices)
# ---------------------------------------------------------------------------

VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


@dataclass
class GraphVertex:
    """Base class for non-layer vertex configs."""

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "GraphVertex":
        d = dict(d)
        cls = VERTEX_REGISTRY[d.pop("type")]
        return cls(**d)


@register_vertex
@dataclass
class MergeVertex(GraphVertex):
    """Concatenate inputs along the feature (last) axis
    (reference nn/conf/graph/MergeVertex.java)."""


@register_vertex
@dataclass
class ElementWiseVertex(GraphVertex):
    """Elementwise combine: add | subtract | product | average | max
    (reference nn/conf/graph/ElementWiseVertex.java — Add/Subtract/Product)."""

    op: str = "add"

    def __post_init__(self):
        if self.op not in ("add", "subtract", "product", "average", "max"):
            raise ValueError(f"unknown elementwise op {self.op}")


@register_vertex
@dataclass
class SubsetVertex(GraphVertex):
    """Feature-range slice [from_index, to_index] inclusive, reference
    nn/conf/graph/SubsetVertex.java semantics."""

    from_index: int = 0
    to_index: int = 0


@register_vertex
@dataclass
class ScaleVertex(GraphVertex):
    """Multiply input by a fixed scalar."""

    scale: float = 1.0


@register_vertex
@dataclass
class PreprocessorVertex(GraphVertex):
    """Wraps an InputPreProcessor as a standalone vertex
    (reference nn/conf/graph/PreprocessorVertex.java)."""

    preprocessor: Any = None

    def to_dict(self) -> Dict[str, Any]:
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_to_dict

        return {
            "type": "PreprocessorVertex",
            "preprocessor": preprocessor_to_dict(self.preprocessor),
        }


@register_vertex
@dataclass
class LastTimeStepVertex(GraphVertex):
    """[B,T,F] -> [B,F]: final time step, or the last unmasked step when the
    named input carries a mask (reference nn/conf/graph/rnn/LastTimeStepVertex.java)."""

    mask_input: Optional[str] = None


@register_vertex
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[B,F] -> [B,T,F] with T taken from the named reference input
    (reference nn/conf/graph/rnn/DuplicateToTimeSeriesVertex.java)."""

    reference_input: str = ""


def _vertex_from_dict(d: Dict[str, Any]) -> GraphVertex:
    d = dict(d)
    t = d["type"]
    if t == "PreprocessorVertex":
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_from_dict

        return PreprocessorVertex(preprocessor=preprocessor_from_dict(d["preprocessor"]))
    return GraphVertex.from_dict(d)


# ---------------------------------------------------------------------------
# the graph configuration
# ---------------------------------------------------------------------------


@dataclass
class ComputationGraphConfiguration:
    """Serializable DAG spec. `vertices[name]` is either a resolved layer
    conf (layer vertex) or a GraphVertex; `vertex_inputs[name]` lists input
    names (graph inputs or other vertices) in order."""

    inputs: List[str] = field(default_factory=list)
    vertices: Dict[str, Any] = field(default_factory=dict)
    vertex_inputs: Dict[str, List[str]] = field(default_factory=dict)
    outputs: List[str] = field(default_factory=list)
    input_preprocessors: Dict[str, Any] = field(default_factory=dict)
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    gradient_checkpointing: bool = False  # remat layer activations (jax.checkpoint)
    dtype_policy: str = "strict"  # 'performance' = bf16 compute / f32 masters
    tbptt_back_length: int = 20
    seed: int = 123
    iterations: int = 1
    optimization_algo: str = "stochastic_gradient_descent"
    max_num_line_search_iterations: int = 5
    minimize: bool = True
    lr_policy: str = "none"
    lr_policy_decay_rate: Optional[float] = None
    lr_policy_steps: Optional[float] = None
    lr_policy_power: Optional[float] = None
    lr_schedule: Optional[Dict[int, float]] = None
    momentum_schedule: Optional[Dict[int, float]] = None
    regularization: bool = False

    # ---------------------------------------------------------------- checks
    def validate(self) -> None:
        """Structural validation (reference ComputationGraphConfiguration
        .validate(): unknown inputs, missing outputs, cycles)."""
        if not self.inputs:
            raise ValueError("graph has no inputs (addInputs)")
        if not self.outputs:
            raise ValueError("graph has no outputs (setOutputs)")
        known = set(self.inputs) | set(self.vertices)
        for name, ins in self.vertex_inputs.items():
            for i in ins:
                if i not in known:
                    raise ValueError(f"vertex '{name}' references unknown input '{i}'")
        for o in self.outputs:
            if o not in self.vertices:
                raise ValueError(f"output '{o}' is not a vertex")
        self.topological_order()  # raises on cycle

    def topological_order(self) -> List[str]:
        """Kahn topological sort of vertex names (reference
        ComputationGraph.topologicalSortOrder() :279,511-540)."""
        indeg = {name: 0 for name in self.vertices}
        consumers: Dict[str, List[str]] = {}
        for name, ins in self.vertex_inputs.items():
            for i in ins:
                if i in self.vertices:
                    indeg[name] += 1
                    consumers.setdefault(i, []).append(name)
        # deterministic order: insertion order of `vertices` for ties
        ready = [n for n in self.vertices if indeg[n] == 0]
        order: List[str] = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for c in consumers.get(n, []):
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"graph has a cycle involving {sorted(cyc)}")
        return order

    # ----------------------------------------------------------------- serde
    def to_dict(self) -> Dict[str, Any]:
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_to_dict

        def vert(v):
            if isinstance(v, Layer):
                d = v.to_dict()
                d["vertex_kind"] = "layer"
                return d
            d = v.to_dict()
            d["vertex_kind"] = "graph"
            return d

        return {
            "format": "deeplearning4j_tpu/ComputationGraphConfiguration",
            "version": 1,
            "inputs": list(self.inputs),
            "vertices": {k: vert(v) for k, v in self.vertices.items()},
            "vertex_inputs": {k: list(v) for k, v in self.vertex_inputs.items()},
            "outputs": list(self.outputs),
            "input_preprocessors": {
                k: preprocessor_to_dict(v)
                for k, v in self.input_preprocessors.items()
            },
            "backprop": self.backprop,
            "pretrain": self.pretrain,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "gradient_checkpointing": self.gradient_checkpointing,
            "dtype_policy": self.dtype_policy,
            "tbptt_back_length": self.tbptt_back_length,
            "seed": self.seed,
            "iterations": self.iterations,
            "optimization_algo": self.optimization_algo,
            "max_num_line_search_iterations": self.max_num_line_search_iterations,
            "minimize": self.minimize,
            "lr_policy": self.lr_policy,
            "lr_policy_decay_rate": self.lr_policy_decay_rate,
            "lr_policy_steps": self.lr_policy_steps,
            "lr_policy_power": self.lr_policy_power,
            "lr_schedule": (
                {str(k): v for k, v in self.lr_schedule.items()}
                if self.lr_schedule
                else None
            ),
            "momentum_schedule": (
                {str(k): v for k, v in self.momentum_schedule.items()}
                if self.momentum_schedule
                else None
            ),
            "regularization": self.regularization,
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ComputationGraphConfiguration":
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_from_dict

        def vert(vd):
            vd = dict(vd)
            kind = vd.pop("vertex_kind")
            if kind == "layer":
                return layer_from_dict(vd)
            return _vertex_from_dict(vd)

        return ComputationGraphConfiguration(
            inputs=list(d["inputs"]),
            vertices={k: vert(v) for k, v in d["vertices"].items()},
            vertex_inputs={k: list(v) for k, v in d["vertex_inputs"].items()},
            outputs=list(d["outputs"]),
            input_preprocessors={
                k: preprocessor_from_dict(v)
                for k, v in (d.get("input_preprocessors") or {}).items()
            },
            backprop=d.get("backprop", True),
            pretrain=d.get("pretrain", False),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            gradient_checkpointing=d.get("gradient_checkpointing", False),
            dtype_policy=d.get("dtype_policy", "strict"),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            seed=d.get("seed", 123),
            iterations=d.get("iterations", 1),
            optimization_algo=d.get("optimization_algo", "stochastic_gradient_descent"),
            max_num_line_search_iterations=d.get("max_num_line_search_iterations", 5),
            minimize=d.get("minimize", True),
            lr_policy=d.get("lr_policy", "none"),
            lr_policy_decay_rate=d.get("lr_policy_decay_rate"),
            lr_policy_steps=d.get("lr_policy_steps"),
            lr_policy_power=d.get("lr_policy_power"),
            lr_schedule=(
                {int(k): v for k, v in d["lr_schedule"].items()}
                if d.get("lr_schedule")
                else None
            ),
            momentum_schedule=(
                {int(k): v for k, v in d["momentum_schedule"].items()}
                if d.get("momentum_schedule")
                else None
            ),
            regularization=d.get("regularization", False),
        )

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))

    # YAML round-trip (reference NeuralNetConfiguration.java:285-345)
    def to_yaml(self) -> str:
        import yaml

        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml

        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))


# ---------------------------------------------------------------------------
# GraphBuilder
# ---------------------------------------------------------------------------


class GraphBuilder:
    """Fluent DAG builder (reference GraphBuilder :569-605).

    Usage:
        conf = (NeuralNetConfiguration.builder().learning_rate(0.1)
                .graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_in=4, n_out=8), "in")
                .add_vertex("merge", MergeVertex(), "d1", "in")
                .add_layer("out", OutputLayer(n_in=12, n_out=3,
                           activation="softmax", loss_function="mcxent"),
                           "merge")
                .set_outputs("out")
                .build())
    """

    def __init__(self, parent):
        self._parent = parent  # nn.conf.builder.Builder
        self._inputs: List[str] = []
        self._vertices: Dict[str, Any] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._outputs: List[str] = []
        self._input_preprocessors: Dict[str, Any] = {}
        self._backprop = True
        self._pretrain = False
        self._backprop_type = "standard"
        self._tbptt_fwd_length = 20
        self._gradient_checkpointing = False
        self._dtype_policy = "strict"
        self._tbptt_back_length = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(
        self, name: str, layer: Layer, *inputs: str, preprocessor=None
    ) -> "GraphBuilder":
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"duplicate vertex name '{name}'")
        self._vertices[name] = layer
        self._vertex_inputs[name] = list(inputs)
        if preprocessor is not None:
            self._input_preprocessors[name] = preprocessor
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"duplicate vertex name '{name}'")
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def backprop(self, b: bool) -> "GraphBuilder":
        self._backprop = bool(b)
        return self

    def pretrain(self, b: bool) -> "GraphBuilder":
        self._pretrain = bool(b)
        return self

    def backprop_type(self, t: str) -> "GraphBuilder":
        t = t.lower()
        if t not in ("standard", "truncated_bptt"):
            raise ValueError(f"unknown backprop type {t}")
        self._backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_fwd_length = int(n)
        return self

    def gradient_checkpointing(self, enabled: bool = True) -> "GraphBuilder":
        """Rematerialize layer activations in backward (jax.checkpoint)."""
        self._gradient_checkpointing = bool(enabled)
        return self

    def dtype_policy(self, policy: str) -> "GraphBuilder":
        """'strict' or 'performance' (bf16 compute / f32 masters)."""
        if policy not in ("strict", "performance"):
            raise ValueError(f"unknown dtype_policy {policy!r}")
        self._dtype_policy = policy
        return self

    def t_bptt_backward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_back_length = int(n)
        return self

    def build(self) -> ComputationGraphConfiguration:
        g = self._parent.global_conf()
        vertices = {
            k: (resolve(copy.deepcopy(v), g) if isinstance(v, Layer) else v)
            for k, v in self._vertices.items()
        }
        conf = ComputationGraphConfiguration(
            inputs=list(self._inputs),
            vertices=vertices,
            vertex_inputs={k: list(v) for k, v in self._vertex_inputs.items()},
            outputs=list(self._outputs),
            input_preprocessors=dict(self._input_preprocessors),
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd_length,
            gradient_checkpointing=self._gradient_checkpointing,
            dtype_policy=self._dtype_policy,
            tbptt_back_length=self._tbptt_back_length,
            **self._parent.training_conf(),
        )
        conf.validate()
        return conf
