"""Configuration DSL.

The serializable model spec — same role as the reference's
``NeuralNetConfiguration.Builder`` -> ``MultiLayerConfiguration`` /
``ComputationGraphConfiguration`` Jackson tree
(deeplearning4j-core/.../nn/conf/NeuralNetConfiguration.java:285-345,377-703).
Configs are frozen dataclasses with JSON round-trip; they are the unit that
checkpoints, broadcast, and the CLI exchange.
"""

from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    AutoEncoder,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GRU,
    GravesBidirectionalLSTM,
    GravesLSTM,
    LocalResponseNormalization,
    OutputLayer,
    RBM,
    RnnOutputLayer,
    SubsamplingLayer,
    layer_from_dict,
)
from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
