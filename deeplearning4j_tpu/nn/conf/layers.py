"""Per-layer configuration dataclasses.

Mirrors the reference's ``nn/conf/layers`` package (20 classes, SURVEY.md
section 2.1): Dense, Convolution, Subsampling, BatchNormalization,
LocalResponseNormalization, GravesLSTM, GravesBidirectionalLSTM, GRU,
Embedding, AutoEncoder, RBM, OutputLayer, RnnOutputLayer, ActivationLayer.

Hyperparameter fields default to ``None`` = "inherit from the global builder"
— reproducing the reference's layerwise-override resolution
(NeuralNetConfiguration.java:703-860). :func:`resolve` fills a layer conf from
the global defaults; the resolved conf is what the runtime layers consume.

Data-format conventions (TPU-idiomatic, diverging deliberately from the
reference):
  - CNN tensors are NHWC (reference: NCHW) — better XLA/TPU layouts.
  - RNN tensors are [batch, time, features] (reference: [batch, features, time]).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# registry for JSON serde (role of Jackson subtype registration,
# NeuralNetConfiguration.java:285-345)
# ---------------------------------------------------------------------------

LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_to_dict(layer: "Layer") -> Dict[str, Any]:
    d = dataclasses.asdict(layer)
    d["type"] = type(layer).__name__
    return d


def layer_from_dict(d: Dict[str, Any]) -> "Layer":
    d = dict(d)
    cls = LAYER_REGISTRY[d.pop("type")]
    # tolerate tuples serialized as lists
    obj = cls(**d)
    return obj


def _tupled(v):
    return tuple(v) if isinstance(v, list) else v


# ---------------------------------------------------------------------------
# base classes
# ---------------------------------------------------------------------------

# Fields a layer may leave as None to inherit the global builder value
# (reference: layerwise override resolution NeuralNetConfiguration.java:703-860).
INHERITABLE = (
    "activation",
    "weight_init",
    "dist",
    "bias_init",
    "learning_rate",
    "bias_learning_rate",
    "l1",
    "l2",
    "dropout",
    "updater",
    "momentum",
    "rho",
    "rms_decay",
    "adam_mean_decay",
    "adam_var_decay",
    "epsilon",
    "gradient_normalization",
    "gradient_normalization_threshold",
)

# True defaults, applied when neither layer nor builder sets a value.
# Values follow the reference's Builder defaults
# (NeuralNetConfiguration.java:377-460): activation sigmoid, weightInit xavier,
# lr 0.1, momentum 0.5, rmsDecay 0.95, adam 0.9/0.999, updater sgd.
GLOBAL_DEFAULTS: Dict[str, Any] = {
    "activation": "sigmoid",
    "weight_init": "xavier",
    "dist": None,
    "bias_init": 0.0,
    "learning_rate": 0.1,
    "bias_learning_rate": None,  # None -> use learning_rate
    "l1": 0.0,
    "l2": 0.0,
    "dropout": 0.0,
    "updater": "sgd",
    "momentum": 0.5,
    "rho": 0.95,
    "rms_decay": 0.95,
    "adam_mean_decay": 0.9,
    "adam_var_decay": 0.999,
    "epsilon": 1e-8,
    "gradient_normalization": None,
    "gradient_normalization_threshold": 1.0,
}


@dataclass
class Layer:
    """Base layer conf. All hyperparams optional -> inherit from builder."""

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[dict] = None
    bias_init: Optional[float] = None
    learning_rate: Optional[float] = None
    bias_learning_rate: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    updater: Optional[str] = None
    momentum: Optional[float] = None
    rho: Optional[float] = None
    rms_decay: Optional[float] = None
    adam_mean_decay: Optional[float] = None
    adam_var_decay: Optional[float] = None
    epsilon: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return layer_to_dict(self)


def resolve(layer: Layer, global_conf: Optional[Dict[str, Any]] = None) -> Layer:
    """Return a copy with all inheritable Nones filled from global/builder defaults."""
    global_conf = global_conf or {}
    updates = {}
    for f in INHERITABLE:
        if getattr(layer, f) is None:
            v = global_conf.get(f)
            if v is None:
                v = GLOBAL_DEFAULTS[f]
            updates[f] = v
    resolved = dataclasses.replace(layer, **updates)
    if resolved.bias_learning_rate is None:
        resolved.bias_learning_rate = resolved.learning_rate
    return resolved


@dataclass
class FeedForwardLayer(Layer):
    n_in: int = 0
    n_out: int = 0


# ---------------------------------------------------------------------------
# concrete layers
# ---------------------------------------------------------------------------


@register_layer
@dataclass
class DenseLayer(FeedForwardLayer):
    """Fully-connected layer (reference: nn/conf/layers/DenseLayer.java)."""


@register_layer
@dataclass
class OutputLayer(FeedForwardLayer):
    """Output layer with a loss function (reference: nn/conf/layers/OutputLayer.java)."""

    loss_function: str = "mcxent"


@register_layer
@dataclass
class RnnOutputLayer(FeedForwardLayer):
    """Per-timestep output layer (reference: nn/conf/layers/RnnOutputLayer.java)."""

    loss_function: str = "mcxent"


@register_layer
@dataclass
class ConvolutionLayer(FeedForwardLayer):
    """2D convolution; n_in = input channels, n_out = filters.

    Reference: nn/conf/layers/ConvolutionLayer.java (kernel/stride/padding);
    runtime was im2col+gemm (ConvolutionLayer.java:146-166) — here it lowers to
    ``lax.conv_general_dilated`` (NHWC/HWIO), XLA's native conv HLO.
    """

    kernel_size: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)

    def __post_init__(self):
        self.kernel_size = _tupled(self.kernel_size)
        self.stride = _tupled(self.stride)
        self.padding = _tupled(self.padding)


@register_layer
@dataclass
class SubsamplingLayer(Layer):
    """Spatial pooling: MAX / AVG / SUM (reference: nn/conf/layers/SubsamplingLayer.java)."""

    pooling_type: str = "max"
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)

    def __post_init__(self):
        self.kernel_size = _tupled(self.kernel_size)
        self.stride = _tupled(self.stride)
        self.padding = _tupled(self.padding)


@register_layer
@dataclass
class BatchNormalization(FeedForwardLayer):
    """Batch normalization (reference: nn/conf/layers/BatchNormalization.java;
    runtime nn/layers/normalization/BatchNormalization.java, 348 LoC).

    gamma/beta are trainable params; running mean/var live in layer *state*
    (reference stores them in the param vector via
    BatchNormalizationParamInitializer — pytree state is the functional
    equivalent)."""

    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    lock_gamma_beta: bool = False


@register_layer
@dataclass
class LocalResponseNormalization(Layer):
    """LRN across channels (reference: nn/conf/layers/LocalResponseNormalization.java)."""

    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75


@register_layer
@dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Index -> vector lookup (reference: nn/conf/layers/EmbeddingLayer.java;
    runtime feedforward/embedding/EmbeddingLayer.java). Input is int indices;
    forward is a gather, backward a scatter-add (XLA-native)."""


@register_layer
@dataclass
class ActivationLayer(Layer):
    """Standalone activation (reference: nn/conf/layers/ActivationLayer.java)."""


@register_layer
@dataclass
class AutoEncoder(FeedForwardLayer):
    """Denoising autoencoder (reference: nn/conf/layers/AutoEncoder.java;
    runtime feedforward/autoencoder/AutoEncoder.java)."""

    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss_function: str = "reconstruction_crossentropy"


@register_layer
@dataclass
class RBM(FeedForwardLayer):
    """Restricted Boltzmann machine trained by CD-k
    (reference: nn/conf/layers/RBM.java; runtime feedforward/rbm/RBM.java:101-137
    contrastiveDivergence). hidden/visible unit types: binary | gaussian |
    rectified | softmax."""

    hidden_unit: str = "binary"
    visible_unit: str = "binary"
    k: int = 1
    sparsity: float = 0.0
    loss_function: str = "reconstruction_crossentropy"


@register_layer
@dataclass
class GravesLSTM(FeedForwardLayer):
    """LSTM with peepholes, Graves (2013) variant
    (reference: nn/conf/layers/GravesLSTM.java; runtime
    nn/layers/recurrent/LSTMHelpers.java — fwd loop :132, bwd :273,
    weight layout [wI,wF,wO,wG,wFF,wOO,wGG] :58,97-99).
    Runtime here is a single fused gate matmul inside ``lax.scan``."""

    forget_gate_bias_init: float = 1.0


@register_layer
@dataclass
class GravesBidirectionalLSTM(FeedForwardLayer):
    """Bidirectional Graves LSTM (reference:
    nn/conf/layers/GravesBidirectionalLSTM.java; runtime
    nn/layers/recurrent/GravesBidirectionalLSTM.java, 313 LoC).
    Output is the sum of forward and backward passes (reference semantics)."""

    forget_gate_bias_init: float = 1.0


@register_layer
@dataclass
class GRU(FeedForwardLayer):
    """Gated recurrent unit (reference: nn/conf/layers/GRU.java; runtime
    nn/layers/recurrent/GRU.java, 399 LoC)."""


@register_layer
@dataclass
class MultiHeadAttention(FeedForwardLayer):
    """Multi-head self-attention over [N, T, F] sequences.

    Beyond-reference capability (the reference's only long-sequence tool is
    truncated BPTT — SURVEY.md section 5): pairs with the framework's ring
    attention (parallel/sequence_parallel.py) so sequences shard over the
    mesh's 'seq' axis and attention stays exact at any length.
    n_out is the model width; head_dim = n_out // num_heads."""

    num_heads: int = 4
    causal: bool = False

    def __post_init__(self):
        if self.n_out and self.num_heads and self.n_out % self.num_heads:
            raise ValueError(
                f"n_out={self.n_out} not divisible by num_heads={self.num_heads}"
            )
