"""MultiLayerConfiguration — the serializable stack spec.

Mirrors the reference's ``MultiLayerConfiguration`` (334 LoC:
backprop/pretrain flags, TBPTT lengths, JSON/YAML round-trip —
deeplearning4j-core/.../nn/conf/MultiLayerConfiguration.java; TBPTT defaults 20
at :55-56). JSON is the canonical wire/checkpoint format, as in the reference
where the config JSON is the model identity.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.nn.conf.layers import Layer, layer_from_dict


@dataclass
class MultiLayerConfiguration:
    layers: List[Layer] = field(default_factory=list)
    input_preprocessors: Dict[int, Any] = field(default_factory=dict)
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "standard"  # standard | truncated_bptt
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    # remat: recompute per-layer activations in the backward pass instead of
    # storing them (jax.checkpoint) — trades FLOPs for HBM, enabling bigger
    # batches/deeper nets on TPU. No reference equivalent (2016 JVM had no
    # activation rematerialization); TPU-first addition.
    gradient_checkpointing: bool = False
    # 'strict' = f32 everywhere (reference ND4J semantics, the north-star
    # mode); 'performance' = bf16 compute / f32 masters (MXU-native)
    dtype_policy: str = "strict"
    # training hyperparams (from the Builder)
    seed: int = 123
    iterations: int = 1
    optimization_algo: str = "stochastic_gradient_descent"
    max_num_line_search_iterations: int = 5
    minimize: bool = True
    lr_policy: str = "none"
    lr_policy_decay_rate: Optional[float] = None
    lr_policy_steps: Optional[float] = None
    lr_policy_power: Optional[float] = None
    lr_schedule: Optional[Dict[int, float]] = None
    momentum_schedule: Optional[Dict[int, float]] = None
    regularization: bool = False

    # -- serde --------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_to_dict

        return {
            "format": "deeplearning4j_tpu/MultiLayerConfiguration",
            "version": 1,
            "layers": [l.to_dict() for l in self.layers],
            "input_preprocessors": {
                str(k): preprocessor_to_dict(v)
                for k, v in self.input_preprocessors.items()
            },
            "backprop": self.backprop,
            "pretrain": self.pretrain,
            "backprop_type": self.backprop_type,
            "gradient_checkpointing": self.gradient_checkpointing,
            "dtype_policy": self.dtype_policy,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "seed": self.seed,
            "iterations": self.iterations,
            "optimization_algo": self.optimization_algo,
            "max_num_line_search_iterations": self.max_num_line_search_iterations,
            "minimize": self.minimize,
            "lr_policy": self.lr_policy,
            "lr_policy_decay_rate": self.lr_policy_decay_rate,
            "lr_policy_steps": self.lr_policy_steps,
            "lr_policy_power": self.lr_policy_power,
            "lr_schedule": (
                {str(k): v for k, v in self.lr_schedule.items()}
                if self.lr_schedule
                else None
            ),
            "momentum_schedule": (
                {str(k): v for k, v in self.momentum_schedule.items()}
                if self.momentum_schedule
                else None
            ),
            "regularization": self.regularization,
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "MultiLayerConfiguration":
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_from_dict

        return MultiLayerConfiguration(
            layers=[layer_from_dict(ld) for ld in d["layers"]],
            input_preprocessors={
                int(k): preprocessor_from_dict(v)
                for k, v in (d.get("input_preprocessors") or {}).items()
            },
            backprop=d.get("backprop", True),
            pretrain=d.get("pretrain", False),
            backprop_type=d.get("backprop_type", "standard"),
            gradient_checkpointing=d.get("gradient_checkpointing", False),
            dtype_policy=d.get("dtype_policy", "strict"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            seed=d.get("seed", 123),
            iterations=d.get("iterations", 1),
            optimization_algo=d.get(
                "optimization_algo", "stochastic_gradient_descent"
            ),
            max_num_line_search_iterations=d.get(
                "max_num_line_search_iterations", 5
            ),
            minimize=d.get("minimize", True),
            lr_policy=d.get("lr_policy", "none"),
            lr_policy_decay_rate=d.get("lr_policy_decay_rate"),
            lr_policy_steps=d.get("lr_policy_steps"),
            lr_policy_power=d.get("lr_policy_power"),
            lr_schedule=(
                {int(k): v for k, v in d["lr_schedule"].items()}
                if d.get("lr_schedule")
                else None
            ),
            momentum_schedule=(
                {int(k): v for k, v in d["momentum_schedule"].items()}
                if d.get("momentum_schedule")
                else None
            ),
            regularization=d.get("regularization", False),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    # YAML round-trip (reference NeuralNetConfiguration.java:285-345 has both
    # Jackson JSON and YAML mappers; same dict schema either way)
    def to_yaml(self) -> str:
        import yaml

        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml

        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))
