"""Fluent configuration builder.

Mirrors ``NeuralNetConfiguration.Builder`` (reference:
deeplearning4j-core/.../nn/conf/NeuralNetConfiguration.java:377-703 fluent
setters; ``ListBuilder`` for layer stacks :151-180) including the enums:

  - OptimizationAlgorithm (nn/api/OptimizationAlgorithm.java:26-32):
    line_gradient_descent | conjugate_gradient | hessian_free | lbfgs |
    stochastic_gradient_descent
  - Updater (nn/conf/Updater.java:10-17): sgd | adam | adadelta | nesterovs |
    adagrad | rmsprop | none
  - LearningRatePolicy (nn/conf/LearningRatePolicy.java:21-29): none |
    exponential | inverse | poly | sigmoid | step | schedule | score
  - GradientNormalization: renormalize_l2_per_layer |
    renormalize_l2_per_param_type | clip_elementwise_absolute_value |
    clip_l2_per_layer | clip_l2_per_param_type

Usage:
    conf = (NeuralNetConfiguration.builder()
            .seed(42).learning_rate(0.1).updater("nesterovs").momentum(0.9)
            .list()
            .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .backprop(True).pretrain(False)
            .build())
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.nn.conf.layers import GLOBAL_DEFAULTS, Layer, resolve

OPTIMIZATION_ALGOS = (
    "stochastic_gradient_descent",
    "line_gradient_descent",
    "conjugate_gradient",
    "lbfgs",
    "hessian_free",
)

LR_POLICIES = (
    "none",
    "exponential",
    "inverse",
    "poly",
    "sigmoid",
    "step",
    "schedule",
    "score",
)


class NeuralNetConfiguration:
    """Global (per-network) hyperparameters + the builder entry point."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._global: Dict[str, Any] = {}  # inheritable layer defaults
        self._seed: int = 123
        self._iterations: int = 1
        self._optimization_algo: str = "stochastic_gradient_descent"
        self._max_num_line_search_iterations: int = 5
        self._minimize: bool = True
        self._use_drop_connect: bool = False
        self._lr_policy: str = "none"
        self._lr_policy_decay_rate: Optional[float] = None
        self._lr_policy_steps: Optional[float] = None
        self._lr_policy_power: Optional[float] = None
        self._lr_schedule: Optional[Dict[int, float]] = None
        self._momentum_schedule: Optional[Dict[int, float]] = None
        self._regularization: bool = False

    # -- fluent global setters (subset mirrors Builder fields :377-703) -----
    def seed(self, s: int):
        self._seed = int(s)
        return self

    def iterations(self, n: int):
        self._iterations = int(n)
        return self

    def optimization_algo(self, algo: str):
        algo = algo.lower()
        if algo not in OPTIMIZATION_ALGOS:
            raise ValueError(f"unknown optimization algo {algo}")
        self._optimization_algo = algo
        return self

    def max_num_line_search_iterations(self, n: int):
        self._max_num_line_search_iterations = int(n)
        return self

    def minimize(self, b: bool = True):
        self._minimize = bool(b)
        return self

    def regularization(self, b: bool = True):
        self._regularization = bool(b)
        return self

    def learning_rate_policy(self, policy: str):
        policy = policy.lower()
        if policy not in LR_POLICIES:
            raise ValueError(f"unknown lr policy {policy}")
        self._lr_policy = policy
        return self

    def lr_policy_decay_rate(self, v: float):
        self._lr_policy_decay_rate = float(v)
        return self

    def lr_policy_steps(self, v: float):
        self._lr_policy_steps = float(v)
        return self

    def lr_policy_power(self, v: float):
        self._lr_policy_power = float(v)
        return self

    def learning_rate_schedule(self, schedule: Dict[int, float]):
        self._lr_schedule = {int(k): float(v) for k, v in schedule.items()}
        self._lr_policy = "schedule"
        return self

    def momentum_after(self, schedule: Dict[int, float]):
        self._momentum_schedule = {int(k): float(v) for k, v in schedule.items()}
        return self

    def _set(self, k, v):
        self._global[k] = v
        return self

    def activation(self, v: str):
        return self._set("activation", v)

    def weight_init(self, v: str):
        return self._set("weight_init", v)

    def dist(self, v: dict):
        return self._set("dist", v)

    def bias_init(self, v: float):
        return self._set("bias_init", float(v))

    def learning_rate(self, v: float):
        return self._set("learning_rate", float(v))

    def bias_learning_rate(self, v: float):
        return self._set("bias_learning_rate", float(v))

    def l1(self, v: float):
        self._regularization = True
        return self._set("l1", float(v))

    def l2(self, v: float):
        self._regularization = True
        return self._set("l2", float(v))

    def drop_out(self, v: float):
        return self._set("dropout", float(v))

    def updater(self, v: str):
        return self._set("updater", v.lower())

    def momentum(self, v: float):
        return self._set("momentum", float(v))

    def rho(self, v: float):
        return self._set("rho", float(v))

    def rms_decay(self, v: float):
        return self._set("rms_decay", float(v))

    def adam_mean_decay(self, v: float):
        return self._set("adam_mean_decay", float(v))

    def adam_var_decay(self, v: float):
        return self._set("adam_var_decay", float(v))

    def epsilon(self, v: float):
        return self._set("epsilon", float(v))

    def gradient_normalization(self, v: str):
        return self._set("gradient_normalization", v.lower())

    def gradient_normalization_threshold(self, v: float):
        return self._set("gradient_normalization_threshold", float(v))

    # -- transition to the layer-stack builder ------------------------------
    def list(self) -> "ListBuilder":
        return ListBuilder(self)

    def graph_builder(self):
        """Transition to the DAG builder (reference
        ComputationGraphConfiguration.GraphBuilder :569-605)."""
        from deeplearning4j_tpu.nn.conf.graph import GraphBuilder

        return GraphBuilder(self)

    def global_conf(self) -> Dict[str, Any]:
        g = dict(GLOBAL_DEFAULTS)
        g.update(self._global)
        return g

    def training_conf(self) -> Dict[str, Any]:
        """The non-layer training hyperparams carried into the network conf."""
        return {
            "seed": self._seed,
            "iterations": self._iterations,
            "optimization_algo": self._optimization_algo,
            "max_num_line_search_iterations": self._max_num_line_search_iterations,
            "minimize": self._minimize,
            "lr_policy": self._lr_policy,
            "lr_policy_decay_rate": self._lr_policy_decay_rate,
            "lr_policy_steps": self._lr_policy_steps,
            "lr_policy_power": self._lr_policy_power,
            "lr_schedule": self._lr_schedule,
            "momentum_schedule": self._momentum_schedule,
            "regularization": self._regularization,
        }


class ListBuilder:
    """Layer-stack builder (reference ListBuilder :151-180)."""

    def __init__(self, parent: Builder):
        self._parent = parent
        self._layers: Dict[int, Layer] = {}
        self._preprocessors: Dict[int, Any] = {}
        self._backprop = True
        self._pretrain = False
        self._backprop_type = "standard"
        self._gradient_checkpointing = False
        self._dtype_policy = "strict"
        self._tbptt_fwd_length = 20
        self._tbptt_back_length = 20

    def layer(self, index: int, layer: Layer) -> "ListBuilder":
        self._layers[int(index)] = layer
        return self

    def add(self, layer: Layer) -> "ListBuilder":
        self._layers[len(self._layers)] = layer
        return self

    def input_preprocessor(self, index: int, preprocessor) -> "ListBuilder":
        self._preprocessors[int(index)] = preprocessor
        return self

    def backprop(self, b: bool) -> "ListBuilder":
        self._backprop = bool(b)
        return self

    def pretrain(self, b: bool) -> "ListBuilder":
        self._pretrain = bool(b)
        return self

    def backprop_type(self, t: str) -> "ListBuilder":
        t = t.lower()
        if t not in ("standard", "truncated_bptt"):
            raise ValueError(f"unknown backprop type {t}")
        self._backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "ListBuilder":
        self._tbptt_fwd_length = int(n)
        return self

    def gradient_checkpointing(self, enabled: bool = True) -> "ListBuilder":
        """Rematerialize layer activations in backward (jax.checkpoint):
        less HBM, more FLOPs. TPU-first addition (no 2016 reference
        equivalent)."""
        self._gradient_checkpointing = bool(enabled)
        return self

    def dtype_policy(self, policy: str) -> "ListBuilder":
        """'strict' (f32, reference semantics) or 'performance' (bf16
        compute with f32 master params — the MXU-native mixed precision)."""
        if policy not in ("strict", "performance"):
            raise ValueError(f"unknown dtype_policy {policy!r}")
        self._dtype_policy = policy
        return self

    def t_bptt_backward_length(self, n: int) -> "ListBuilder":
        self._tbptt_back_length = int(n)
        return self

    def build(self):
        from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration

        if not self._layers:
            raise ValueError("no layers configured")
        n = max(self._layers) + 1
        missing = [i for i in range(n) if i not in self._layers]
        if missing:
            raise ValueError(f"missing layer indices: {missing}")
        g = self._parent.global_conf()
        layers: List[Layer] = [
            resolve(copy.deepcopy(self._layers[i]), g) for i in range(n)
        ]
        return MultiLayerConfiguration(
            layers=layers,
            input_preprocessors=dict(self._preprocessors),
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            gradient_checkpointing=self._gradient_checkpointing,
            dtype_policy=self._dtype_policy,
            tbptt_fwd_length=self._tbptt_fwd_length,
            tbptt_back_length=self._tbptt_back_length,
            **self._parent.training_conf(),
        )
