"""Multi-head self-attention layer runtime.

Beyond-reference layer (SURVEY.md section 5 notes the reference's only
long-sequence mechanism is truncated BPTT): functional MHA over [N, T, F]
activations, with the math shared with the sequence-parallel ring-attention
path (parallel/sequence_parallel.py) — single-device here, sharded exact
attention when driven through ring_attention_sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import BaseLayerImpl
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.parallel.sequence_parallel import (
    mha_apply,
    multi_head_attention,
)


class MultiHeadAttentionImpl(BaseLayerImpl):
    def initialize(self, key, input_shape):
        t, f = input_shape
        conf = self.conf
        n_in = conf.n_in or f
        n_out = conf.n_out or n_in
        head_dim = n_out // conf.num_heads

        def w(k, shape):
            return init_weights(
                k, shape, conf.weight_init or "xavier", shape[0], shape[1],
                conf.dist,
            )

        k1, k2, k3, k4 = jax.random.split(key, 4)
        proj = conf.num_heads * head_dim
        params = {
            "Wq": w(k1, (n_in, proj)),
            "Wk": w(k2, (n_in, proj)),
            "Wv": w(k3, (n_in, proj)),
            "Wo": w(k4, (proj, n_out)),
            "b": jnp.zeros((n_out,), jnp.float32),
        }
        return params, {}, (t, n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              carry_state=False):
        x = self._dropout_in(x, train, rng)
        y = mha_apply(
            {k: params[k] for k in ("Wq", "Wk", "Wv", "Wo")},
            x,
            self.conf.num_heads,
            causal=self.conf.causal,
            key_mask=mask,  # padded timesteps excluded from the softmax
        ) + params["b"]
        y = self.act(y)
        if mask is not None:
            y = y * jnp.asarray(mask, y.dtype)[..., None]
        return y, state

    def step(self, params, state, x_t):
        """Streaming single-step inference (rnnTimeStep) with a KV cache:
        the attention analog of carried LSTM state. x_t: [N, F]."""
        conf = self.conf
        n = x_t.shape[0]
        proj = params["Wq"].shape[1]
        head_dim = proj // conf.num_heads

        def split(w):
            return (x_t @ w).reshape(n, 1, conf.num_heads, head_dim)

        q, k_new, v_new = split(params["Wq"]), split(params["Wk"]), split(params["Wv"])
        k_cache = state.get("k_cache")
        if k_cache is None or k_cache.shape[0] != n:
            k, v = k_new, v_new
        else:
            k = jnp.concatenate([k_cache, k_new], axis=1)
            v = jnp.concatenate([state["v_cache"], v_new], axis=1)
        att = multi_head_attention(q, k, v, causal=False)  # all cache visible
        y = att.reshape(n, proj) @ params["Wo"] + params["b"]
        return self.act(y), {"k_cache": k, "v_cache": v}
