"""Recurrent layers: GravesLSTM (peepholes), GravesBidirectionalLSTM, GRU.

Reference runtime: nn/layers/recurrent/LSTMHelpers.java (415 LoC; forward
time-loop :132, backward :273, per-step gemms :145,403; recurrent weight
layout [wI,wF,wO,wG,wFF,wOO,wGG] :58,97-99), GravesBidirectionalLSTM.java,
GRU.java (399 LoC).

TPU-first design:
  - The input projection x@W_x for ALL timesteps is ONE [N*T, 4H] matmul
    hoisted out of the recurrence (MXU-sized), leaving only the [N,H]@[H,4H]
    recurrent matmul inside ``lax.scan``.
  - The backward pass is jax autodiff through the scan (no hand-written BPTT).
  - Per-timestep masking keeps both the output and the carried state frozen
    through padded steps (reference: variable-length masking,
    MultiLayerNetwork.setLayerMaskArrays:1053).
  - Streaming inference (`rnnTimeStep`, reference MultiLayerNetwork:2152)
    reuses `step` with state carried in the layer state pytree.

Gate math (Graves 2013 variant, as in the reference — peepholes on input and
forget gates from c_{t-1}, on output gate from c_t):
    i = sigmoid(xW_i + hU_i + p_i * c_prev + b_i)
    f = sigmoid(xW_f + hU_f + p_f * c_prev + b_f)
    g = act(xW_g + hU_g + b_g)                      # block input
    c = f * c_prev + i * g
    o = sigmoid(xW_o + hU_o + p_o * c + b_o)
    h = o * act(c)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers.base import BaseLayerImpl
from deeplearning4j_tpu.nn.weights import init_weights


def _init_lstm_params(conf, key, n_in, n_out):
    k1, k2, k3 = jax.random.split(key, 3)
    W = init_weights(k1, (n_in, 4 * n_out), conf.weight_init, n_in, n_out, conf.dist)
    U = init_weights(k2, (n_out, 4 * n_out), conf.weight_init, n_out, n_out, conf.dist)
    p = jnp.zeros((3, n_out), jnp.float32)  # peepholes [i, f, o]
    b = jnp.zeros((4 * n_out,), jnp.float32)
    # forget-gate bias init (reference GravesLSTM forgetGateBiasInit, default 1)
    b = b.at[n_out : 2 * n_out].set(conf.forget_gate_bias_init)
    return {"W": W, "U": U, "p": p, "b": b}


def _lstm_step(act, params, h_prev, c_prev, xproj_t, mask_t):
    """One LSTM step. xproj_t = x_t @ W + b precomputed. mask_t: [N,1] or None."""
    n_out = h_prev.shape[-1]
    z = xproj_t + h_prev @ params["U"]
    zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
    p = params["p"]
    i = jax.nn.sigmoid(zi + p[0] * c_prev)
    f = jax.nn.sigmoid(zf + p[1] * c_prev)
    g = act(zg)
    c = f * c_prev + i * g
    o = jax.nn.sigmoid(zo + p[2] * c)
    h = o * act(c)
    if mask_t is not None:
        h = jnp.where(mask_t, h, h_prev)
        c = jnp.where(mask_t, c, c_prev)
    return h, c


def _scan_lstm(act, params, x, h0, c0, mask, reverse=False, is_tanh=False,
               backprop_window=None):
    """x: [N,T,F] -> outputs [N,T,H], final (h,c).

    backprop_window=B < T reproduces the reference's distinct TBPTT back
    length (LSTMHelpers.backpropGradientHelper:219,255 — the backward loop
    stops at endIdx = T - B, accumulating weight gradients and emitting
    epsilon only for the last B steps): the first T-B steps run under
    stop_gradient (values flow, gradients don't), the last B normally.
    """
    n, t, _ = x.shape
    if backprop_window is not None and 0 < backprop_window < t and not reverse:
        cut = t - backprop_window
        m_e = mask[:, :cut] if mask is not None else None
        m_l = mask[:, cut:] if mask is not None else None
        ys_e, h_m, c_m = _scan_lstm(
            act, params, x[:, :cut], h0, c0, m_e, is_tanh=is_tanh
        )
        ys_e = lax.stop_gradient(ys_e)
        h_m = lax.stop_gradient(h_m)
        c_m = lax.stop_gradient(c_m)
        ys_l, h_f, c_f = _scan_lstm(
            act, params, x[:, cut:], h_m, c_m, m_l, is_tanh=is_tanh
        )
        return jnp.concatenate([ys_e, ys_l], axis=1), h_f, c_f
    n_out = h0.shape[-1]
    xproj = (x.reshape(n * t, -1) @ params["W"] + params["b"]).reshape(n, t, 4 * n_out)
    if is_tanh and mask is None and not reverse and t >= 8:
        # hot path: fused pallas kernel keeps U/h/c VMEM-resident across the
        # whole recurrence (ops/pallas_kernels.py; cuDNN-helper role).
        # t >= 8: for near-single-step calls (rnn_time_step streaming) the
        # kernel's launch overhead loses to the fused scan (measured).
        from deeplearning4j_tpu.ops import pallas_kernels as pk

        if (pk.pallas_enabled() and pk.lstm_scan_fits(n, n_out, t)
                and pk.lstm_kernel_wins(n, n_out, t)):
            hs, h_f, c_f = pk.lstm_pallas_scan(
                xproj, params["U"], params["p"], h0, c0
            )
            # kernel computes in f32; preserve the caller's dtype contract
            return (hs.astype(x.dtype), h_f.astype(x.dtype),
                    c_f.astype(x.dtype))
    xproj_t = jnp.swapaxes(xproj, 0, 1)  # [T,N,4H] scan over leading axis
    mask_t = None
    if mask is not None:
        mask_t = jnp.swapaxes(
            jnp.asarray(mask, bool)[..., None], 0, 1
        )  # [T,N,1]

    def step(carry, inp):
        h_prev, c_prev = carry
        if mask is not None:
            xp, m = inp
        else:
            xp, m = inp, None
        h, c = _lstm_step(act, params, h_prev, c_prev, xp, m)
        return (h, c), h

    xs = (xproj_t, mask_t) if mask is not None else xproj_t
    (h_f, c_f), hs = lax.scan(step, (h0, c0), xs, reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), h_f, c_f


class GravesLSTMImpl(BaseLayerImpl):
    def initialize(self, key, input_shape):
        t, f = input_shape
        n_in = self.conf.n_in or f
        n_out = self.conf.n_out
        params = _init_lstm_params(self.conf, key, n_in, n_out)
        state = {
            "h": jnp.zeros((0, n_out), jnp.float32),  # streaming state, sized lazily
            "c": jnp.zeros((0, n_out), jnp.float32),
        }
        return params, state, (t, n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              carry_state=False, backprop_window=None):
        """carry_state=True resumes from state['h'/'c'] (TBPTT window chaining,
        reference doTruncatedBPTT; state shape must match the batch).
        backprop_window truncates the in-window backward pass (distinct
        tbptt_back_length — see _scan_lstm)."""
        x = self._dropout_in(x, train, rng)
        n = x.shape[0]
        n_out = self.conf.n_out
        if carry_state and state["h"].shape[0] == n:
            h0 = jnp.asarray(state["h"], x.dtype)
            c0 = jnp.asarray(state["c"], x.dtype)
        else:
            h0 = jnp.zeros((n, n_out), x.dtype)
            c0 = jnp.zeros((n, n_out), x.dtype)
        ys, h_f, c_f = _scan_lstm(
            self.act, params, x, h0, c0, mask,
            is_tanh=(self.conf.activation or "tanh") == "tanh",
            backprop_window=backprop_window,
        )
        if mask is not None:
            ys = ys * jnp.asarray(mask, ys.dtype)[..., None]
        return ys, {"h": h_f, "c": c_f}

    def step(self, params, state, x_t):
        """Single-timestep stateful inference (rnnTimeStep). x_t: [N,F]."""
        n = x_t.shape[0]
        n_out = self.conf.n_out
        h = state["h"] if state["h"].shape[0] == n else jnp.zeros((n, n_out), x_t.dtype)
        c = state["c"] if state["c"].shape[0] == n else jnp.zeros((n, n_out), x_t.dtype)
        xproj = x_t @ params["W"] + params["b"]
        h, c = _lstm_step(self.act, params, h, c, xproj, None)
        return h, {"h": h, "c": c}


class GravesBidirectionalLSTMImpl(BaseLayerImpl):
    """Forward + backward LSTM; outputs are summed (reference
    GravesBidirectionalLSTM.java combines the two direction activations)."""

    def initialize(self, key, input_shape):
        t, f = input_shape
        n_in = self.conf.n_in or f
        n_out = self.conf.n_out
        kf, kb = jax.random.split(key)
        params = {
            "fwd": _init_lstm_params(self.conf, kf, n_in, n_out),
            "bwd": _init_lstm_params(self.conf, kb, n_in, n_out),
        }
        return params, {}, (t, n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              carry_state=False, backprop_window=None):
        # bidirectional layers cannot carry state across TBPTT windows (the
        # backward pass needs the full window anyway; reference behaves the
        # same), and backprop_window is ignored: the two directions would
        # truncate at opposite ends, so the whole window backprops
        x = self._dropout_in(x, train, rng)
        n = x.shape[0]
        n_out = self.conf.n_out
        zeros = jnp.zeros((n, n_out), x.dtype)
        ys_f, _, _ = _scan_lstm(self.act, params["fwd"], x, zeros, zeros, mask)
        ys_b, _, _ = _scan_lstm(
            self.act, params["bwd"], x, zeros, zeros, mask, reverse=True
        )
        ys = ys_f + ys_b
        if mask is not None:
            ys = ys * jnp.asarray(mask, ys.dtype)[..., None]
        return ys, state


class GRUImpl(BaseLayerImpl):
    """Standard GRU (reference nn/layers/recurrent/GRU.java):
        r = sigmoid(xW_r + hU_r + b_r)
        z = sigmoid(xW_z + hU_z + b_z)
        n = act(xW_n + (r*h)U_n + b_n)
        h' = (1-z)*n + z*h
    """

    def initialize(self, key, input_shape):
        t, f = input_shape
        n_in = self.conf.n_in or f
        n_out = self.conf.n_out
        k1, k2 = jax.random.split(key)
        W = init_weights(k1, (n_in, 3 * n_out), self.conf.weight_init, n_in, n_out, self.conf.dist)
        U = init_weights(k2, (n_out, 3 * n_out), self.conf.weight_init, n_out, n_out, self.conf.dist)
        b = jnp.zeros((3 * n_out,), jnp.float32)
        state = {"h": jnp.zeros((0, n_out), jnp.float32)}
        return {"W": W, "U": U, "b": b}, state, (t, n_out)

    def _step(self, params, h_prev, xproj_t, mask_t):
        n_out = h_prev.shape[-1]
        zr, zz, zn = jnp.split(xproj_t, 3, axis=-1)
        Ur, Uz, Un = jnp.split(params["U"], 3, axis=-1)
        r = jax.nn.sigmoid(zr + h_prev @ Ur)
        z = jax.nn.sigmoid(zz + h_prev @ Uz)
        n = self.act(zn + (r * h_prev) @ Un)
        h = (1.0 - z) * n + z * h_prev
        if mask_t is not None:
            h = jnp.where(mask_t, h, h_prev)
        return h

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              carry_state=False, backprop_window=None):
        x = self._dropout_in(x, train, rng)
        n, t, _ = x.shape
        n_out = self.conf.n_out
        if carry_state and state["h"].shape[0] == n:
            h0 = jnp.asarray(state["h"], x.dtype)
        else:
            h0 = jnp.zeros((n, n_out), x.dtype)
        ys, h_f = self._scan(params, x, h0, mask, backprop_window)
        if mask is not None:
            ys = ys * jnp.asarray(mask, ys.dtype)[..., None]
        return ys, {"h": h_f}

    def _scan(self, params, x, h0, mask, backprop_window=None):
        """[N,T,F] scan; backprop_window splits with stop_gradient like
        _scan_lstm (reference tbpttBackpropGradient back-length truncation)."""
        n, t, _ = x.shape
        n_out = self.conf.n_out
        if backprop_window is not None and 0 < backprop_window < t:
            cut = t - backprop_window
            m_e = mask[:, :cut] if mask is not None else None
            m_l = mask[:, cut:] if mask is not None else None
            ys_e, h_m = self._scan(params, x[:, :cut], h0, m_e)
            ys_e = lax.stop_gradient(ys_e)
            h_m = lax.stop_gradient(h_m)
            ys_l, h_f = self._scan(params, x[:, cut:], h_m, m_l)
            return jnp.concatenate([ys_e, ys_l], axis=1), h_f
        xproj = (x.reshape(n * t, -1) @ params["W"] + params["b"]).reshape(
            n, t, 3 * n_out
        )
        xproj_t = jnp.swapaxes(xproj, 0, 1)
        mask_t = None
        if mask is not None:
            mask_t = jnp.swapaxes(jnp.asarray(mask, bool)[..., None], 0, 1)

        def step(h_prev, inp):
            if mask is not None:
                xp, m = inp
            else:
                xp, m = inp, None
            h = self._step(params, h_prev, xp, m)
            return h, h

        xs = (xproj_t, mask_t) if mask is not None else xproj_t
        h_f, hs = lax.scan(step, h0, xs)
        return jnp.swapaxes(hs, 0, 1), h_f

    def step(self, params, state, x_t):
        n = x_t.shape[0]
        n_out = self.conf.n_out
        h = state["h"] if state["h"].shape[0] == n else jnp.zeros((n, n_out), x_t.dtype)
        xproj = x_t @ params["W"] + params["b"]
        h = self._step(params, h, xproj, None)
        return h, {"h": h}
