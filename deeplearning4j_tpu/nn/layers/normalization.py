"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Reference runtime: nn/layers/normalization/BatchNormalization.java (348 LoC)
and LocalResponseNormalization.java, with cuDNN helpers in the cuda module
(CudnnBatchNormalizationHelper.java, CudnnLocalResponseNormalizationHelper.java).
Both are plain fused XLA element-wise/reduction code here.

BatchNorm state: running mean/var live in the layer *state* pytree (the
reference stores them in the flat param vector via
BatchNormalizationParamInitializer — gamma/beta/mean/var); only gamma/beta are
trainable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers.base import BaseLayerImpl


class BatchNormalizationImpl(BaseLayerImpl):
    def initialize(self, key, input_shape):
        n = input_shape[-1]  # features (FF) or channels (NHWC CNN)
        conf = self.conf
        params = {}
        if not conf.lock_gamma_beta:
            params["gamma"] = jnp.full((n,), conf.gamma, jnp.float32)
            params["beta"] = jnp.full((n,), conf.beta, jnp.float32)
        state = {
            "mean": jnp.zeros((n,), jnp.float32),
            "var": jnp.ones((n,), jnp.float32),
        }
        return params, state, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        conf = self.conf
        axes = tuple(range(x.ndim - 1))  # all but channel/feature axis
        if train:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            # running estimates: new = decay*old + (1-decay)*batch
            new_state = {
                "mean": conf.decay * state["mean"] + (1 - conf.decay) * mean,
                "var": conf.decay * state["var"] + (1 - conf.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        xhat = (x - mean) / jnp.sqrt(var + conf.eps)
        if conf.lock_gamma_beta:
            y = conf.gamma * xhat + conf.beta
        else:
            y = params["gamma"] * xhat + params["beta"]
        return y, new_state


class LocalResponseNormalizationImpl(BaseLayerImpl):
    """Cross-channel LRN on NHWC: y = x / (k + alpha*sum_window(x^2))^beta
    (reference LocalResponseNormalization.java; AlexNet-style)."""

    def initialize(self, key, input_shape):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        conf = self.conf
        half = int(conf.n) // 2
        sq = x * x
        # sum over a window of `n` adjacent channels (last axis)
        window = lax.reduce_window(
            sq,
            0.0,
            lax.add,
            (1,) * (x.ndim - 1) + (int(conf.n),),
            (1,) * x.ndim,
            ((0, 0),) * (x.ndim - 1) + ((half, int(conf.n) - 1 - half),),
        )
        return x / (conf.k + conf.alpha * window) ** conf.beta, state
