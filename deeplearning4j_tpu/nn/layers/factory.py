"""Config -> runtime layer mapping.

Role of the reference's ``nn/layers/factory/LayerFactories``
(deeplearning4j-core/.../nn/layers/factory/) which maps conf classes to
runtime impls. Kept as an explicit registry so alternative backends
(e.g. pallas-kernel variants) can be swapped in per layer type — the
TPU equivalent of the reference's reflective cuDNN-helper loading
(ConvolutionLayer.java:64-70).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import layers as conf_layers
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayerImpl,
    SubsamplingLayerImpl,
)
from deeplearning4j_tpu.nn.layers.feedforward import (
    ActivationLayerImpl,
    AutoEncoderImpl,
    DenseLayerImpl,
    EmbeddingLayerImpl,
    OutputLayerImpl,
    RBMImpl,
    RnnOutputLayerImpl,
)
from deeplearning4j_tpu.nn.layers.normalization import (
    BatchNormalizationImpl,
    LocalResponseNormalizationImpl,
)
from deeplearning4j_tpu.nn.layers.recurrent import (
    GRUImpl,
    GravesBidirectionalLSTMImpl,
    GravesLSTMImpl,
)
from deeplearning4j_tpu.nn.layers.attention import MultiHeadAttentionImpl

FACTORY = {
    conf_layers.DenseLayer: DenseLayerImpl,
    conf_layers.OutputLayer: OutputLayerImpl,
    conf_layers.RnnOutputLayer: RnnOutputLayerImpl,
    conf_layers.EmbeddingLayer: EmbeddingLayerImpl,
    conf_layers.ActivationLayer: ActivationLayerImpl,
    conf_layers.AutoEncoder: AutoEncoderImpl,
    conf_layers.RBM: RBMImpl,
    conf_layers.ConvolutionLayer: ConvolutionLayerImpl,
    conf_layers.SubsamplingLayer: SubsamplingLayerImpl,
    conf_layers.BatchNormalization: BatchNormalizationImpl,
    conf_layers.LocalResponseNormalization: LocalResponseNormalizationImpl,
    conf_layers.GravesLSTM: GravesLSTMImpl,
    conf_layers.GravesBidirectionalLSTM: GravesBidirectionalLSTMImpl,
    conf_layers.GRU: GRUImpl,
    conf_layers.MultiHeadAttention: MultiHeadAttentionImpl,
}

# recurrent layers with carryable state (TBPTT chaining / rnnTimeStep)
STATEFUL_RNN_CONFS = (
    conf_layers.GravesLSTM,
    conf_layers.GravesBidirectionalLSTM,
    conf_layers.GRU,
)

# layer families for preprocessor auto-insertion / input-type checking
RNN_CONFS = (
    conf_layers.GravesLSTM,
    conf_layers.GravesBidirectionalLSTM,
    conf_layers.GRU,
    conf_layers.RnnOutputLayer,
    conf_layers.MultiHeadAttention,  # consumes/produces [N, T, F]
)
CNN_CONFS = (
    conf_layers.ConvolutionLayer,
    conf_layers.SubsamplingLayer,
    conf_layers.LocalResponseNormalization,
)


def create_layer(conf):
    try:
        impl_cls = FACTORY[type(conf)]
    except KeyError:
        raise ValueError(
            f"No runtime implementation for layer conf {type(conf).__name__}"
        ) from None
    return impl_cls(conf)
