"""Runtime layer implementations.

Functional equivalents of the reference's ``nn/layers/**`` runtime classes
(SURVEY.md section 2.1 "nn/layers"): each config dataclass in
``nn/conf/layers.py`` maps (via :mod:`.factory`) to an impl exposing

    initialize(key, input_shape) -> (params, state, output_shape)
    apply(params, state, x, *, train, rng, mask) -> (y, new_state)

There is no ``backpropGradient`` anywhere — jax autodiff differentiates the
whole network; the reference's hand-written backward passes survive only as
the gradient-check oracle in utils/gradient_check.py.
"""

from deeplearning4j_tpu.nn.layers.factory import create_layer
