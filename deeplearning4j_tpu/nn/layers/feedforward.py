"""Feedforward layers: Dense, Output, Embedding, Activation, AutoEncoder, RBM.

Reference runtime classes (SURVEY.md section 2.1 "nn/layers"):
  - feedforward/dense/DenseLayer.java (via BaseLayer.preOutput/activate)
  - BaseOutputLayer.java / OutputLayer.java (loss handled by the container)
  - feedforward/embedding/EmbeddingLayer.java (gather fwd, scatter-add bwd —
    here XLA's take/segment-sum)
  - feedforward/autoencoder/AutoEncoder.java (denoising AE; corruption +
    reconstruct with tied-ish decoder W^T + visible bias)
  - feedforward/rbm/RBM.java:101-137 (CD-k contrastiveDivergence) — Gibbs
    sampling expressed with explicit jax.random keys; the CD parameter update
    is computed in closed form (it is not a loss gradient).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import BaseLayerImpl
from deeplearning4j_tpu.nn.losses import loss_fn
from deeplearning4j_tpu.ops.activations import activation


class DenseLayerImpl(BaseLayerImpl):
    def initialize(self, key, input_shape):
        n_in = self.conf.n_in or input_shape[-1]
        params = self._init_dense_params(key, n_in, self.conf.n_out)
        return params, {}, (self.conf.n_out,)

    def preout(self, params, x):
        return x @ params["W"] + params["b"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return self.act(self.preout(params, x)), state


class OutputLayerImpl(DenseLayerImpl):
    """Dense + loss function. The container computes the loss from `preout`
    (fusing softmax+MCXENT via log-softmax, BaseOutputLayer.java:90-91);
    `apply` yields the activated output for inference."""

    def loss(self, params, x, labels, mask=None):
        from deeplearning4j_tpu.nn import losses

        z = self.preout(params, x)
        name = self.conf.loss_function
        if losses.fused_with_softmax(name) and self.conf.activation == "softmax":
            return losses.mcxent_from_logits(labels, z, mask)
        return loss_fn(name)(labels, self.act(z), mask)


class RnnOutputLayerImpl(OutputLayerImpl):
    """Applies the dense output per timestep on [N,T,F] input
    (reference: recurrent/RnnOutputLayer.java — 2d reshape + super)."""

    def initialize(self, key, input_shape):
        t, f = input_shape
        n_in = self.conf.n_in or f
        params = self._init_dense_params(key, n_in, self.conf.n_out)
        return params, {}, (t, self.conf.n_out)

    def preout(self, params, x):
        return x @ params["W"] + params["b"]  # broadcasting handles [N,T,F]


class EmbeddingLayerImpl(BaseLayerImpl):
    def initialize(self, key, input_shape):
        n_in = self.conf.n_in  # vocab size; cannot be inferred from data shape
        params = self._init_dense_params(key, n_in, self.conf.n_out)
        return params, {}, (self.conf.n_out,)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim >= 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]  # reference passes [N,1] index column
        y = jnp.take(params["W"], idx, axis=0) + params["b"]
        return self.act(y), state


class ActivationLayerImpl(BaseLayerImpl):
    def initialize(self, key, input_shape):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return self.act(x), state


class AutoEncoderImpl(BaseLayerImpl):
    """Denoising autoencoder. Forward = encoder; pretraining objective =
    reconstruction loss after input corruption
    (reference feedforward/autoencoder/AutoEncoder.java)."""

    def initialize(self, key, input_shape):
        n_in = self.conf.n_in or input_shape[-1]
        params = self._init_dense_params(key, n_in, self.conf.n_out)
        params["vb"] = jnp.zeros((n_in,), jnp.float32)  # visible bias
        return params, {}, (self.conf.n_out,)

    def encode(self, params, x):
        return self.act(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return self.act(h @ params["W"].T + params["vb"])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return self.encode(params, x), state

    def pretrain_loss(self, params, x, rng):
        corrupted = x
        if self.conf.corruption_level and self.conf.corruption_level > 0:
            keep = jax.random.bernoulli(
                rng, 1.0 - self.conf.corruption_level, x.shape
            )
            corrupted = jnp.where(keep, x, 0.0)
        recon = self.decode(params, self.encode(params, corrupted))
        return loss_fn(self.conf.loss_function)(x, recon, None)


class RBMImpl(BaseLayerImpl):
    """RBM with CD-k pretraining (reference feedforward/rbm/RBM.java; CD loop
    :101-137). Unit types: binary | gaussian | rectified (visible/hidden)."""

    def initialize(self, key, input_shape):
        n_in = self.conf.n_in or input_shape[-1]
        params = self._init_dense_params(key, n_in, self.conf.n_out)
        params["vb"] = jnp.zeros((n_in,), jnp.float32)
        return params, {}, (self.conf.n_out,)

    # -- unit activations ----------------------------------------------------
    def _hidden_mean(self, params, v):
        z = v @ params["W"] + params["b"]
        h = self.conf.hidden_unit
        if h == "binary":
            return jax.nn.sigmoid(z)
        if h == "rectified":
            return jax.nn.relu(z)
        if h == "gaussian":
            return z
        if h == "softmax":
            return jax.nn.softmax(z, axis=-1)
        raise ValueError(f"unknown hidden unit {h}")

    def _visible_mean(self, params, h):
        z = h @ params["W"].T + params["vb"]
        v = self.conf.visible_unit
        if v == "binary":
            return jax.nn.sigmoid(z)
        if v == "gaussian":
            return z
        if v == "linear":
            return z
        if v == "softmax":
            return jax.nn.softmax(z, axis=-1)
        raise ValueError(f"unknown visible unit {v}")

    def _sample_hidden(self, params, v, key):
        mean = self._hidden_mean(params, v)
        if self.conf.hidden_unit == "binary":
            return jax.random.bernoulli(key, mean).astype(v.dtype), mean
        if self.conf.hidden_unit == "gaussian":
            return mean + jax.random.normal(key, mean.shape, mean.dtype), mean
        return mean, mean

    def _sample_visible(self, params, h, key):
        mean = self._visible_mean(params, h)
        if self.conf.visible_unit == "binary":
            return jax.random.bernoulli(key, mean).astype(h.dtype), mean
        if self.conf.visible_unit == "gaussian":
            return mean + jax.random.normal(key, mean.shape, mean.dtype), mean
        return mean, mean

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return self._hidden_mean(params, x), state

    def cd_grads(self, params, v0, rng):
        """CD-k gradient estimate: positive phase <v0 h0> minus negative phase
        <vk hk>, normalized per example. Returns a grads dict with the SAME
        keys as params (sign: gradient-to-*subtract*, matching our updaters).
        Reference math: RBM.java contrastiveDivergence :101-137."""
        k = max(1, int(self.conf.k))
        h0_mean = self._hidden_mean(params, v0)
        keys = jax.random.split(rng, 2 * k + 1)
        h_sample, _ = self._sample_hidden(params, v0, keys[0])
        vk, hk_mean = v0, h0_mean
        for i in range(k):
            vk, _ = self._sample_visible(params, h_sample, keys[2 * i + 1])
            h_sample, hk_mean = self._sample_hidden(params, vk, keys[2 * i + 2])
        n = v0.shape[0]
        gW = -(v0.T @ h0_mean - vk.T @ hk_mean) / n
        gb = -jnp.mean(h0_mean - hk_mean, axis=0)
        gvb = -jnp.mean(v0 - vk, axis=0)
        return {"W": gW, "b": gb, "vb": gvb}

    def pretrain_loss(self, params, x, rng):
        """Monitoring proxy: reconstruction cross-entropy after one Gibbs step."""
        h = self._hidden_mean(params, x)
        recon = self._visible_mean(params, h)
        return loss_fn("reconstruction_crossentropy")(x, recon, None)
