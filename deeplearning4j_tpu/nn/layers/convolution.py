"""Convolution + pooling layers.

Reference runtime: nn/layers/convolution/ConvolutionLayer.java (im2col+gemm,
:146-166) and SubsamplingLayer.java (326 LoC), accelerated by the cuDNN
helpers in deeplearning4j-cuda-7.5. On TPU both lower to native XLA HLOs —
``lax.conv_general_dilated`` hits the MXU directly; pooling is
``lax.reduce_window`` — so the whole Java+cuDNN helper stack collapses into
this file (SURVEY.md section 2.2 closing note).

Layout: NHWC activations, HWIO weights (TPU-friendly; reference is NCHW).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers.base import BaseLayerImpl
from deeplearning4j_tpu.nn.weights import init_weights


class ConvolutionLayerImpl(BaseLayerImpl):
    def initialize(self, key, input_shape):
        h, w, c_in = input_shape
        conf = self.conf
        if conf.n_in and conf.n_in != c_in:
            raise ValueError(f"conv n_in={conf.n_in} != input channels {c_in}")
        kh, kw = conf.kernel_size
        fan_in = c_in * kh * kw
        fan_out = conf.n_out * kh * kw
        W = init_weights(
            key,
            (kh, kw, c_in, conf.n_out),
            conf.weight_init,
            fan_in=fan_in,
            fan_out=fan_out,
            dist=conf.dist,
        )
        b = jnp.full((conf.n_out,), conf.bias_init or 0.0, jnp.float32)
        oh = (h + 2 * conf.padding[0] - kh) // conf.stride[0] + 1
        ow = (w + 2 * conf.padding[1] - kw) // conf.stride[1] + 1
        return {"W": W, "b": b}, {}, (oh, ow, conf.n_out)

    def preout(self, params, x):
        conf = self.conf
        pad = [(conf.padding[0],) * 2, (conf.padding[1],) * 2]
        kwargs = dict(
            window_strides=conf.stride,
            padding=pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        from deeplearning4j_tpu.ops.precision import (
            conv_f32_3pass,
            strict_conv_active,
        )

        if strict_conv_active():
            # north-star strict mode: f32-class conv via three DEFAULT-
            # precision passes (ops/precision.py — the HIGHEST-precision
            # conv compile wedges the remote TPU compile helper)
            y = conv_f32_3pass(x, params["W"], **kwargs)
        else:
            y = lax.conv_general_dilated(x, params["W"], **kwargs)
        return y + params["b"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return self.act(self.preout(params, x)), state


class SubsamplingLayerImpl(BaseLayerImpl):
    """MAX / AVG / SUM pooling (reference SubsamplingLayer PoolingType)."""

    def initialize(self, key, input_shape):
        h, w, c = input_shape
        kh, kw = self.conf.kernel_size
        sh, sw = self.conf.stride
        ph, pw = self.conf.padding
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (w + 2 * pw - kw) // sw + 1
        return {}, {}, (oh, ow, c)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        conf = self.conf
        kh, kw = conf.kernel_size
        sh, sw = conf.stride
        ph, pw = conf.padding
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        padding = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        pt = conf.pooling_type.lower()
        if pt == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, padding)
        elif pt in ("avg", "average"):
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
            y = s / float(kh * kw)
        elif pt == "sum":
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
        else:
            raise ValueError(f"unknown pooling type {pt}")
        return y, state
