"""Layer protocol + shared helpers (dropout, dense affine).

Role of the reference's ``BaseLayer``
(deeplearning4j-core/.../nn/layers/BaseLayer.java): activation application
(:369-372, by name through the op registry) and inverted-dropout on layer
input (:455 applyDropOutIfNecessary; util/Dropout.java).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import activation

Array = jax.Array
Params = Dict[str, Array]
State = Dict[str, Array]


def inverted_dropout(x: Array, rate: float, train: bool, rng: Optional[Array]) -> Array:
    """Inverted dropout, applied to layer *input* (reference util/Dropout.java:
    retain with prob (1-rate), scale by 1/(1-rate) at train time)."""
    if not train or rate <= 0.0:
        return x
    if rng is None:
        raise ValueError("dropout requires an rng key at train time")
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


class BaseLayerImpl:
    """Base for all runtime layers. Subclasses set params in `initialize` and
    define `apply`. Stateless layers return `state={}` unchanged."""

    def __init__(self, conf):
        self.conf = conf
        self.act = activation(conf.activation) if conf.activation else None

    # -- override points ----------------------------------------------------
    def initialize(self, key, input_shape) -> Tuple[Params, State, Tuple[int, ...]]:
        raise NotImplementedError

    def apply(
        self,
        params: Params,
        state: State,
        x: Array,
        *,
        train: bool = False,
        rng: Optional[Array] = None,
        mask: Optional[Array] = None,
    ) -> Tuple[Array, State]:
        raise NotImplementedError

    # -- helpers ------------------------------------------------------------
    def _dropout_in(self, x, train, rng):
        return inverted_dropout(x, self.conf.dropout or 0.0, train, rng)

    def _init_dense_params(self, key, n_in, n_out) -> Params:
        wkey, _ = jax.random.split(key)
        W = init_weights(
            wkey,
            (n_in, n_out),
            self.conf.weight_init,
            fan_in=n_in,
            fan_out=n_out,
            dist=self.conf.dist,
        )
        b = jnp.full((n_out,), self.conf.bias_init or 0.0, jnp.float32)
        return {"W": W, "b": b}

    # Regularizable param names: l1/l2 apply to weights, not biases
    # (reference BaseLayer.calcL2/calcL1 use only W).
    WEIGHT_PARAMS = ("W",)
