"""Loss functions for output layers.

Mirrors the reference's ``LossFunctions.LossFunction`` enum used by
``BaseOutputLayer`` (deeplearning4j-core/.../nn/layers/BaseOutputLayer.java:89-116,198):
MSE, EXPLL, XENT, MCXENT, RMSE_XENT, SQUARED_LOSS, RECONSTRUCTION_CROSSENTROPY,
NEGATIVELOGLIKELIHOOD. The special case at BaseOutputLayer.java:90-91 —
softmax + (NLL|MCXENT) computed via log-softmax for stability — is reproduced
here by fusing the output activation into the loss when applicable.

All losses:
  - take ``(labels, preactivation_or_activation, mask)``,
  - reduce to *mean per example* (reference score = total loss / minibatch,
    BaseOutputLayer.computeScore),
  - support per-timestep masks for RNN outputs (mask shape broadcastable to
    the leading axes of labels).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

_EPS = 1e-10


def _masked_mean_per_example(per_elem: Array, mask: Optional[Array]) -> Array:
    """Sum loss over feature axis, average over examples (and masked steps).

    per_elem: [..., features] per-element loss.
    mask: broadcastable to per_elem.shape[:-1]; 1 = keep.
    """
    per_row = jnp.sum(per_elem, axis=-1)  # [...]
    if mask is not None:
        mask = jnp.asarray(mask, per_row.dtype)
        mask = jnp.broadcast_to(mask, per_row.shape)
        total = jnp.sum(per_row * mask)
        count = jnp.maximum(jnp.sum(mask), 1.0)
        return total / count
    return jnp.mean(per_row)


def mse(labels, output, mask=None):
    return _masked_mean_per_example(0.5 * (output - labels) ** 2, mask)


def squared_loss(labels, output, mask=None):
    return _masked_mean_per_example((output - labels) ** 2, mask)


def rmse_xent(labels, output, mask=None):
    # reference: sqrt of per-element squared error (legacy, rarely used)
    return _masked_mean_per_example(jnp.sqrt((output - labels) ** 2 + _EPS), mask)


def xent(labels, output, mask=None):
    """Binary cross entropy; `output` is post-sigmoid activation."""
    p = jnp.clip(output, _EPS, 1.0 - _EPS)
    per = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))
    return _masked_mean_per_example(per, mask)


def mcxent_from_logits(labels, logits, mask=None):
    """Softmax + multi-class cross entropy fused via log-softmax.

    The numerically-stable path of BaseOutputLayer.java:90-91.
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    return _masked_mean_per_example(-labels * logp, mask)


def mcxent(labels, output, mask=None):
    """Multi-class cross entropy on an already-activated output."""
    return _masked_mean_per_example(-labels * jnp.log(jnp.clip(output, _EPS, 1.0)), mask)


def negativeloglikelihood(labels, output, mask=None):
    return mcxent(labels, output, mask)


def expll(labels, output, mask=None):
    """Exponential log likelihood (Poisson-style): mean(output - labels*log(output))."""
    return _masked_mean_per_example(
        output - labels * jnp.log(jnp.clip(output, _EPS, None)), mask
    )


def reconstruction_crossentropy(labels, output, mask=None):
    return xent(labels, output, mask)


LOSSES: Dict[str, Callable] = {
    "mse": mse,
    "squared_loss": squared_loss,
    "rmse_xent": rmse_xent,
    "xent": xent,
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "expll": expll,
    "reconstruction_crossentropy": reconstruction_crossentropy,
}

# Losses where the stable fused-from-logits path exists when paired with softmax.
_FUSED_SOFTMAX = {"mcxent", "negativeloglikelihood"}


def loss_fn(name: str) -> Callable:
    try:
        return LOSSES[name.lower()]
    except KeyError:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(LOSSES)}") from None


def fused_with_softmax(name: str) -> bool:
    return name.lower() in _FUSED_SOFTMAX
