"""MultiLayerNetwork — the sequential network container.

Functional re-design of the reference's ``MultiLayerNetwork`` (2,372 LoC,
deeplearning4j-core/.../nn/multilayer/MultiLayerNetwork.java):

  reference mechanism                        -> here
  -----------------------------------------------------------------------
  init() flat param view array (:349-440)    -> list-of-dicts param pytree
  computeGradientAndScore (:1786)            -> jax.value_and_grad of _loss
  backprop()/calcBackpropGradients (:1071)   -> autodiff (no hand backward)
  Solver/StochasticGradientDescent iteration -> ONE jitted train_step:
                                                forward+backward+updater+step
                                                compiled to a single XLA program
  fit(DataSetIterator) (:1017)               -> fit / fit_iterator
  pretrain() layerwise RBM/AE (:165-213)     -> pretrain()
  output()/feedForward (:619-704)            -> output()
  evaluate (:2316)                           -> evaluate()
  rnnTimeStep (:2152)                        -> rnn_time_step()  [stateful]
  setLayerMaskArrays (:1053)                 -> mask/label_mask arguments
  doTruncatedBPTT (:1162)                    -> fit with tbptt window slicing

The whole-step jit is the single biggest architectural win over the
reference's op-by-op dispatch (SURVEY.md section 7 "Architectural
translations").
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf import layers as conf_layers
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.factory import (
    CNN_CONFS,
    RNN_CONFS,
    STATEFUL_RNN_CONFS,
    create_layer,
)
from deeplearning4j_tpu.nn.layers.feedforward import (
    AutoEncoderImpl,
    OutputLayerImpl,
    RBMImpl,
)
from deeplearning4j_tpu.ops import dispatch, lowprec, rng as rng_mod
from deeplearning4j_tpu.optimize.updaters import MultiLayerUpdater, apply_updates

logger = logging.getLogger("deeplearning4j_tpu")

# param leaf names regularized by l1/l2 (weights + recurrent weights, never
# biases — reference BaseLayer.calcL1/calcL2)
_REG_PARAM_NAMES = ("W", "U")


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = [create_layer(lc) for lc in conf.layers]
        self.updater = MultiLayerUpdater(conf.layers, conf)
        self.params: Optional[List[Dict[str, Any]]] = None
        self.states: Optional[List[Dict[str, Any]]] = None
        self.updater_state = None
        self.iteration = 0
        self.listeners = []
        self._score_dev = None  # device array; fetched lazily via score_value
        self._rng = rng_mod.key(conf.seed)
        self._jit_cache: Dict[Any, Any] = {}
        self._input_shape: Optional[Tuple[int, ...]] = None
        # bf16 loss-scaled training (DL4J_TPU_BF16, ops/lowprec.py):
        # device-side {scale, good, skipped} tree, created lazily by the
        # first lp train step and snapshotted through training_state()
        self._loss_scale = None
        self.dispatch_stats = dispatch.DispatchStats()
        from deeplearning4j_tpu.ops.memory import MemoryStats

        # AOT memory ledger beside dispatch_stats (ops/memory.py) —
        # populated on demand via measure_memory / .measure_memory on the
        # instrumented jits, never implicitly on the hot path
        self.memory_stats = MemoryStats()
        # ingest telemetry beside dispatch/memory stats (etl/stats.py):
        # adopted from the staged iterator the last fit_iterator consumed
        # (InputPipeline / AsyncDataSetIterator); None for direct fits
        self.pipeline_stats = None
        # batch-statistics layers make shape bucketing unsound in training:
        # the pad rows would enter the BN batch mean/var (loss masking
        # cannot undo that), so fit() skips bucketing for these nets
        self._bucketing_blocked = any(
            isinstance(lc, conf_layers.BatchNormalization)
            for lc in conf.layers
        )
        # True while fit_iterator drives fit(): the scope where bucketing's
        # "auto" mode applies (dispatch.bucketing_mode)
        self._bucket_scope = False
        # every *_stats ledger above joins the central MetricsRegistry
        # (obs/registry.py) — one Prometheus scrape covers them all; the
        # attach points for later ledgers (pipeline_stats adoption,
        # ResilientTrainer/fleet resilience_stats) re-register
        from deeplearning4j_tpu.obs.registry import register_net

        register_net(self)

    # ------------------------------------------------------------------ init
    def _infer_input_shape(self) -> Tuple[int, ...]:
        l0 = self.conf.layers[0]
        if isinstance(l0, RNN_CONFS):
            return (-1, l0.n_in)
        if isinstance(l0, conf_layers.ConvolutionLayer):
            raise ValueError(
                "CNN-first networks need an explicit input_shape=(h, w, c) "
                "(reference requires the same via ConvolutionLayerSetup)"
            )
        if isinstance(l0, conf_layers.FeedForwardLayer):
            return (l0.n_in,)
        raise ValueError(
            f"cannot infer input shape from first layer {type(l0).__name__}; "
            "pass input_shape to init()"
        )

    def init(self, input_shape: Optional[Sequence[int]] = None) -> "MultiLayerNetwork":
        """Initialize params/state, inferring per-layer shapes through the
        stack (role of reference init() :349-440 + ConvolutionLayerSetup)."""
        shape = tuple(input_shape) if input_shape else self._infer_input_shape()
        self._input_shape = shape
        params, states = [], []
        for i, layer in enumerate(self.layers):
            pp = self.conf.input_preprocessors.get(i)
            if pp is not None:
                shape = pp.out_shape(shape)
            k = rng_mod.layer_key(self._rng, i, "init")
            p, s, shape = layer.initialize(k, shape)
            params.append(p)
            states.append(s)
        self.params = params
        self.states = states
        self.updater_state = self.updater.init(params)
        return self

    def num_params(self) -> int:
        return sum(
            int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(self.params)
        )

    # --------------------------------------------------------------- forward
    def _apply_preprocessor(self, i, x, batch_n):
        pp = self.conf.input_preprocessors.get(i)
        if pp is None:
            return x
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            CnnToRnnPreProcessor,
            FeedForwardToRnnPreProcessor,
        )

        if isinstance(pp, (FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor)):
            return pp(x, time_steps=x.shape[0] // batch_n)
        return pp(x)

    def _forward(
        self,
        params,
        states,
        x,
        *,
        train: bool,
        rng=None,
        mask=None,
        upto: Optional[int] = None,
        carry_state: bool = False,
        backprop_window: Optional[int] = None,
        remat_prevent_cse: bool = True,
    ):
        """Forward through layers [0, upto). Returns (activations list incl.
        input, new_states). Mask is passed to recurrent-family layers only.
        carry_state=True resumes recurrent layers from their stored state
        (TBPTT window chaining). backprop_window truncates each recurrent
        layer's in-window backward pass (distinct tbptt_back_length,
        reference LSTMHelpers.backpropGradientHelper:255)."""
        from deeplearning4j_tpu.nn.common import apply_layer

        n_layers = len(self.layers) if upto is None else upto
        batch_n = x.shape[0]
        acts = [x]
        new_states = list(states)
        for i in range(n_layers):
            layer = self.layers[i]
            x = self._apply_preprocessor(i, x, batch_n)
            lrng = (
                rng_mod.layer_key(rng, i, "dropout") if rng is not None else None
            )
            lmask = mask if isinstance(self.conf.layers[i], RNN_CONFS) else None
            kwargs = {}
            if carry_state and isinstance(self.conf.layers[i], STATEFUL_RNN_CONFS):
                kwargs["carry_state"] = True
            if backprop_window is not None and isinstance(
                self.conf.layers[i], STATEFUL_RNN_CONFS
            ):
                kwargs["backprop_window"] = backprop_window
            y, ns = apply_layer(
                layer, self.conf, params[i], states[i], x, lrng, lmask,
                kwargs, train=train, remat_prevent_cse=remat_prevent_cse,
            )
            new_states[i] = ns
            acts.append(y)
            x = y
        return acts, new_states

    def _regularization_penalty(self, params):
        """0.5*l2*|W|^2 + l1*|W|_1 summed over layers (weights only)."""
        total = jnp.asarray(0.0, jnp.float32)
        for lc, p in zip(self.conf.layers, params):
            l1 = lc.l1 or 0.0
            l2 = lc.l2 or 0.0
            if l1 == 0.0 and l2 == 0.0:
                continue

            def visit(path, leaf, acc):
                name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
                if name in _REG_PARAM_NAMES:
                    if l2:
                        acc = acc + 0.5 * l2 * jnp.sum(jnp.square(leaf))
                    if l1:
                        acc = acc + l1 * jnp.sum(jnp.abs(leaf))
                return acc

            leaves = jax.tree_util.tree_leaves_with_path(p)
            for path, leaf in leaves:
                total = visit(path, leaf, total)
        return total

    def _loss(
        self,
        params,
        states,
        x,
        labels,
        *,
        train,
        rng,
        mask=None,
        label_mask=None,
        carry_state: bool = False,
        backprop_window: Optional[int] = None,
        remat_prevent_cse: bool = True,
    ):
        out_impl = self.layers[-1]
        if not isinstance(out_impl, OutputLayerImpl):
            raise ValueError("last layer must be an OutputLayer/RnnOutputLayer")
        acts, new_states = self._forward(
            params,
            states,
            x,
            train=train,
            rng=rng,
            mask=mask,
            upto=len(self.layers) - 1,
            carry_state=carry_state,
            backprop_window=backprop_window,
            remat_prevent_cse=remat_prevent_cse,
        )
        last_in = self._apply_preprocessor(
            len(self.layers) - 1, acts[-1], x.shape[0]
        )
        from deeplearning4j_tpu.nn.common import cast_loss_input

        last_in = cast_loss_input(last_in)
        if train and (self.conf.layers[-1].dropout or 0.0) > 0 and rng is not None:
            last_in = out_impl._dropout_in(
                last_in, train, rng_mod.layer_key(rng, len(self.layers) - 1, "dropout")
            )
        lmask = label_mask if label_mask is not None else mask
        loss = out_impl.loss(params[-1], last_in, labels, lmask)
        return loss + self._regularization_penalty(params), new_states

    # ------------------------------------------------------------- jit cache
    def _get_train_step(
        self,
        has_mask: bool,
        has_label_mask: bool,
        carry_state: bool = False,
        backprop_window: Optional[int] = None,
    ):
        lp = lowprec.train_policy()
        key = ("train_step", has_mask, has_label_mask, carry_state,
               backprop_window, lp)
        if key in self._jit_cache:
            return self._jit_cache[key]

        def train_step(params, states, upd_state, x, labels, iteration, rng, mask, label_mask):
            def loss_fn(p):
                return self._loss(
                    p,
                    states,
                    x,
                    labels,
                    train=True,
                    rng=rng,
                    mask=mask,
                    label_mask=label_mask,
                    carry_state=carry_state,
                    backprop_window=backprop_window,
                )

            (loss, new_states), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params
            )
            updates, upd_state = self.updater.update(
                grads, upd_state, params, iteration
            )
            params = apply_updates(params, updates, self.conf.minimize)
            return params, new_states, upd_state, loss

        if lp:
            return self._build_lowprec_step(key, carry_state, backprop_window)

        # params/states/upd_state are donated: every caller (fit,
        # _fit_tbptt, ParallelWrapper) re-binds them from the returned
        # triple, so the superseded buffers are never re-read and the
        # update happens in-place in HBM instead of copying the whole
        # training state each step
        fn = dispatch.instrumented_jit(
            train_step, "train_step", self.dispatch_stats,
            donate=(0, 1, 2), step=True, mem_stats=self.memory_stats)
        self._jit_cache[key] = fn
        return fn

    def _ensure_loss_scale(self):
        if self._loss_scale is None:
            self._loss_scale = lowprec.init_scale_state()
        return self._loss_scale

    @property
    def loss_scale(self) -> Optional[dict]:
        """Host snapshot of the dynamic loss-scale state (None when bf16
        training never ran). This is a deliberate sync point — it also
        refreshes dispatch_stats.loss_scale_skips."""
        snap = lowprec.scale_snapshot(self._loss_scale)
        if snap is not None:
            self.dispatch_stats.loss_scale_skips = snap["skipped"]
        return snap

    def _build_lowprec_step(self, key, carry_state, backprop_window):
        """bf16 master-weight train step (Micikevicius et al., ICLR 2018):
        f32 master params + updater state; the loss closure casts params
        and floating inputs to bf16 at the step boundary (the cast's
        transpose returns f32 grads); the loss is SCALED before the
        backward pass and the grads unscaled after; non-finite grads skip
        the update (select back the previous state) and halve the scale.

        The inner jit takes the loss-scale tree as a 4th donated arg; the
        returned wrapper keeps the ORIGINAL 9-arg signature (every caller
        — fit, _fit_tbptt, data_parallel, bench — re-binds the same
        4-tuple), injecting/rebinding ``self._loss_scale`` itself."""

        def lp_step(params, states, upd_state, ls, x, labels, iteration,
                    rng, mask, label_mask):
            scale = ls["scale"]

            def loss_fn(p):
                loss, new_states = self._loss(
                    lowprec.cast_tree(p),
                    states,
                    lowprec.cast_array(x),
                    labels,
                    train=True,
                    rng=rng,
                    mask=mask,
                    label_mask=label_mask,
                    carry_state=carry_state,
                    backprop_window=backprop_window,
                )
                return loss.astype(jnp.float32) * scale, (loss, new_states)

            (_, (loss, new_states)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = lowprec.unscale(grads, scale)
            finite = lowprec.finite_tree(grads)
            updates, new_upd = self.updater.update(
                grads, upd_state, params, iteration
            )
            new_params = apply_updates(params, updates, self.conf.minimize)
            params = lowprec.select_trees(finite, new_params, params)
            upd_state = lowprec.select_trees(finite, new_upd, upd_state)
            states = lowprec.select_trees(finite, new_states, states)
            ls = lowprec.advance_scale(ls, finite)
            return params, states, upd_state, ls, loss.astype(jnp.float32)

        inner = dispatch.instrumented_jit(
            lp_step, "train_step", self.dispatch_stats,
            donate=(0, 1, 2, 3), step=True, mem_stats=self.memory_stats)
        net = self

        def wrapper(params, states, upd_state, x, labels, iteration, rng,
                    mask, label_mask):
            ls = net._ensure_loss_scale()
            params, states, upd_state, ls, loss = inner(
                params, states, upd_state, ls, x, labels, iteration, rng,
                mask, label_mask)
            net._loss_scale = ls
            return params, states, upd_state, loss

        def measure_memory(params, states, upd_state, x, labels, iteration,
                           rng, mask, label_mask):
            return inner.measure_memory(
                params, states, upd_state, net._ensure_loss_scale(), x,
                labels, iteration, rng, mask, label_mask)

        wrapper.measure_memory = measure_memory
        wrapper.lowprec = True
        self._jit_cache[key] = wrapper
        return wrapper

    def measure_memory(self, features, labels, mask=None, label_mask=None):
        """AOT memory accounting for this net's train step on the given
        batch shape (ops/memory: lower + compile + memory_analysis, no
        execution) — recorded under 'train_step' in self.memory_stats.
        Returns the byte dict, or None when the backend exposes no
        memory stats."""
        if self.params is None:
            self.init()
        features = jnp.asarray(features)
        labels = jnp.asarray(labels)
        step = self._get_train_step(mask is not None, label_mask is not None)
        return step.measure_memory(
            self.params, self.states, self.updater_state, features, labels,
            jnp.asarray(self.iteration, jnp.int32), self._rng, mask,
            label_mask)

    def _get_output_fn(self, train: bool = False):
        key = ("output", train)
        if key not in self._jit_cache:

            def out_fn(params, states, x):
                acts, _ = self._forward(params, states, x, train=False)
                return acts[-1]

            self._jit_cache[key] = dispatch.instrumented_jit(
                out_fn, "output", self.dispatch_stats,
                mem_stats=self.memory_stats)
        return self._jit_cache[key]

    def _get_score_fn(self, has_mask: bool, has_label_mask: bool):
        key = ("score", has_mask, has_label_mask)
        if key not in self._jit_cache:

            def score_fn(params, states, x, labels, mask, label_mask):
                loss, _ = self._loss(
                    params,
                    states,
                    x,
                    labels,
                    train=False,
                    rng=None,
                    mask=mask,
                    label_mask=label_mask,
                )
                return loss

            self._jit_cache[key] = dispatch.instrumented_jit(
                score_fn, "score", self.dispatch_stats)
        return self._jit_cache[key]

    # ------------------------------------------------------------------- fit
    @property
    def score_value(self) -> float:
        """Last training loss. Syncing with the device happens HERE, not in
        the step loop — fit() stays async so steps pipeline on TPU (the
        reference's per-iteration score readback is a hidden sync point)."""
        return float("nan") if self._score_dev is None else float(self._score_dev)

    @score_value.setter
    def score_value(self, v):
        self._score_dev = v

    def _record_iteration(self, loss):
        self._score_dev = loss
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, float(loss))
        self.iteration += 1

    def fit(self, features, labels, mask=None, label_mask=None) -> float:
        """One DataSet fit: `conf.iterations` optimizer iterations on this
        batch (reference fit(DataSet) semantics with the Solver loop)."""
        if self.params is None:
            self.init()
        features = jnp.asarray(features)
        labels = jnp.asarray(labels)
        if self.conf.backprop_type == "truncated_bptt" and features.ndim == 3:
            return self._fit_tbptt(features, labels, mask, label_mask)
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            from deeplearning4j_tpu.optimize.solvers import Solver

            return Solver(self).optimize(features, labels, mask, label_mask)
        features, labels, mask, label_mask = self._bucket_batch(
            features, labels, mask, label_mask
        )
        step = self._get_train_step(mask is not None, label_mask is not None)
        loss = None
        for _ in range(max(1, self.conf.iterations)):
            srng = rng_mod.step_key(self._rng, self.iteration)
            self.params, self.states, self.updater_state, loss = step(
                self.params,
                self.states,
                self.updater_state,
                features,
                labels,
                jnp.asarray(self.iteration, jnp.int32),
                srng,
                mask,
                label_mask,
            )
            self._record_iteration(loss)
        return loss

    def _bucket_batch(self, features, labels, mask, label_mask):
        """Shape bucketing (dispatch.bucket_size): pad a ragged batch up to
        its bucket and mask the pad rows out of the loss, so fit() compiles
        once per BUCKET instead of once per batch shape. The reference's
        fit(DataSet) (MultiLayerNetwork.java:1017) accepts arbitrary shapes
        because a JVM re-dispatch is cheap; here every new shape is a full
        XLA retrace of the whole-step program.

        The row-validity mask rides the existing label-mask plumbing
        (nn/losses._masked_mean_per_example divides by the mask sum), which
        makes the padding semantically free; it is attached even when no
        padding happened so a padded 100-batch and an exact 128-batch share
        ONE jit signature. Applies per dispatch.bucketing_mode — by default
        only inside fit_iterator's loop (direct fit() stays byte-exact for
        the equivalence contracts). Skipped for BatchNormalization nets
        (pad rows would enter the batch statistics) and for the
        TBPTT/Solver paths, which dispatch before this hook."""
        mode = dispatch.bucketing_mode()
        if (mode == "off" or (mode == "auto" and not self._bucket_scope)
                or self._bucketing_blocked):
            return features, labels, mask, label_mask
        n = features.shape[0]
        target = dispatch.bucket_size(n)
        if target != n:
            features, labels, mask, label_mask = dispatch.pad_rows(
                self.dispatch_stats, target,
                [features, labels, mask, label_mask],
            )
        if label_mask is None:
            # the same fallback _loss applies (lmask = label_mask or mask),
            # made explicit so the padded and unpadded signatures agree;
            # pad rows of a padded feature mask are already all-zero
            label_mask = mask if mask is not None else (
                dispatch.row_validity_mask(
                    n, target,
                    labels.shape[1] if labels.ndim == 3 else None,
                )
            )
        return features, labels, mask, label_mask

    def _get_fit_batches_fn(self, has_mask: bool, has_label_mask: bool):
        """K train steps fused into ONE lax.scan — the reference's
        fit(DataSetIterator) hot loop (MultiLayerNetwork.java:1017) as a
        single XLA program. Per-step semantics (updater state, iteration
        counter, per-step rng stream) are identical to K fit() calls; the
        fusion removes the per-step host dispatch, which dominates step
        time for small/medium models on a remote-attached TPU."""
        lp = lowprec.train_policy()
        key = ("fit_batches", has_mask, has_label_mask, lp)
        if key in self._jit_cache:
            return self._jit_cache[key]

        n_iters = max(1, self.conf.iterations)

        def one_iter(params, states, upd_state, x, y, it, rng, mask, lmask):
            def loss_fn(p):
                return self._loss(
                    p, states, x, y, train=True,
                    rng=rng_mod.step_key(rng, it),
                    mask=mask, label_mask=lmask,
                    # inside lax.scan the loop boundary already
                    # prevents CSE; skip the remat barriers
                    remat_prevent_cse=False,
                )

            (loss, states), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, upd_state = self.updater.update(
                grads, upd_state, params, it
            )
            params = apply_updates(params, updates, self.conf.minimize)
            return params, states, upd_state, loss

        def one_iter_lp(params, states, upd_state, ls, x, y, it, rng,
                        mask, lmask):
            # same scaled-loss/unscale/skip discipline as
            # _build_lowprec_step, inlined into the scan body
            scale = ls["scale"]

            def loss_fn(p):
                loss, new_states = self._loss(
                    lowprec.cast_tree(p), states, lowprec.cast_array(x), y,
                    train=True, rng=rng_mod.step_key(rng, it),
                    mask=mask, label_mask=lmask,
                    remat_prevent_cse=False,
                )
                return loss.astype(jnp.float32) * scale, (loss, new_states)

            (_, (loss, new_states)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = lowprec.unscale(grads, scale)
            finite = lowprec.finite_tree(grads)
            updates, new_upd = self.updater.update(
                grads, upd_state, params, it
            )
            new_params = apply_updates(params, updates, self.conf.minimize)
            params = lowprec.select_trees(finite, new_params, params)
            upd_state = lowprec.select_trees(finite, new_upd, upd_state)
            states = lowprec.select_trees(finite, new_states, states)
            ls = lowprec.advance_scale(ls, finite)
            return params, states, upd_state, ls, loss.astype(jnp.float32)

        def scan_fn(params, states, upd_state, xs, ys, it0, rng, masks, lmasks):
            def body(carry, inp):
                params, states, upd_state, it = carry
                x = inp[0]
                y = inp[1]
                mask = inp[2] if has_mask else None
                lmask = inp[3] if has_label_mask else None

                # conf.iterations optimizer iterations per batch, exactly
                # like fit()'s Solver loop (statically unrolled)
                iter_losses = []
                for _ in range(n_iters):
                    params, states, upd_state, loss = one_iter(
                        params, states, upd_state, x, y, it, rng, mask,
                        lmask)
                    it = it + 1
                    iter_losses.append(loss)
                return (params, states, upd_state, it), jnp.stack(iter_losses)

            zeros = jnp.zeros((xs.shape[0],), jnp.float32)
            inputs = (xs, ys, masks if has_mask else zeros,
                      lmasks if has_label_mask else zeros)
            (params, states, upd_state, _), losses = jax.lax.scan(
                body, (params, states, upd_state, it0), inputs
            )
            return params, states, upd_state, losses.reshape(-1)

        if lp:
            def lp_scan_fn(params, states, upd_state, ls, xs, ys, it0, rng,
                           masks, lmasks):
                def body(carry, inp):
                    params, states, upd_state, ls, it = carry
                    x = inp[0]
                    y = inp[1]
                    mask = inp[2] if has_mask else None
                    lmask = inp[3] if has_label_mask else None
                    iter_losses = []
                    for _ in range(n_iters):
                        params, states, upd_state, ls, loss = one_iter_lp(
                            params, states, upd_state, ls, x, y, it, rng,
                            mask, lmask)
                        it = it + 1
                        iter_losses.append(loss)
                    return ((params, states, upd_state, ls, it),
                            jnp.stack(iter_losses))

                zeros = jnp.zeros((xs.shape[0],), jnp.float32)
                inputs = (xs, ys, masks if has_mask else zeros,
                          lmasks if has_label_mask else zeros)
                (params, states, upd_state, ls, _), losses = jax.lax.scan(
                    body, (params, states, upd_state, ls, it0), inputs
                )
                return params, states, upd_state, ls, losses.reshape(-1)

            inner = dispatch.instrumented_jit(
                lp_scan_fn, "fit_batches", self.dispatch_stats,
                donate=(0, 1, 2, 3), step=True,
                mem_stats=self.memory_stats)
            net = self

            def wrapper(params, states, upd_state, xs, ys, it0, rng,
                        masks, lmasks):
                ls = net._ensure_loss_scale()
                params, states, upd_state, ls, losses = inner(
                    params, states, upd_state, ls, xs, ys, it0, rng,
                    masks, lmasks)
                net._loss_scale = ls
                return params, states, upd_state, losses

            wrapper.lowprec = True
            self._jit_cache[key] = wrapper
            return wrapper

        # same donation contract as the train step: fit_batches re-binds
        # params/states/upd_state from the scan's outputs
        fn = dispatch.instrumented_jit(
            scan_fn, "fit_batches", self.dispatch_stats,
            donate=(0, 1, 2), step=True, mem_stats=self.memory_stats)
        self._jit_cache[key] = fn
        return fn

    def _has_scanned_conv(self) -> bool:
        return any(isinstance(lc, (conf_layers.ConvolutionLayer,
                                   conf_layers.SubsamplingLayer))
                   for lc in self.conf.layers)

    def _fit_batches_fallback(self, features, labels, masks, label_masks):
        """Per-step drain for fit_batches when the fusion policy says the
        scanned program would lose (dispatch.fusion_enabled: XLA:CPU
        pessimizes scan-of-conv ~15x, BENCH_NOTES round-6). Semantics are
        identical by construction — fit_batches is DEFINED as equivalent
        to K fit() calls — and the fallback is recorded in
        dispatch_stats.fused_fallbacks; DL4J_TPU_FUSE=force overrides."""
        from deeplearning4j_tpu.optimize.listeners import (
            CollectScoresIterationListener,
        )

        self.dispatch_stats.fused_fallbacks += 1
        col = CollectScoresIterationListener(frequency=1)
        self.listeners.append(col)
        try:
            for k in range(features.shape[0]):
                self.fit(features[k], labels[k],
                         masks[k] if masks is not None else None,
                         label_masks[k] if label_masks is not None else None)
        finally:
            self.listeners.remove(col)
        return np.asarray([s for _, s in col.scores], np.float32)

    def fit_batches(self, features, labels, masks=None, label_masks=None):
        """Fit each leading-axis slice of ``features`` [K, N, ...] /
        ``labels`` [K, ...] inside a single compiled scan — equivalent to
        ``for k in range(K): fit(features[k], labels[k], ...)`` (including
        ``conf.iterations`` optimizer iterations per batch) but without the
        per-step host round-trips. Returns the per-iteration losses as a
        length K*iterations numpy array. SGD-algorithm, non-TBPTT path."""
        if self.params is None:
            self.init()
        if self.conf.backprop_type == "truncated_bptt":
            raise ValueError("fit_batches: use fit() for TBPTT training")
        if self.conf.optimization_algo != "stochastic_gradient_descent":
            raise ValueError("fit_batches supports SGD-family training only")
        features = jnp.asarray(features)
        labels = jnp.asarray(labels)
        if not dispatch.fusion_enabled(scanned_conv=self._has_scanned_conv()):
            return self._fit_batches_fallback(
                features, labels,
                jnp.asarray(masks) if masks is not None else None,
                jnp.asarray(label_masks) if label_masks is not None else None)
        fn = self._get_fit_batches_fn(masks is not None, label_masks is not None)
        zeros = jnp.zeros((features.shape[0],), jnp.float32)
        self.params, self.states, self.updater_state, losses = fn(
            self.params, self.states, self.updater_state,
            features, labels,
            jnp.asarray(self.iteration, jnp.int32),
            self._rng,
            jnp.asarray(masks) if masks is not None else zeros,
            jnp.asarray(label_masks) if label_masks is not None else zeros,
        )
        self._score_dev = losses[-1]
        # ONE bulk readback (per-element float() would be K sequential
        # round-trips — the tunnel-wedging pattern loss_curve documents)
        losses_np = np.asarray(losses)
        for k in range(losses_np.shape[0]):
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration, float(losses_np[k]))
            self.iteration += 1
        return losses_np

    def _reset_rnn_states(self, batch_n: int) -> None:
        """Zero recurrent state sized for this batch (sequence start —
        reference rnnClearPreviousState before doTruncatedBPTT)."""
        for i, lc in enumerate(self.conf.layers):
            if isinstance(lc, STATEFUL_RNN_CONFS):
                self.states[i] = {
                    k: jnp.zeros((batch_n, lc.n_out), jnp.float32)
                    for k in self.states[i]
                }

    def _tbptt_windows(self, features, labels, mask=None, label_mask=None):
        """Yield (f_w, l_w, m_w, lm_w) fwd-length window slices along time
        (reference doTruncatedBPTT :1183-1199 subset extraction)."""
        t_total = features.shape[1]
        w = self.conf.tbptt_fwd_length
        for window_start in range(0, t_total, w):
            sl = slice(window_start, min(window_start + w, t_total))
            f_w = features[:, sl]
            l_w = labels[:, sl] if labels.ndim == 3 else labels
            m_w = (
                mask[:, sl]
                if mask is not None and mask.ndim >= 2 and mask.shape[1] == t_total
                else mask
            )
            lm_w = (
                label_mask[:, sl]
                if label_mask is not None and labels.ndim == 3
                else label_mask
            )
            yield f_w, l_w, m_w, lm_w

    def _tbptt_backprop_window(self) -> Optional[int]:
        from deeplearning4j_tpu.nn.common import tbptt_backprop_window

        return tbptt_backprop_window(self.conf)

    def _fit_tbptt(self, features, labels, mask=None, label_mask=None) -> float:
        """Truncated BPTT: slice the time axis into fwd-length windows;
        recurrent state carries forward across windows (stop-gradient at the
        window boundary — state enters the next jitted step as data), matching
        reference doTruncatedBPTT :1162-1233. A shorter tbptt_back_length
        truncates the backward pass inside each window via stop-gradient
        segments (LSTMHelpers.backpropGradientHelper:255)."""
        if features.ndim != 3:
            raise ValueError(
                "backprop_type='truncated_bptt' requires [B,T,F] features"
            )
        loss = float("nan")
        self._reset_rnn_states(features.shape[0])
        bw = self._tbptt_backprop_window()
        for f_w, l_w, m_w, lm_w in self._tbptt_windows(
            features, labels, mask, label_mask
        ):
            step = self._get_train_step(
                m_w is not None, lm_w is not None, carry_state=True,
                backprop_window=bw,
            )
            srng = rng_mod.step_key(self._rng, self.iteration)
            self.params, self.states, self.updater_state, loss = step(
                self.params,
                self.states,
                self.updater_state,
                f_w,
                l_w,
                jnp.asarray(self.iteration, jnp.int32),
                srng,
                m_w,
                lm_w,
            )
            self._record_iteration(loss)
        return loss

    def fit_iterator(self, iterator, num_epochs: int = 1,
                     fused_batches: int = 1) -> "MultiLayerNetwork":
        """fit(DataSetIterator) equivalent (reference :1017). Async prefetch
        is provided by wrapping with datasets.AsyncDataSetIterator.

        fused_batches=K > 1: stack K consecutive same-shape DataSets and
        run them through fit_batches — ONE XLA program per K optimizer
        steps instead of K dispatches (~5ms each through the remote-TPU
        tunnel; the lenet5_fused bench leg measures the win). Falls back
        to per-step fit() for ragged tails, shape changes, mixed mask
        presence, and TBPTT (whose window loop fit() already handles).

        Input staging: ``DL4J_TPU_PIPELINE_WORKERS`` > 0 wraps a plain
        iterator in ``etl/pipeline.InputPipeline`` (parallel off-thread
        assembly + device staging; value-identical stream, so the
        equivalence contracts hold); whichever staged iterator feeds the
        loop, its telemetry is adopted as ``net.pipeline_stats``."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.etl.pipeline import maybe_wrap

        iterator = maybe_wrap(iterator)
        if getattr(iterator, "pipeline_stats", None) is not None:
            self.pipeline_stats = iterator.pipeline_stats
            from deeplearning4j_tpu.obs.registry import register_net

            register_net(self)  # the freshly adopted ingest ledger
        if self.conf.pretrain:
            self.pretrain(iterator)
            if hasattr(iterator, "reset"):
                iterator.reset()
        fused = (fused_batches > 1
                 and self.conf.backprop_type != "truncated_bptt"
                 # fit_batches is SGD-family only; Solver algos (CG/LBFGS/
                 # line search) fall back to the per-step fit() they need
                 and self.conf.optimization_algo
                 == "stochastic_gradient_descent")
        from deeplearning4j_tpu.nn.common import fused_iterator_loop

        fit_one = lambda ds: self.fit(ds.features, ds.labels,
                                      ds.features_mask, ds.labels_mask)
        # the iterator loop is bucketing's "auto" scope: ragged tails and
        # shape drift land here, and each one costs a full XLA retrace
        # unless padded up to a bucket (dispatch.bucketing_mode)
        self._bucket_scope = True
        try:
            for _ in range(num_epochs):
                if not fused:
                    for ds in iterator:
                        fit_one(ds)
                else:
                    fused_iterator_loop(
                        iterator, fused_batches,
                        can_stack=lambda ds: True,  # fit_batches stacks masks
                        same_shape=self._stackable,
                        fit_one=fit_one,
                        fit_fused=self._fit_fused,
                    )
                if hasattr(iterator, "reset"):
                    iterator.reset()
        finally:
            self._bucket_scope = False
        return self

    @staticmethod
    def _stackable(a, b) -> bool:
        return (
            np.asarray(a.features).shape == np.asarray(b.features).shape
            and np.asarray(a.labels).shape == np.asarray(b.labels).shape
            and (a.features_mask is None) == (b.features_mask is None)
            and (a.labels_mask is None) == (b.labels_mask is None)
        )

    def _fit_fused(self, buf) -> None:
        stack = lambda get: (
            None if get(buf[0]) is None
            else np.stack([np.asarray(get(d)) for d in buf])
        )
        self.fit_batches(
            stack(lambda d: d.features), stack(lambda d: d.labels),
            stack(lambda d: d.features_mask),
            stack(lambda d: d.labels_mask),
        )

    # -------------------------------------------------------------- pretrain
    def pretrain(self, data, num_epochs: int = 1) -> None:
        """Greedy layerwise pretraining for AutoEncoder/RBM layers
        (reference pretrain(DataSetIterator) :165-213)."""
        if self.params is None:
            self.init()

        def batches():
            if hasattr(data, "__iter__") and not hasattr(data, "shape"):
                for ds in data:
                    yield jnp.asarray(ds.features)
                if hasattr(data, "reset"):
                    data.reset()
            else:
                yield jnp.asarray(data)

        for i, layer in enumerate(self.layers):
            if not isinstance(layer, (AutoEncoderImpl, RBMImpl)):
                continue
            lc = self.conf.layers[i]
            from deeplearning4j_tpu.optimize.updaters import LayerUpdater

            lu = LayerUpdater(lc, self.conf)
            lu_state = lu.init(self.params[i])

            if isinstance(layer, RBMImpl):

                def grads_fn(p, x, k):
                    return layer.cd_grads(p, x, k), None

            else:

                def grads_fn(p, x, k):
                    g = jax.grad(lambda pp: layer.pretrain_loss(pp, x, k))(p)
                    return g, None

            def _pretrain_step(p, s, x, it, k):
                g, _ = grads_fn(p, x, k)
                upd, s = lu.update(g, s, p, it)
                p = apply_updates(p, upd, True)
                return p, s

            # donated: self.params[i] and lu_state are re-bound from the
            # returned pair each call; earlier layers' params (read by the
            # inference forward above) are not arguments here
            pretrain_step = dispatch.instrumented_jit(
                _pretrain_step, "pretrain_step", self.dispatch_stats,
                donate=(0, 1), step=True)

            it_count = 0
            for _ in range(num_epochs):
                for xb in batches():
                    batch_n = xb.shape[0]
                    # forward through earlier layers in inference mode
                    if i > 0:
                        acts, _ = self._forward(
                            self.params, self.states, xb, train=False, upto=i
                        )
                        xb = acts[-1]
                    # apply this layer's input preprocessor (forward applies
                    # preprocessor i only when running layer i, which upto=i
                    # excludes)
                    xb = self._apply_preprocessor(i, xb, batch_n)
                    k = rng_mod.step_key(
                        rng_mod.layer_key(self._rng, i, "sample"), it_count
                    )
                    self.params[i], lu_state = pretrain_step(
                        self.params[i],
                        lu_state,
                        xb,
                        jnp.asarray(it_count, jnp.int32),
                        k,
                    )
                    it_count += 1
            logger.info("pretrained layer %d (%s)", i, type(lc).__name__)

    # ------------------------------------------------------------- inference
    def output(self, x) -> jax.Array:
        """Batch inference (reference output(INDArray) :619-704). Ragged
        batches are bucket-padded and sliced back — inference-mode padding
        is unconditionally safe (BN uses running stats, dropout is off), so
        a stream of arbitrary batch sizes compiles O(log n) programs."""
        fn = self._get_output_fn()
        x = jnp.asarray(x)
        n = x.shape[0]
        target = dispatch.inference_bucket(self.dispatch_stats, n)
        if target is not None:
            return fn(self.params, self.states,
                      dispatch.pad_axis0(x, target))[:n]
        return fn(self.params, self.states, x)

    def feed_forward(self, x, train: bool = False):
        """All layer activations (reference feedForward(train)). train=True
        applies dropout (fresh step key) and batch-stats normalization."""
        rng = rng_mod.step_key(self._rng, self.iteration) if train else None
        acts, _ = self._forward(
            self.params, self.states, jnp.asarray(x), train=train, rng=rng
        )
        return acts

    def score(self, features, labels, mask=None, label_mask=None) -> float:
        fn = self._get_score_fn(mask is not None, label_mask is not None)
        return float(
            fn(self.params, self.states, jnp.asarray(features), jnp.asarray(labels), mask, label_mask)
        )

    def evaluate(self, iterator):
        """Evaluate over an iterator (reference evaluate(DataSetIterator) :2316)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        ev = Evaluation()
        for ds in iterator:
            out = self.output(ds.features)
            ev.eval(np.asarray(ds.labels), np.asarray(out), mask=ds.labels_mask)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return ev

    # ------------------------------------------------- stateful rnn streaming
    def rnn_clear_previous_state(self):
        """Zero streaming RNN state WITHOUT touching params (reference
        rnnClearPreviousState just clears stateMap). State leaves go back to
        the lazily-sized empty form; the next rnn_time_step re-sizes them."""
        for i, layer in enumerate(self.layers):
            if hasattr(layer, "step"):
                self.states[i] = {
                    k: jnp.zeros((0,) + v.shape[1:], v.dtype)
                    for k, v in self.states[i].items()
                }

    def _sized_rnn_states(self, states, n: int):
        """States with stream-state leaves sized for batch n. Only the
        intentionally cleared (0, ...) form is re-sized; any other batch
        mismatch raises (silently zeroing carried state would produce wrong
        predictions with no signal — call rnn_clear_previous_state first)."""
        out = list(states)
        for i, layer in enumerate(self.layers):
            if hasattr(layer, "step"):
                sized = {}
                for k, v in states[i].items():
                    if v.shape[0] == n:
                        sized[k] = v
                    elif v.shape[0] == 0:
                        sized[k] = jnp.zeros((n,) + v.shape[1:], v.dtype)
                    else:
                        raise ValueError(
                            f"rnn_time_step batch {n} != carried state batch "
                            f"{v.shape[0]} (layer {i}); call "
                            "rnn_clear_previous_state() to start a new stream"
                        )
                out[i] = sized
        return out

    def _get_rnn_step_fn(self):
        """Jitted single-timestep forward through the whole stack with carried
        RNN state — the streaming-inference hot path (reference rnnTimeStep
        :2152 keeps a stateMap per layer; here state is an explicit pytree so
        the step is one compiled XLA program)."""
        key = ("rnn_step",)
        if key not in self._jit_cache:
            self._jit_cache[key] = dispatch.instrumented_jit(
                self._rnn_step_body, "rnn_step", self.dispatch_stats)
        return self._jit_cache[key]

    def _get_rnn_seq_fn(self):
        """Jitted [N,T,F] stepwise path: lax.scan of the single-step function
        over time (state carries across calls like repeated rnn_time_step)."""
        key = ("rnn_seq",)
        if key not in self._jit_cache:

            def seq_fn(params, states, x):
                def body(states, x_t):
                    y, new_states = self._rnn_step_body(params, states, x_t)
                    return new_states, y

                states, ys = jax.lax.scan(body, states, jnp.swapaxes(x, 0, 1))
                return jnp.swapaxes(ys, 0, 1), states

            self._jit_cache[key] = dispatch.instrumented_jit(
                seq_fn, "rnn_seq", self.dispatch_stats)
        return self._jit_cache[key]

    def _rnn_step_body(self, params, states, x):
        new_states = list(states)
        for i, layer in enumerate(self.layers):
            if hasattr(layer, "step"):
                x, new_states[i] = layer.step(params[i], states[i], x)
            else:
                x, _ = layer.apply(params[i], states[i], x, train=False)
        return x, new_states

    def rnn_time_step(self, x_t) -> jax.Array:
        """Stateful streaming inference (reference rnnTimeStep :2152).
        x_t: [N, F] (single step) or [N, T, F] (scanned stepwise). State
        carries across calls; both paths are single jitted XLA programs."""
        x_t = jnp.asarray(x_t)
        n = x_t.shape[0]
        states = self._sized_rnn_states(self.states, n)
        if x_t.ndim == 3:
            ys, self.states = self._get_rnn_seq_fn()(self.params, states, x_t)
            return ys
        y, self.states = self._get_rnn_step_fn()(self.params, states, x_t)
        return y

    def apply_lr_score_decay(self) -> None:
        """Multiply the effective LR by lr_policy_decay_rate (reference
        Model.applyLearningRateScoreDecay — the event-driven 'score' LR
        policy, fired by BaseOptimizer.checkTerminalConditions:239 on an
        eps-plateau). The cumulative factor lives in updater state."""
        from deeplearning4j_tpu.nn.common import decay_lr_scale_entry

        rate = self.conf.lr_policy_decay_rate
        if rate is None:
            return
        self.updater_state = [
            decay_lr_scale_entry(s, rate) for s in self.updater_state
        ]

    # ------------------------------------------------------------ resilience
    def training_state(self) -> Dict[str, Any]:
        """Everything beyond params/states/updater that exact resume needs
        (resilience/checkpoint.py): the iteration counter (the per-step RNG
        stream and every LR schedule fold it in) and the base RNG key. The
        reference's ModelSerializer drops both (ModelSerializer.java:70-110
        writes config+coefficients+updater only), which is why a restored
        reference run drifts from the uninterrupted one. Under bf16
        training (DL4J_TPU_BF16) the dynamic loss-scale state rides along
        so kill/resume keeps the exact scale/skip trajectory."""
        st = {
            "iteration": int(self.iteration),
            "rng": np.asarray(self._rng, np.uint32).tolist(),
        }
        snap = self.loss_scale  # property: also syncs loss_scale_skips
        if snap is not None:
            st["loss_scale"] = snap
        return st

    def restore_training_state(self, st: Dict[str, Any]) -> None:
        """Inverse of :meth:`training_state`; tolerant of partial dicts so
        pre-resilience checkpoints (no rng section) keep loading."""
        if st.get("iteration") is not None:
            self.iteration = int(st["iteration"])
        if st.get("rng") is not None:
            self._rng = jnp.asarray(np.asarray(st["rng"], dtype=np.uint32))
        if st.get("loss_scale") is not None:
            self._loss_scale = lowprec.scale_from_snapshot(st["loss_scale"])

    # ------------------------------------------------------------- listeners
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def clone(self) -> "MultiLayerNetwork":
        import copy

        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        if self.params is not None:
            net._input_shape = self._input_shape
            # REAL copies, not leaf-sharing (tree_map identity): under
            # buffer donation the original's next train step would delete
            # shared leaves out from under the clone
            net.params = jax.tree_util.tree_map(jnp.copy, self.params)
            net.states = jax.tree_util.tree_map(jnp.copy, self.states)
            net.updater_state = jax.tree_util.tree_map(
                jnp.copy, self.updater_state
            )
            net.iteration = self.iteration
        return net
