"""Score calculators (reference earlystopping/scorecalc/DataSetLossCalculator.java):
average loss over a held-out iterator, used as the early-stopping signal."""

from __future__ import annotations


class DataSetLossCalculator:
    """Average loss over all batches of a validation iterator (reference
    DataSetLossCalculator.java — average=True semantics)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        total = 0.0
        count = 0
        from deeplearning4j_tpu.earlystopping.trainer import score_dataset

        for ds in self.iterator:
            n = ds.num_examples()
            s = score_dataset(net, ds)
            total += s * (n if self.average else 1.0)
            count += n
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        if count == 0:
            return float("nan")
        return total / count if self.average else total
