"""EarlyStoppingConfiguration (reference earlystopping/EarlyStoppingConfiguration.java):
ties together score calculator, terminations, saver, and evaluation cadence."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional


@dataclass
class EarlyStoppingConfiguration:
    score_calculator: Any = None
    epoch_terminations: List[Any] = field(default_factory=list)
    iteration_terminations: List[Any] = field(default_factory=list)
    model_saver: Any = None
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1

    class Builder:
        def __init__(self):
            self._c = EarlyStoppingConfiguration()

        def score_calculator(self, sc):
            self._c.score_calculator = sc
            return self

        def epoch_termination_conditions(self, *conds):
            self._c.epoch_terminations = list(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._c.iteration_terminations = list(conds)
            return self

        def model_saver(self, saver):
            self._c.model_saver = saver
            return self

        def save_last_model(self, b: bool = True):
            self._c.save_last_model = bool(b)
            return self

        def evaluate_every_n_epochs(self, n: int):
            self._c.evaluate_every_n_epochs = int(n)
            return self

        def build(self) -> "EarlyStoppingConfiguration":
            return self._c

    @staticmethod
    def builder() -> "EarlyStoppingConfiguration.Builder":
        return EarlyStoppingConfiguration.Builder()
