"""EarlyStoppingResult (reference earlystopping/EarlyStoppingResult.java)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class EarlyStoppingResult:
    termination_reason: str  # epoch | iteration | error
    termination_details: str
    score_vs_epoch: Dict[int, float] = field(default_factory=dict)
    best_model_epoch: int = -1
    best_model_score: float = float("inf")
    total_epochs: int = 0
    best_model: Optional[Any] = None
