"""Early stopping: configuration, terminations, savers, trainer.

Mirrors the reference's ``earlystopping`` package (22 files, 1,525 LoC —
SURVEY.md section 2.1): EarlyStoppingConfiguration + BaseEarlyStoppingTrainer
epoch loop with score calculation, termination checks, and best-model saving
(deeplearning4j-core/.../earlystopping/trainer/BaseEarlyStoppingTrainer.java:82-160).
"""

from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.result import EarlyStoppingResult
from deeplearning4j_tpu.earlystopping.savers import (
    InMemoryModelSaver,
    LocalFileModelSaver,
)
from deeplearning4j_tpu.earlystopping.scorecalc import DataSetLossCalculator
from deeplearning4j_tpu.earlystopping.terminations import (
    BestScoreEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer

__all__ = [
    "EarlyStoppingConfiguration",
    "EarlyStoppingResult",
    "EarlyStoppingTrainer",
    "InMemoryModelSaver",
    "LocalFileModelSaver",
    "DataSetLossCalculator",
    "MaxEpochsTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition",
    "MaxTimeIterationTerminationCondition",
    "MaxScoreIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition",
]
