"""EarlyStoppingTrainer — the epoch loop with termination checks and
best-model saving.

Mirrors the reference's ``BaseEarlyStoppingTrainer.fit()``
(deeplearning4j-core/.../earlystopping/trainer/BaseEarlyStoppingTrainer.java:82-160):
per epoch, fit all minibatches (checking iteration terminations each batch),
score on the validation calculator every N epochs, track/save the best model,
check epoch terminations; on a training exception fall back to the best saved
model (:119-124 — the framework's failure-recovery hook). Works for both
MultiLayerNetwork and ComputationGraph (the reference needs a separate
EarlyStoppingGraphTrainer; here the container API is uniform)."""

from __future__ import annotations

import logging

from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.result import EarlyStoppingResult

logger = logging.getLogger("deeplearning4j_tpu")


def fit_dataset(net, ds) -> float:
    """Fit one DataSet or MultiDataSet on either container."""
    if hasattr(ds, "features_list"):
        return float(
            net.fit(ds.features_list, ds.labels_list, ds.features_masks, ds.labels_masks)
        )
    return float(net.fit(ds.features, ds.labels, ds.features_mask, ds.labels_mask))


def score_dataset(net, ds) -> float:
    """Score one DataSet or MultiDataSet on either container."""
    if hasattr(ds, "features_list"):
        return float(
            net.score(ds.features_list, ds.labels_list, ds.features_masks, ds.labels_masks)
        )
    return float(net.score(ds.features, ds.labels, ds.features_mask, ds.labels_mask))


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator):
        self.config = config
        self.net = net
        self.train_iterator = train_iterator

    def _epoch_losses(self):
        """Yield one loss per training unit within an epoch (overridable —
        the distributed trainer yields one loss per master round)."""
        for ds in self.train_iterator:
            yield fit_dataset(self.net, ds)

    def fit(self, max_epochs: int = 1_000_000) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_terminations + cfg.iteration_terminations:
            c.initialize()
        if self.net.params is None:
            self.net.init()

        result = EarlyStoppingResult("epoch", "max_epochs loop bound reached")
        best_score = float("inf")
        epoch = 0
        try:
            for epoch in range(max_epochs):
                stop_iter = None
                for loss in self._epoch_losses():
                    for c in cfg.iteration_terminations:
                        if c.terminate(loss):
                            stop_iter = c
                            break
                    if stop_iter is not None:
                        break
                if hasattr(self.train_iterator, "reset"):
                    self.train_iterator.reset()

                if stop_iter is not None:
                    result.termination_reason = "iteration"
                    result.termination_details = repr(stop_iter)
                    break

                if epoch % max(1, cfg.evaluate_every_n_epochs) == 0:
                    if cfg.score_calculator is not None:
                        score = float(cfg.score_calculator.calculate_score(self.net))
                    else:
                        score = float(self.net.score_value)
                    result.score_vs_epoch[epoch] = score
                    if score < best_score:
                        best_score = score
                        result.best_model_epoch = epoch
                        result.best_model_score = score
                        if cfg.model_saver is not None:
                            cfg.model_saver.save_best_model(self.net, score)
                        else:
                            result.best_model = self.net.clone()
                    if cfg.save_last_model and cfg.model_saver is not None:
                        cfg.model_saver.save_latest_model(self.net, score)

                    stop_epoch = None
                    for c in cfg.epoch_terminations:
                        if c.terminate(epoch, score):
                            stop_epoch = c
                            break
                    if stop_epoch is not None:
                        result.termination_reason = "epoch"
                        result.termination_details = repr(stop_epoch)
                        break
        except Exception as e:  # noqa: BLE001 — reference catches Exception too
            logger.exception("early stopping: training failed, using best model")
            result.termination_reason = "error"
            result.termination_details = f"{type(e).__name__}: {e}"

        result.total_epochs = epoch + 1
        if result.best_model is None and cfg.model_saver is not None:
            result.best_model = cfg.model_saver.get_best_model()
        if result.best_model is None:
            result.best_model = self.net
        return result
