"""Termination conditions (reference earlystopping/termination/).

Two families, as in the reference:
  - Epoch terminations: checked once per epoch with the epoch's score
    (MaxEpochs, ScoreImprovement, BestScore).
  - Iteration terminations: checked every iteration/minibatch
    (MaxTime, MaxScore, InvalidScore).
"""

from __future__ import annotations

import math
import time


# ---------------------------------------------------------------------------
# epoch termination conditions
# ---------------------------------------------------------------------------


class MaxEpochsTerminationCondition:
    """Stop after N epochs (reference MaxEpochsTerminationCondition.java)."""

    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def initialize(self):
        pass

    def terminate(self, epoch_num: int, score: float) -> bool:
        return epoch_num + 1 >= self.max_epochs

    def __repr__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition:
    """Stop when no score improvement for N consecutive epochs
    (reference ScoreImprovementEpochTerminationCondition.java)."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self.best_score = None
        self.epochs_without = 0

    def initialize(self):
        self.best_score = None
        self.epochs_without = 0

    def terminate(self, epoch_num: int, score: float) -> bool:
        if self.best_score is None or self.best_score - score > self.min_improvement:
            self.best_score = score if self.best_score is None else min(
                self.best_score, score
            )
            self.epochs_without = 0
            return False
        self.epochs_without += 1
        return self.epochs_without > self.patience

    def __repr__(self):
        return (
            f"ScoreImprovementEpochTerminationCondition({self.patience}, "
            f"{self.min_improvement})"
        )


class BestScoreEpochTerminationCondition:
    """Stop once score reaches a target value (reference
    BestScoreEpochTerminationCondition.java)."""

    def __init__(self, best_expected_score: float):
        self.target = float(best_expected_score)

    def initialize(self):
        pass

    def terminate(self, epoch_num: int, score: float) -> bool:
        return score < self.target

    def __repr__(self):
        return f"BestScoreEpochTerminationCondition({self.target})"


# ---------------------------------------------------------------------------
# iteration termination conditions
# ---------------------------------------------------------------------------


class MaxTimeIterationTerminationCondition:
    """Wall-clock budget (reference MaxTimeIterationTerminationCondition.java)."""

    def __init__(self, max_seconds: float):
        self.max_seconds = float(max_seconds)
        self._start = None

    def initialize(self):
        self._start = time.monotonic()

    def terminate(self, last_score: float) -> bool:
        if self._start is None:
            self.initialize()
        return (time.monotonic() - self._start) >= self.max_seconds

    def __repr__(self):
        return f"MaxTimeIterationTerminationCondition({self.max_seconds}s)"


class MaxScoreIterationTerminationCondition:
    """Stop if score exceeds a ceiling — divergence guard (reference
    MaxScoreIterationTerminationCondition.java)."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def initialize(self):
        pass

    def terminate(self, last_score: float) -> bool:
        return last_score > self.max_score

    def __repr__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class InvalidScoreIterationTerminationCondition:
    """Stop on NaN/Inf score (reference
    InvalidScoreIterationTerminationCondition.java — the failure-detection
    hook noted in SURVEY.md section 5)."""

    def initialize(self):
        pass

    def terminate(self, last_score: float) -> bool:
        return math.isnan(last_score) or math.isinf(last_score)

    def __repr__(self):
        return "InvalidScoreIterationTerminationCondition()"
