"""Distributed early stopping.

Capability mirror of the reference SparkEarlyStoppingTrainer /
SparkEarlyStoppingGraphTrainer (dl4j-spark/.../spark/earlystopping/): the
epoch loop, terminations, scoring and best-model saving are identical to the
local trainer, but each epoch's fitting is delegated to a TrainingMaster
round (one full pass of parameter-averaged distributed training) instead of
serial minibatch fits."""

from __future__ import annotations

from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
from deeplearning4j_tpu.parallel.training_master import TrainingMaster


class DistributedEarlyStoppingTrainer(EarlyStoppingTrainer):
    def __init__(
        self,
        config: EarlyStoppingConfiguration,
        training_master: TrainingMaster,
        net,
        train_iterator,
    ):
        super().__init__(config, net, train_iterator)
        self.training_master = training_master

    def _epoch_losses(self):
        """One TrainingMaster round == one epoch (SparkEarlyStoppingTrainer
        semantics: each epoch is a full executeTraining over the RDD); the
        round's final score feeds the iteration terminations so NaN/
        divergence conditions still fire."""
        self.training_master.execute_training(self.net, self.train_iterator)
        yield float(self.net.score_value)
