"""Distributed early stopping.

Capability mirror of the reference SparkEarlyStoppingTrainer /
SparkEarlyStoppingGraphTrainer (dl4j-spark/.../spark/earlystopping/): the
epoch loop, terminations, scoring and best-model saving are identical to the
local trainer, but each epoch's fitting is delegated to a TrainingMaster
round (one full pass of parameter-averaged distributed training) instead of
serial minibatch fits."""

from __future__ import annotations

from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.result import EarlyStoppingResult
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
from deeplearning4j_tpu.parallel.training_master import TrainingMaster


class DistributedEarlyStoppingTrainer(EarlyStoppingTrainer):
    def __init__(
        self,
        config: EarlyStoppingConfiguration,
        training_master: TrainingMaster,
        net,
        train_iterator,
    ):
        super().__init__(config, net, train_iterator)
        self.training_master = training_master

    def fit(self, max_epochs: int = 1_000_000) -> EarlyStoppingResult:
        # Reuse the serial epoch loop but swap the per-epoch fit: one
        # TrainingMaster round == one "epoch" (SparkEarlyStoppingTrainer
        # semantics: each epoch is a full executeTraining over the RDD).
        master = self.training_master
        net = self.net
        iterator = self.train_iterator

        class _MasterEpochIterator:
            """Adapter: iterating it performs the distributed round and
            yields nothing (losses are tracked on the net), so the base
            trainer's minibatch loop degenerates to one master call."""

            def __iter__(self):
                master.execute_training(net, iterator)
                return iter(())

            def reset(self):
                if hasattr(iterator, "reset"):
                    iterator.reset()

        inner = EarlyStoppingTrainer(self.config, net, _MasterEpochIterator())
        return inner.fit(max_epochs=max_epochs)
