"""Model savers (reference earlystopping/saver/).

InMemoryModelSaver keeps clones in RAM; LocalFileModelSaver writes
bestModel/latestModel checkpoints via ModelSerializer (reference
LocalFileModelSaver.java writes bestModel.bin / latestModel.bin — with a
bare FileOutputStream, so a crash mid-save tears the file). Here every
file save routes through the resilience plane's crash-safe writer
(resilience/checkpoint.atomic_replace: tmp + fsync + rename), and
``CheckpointManagerSaver`` layers the full manager (async, digested,
retained, corruption-fallback) under the early-stopping contract.
"""

from __future__ import annotations

import os
from typing import Optional


class InMemoryModelSaver:
    """Keep best/latest model clones in memory (reference InMemoryModelSaver.java)."""

    def __init__(self):
        self.best = None
        self.latest = None

    def save_best_model(self, net, score: float) -> None:
        self.best = net.clone()

    def save_latest_model(self, net, score: float) -> None:
        self.latest = net.clone()

    def get_best_model(self):
        return self.best

    def get_latest_model(self):
        return self.latest


class LocalFileModelSaver:
    """Checkpoint best/latest to <dir>/bestModel.zip, latestModel.zip
    (reference LocalFileModelSaver.java; format = ModelSerializer ZIP of
    configuration.json + coefficients + updater state)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    @property
    def best_path(self) -> str:
        return os.path.join(self.directory, "bestModel.zip")

    @property
    def latest_path(self) -> str:
        return os.path.join(self.directory, "latestModel.zip")

    @staticmethod
    def _atomic_write(net, path: str) -> None:
        """Crash-safe save: serialize straight to a tmp FILE (not an
        in-memory buffer — a multi-GB model would double its host
        footprint), fsync, then rename — the previous
        bestModel/latestModel survives any mid-save death."""
        from deeplearning4j_tpu.resilience.checkpoint import fsync_file
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        tmp = f"{path}.tmp-{os.getpid()}"
        ModelSerializer.write_model(net, tmp,
                                    training_state=net.training_state()
                                    if hasattr(net, "training_state")
                                    else None)
        fsync_file(tmp)
        os.replace(tmp, path)

    def save_best_model(self, net, score: float) -> None:
        self._atomic_write(net, self.best_path)

    def save_latest_model(self, net, score: float) -> None:
        self._atomic_write(net, self.latest_path)

    def get_best_model(self):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        if not os.path.exists(self.best_path):
            return None
        return ModelSerializer.restore(self.best_path)

    def get_latest_model(self):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        if not os.path.exists(self.latest_path):
            return None
        return ModelSerializer.restore(self.latest_path)


class CheckpointManagerSaver:
    """Early-stopping saver backed by the resilience CheckpointManager:
    'latest' saves become managed checkpoints (async write, sha256
    manifest, keep-last-k retention, corrupt-checkpoint fallback on
    load), while 'best' stays a pinned atomic zip that retention can
    never prune — the reference saver contract
    (LocalFileModelSaver.java) on top of the production checkpoint
    plane."""

    def __init__(self, directory: str, manager: Optional[object] = None,
                 keep_last: int = 3):
        from deeplearning4j_tpu.resilience.checkpoint import CheckpointManager

        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.manager = manager or CheckpointManager(
            os.path.join(directory, "latest"), keep_last=keep_last)
        if getattr(self.manager, "backend", "zip") != "zip":
            # get_latest_model reconstructs a standalone net from the
            # model.zip payload; the sharded layout restores INTO an
            # existing template, which this saver has no way to build
            raise ValueError(
                "CheckpointManagerSaver requires a zip-backend "
                "CheckpointManager (sharded payloads restore into an "
                "existing net via CheckpointManager.restore)")
        # continue the step chain across process restarts: starting back
        # at 0 would hand retention a checkpoint older than the keep set
        # (pruned on the spot) and leave get_latest_model stale
        self._saves = max(
            (s for s, _ in self.manager.checkpoints()), default=0)

    @property
    def best_path(self) -> str:
        return os.path.join(self.directory, "bestModel.zip")

    def save_best_model(self, net, score: float) -> None:
        LocalFileModelSaver._atomic_write(net, self.best_path)

    def save_latest_model(self, net, score: float) -> None:
        self._saves += 1
        self.manager.save(net, step=self._saves)

    def get_best_model(self):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        if not os.path.exists(self.best_path):
            return None
        return ModelSerializer.restore(self.best_path)

    def get_latest_model(self):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        self.manager.flush()
        found = self.manager.latest_intact()
        if found is None:
            return None
        path, _ = found
        return ModelSerializer.restore(os.path.join(path, "model.zip"))
