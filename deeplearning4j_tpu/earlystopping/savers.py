"""Model savers (reference earlystopping/saver/).

InMemoryModelSaver keeps clones in RAM; LocalFileModelSaver writes
bestModel/latestModel checkpoints via ModelSerializer (reference
LocalFileModelSaver.java writes bestModel.bin / latestModel.bin).
"""

from __future__ import annotations

import os
from typing import Optional


class InMemoryModelSaver:
    """Keep best/latest model clones in memory (reference InMemoryModelSaver.java)."""

    def __init__(self):
        self.best = None
        self.latest = None

    def save_best_model(self, net, score: float) -> None:
        self.best = net.clone()

    def save_latest_model(self, net, score: float) -> None:
        self.latest = net.clone()

    def get_best_model(self):
        return self.best

    def get_latest_model(self):
        return self.latest


class LocalFileModelSaver:
    """Checkpoint best/latest to <dir>/bestModel.zip, latestModel.zip
    (reference LocalFileModelSaver.java; format = ModelSerializer ZIP of
    configuration.json + coefficients + updater state)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    @property
    def best_path(self) -> str:
        return os.path.join(self.directory, "bestModel.zip")

    @property
    def latest_path(self) -> str:
        return os.path.join(self.directory, "latestModel.zip")

    def save_best_model(self, net, score: float) -> None:
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        ModelSerializer.write_model(net, self.best_path)

    def save_latest_model(self, net, score: float) -> None:
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        ModelSerializer.write_model(net, self.latest_path)

    def get_best_model(self):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        if not os.path.exists(self.best_path):
            return None
        return ModelSerializer.restore(self.best_path)

    def get_latest_model(self):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        if not os.path.exists(self.latest_path):
            return None
        return ModelSerializer.restore(self.latest_path)
