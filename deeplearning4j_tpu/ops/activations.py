"""String-named activation registry.

The reference configures activations by name and executes them through the
ND4J op factory (deeplearning4j-core/.../nn/layers/BaseLayer.java:369-372
``Nd4j.getOpFactory().createTransform(conf.getLayer().getActivationFunction(), input)``).
We keep the string-named surface (it is the config-DSL contract) but each name
maps to a pure jax function that XLA fuses into the surrounding program.

Names mirror the reference-era set: sigmoid, tanh, relu, leakyrelu, softmax,
identity/linear, softsign, softplus, hardtanh, cube, elu, rectifiedtanh,
hardsigmoid, step — plus maxout is handled at the layer level.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

Array = jax.Array

ACTIVATIONS: Dict[str, Callable[[Array], Array]] = {}


def _register(*names):
    def deco(fn):
        for n in names:
            ACTIVATIONS[n] = fn
        return fn

    return deco


@_register("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@_register("tanh")
def tanh(x):
    return jnp.tanh(x)


@_register("relu")
def relu(x):
    return jax.nn.relu(x)


@_register("leakyrelu")
def leakyrelu(x):
    # reference LeakyReLU default alpha = 0.01
    return jax.nn.leaky_relu(x, negative_slope=0.01)


@_register("softmax")
def softmax(x):
    # row-wise softmax over the feature axis (last axis in our conventions)
    return jax.nn.softmax(x, axis=-1)


@_register("identity", "linear")
def identity(x):
    return x


@_register("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@_register("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@_register("hardtanh")
def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


@_register("hardsigmoid")
def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@_register("cube")
def cube(x):
    return x * x * x


@_register("elu")
def elu(x):
    return jax.nn.elu(x)


@_register("rectifiedtanh")
def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


@_register("step")
def step(x):
    return jnp.where(x > 0.0, 1.0, 0.0)


@_register("gelu")
def gelu(x):  # not in the 2016 reference; standard for modern models
    return jax.nn.gelu(x)


@_register("swish", "silu")
def swish(x):
    return jax.nn.silu(x)


def activation(name: str) -> Callable[[Array], Array]:
    try:
        return ACTIVATIONS[name.lower()]
    except KeyError:
        raise ValueError(
            f"Unknown activation '{name}'. Known: {sorted(ACTIVATIONS)}"
        ) from None
