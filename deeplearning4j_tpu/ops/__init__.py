"""Tensor substrate: dtype/RNG policy and the named-op registries.

Stands in for the external ND4J layer the reference depends on
(SURVEY.md L0: INDArray / Nd4j factory / OpExecutioner string-named ops).
Here the "backend" is jax.numpy/XLA; what remains of ND4J's surface is the
policy (dtypes, RNG determinism) and the string-named activation registry that
the config DSL references (reference executes activations by name through the
op factory: deeplearning4j-core/.../nn/layers/BaseLayer.java:369-372).
"""

from deeplearning4j_tpu.ops.dtypes import DtypePolicy, get_policy, set_policy, float32_strict
from deeplearning4j_tpu.ops.activations import activation, ACTIVATIONS
from deeplearning4j_tpu.ops.dispatch import DispatchStats
