"""Tensor substrate: dtype/RNG policy and the named-op registries.

Stands in for the external ND4J layer the reference depends on
(SURVEY.md L0: INDArray / Nd4j factory / OpExecutioner string-named ops).
Here the "backend" is jax.numpy/XLA; what remains of ND4J's surface is the
policy (dtypes, RNG determinism) and the string-named activation registry that
the config DSL references (reference executes activations by name through the
op factory: deeplearning4j-core/.../nn/layers/BaseLayer.java:369-372).

Re-exports are LAZY (PEP 562): ``deeplearning4j_tpu.ops.env`` — the central
DL4J_TPU_* knob table — must stay importable without pulling jax, because the
jax-free obs plane (obs/journal.py's "read directly to keep obs jax-free"
rule) reads its knobs through it. An eager ``from .dispatch import ...`` here
would drag jax into every obs import.
"""

_EXPORTS = {
    "DtypePolicy": "dtypes",
    "get_policy": "dtypes",
    "set_policy": "dtypes",
    "float32_strict": "dtypes",
    "activation": "activations",
    "ACTIVATIONS": "activations",
    "DispatchStats": "dispatch",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
