"""Pallas TPU flash-attention kernel.

Dense attention materializes the [T, T] score matrix in HBM per (batch,
head) — at T=4096 that is 64MB of f32 traffic each way, and HBM bandwidth
(not MXU FLOPs) bounds the op. The kernel below never materializes scores:
each q block stays in VMEM while k/v blocks stream through an online-softmax
accumulation (running max + denominator), so HBM traffic drops from
O(T^2) to O(T * D) per row — the flash-attention recipe, written per
/opt/skills/guides/pallas_guide.md.

The reference has no attention at all (2016 — SURVEY.md section 2.7: its
only long-sequence mechanism is truncated BPTT); attention enters this
framework via the MultiHeadAttention layer conf and the transformer
flagship (models/transformer.py), and THIS kernel is their TPU hot path.
The multi-chip path (ring attention over the 'seq' axis,
parallel/sequence_parallel.py) composes with it: the ring rotates K/V
shards between chips while each chip's local block product can run through
this kernel.

Scope & fallback policy (mirrors ops/pallas_kernels.py):
  - pallas forward kernel + blocked XLA backward: the fwd saves each row's
    log-sum-exp, and the custom_vjp recomputes probabilities K-block by
    K-block (lax.scan), so neither pass ever materializes the [T, T]
    score matrix;
  - causal and full attention; key padding masks run through the EXTENDED
    kernel (_flash_ext: additive key bias + traced visibility offset),
    which also powers the ring's local block product
    (flash_attention_block — shard-level causality as qi + off >= ki);
  - engages when pallas is enabled (ops.pallas_kernels.pallas_enabled) and
    the k/v rows fit VMEM (flash_fits / ext_fits); else dense XLA;
  - CPU tests run the same kernels under interpret=True.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.pallas_kernels import pallas_enabled

_BLOCK_Q = 128
_BLOCK_K = 128
# K + V resident per (batch, head): 2 * T * D floats; budget well under the
# ~16MB/core VMEM, leaving room for the double-buffered q/o blocks + scratch.
_KV_BUDGET_FLOATS = 1_500_000


def flash_fits(t: int, d: int) -> bool:
    return (t % _BLOCK_Q == 0 and t % _BLOCK_K == 0
            and 2 * t * d <= _KV_BUDGET_FLOATS)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal: bool,
                  scale: float, block_k: int):
    """One q block vs all k/v blocks of one (batch*head) row.
    q_ref/o_ref: [1, Bq, D]; k_ref/v_ref: [1, T, D]; lse_ref: [1, 8, Bq]
    (log-sum-exp of each row's scores, broadcast over an 8-sublane padding
    dim for Mosaic block alignment — the residual the blocked backward
    needs to recompute softmax probabilities without the running max)."""
    q = q_ref[0].astype(jnp.float32) * scale          # [Bq, D]
    bq, d = q.shape
    t = k_ref.shape[1]
    qi = pl.program_id(1) * bq + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)

    m0 = jnp.full((bq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [Bq, Bk]
        if causal:
            ki = j * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
            s = jnp.where(qi >= ki, s, -jnp.inf)
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # a fully-masked block leaves m_new at -inf on no row in the causal
        # case (the diagonal is always visible); guard anyway for the loop
        # iterations before any visible key
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l = l * corr + p.sum(-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())))
        return m_new, l, acc

    if causal:
        # keys strictly after this q block's last row never contribute
        n_blocks = (pl.program_id(1) * bq + bq + block_k - 1) // block_k
    else:
        n_blocks = t // block_k
    m, l, acc = lax.fori_loop(0, n_blocks, body, (m0, l0, a0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse block is [1, 8, Bq]: Mosaic requires the last two block dims be
    # (8, 128)-aligned, so the scalar-per-row lse is broadcast across an
    # 8-sublane dim the caller slices back off
    lse_ref[0] = jnp.broadcast_to(
        (m_safe_final(m) + jnp.log(l_safe))[None, :], (8, l.shape[0]))


def m_safe_final(m):
    """-inf running max (row saw no visible key) -> 0 so lse stays finite."""
    return jnp.where(jnp.isfinite(m), m, 0.0)


def _flash_raw(q, k, v, *, causal: bool, interpret: bool):
    """q,k,v: [B, T, D] (B = batch*heads) -> (out [B, T, D], lse [B, 8, T])."""
    b, t, d = q.shape
    if t % _BLOCK_Q != 0 or t % _BLOCK_K != 0:
        # without this guard tail rows would silently come back unwritten
        # (NaN) — the grid and key loop both floor-divide by the block size
        raise ValueError(
            f"flash attention needs T divisible by {max(_BLOCK_Q, _BLOCK_K)}; "
            f"got T={t} (use attention_auto for automatic dense fallback)")
    scale = 1.0 / (d ** 0.5)
    grid = (b, t // _BLOCK_Q)
    return pl.pallas_call(
        functools.partial(_flash_kernel, causal=causal, scale=scale,
                          block_k=_BLOCK_K),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _BLOCK_Q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _BLOCK_Q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, _BLOCK_Q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, 8, t), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _dense_reference(q, k, v, *, causal: bool):
    """XLA dense attention on [B, T, D] (autodiff oracle + fallback).
    Softmax upcast is at-least-f32 (ops/dtypes.softmax_dtype): bf16
    upcasts as before, f64 stays f64 for the gradcheck substrate."""
    from deeplearning4j_tpu.ops.dtypes import softmax_dtype

    d = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q, k)
    s = s.astype(softmax_dtype(s.dtype)) / (d ** 0.5)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, interpret):
    return _flash_raw(q, k, v, causal=causal, interpret=interpret)[0]


def _flash_fwd(q, k, v, causal, interpret):
    o, lse = _flash_raw(q, k, v, causal=causal, interpret=interpret)
    return o, (q, k, v, o, lse[:, 0, :])  # drop the sublane-padding dim


def _flash_bwd(causal, interpret, res, g):
    """Blocked flash backward in plain XLA: softmax probabilities are
    recomputed per K-block from the saved log-sum-exp, so peak memory is
    O(T * block_k) per (batch*head) — never the [T, T] score matrix the
    dense autodiff would materialize (which OOMs at large batch*T).

    Standard flash-attention backward identities:
      D_i  = sum_d dO_id O_id
      P_ij = exp(S_ij - lse_i)
      dV_j = P^T dO;  dP = dO V^T;  dS = P * (dP - D);  dQ += dS K;
      dK_j = dS^T Q.
    """
    q, k, v, o, lse = res
    b, t, d = q.shape
    scale = 1.0 / (d ** 0.5)
    f32 = lambda a: a.astype(jnp.float32)
    q32, k32, v32 = f32(q), f32(k), f32(v)
    g32 = f32(g)
    Dvec = (g32 * f32(o)).sum(-1)                      # [B, T]
    nb = t // _BLOCK_K
    qi = jnp.arange(t)

    def block(dq, j):
        ks = lax.dynamic_slice_in_dim(k32, j * _BLOCK_K, _BLOCK_K, 1)
        vs = lax.dynamic_slice_in_dim(v32, j * _BLOCK_K, _BLOCK_K, 1)
        s = jnp.einsum("bqd,bkd->bqk", q32, ks) * scale
        if causal:
            ki = j * _BLOCK_K + jnp.arange(_BLOCK_K)
            s = jnp.where((qi[:, None] >= ki[None, :])[None], s, -jnp.inf)
        p = jnp.exp(s - lse[..., None])                # masked -> exp(-inf)=0
        dv_j = jnp.einsum("bqk,bqd->bkd", p, g32)
        dp = jnp.einsum("bqd,bkd->bqk", g32, vs)
        ds = p * (dp - Dvec[..., None]) * scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, ks)
        dk_j = jnp.einsum("bqk,bqd->bkd", ds, q32)
        return dq, (dk_j, dv_j)

    dq, (dks, dvs) = lax.scan(block, jnp.zeros_like(q32), jnp.arange(nb))
    # scan stacks K-blocks on the leading axis: [nb, B, Bk, D] -> [B, T, D]
    unstack = lambda a: a.transpose(1, 0, 2, 3).reshape(b, t, d)
    return (dq.astype(q.dtype), unstack(dks).astype(k.dtype),
            unstack(dvs).astype(v.dtype))


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Extended kernel: additive key bias (padding masks) + TRACED causal offset
# (ring attention). Kept separate from _flash so the mask-free single-device
# hot path (and its PALLAS_BENCH numbers) is untouched.
#
# The offset generalizes causal masking to sequence SHARDS: a key is visible
# iff qi + off >= ki (local indices). off = 0 is plain causal; off >= T makes
# everything visible (non-causal); off <= -T hides everything (a ring step
# whose K/V shard lies entirely in the future). Because off is a traced
# scalar (scalar-prefetch SMEM operand), the SAME compiled kernel serves
# every step of a lax.scan ring schedule — which is what lets the ring's
# local block product run through pallas at all.
# ---------------------------------------------------------------------------


def _flash_ext_kernel(off_ref, q_ref, k_ref, v_ref, kb_ref, o_ref, lse_ref,
                      *, scale: float, block_k: int):
    """Like _flash_kernel plus: kb_ref [1, 8, T] additive key bias (0 keeps,
    -inf masks; row 0 is real, rows 1-7 Mosaic sublane padding) and off_ref
    scalar-prefetch visibility offset."""
    off = off_ref[0]
    q = q_ref[0].astype(jnp.float32) * scale
    bq, d = q.shape
    t = k_ref.shape[1]
    qi = pl.program_id(1) * bq + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)

    m0 = jnp.full((bq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    a0 = jnp.zeros((bq, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        kb = kb_ref[0, 0, pl.dslice(j * block_k, block_k)]  # [Bk] f32
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [Bq, Bk]
        s = s + kb[None, :]
        ki = j * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        s = jnp.where(qi + off >= ki, s, -jnp.inf)
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l = l * corr + p.sum(-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())))
        return m_new, l, acc

    # off is traced, so no static causal truncation of the key loop (the
    # ring's shards are short; the full sweep is the price of one kernel
    # serving every ring step)
    m, l, acc = lax.fori_loop(0, t // block_k, body, (m0, l0, a0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # rows with NO visible key emit lse = -inf (not a ~-69 sentinel): the
    # ring combiner takes M = max over shard lse's, and a finite sentinel
    # could dominate a real block whose visible logits all sit below it,
    # collapsing the combined output toward the sentinel's zero o-block.
    # -inf gets weight exp(-inf - M_safe) = 0 in the combiner — exact.
    lse = jnp.where(jnp.isfinite(m),
                    m_safe_final(m) + jnp.log(l_safe), -jnp.inf)
    lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, l.shape[0]))


def _flash_ext_raw(q, k, v, kb, off, *, interpret: bool):
    """q,k,v: [B, Tq, D] / [B, Tk, D]; kb: [B, 8, Tk] f32 additive key bias;
    off: [1] i32 -> (out [B, Tq, D], lse [B, 8, Tq]). Tq and Tk may differ
    (ring steps attend a local Q shard against a rotating K/V shard)."""
    b, tq, d = q.shape
    tk = k.shape[1]
    if tq % _BLOCK_Q != 0 or tk % _BLOCK_K != 0:
        raise ValueError(
            f"flash_ext needs Tq % {_BLOCK_Q} == 0 and Tk % {_BLOCK_K} == 0; "
            f"got Tq={tq}, Tk={tk}")
    scale = 1.0 / (d ** 0.5)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, tq // _BLOCK_Q),
        in_specs=[
            pl.BlockSpec((1, _BLOCK_Q, d), lambda b, i, off: (b, i, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i, off: (b, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i, off: (b, 0, 0)),
            pl.BlockSpec((1, 8, tk), lambda b, i, off: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _BLOCK_Q, d), lambda b, i, off: (b, i, 0)),
            pl.BlockSpec((1, 8, _BLOCK_Q), lambda b, i, off: (b, 0, i)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_flash_ext_kernel, scale=scale, block_k=_BLOCK_K),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b, 8, tq), jnp.float32),
        ],
        interpret=interpret,
    )(off, q, k, v, kb)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _flash_ext(q, k, v, kb, off, interpret):
    return _flash_ext_raw(q, k, v, kb, off, interpret=interpret)


def _flash_ext_fwd(q, k, v, kb, off, interpret):
    o, lse = _flash_ext_raw(q, k, v, kb, off, interpret=interpret)
    return (o, lse), (q, k, v, kb, off, o, lse[:, 0, :])


def _flash_ext_bwd(interpret, res, gs):
    """Blocked XLA backward (same identities as _flash_bwd) with the key
    bias and visibility offset applied when recomputing probabilities,
    PLUS the lse cotangent: ring callers combine shard results through the
    returned log-sum-exp, so dL/dlse_i contributes p_ij to dS (the softmax
    jacobian of logsumexp). Masked/invisible keys have p = 0, hence zero
    dK/dV — exact."""
    q, k, v, kb, off, o, lse = res
    g, g_lse = gs
    b, tq, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    f32 = lambda a: a.astype(jnp.float32)
    q32, k32, v32, g32 = f32(q), f32(k), f32(v), f32(g)
    # the kernel emits lse broadcast over 8 sublanes; fold the cotangent
    g_lse_row = (f32(g_lse).sum(axis=1) if g_lse is not None
                 else jnp.zeros((b, tq), jnp.float32))
    kb_row = kb[:, 0, :]                                # [B, Tk]
    Dvec = (g32 * f32(o)).sum(-1)                       # [B, Tq]
    nb = tk // _BLOCK_K
    qi = jnp.arange(tq)

    def block(dq, j):
        ks = lax.dynamic_slice_in_dim(k32, j * _BLOCK_K, _BLOCK_K, 1)
        vs = lax.dynamic_slice_in_dim(v32, j * _BLOCK_K, _BLOCK_K, 1)
        kbs = lax.dynamic_slice_in_dim(kb_row, j * _BLOCK_K, _BLOCK_K, 1)
        s = jnp.einsum("bqd,bkd->bqk", q32, ks) * scale + kbs[:, None, :]
        ki = j * _BLOCK_K + jnp.arange(_BLOCK_K)
        s = jnp.where((qi[:, None] + off[0] >= ki[None, :])[None], s,
                      -jnp.inf)
        # lse = -inf marks a no-visible-key row: p must be 0 there, and
        # exp(-inf - -inf) would be nan — substitute a finite lse first
        lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
        p = jnp.where(jnp.isfinite(s),
                      jnp.exp(s - lse_safe[..., None]), 0.0)
        dv_j = jnp.einsum("bqk,bqd->bkd", p, g32)
        dp = jnp.einsum("bqd,bkd->bqk", g32, vs)
        ds = p * (dp - Dvec[..., None]
                  + g_lse_row[..., None]) * scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, ks)
        dk_j = jnp.einsum("bqk,bqd->bkd", ds, q32)
        return dq, (dk_j, dv_j)

    dq, (dks, dvs) = lax.scan(block, jnp.zeros_like(q32), jnp.arange(nb))
    unstack = lambda a: a.transpose(1, 0, 2, 3).reshape(b, tk, d)
    return (dq.astype(q.dtype), unstack(dks).astype(k.dtype),
            unstack(dvs).astype(v.dtype), jnp.zeros_like(kb),
            np.zeros(off.shape, jax.dtypes.float0))


_flash_ext.defvjp(_flash_ext_fwd, _flash_ext_bwd)


def flash_attention_block(q, k, v, *, offset, key_mask=None,
                          interpret: bool = False):
    """Flash attention of a Q shard against a K/V shard with shard-level
    causal visibility (qi + offset >= ki) and an optional key padding mask.

    q,k,v: [B, Tq, D] / [B, Tk, D] (B = batch*heads, heads already folded);
    offset: traced i32 scalar (see module notes); key_mask: [B, Tk] 0/1.
    Returns (out [B, Tq, D], lse [B, Tq]) — the log-sum-exp lets callers
    combine shard results exactly (ring attention's online softmax)."""
    b, _, _ = q.shape
    tk = k.shape[1]
    if key_mask is None:
        kb = jnp.zeros((b, 8, tk), jnp.float32)
    else:
        km = jnp.asarray(key_mask, bool)
        kb = jnp.broadcast_to(
            jnp.where(km, 0.0, -jnp.inf).astype(jnp.float32)[:, None, :],
            (b, 8, tk))
    off = jnp.asarray(offset, jnp.int32).reshape((1,))
    o, lse = _flash_ext(q, k, v, kb, off, interpret)
    return o, lse[:, 0, :]


def ext_fits(tq: int, tk: int, d: int) -> bool:
    """VMEM gate for the extended kernel (K + V + bias resident)."""
    return (tq % _BLOCK_Q == 0 and tk % _BLOCK_K == 0
            and 2 * tk * d + 8 * tk <= _KV_BUDGET_FLOATS)


def _apply_folded(fn, q, k, v):
    """Run fn on [N*H, T, D]-folded q/k/v and unfold back to [N, T, H, D]."""
    n, t, h, d = q.shape
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(n * h, t, d)
    out = fn(fold(q), fold(k), fold(v))
    return out.reshape(n, h, t, d).transpose(0, 2, 1, 3)


def flash_attention(q, k, v, *, causal: bool = False,
                    interpret: bool = False) -> jax.Array:
    """q,k,v: [N, T, H, D] -> [N, T, H, D] softmax attention, flash kernel."""
    return _apply_folded(
        lambda q, k, v: _flash(q, k, v, causal, interpret), q, k, v)


def dense_attention(q, k, v, *, causal: bool = False) -> jax.Array:
    """q,k,v: [N, T, H, D] -> [N, T, H, D] dense XLA attention (the fallback
    path and the flash kernel's equivalence oracle)."""
    return _apply_folded(
        lambda q, k, v: _dense_reference(q, k, v, causal=causal), q, k, v)


def _fold_heads(x):
    n, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(n * h, t, d)


def _unfold_heads(x, n, h):
    b, t, d = x.shape
    return x.reshape(n, h, t, d).transpose(0, 2, 1, 3)


def flash_attention_masked(q, k, v, key_mask, *, causal: bool = False,
                           interpret: bool = False) -> jax.Array:
    """q,k,v: [N, T, H, D]; key_mask: [N, T] 0/1 — flash attention with
    padded keys excluded from the softmax (the extended kernel's key bias;
    previously masked batches always fell back to dense XLA attention)."""
    n, t, h, d = q.shape
    km = jnp.repeat(jnp.asarray(key_mask, bool), h, axis=0)  # [N*H, T]
    off = t if not causal else 0
    o, _ = flash_attention_block(
        _fold_heads(q), _fold_heads(k), _fold_heads(v),
        offset=off, key_mask=km, interpret=interpret)
    return _unfold_heads(o, n, h)


def _dense_masked(q, k, v, key_mask, *, causal: bool):
    """Dense fallback with a key padding mask, [N, T, H, D] layout."""
    from deeplearning4j_tpu.ops.dtypes import softmax_dtype

    d = q.shape[-1]
    s = jnp.einsum("nqhd,nkhd->nhqk", q, k)
    s = s.astype(softmax_dtype(s.dtype)) / (d ** 0.5)
    if causal:
        t = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s,
                      -jnp.inf)
    km = jnp.asarray(key_mask, bool)[:, None, None, :]
    s = jnp.where(km, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isfinite(s).any(-1, keepdims=True), p, 0.0)
    return jnp.einsum("nhqk,nkhd->nqhd", p.astype(q.dtype), v)


def attention_auto(q, k, v, *, causal: bool = False,
                   key_mask=None) -> jax.Array:
    """Backend registry slot (the reference's reflective cuDNN-helper
    pattern, ConvolutionLayer.java:64-70): flash kernel when pallas is on
    and the shape fits VMEM, dense XLA attention otherwise. key_mask
    ([N, T] 0/1) runs through the extended kernel's key bias — default-on
    only once PALLAS_BENCH.json proves the ext kernel on chip (the
    measured-win rent rule, ops/kernel_gate.py)."""
    from deeplearning4j_tpu.ops.kernel_gate import measured_win

    t, d = q.shape[1], q.shape[3]
    if key_mask is not None:
        if (pallas_enabled() and ext_fits(t, t, d)
                and measured_win("attention", "masked_flash")):
            return flash_attention_masked(q, k, v, key_mask, causal=causal)
        return _dense_masked(q, k, v, key_mask, causal=causal)
    if pallas_enabled() and flash_fits(t, d):
        return flash_attention(q, k, v, causal=causal)
    return dense_attention(q, k, v, causal=causal)
