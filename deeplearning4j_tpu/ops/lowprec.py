"""Low-precision plane: bf16 loss-scaled training + calibrated int8 serving.

Two classic recipes, mapped onto the planes the repo already has (the
reference treats dtype as one global ND4J switch — ``Nd4j.dtype`` /
DataBuffer.Type in nd4j-api — with no calibration or accuracy story):

* **bf16 master-weight training** (Micikevicius et al., ICLR 2018 — mixed
  precision with master weights + dynamic loss scaling): f32 master params
  and updater state stay the source of truth; the train step casts params
  (and floating inputs) to bf16 at the step boundary, computes the loss
  scaled by a dynamic power-of-two factor, unscales the f32 grads, and
  SKIPS the update (halving the scale) when any grad is non-finite. The
  scale doubles again after ``growth_interval`` clean steps. All of it is
  traced into the one whole-step jit, so it composes with donation,
  bucketing, remat and accum_steps unchanged. Distinct from the
  ``DL4J_TPU_STRICT_CONV=3pass`` bf16 hi/lo SPLIT (ops/precision.py), which
  is an f32-accuracy EMULATION technique — this plane genuinely computes in
  bf16 and pays for it with loss scaling.

* **calibrated int8 inference** (Jacob et al., CVPR 2018 — integer-only
  inference with per-channel symmetric scales): per-output-channel weight
  scales from max|W|, per-tensor activation scales from a streaming-absmax
  calibration pass (etl/calibrate.QuantCalibrator), an int8 matmul with
  int32 accumulation dequantized back to f32 for bias + activation.
  :class:`QuantizedNet` wraps a container's inference path layer by layer,
  falling back to the full-precision apply for unsupported layers, so a
  conv stack serves with a quantized dense head and nothing breaks.

Knobs (ops/env.py): DL4J_TPU_BF16, DL4J_TPU_LOSS_SCALE, DL4J_TPU_QUANT,
DL4J_TPU_QUANT_MAX_DELTA, DL4J_TPU_SERVE_KV_DTYPE.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import env

__all__ = [
    "train_policy", "loss_scale_config", "init_scale_state", "cast_tree",
    "cast_array", "finite_tree", "unscale", "select_trees", "advance_scale",
    "scale_snapshot", "scale_from_snapshot", "OPT_SCALE_KEYS",
    "opt_scale_entries", "opt_scale_state", "opt_with_scale",
    "quant_mode", "quant_max_delta", "quantize_weight", "int8_dense",
    "QuantizedNet", "QuantGateError", "kv_dtype", "precision_of",
    "spec_mode", "draft_lm",
]

# ---------------------------------------------------------------------------
# bf16 master-weight training policy
# ---------------------------------------------------------------------------

_DEFAULT_SCALE = 32768.0      # 2^15 — the Micikevicius et al. starting point
_DEFAULT_GROWTH = 2000       # clean steps before the scale doubles


def train_policy() -> bool:
    """True when bf16 loss-scaled training is on. Read at TRACE time (the
    DL4J_TPU_REMAT pattern): the returned value is baked into the step
    program; flipping the knob mid-process retraces via the jit cache
    key."""
    return env.get_bool("DL4J_TPU_BF16")


def loss_scale_config() -> Tuple[float, int]:
    """(initial_scale, growth_interval) from DL4J_TPU_LOSS_SCALE — 'init'
    or 'init:growth_interval'; garbage falls back per the env-table
    contract."""
    spec = env.get_str("DL4J_TPU_LOSS_SCALE") or ""
    init, growth = _DEFAULT_SCALE, _DEFAULT_GROWTH
    if spec:
        head, _, tail = spec.partition(":")
        try:
            init = float(head)
        except ValueError:
            init = _DEFAULT_SCALE
        if tail:
            try:
                growth = int(tail)
            except ValueError:
                growth = _DEFAULT_GROWTH
    return max(init, 1.0), max(growth, 1)


def init_scale_state() -> dict:
    """Fresh device-side loss-scale state: the scale itself plus the
    clean-step and skip counters. Rides the train step as ONE donated
    pytree so no per-step host sync ever reads it; checkpoints snapshot it
    through the containers' training_state()."""
    init, _ = loss_scale_config()
    return {
        "scale": jnp.asarray(init, jnp.float32),
        "good": jnp.zeros((), jnp.int32),
        "skipped": jnp.zeros((), jnp.int32),
    }


def cast_array(x):
    """bf16 cast for floating arrays only — int token/label inputs pass
    through untouched."""
    if isinstance(x, (jax.Array, np.ndarray)) and jnp.issubdtype(
            jnp.asarray(x).dtype, jnp.floating):
        return jnp.asarray(x, jnp.bfloat16)
    return x


def cast_tree(tree, dtype=jnp.bfloat16):
    """Cast every floating leaf to ``dtype`` (master-weight boundary cast:
    grads flow back f32 through the cast's transpose)."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
        tree)


def finite_tree(tree) -> jax.Array:
    """Scalar bool: every floating leaf all-finite (the overflow vote the
    skip decision keys on)."""
    ok = jnp.asarray(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def unscale(grads, scale):
    """grads / scale in f32 — exact for the power-of-two scales the
    dynamic policy produces."""
    inv = (1.0 / scale).astype(jnp.float32)
    return jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32) * inv, grads)


def select_trees(pred, new, old):
    """Elementwise where over two same-structure trees: commit the step's
    outputs when ``pred`` (grads finite) else keep the previous state —
    the halve-and-skip path never lets a NaN reach the master weights."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(pred, n.astype(o.dtype), o), new, old)


def advance_scale(ls: dict, finite) -> dict:
    """One dynamic-loss-scale transition: clean step bumps the good
    counter (doubling the scale each ``growth_interval``); a non-finite
    step halves the scale (floor 1) and bumps the skip counter."""
    _, growth = loss_scale_config()
    good = jnp.where(finite, ls["good"] + 1, 0)
    grow = good >= growth
    scale = jnp.where(
        finite,
        jnp.where(grow, ls["scale"] * 2.0, ls["scale"]),
        jnp.maximum(ls["scale"] * 0.5, 1.0))
    return {
        "scale": scale.astype(jnp.float32),
        "good": jnp.where(grow, 0, good).astype(jnp.int32),
        "skipped": (ls["skipped"] + jnp.where(finite, 0, 1)).astype(
            jnp.int32),
    }


def scale_snapshot(ls: Optional[dict]) -> Optional[dict]:
    """Host-side JSON-able view (ONE bulk readback — this is a sync point;
    callers are the checkpoint path and the explicit loss_scale
    property, never the step loop)."""
    if ls is None:
        return None
    return {
        "scale": float(np.asarray(ls["scale"])),
        "good": int(np.asarray(ls["good"])),
        "skipped": int(np.asarray(ls["skipped"])),
    }


def scale_from_snapshot(st: dict) -> dict:
    return {
        "scale": jnp.asarray(float(st["scale"]), jnp.float32),
        "good": jnp.asarray(int(st["good"]), jnp.int32),
        "skipped": jnp.asarray(int(st["skipped"]), jnp.int32),
    }


# -- flagship models ride the loss-scale state INSIDE the opt tree ---------
# (keeps the step arity, the donation contract and the save/load npz
# round-trip unchanged: transformer/bert init_opt_state add these keys
# when the policy is on, and the step reads them back out)

OPT_SCALE_KEYS = ("loss_scale", "ls_good", "ls_skipped")


def opt_scale_entries() -> dict:
    ls = init_scale_state()
    return {"loss_scale": ls["scale"], "ls_good": ls["good"],
            "ls_skipped": ls["skipped"]}


def opt_scale_state(opt: dict) -> dict:
    return {"scale": opt["loss_scale"], "good": opt["ls_good"],
            "skipped": opt["ls_skipped"]}


def opt_with_scale(opt: dict, ls: dict) -> dict:
    out = dict(opt)
    out.update({"loss_scale": ls["scale"], "ls_good": ls["good"],
                "ls_skipped": ls["skipped"]})
    return out


# ---------------------------------------------------------------------------
# int8 quantized inference
# ---------------------------------------------------------------------------


class QuantGateError(RuntimeError):
    """Measured int8 accuracy delta exceeded DL4J_TPU_QUANT_MAX_DELTA —
    raised inside ModelRegistry.load's try block so the record lands
    BROKEN and the serving default never moves (PR 8 isolation)."""


def quant_mode() -> str:
    """'off' | 'auto' | 'force' from DL4J_TPU_QUANT ('' = auto: quantize
    when quant.json is present and the gate passes)."""
    v = (env.raw("DL4J_TPU_QUANT") or "").strip().lower()
    if v in ("0", "off", "false", "no"):
        return "off"
    if v == "force":
        return "force"
    return "auto"


def quant_max_delta() -> float:
    return float(env.get_float("DL4J_TPU_QUANT_MAX_DELTA") or 0.05)


def quantize_weight(w) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-OUTPUT-channel symmetric int8 quantization of a [in, out]
    weight matrix (Jacob et al. per-channel scheme): scale[j] =
    max|W[:, j]| / 127, W_q = round(W / scale). Deterministic — recomputed
    from the f32 record at load, never serialized."""
    w = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(w), axis=0)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    wq = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return wq, scale.astype(jnp.float32)


def int8_dense(x, wq, w_scale, x_scale, b=None):
    """Quantized dense: int8 x int8 matmul with int32 accumulation,
    dequantized to f32 by the product of the activation scale and the
    per-channel weight scale, bias added in f32. Accepts [..., in]
    inputs (the RnnOutput 3d case reshapes through the same kernel)."""
    x = jnp.asarray(x)
    lead = x.shape[:-1]
    x2 = x.reshape((-1, x.shape[-1])).astype(jnp.float32)
    xq = jnp.clip(jnp.round(x2 / x_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, wq, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (jnp.asarray(x_scale, jnp.float32)
                                   * w_scale)
    if b is not None:
        y = y + jnp.asarray(b, jnp.float32)
    return y.reshape(lead + (y.shape[-1],))


def _supported_dense(layer) -> bool:
    """Dense-family layers the int8 path covers: plain Dense and the
    Output/RnnOutput heads (x @ W + b with an elementwise activation).
    Everything else (conv, subsampling, BN, recurrent, embedding) falls
    back to the f32 apply per layer."""
    from deeplearning4j_tpu.nn.layers.feedforward import (
        DenseLayerImpl,
    )

    return type(layer).__name__ in (
        "DenseLayerImpl", "OutputLayerImpl", "RnnOutputLayerImpl",
    ) and isinstance(layer, DenseLayerImpl)


class QuantizedNet:
    """int8 inference wrapper for a MultiLayerNetwork: mirrors the net's
    inference forward (preprocessors included) but routes every supported
    dense-family layer through :func:`int8_dense` with calibrated
    activation scales; unsupported layers run their normal f32 apply.
    Exposes the container's serving surface (``output``, ``states``,
    ``params``, ``dispatch_stats``) so the registry/warmup/batcher treat
    it exactly like the f32 model it wraps.

    The reference's closest analog is the global ND4J dtype switch
    (SURVEY.md section on nd4j DataBuffer types) — no per-layer fallback,
    no calibration; this class is the beyond-parity form."""

    precision = "int8"

    def __init__(self, net, spec):
        from deeplearning4j_tpu.ops import dispatch

        self.base = net
        self.spec = spec
        scales = list(spec.act_scales)
        if len(scales) < len(net.layers):
            scales += [None] * (len(net.layers) - len(scales))
        quant: List[Optional[dict]] = []
        for i, layer in enumerate(net.layers):
            sc = scales[i]
            p = net.params[i] if net.params is not None else None
            if (sc is None or not sc or p is None or "W" not in p
                    or not _supported_dense(layer)):
                quant.append(None)
                continue
            wq, w_scale = quantize_weight(p["W"])
            quant.append({
                "wq": wq, "w_scale": w_scale,
                "x_scale": jnp.asarray(float(sc), jnp.float32),
                "b": jnp.asarray(p["b"], jnp.float32) if "b" in p else None,
            })
        # .params holds EVERY device buffer this wrapper can reach so the
        # registry's unload sweep (_BUFFER_ATTRS) deletes the quantized
        # tables and the wrapped f32 tree alike
        self.params = {"base": net.params, "quant": quant}
        self.states = net.states
        self.dispatch_stats = dispatch.DispatchStats()
        self._out_fn = None
        from deeplearning4j_tpu.obs.registry import register_net

        register_net(self)

    def quantized_layers(self) -> List[int]:
        return [i for i, q in enumerate(self.params["quant"])
                if q is not None]

    def _forward_quant(self, base_params, quant, states, x):
        net = self.base
        batch_n = x.shape[0]
        for i, layer in enumerate(net.layers):
            x = net._apply_preprocessor(i, x, batch_n)
            q = quant[i]
            if q is None:
                x, _ = layer.apply(base_params[i], states[i], x,
                                   train=False)
            else:
                z = int8_dense(x, q["wq"], q["w_scale"], q["x_scale"],
                               q["b"])
                x = layer.act(z)
        return x

    def _get_output_fn(self):
        from deeplearning4j_tpu.ops import dispatch

        if self._out_fn is None:
            def out_fn(params, states, x):
                return self._forward_quant(
                    params["base"], params["quant"], states, x)

            self._out_fn = dispatch.instrumented_jit(
                out_fn, "output_int8", self.dispatch_stats)
        return self._out_fn

    def output(self, x):
        """Quantized batch inference with the container's bucket-padding
        discipline (MultiLayerNetwork.output): inference padding is
        unconditionally safe, and sharing the bucket ladder keeps the
        warmup-compiled programs hot."""
        from deeplearning4j_tpu.ops import dispatch

        fn = self._get_output_fn()
        x = jnp.asarray(x)
        n = x.shape[0]
        target = dispatch.inference_bucket(self.dispatch_stats, n)
        if target is not None:
            return fn(self.params, self.states,
                      dispatch.pad_axis0(x, target))[:n]
        return fn(self.params, self.states, x)


# ---------------------------------------------------------------------------
# self-speculative drafts (ISSUE 16)
# ---------------------------------------------------------------------------


def spec_mode() -> str:
    """Draft selector for self-speculative decoding from
    DL4J_TPU_SERVE_SPEC: '' = off, 'int8' = weight-quantized self-draft,
    'layers' / 'layers:m' = truncated-layer self-draft."""
    v = (env.get_str("DL4J_TPU_SERVE_SPEC") or "").strip().lower()
    if v in ("", "0", "off", "false", "no"):
        return ""
    if v in ("1", "on", "true", "yes"):
        return "int8"  # bare enable = the default self-draft
    return v


# the per-block weight matrices the int8 self-draft fake-quantizes; LN
# gains/biases and the embedding table stay f32 (the embedding doubles
# as the output head — quantizing it would move the head, not a matmul)
_DRAFT_WEIGHT_KEYS = ("Wq", "Wk", "Wv", "Wo", "W1", "W2")


def _fake_quant_matrix(w):
    """quantize-then-dequantize one [in, out] matrix: the draft keeps the
    target's program family (f32 matmuls over int8-rounded VALUES), so
    on CPU the win is dispatch counts, and the chip's int8 MXU payoff is
    armed behind the same weights-only scheme QuantizedNet gates."""
    wq, scale = quantize_weight(w)
    return (wq.astype(jnp.float32) * scale).astype(jnp.asarray(w).dtype)


def draft_lm(lm, mode: str = "int8"):
    """Build the self-draft TransformerLM a SpeculativeDecoder proposes
    with (serving/speculate.py; Leviathan et al. 2023 draft-verify).

    Two selectable drafts, both derived from the TARGET's own weights so
    no second checkpoint is needed ("self-speculative"):

    * ``int8`` — every per-block weight matrix is fake-quantized
      (per-channel symmetric round-trip through :func:`quantize_weight`,
      the PR 15 scheme): same depth, same decode programs, int8-rounded
      weight values. Honest label: weight-only quantization at f32
      compute — acceptance-rate is what the draft is judged by, and the
      verify step makes ANY draft error harmless.
    * ``layers`` / ``layers:m`` — the first m transformer blocks (default
      half, min 1) under the target's final LN + embedding head: a
      genuinely cheaper program (m/L of the FLOPs and dispatch depth).

    The draft shares the target's embedding/LN buffers (read-only) and
    carries no optimizer state. Mesh-sharded targets are rejected — the
    decode planes are single-device (serving/decode.py module note)."""
    import dataclasses

    from deeplearning4j_tpu.models.transformer import TransformerLM

    if getattr(lm, "mesh", None) is not None:
        raise ValueError("speculative drafts need a single-device LM")
    cfg = lm._run_cfg
    mode = (mode or "int8").strip().lower()
    if mode == "int8":
        blocks = dict(lm.params["blocks"])
        for k in _DRAFT_WEIGHT_KEYS:
            if k in blocks:
                blocks[k] = jax.vmap(_fake_quant_matrix)(blocks[k])
        params = dict(lm.params)
        params["blocks"] = blocks
        dcfg = cfg
    elif mode.startswith("layers"):
        _, _, tail = mode.partition(":")
        m = int(tail) if tail else max(1, cfg.n_layers // 2)
        if not 1 <= m <= cfg.n_layers:
            raise ValueError(
                f"draft depth {m} out of range [1, {cfg.n_layers}]")
        params = dict(lm.params)
        params["blocks"] = jax.tree_util.tree_map(
            lambda a: a[:m], lm.params["blocks"])
        dcfg = dataclasses.replace(cfg, n_layers=m)
    else:
        raise ValueError(
            f"unknown draft mode {mode!r} (want 'int8' or 'layers[:m]')")
    draft = TransformerLM.from_state(dcfg, params)
    # a draft never trains: drop the optimizer zeros from_state allocated
    draft.opt = None
    draft.draft_mode = mode
    return draft


# ---------------------------------------------------------------------------
# serving-plane helpers
# ---------------------------------------------------------------------------


def kv_dtype(cfg) -> Any:
    """Paged-KV arena dtype: DL4J_TPU_SERVE_KV_DTYPE overrides, '' defers
    to the model's compute dtype. bf16 halves kv_block_bytes so the same
    DL4J_TPU_HBM_GB budget admits ~2x the tokens."""
    v = (env.get_str("DL4J_TPU_SERVE_KV_DTYPE") or "").strip().lower()
    if v == "bf16":
        return jnp.bfloat16
    if v == "f32":
        return jnp.float32
    return getattr(cfg, "compute_dtype", jnp.float32)


def precision_of(model) -> str:
    """Active serving precision label for /models and /metrics: 'int8'
    for a QuantizedNet, 'bf16' when the model computes in bf16, else
    'f32'."""
    if getattr(model, "precision", None) == "int8":
        return "int8"
    cd = getattr(getattr(model, "cfg", None), "compute_dtype", None)
    if cd is not None and jnp.dtype(cd) == jnp.dtype(jnp.bfloat16):
        return "bf16"
    return "f32"
