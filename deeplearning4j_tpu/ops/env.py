"""Central ``DL4J_TPU_*`` env-knob registry — the one table every knob read
goes through.

The reference concentrated its runtime configuration in one typed surface
(``NeuralNetConfiguration`` + the ``Builder`` DSL,
deeplearning4j-nn/.../conf/NeuralNetConfiguration.java) precisely so a typo'd
setting failed loudly instead of silently meaning "default". Our env knobs
grew the opposite way: ~40 ``os.environ.get("DL4J_TPU_...")`` reads scattered
over serving/etl/resilience/obs/ops, each with its own duplicated
``_env_int``/``_env_float`` helper and nothing catching a misspelled name.
This module is the typed surface for them: every knob is registered here with
its name, raw default, parser kind and one-line doc, and the graftlint
``env-knob-registry`` rule (analysis/rules_env.py) mechanically enforces that

  * no module outside this one reads a ``DL4J_TPU_*`` var from ``os.environ``
    directly,
  * every ``DL4J_TPU_*`` string literal anywhere in the tree names a
    registered knob (typos fail the gate), and
  * every registered knob is documented in CLAUDE.md.

Import-weight contract: this module must stay importable WITHOUT jax — the
obs plane is deliberately jax-free (obs/journal.py) and reads its knobs here;
``ops/__init__`` is lazy (PEP 562) for the same reason.

Semantics contract: reads are DYNAMIC (``os.environ`` at call time, never
cached) because tests and bench legs flip knobs mid-process, and parse
failures fall back to the default rather than raising — a garbled knob must
not take down a training run, matching the pre-table ``_env_*`` helpers.
Tri-state policy knobs (donate/fuse/bucket) keep their site-local parsing
over :func:`raw`; the table owns the NAME and the documented default, not
every consumer's enum logic.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = [
    "Knob", "KNOBS", "KnobError", "knob", "knob_names", "is_registered",
    "raw", "get_str", "get_int", "get_float", "get_bool", "nonempty",
]


class KnobError(KeyError):
    """Read of an unregistered DL4J_TPU_* name — almost always a typo."""


@dataclass(frozen=True)
class Knob:
    name: str
    default: str          # raw default, as the env string; "" = unset
    kind: str             # int | float | bool | flag | str | path | enum
    doc: str
    choices: Tuple[str, ...] = ()


KNOBS: Dict[str, Knob] = {}


def _register(name: str, default: str, kind: str, doc: str,
              choices: Tuple[str, ...] = ()) -> None:
    KNOBS[name] = Knob(name, default, kind, doc, choices)


# ---------------------------------------------------------------------------
# the table — grouped by plane; keep each doc line greppable next to the
# CLAUDE.md entry the consistency gate checks for
# ---------------------------------------------------------------------------

# dispatch efficiency (ops/dispatch.py)
_register("DL4J_TPU_DONATE", "", "enum",
          "buffer donation for train-step jits: '' auto (on for "
          "accelerators, off on CPU), 0 never, force always",
          choices=("", "0", "1", "force"))
_register("DL4J_TPU_BUCKET_BATCHES", "", "enum",
          "shape bucketing for ragged batches: '' auto (fit_iterator/"
          "output only), 1 every fit, 0 off",
          choices=("", "0", "1", "auto"))
_register("DL4J_TPU_COMPILE_CACHE", "", "path",
          "persistent XLA compile-cache dir; '' = .jax_cache/ under cwd, "
          "0 disables; an explicit JAX_COMPILATION_CACHE_DIR wins")
_register("DL4J_TPU_FUSE", "", "enum",
          "fit_batches scan fusion: '' auto (per-step fallback for "
          "scanned-conv on XLA:CPU), force always, 0 never",
          choices=("", "0", "1", "force"))

# HBM-lean training (ops/remat.py + ops/memory.py)
_register("DL4J_TPU_REMAT", "", "enum",
          "activation-remat policy ladder for block scans and per-layer "
          "remat: none (default) / dots / block",
          choices=("", "none", "dots", "block"))
_register("DL4J_TPU_HBM_GB", "16", "float",
          "per-chip HBM budget (GB) the transformer preflight/auto-fit "
          "sizers fit against")
_register("DL4J_TPU_MEM_MEASURE_ELEMS", "2000000", "int",
          "batch*seq*d_model element ceiling under which measure_memory "
          "AOT-compiles on the CPU substrate for measured bytes")

# precision + pallas kernel gate (ops/)
_register("DL4J_TPU_STRICT_CONV", "", "enum",
          "3pass forces the three-pass bf16-split strict conv everywhere "
          "(equivalence harness)", choices=("", "3pass"))
_register("DL4J_TPU_PALLAS", "", "enum",
          "pallas LSTM kernel gate: '' auto (TPU only, measured-win "
          "table), 0 off, force on even off-TPU (interpret-mode tests)",
          choices=("", "0", "false", "False", "force"))
_register("DL4J_TPU_PALLAS_FORCE", "", "flag",
          "1 bypasses the PALLAS_BENCH.json measured-win gate (bench legs "
          "measuring the kernel itself)")
_register("DL4J_TPU_PALLAS_PAGED", "", "enum",
          "paged-decode attention kernel gate (ops/pallas_paged.py): '' "
          "auto (TPU + fit + measured-win 'paged' group), 0 off, force on "
          "even off-TPU (interpret-mode tests)",
          choices=("", "0", "false", "False", "force"))
_register("DL4J_TPU_PALLAS_SGNS", "", "enum",
          "fused SGNS gather-dot-scatter kernel gate (ops/pallas_sgns.py): "
          "'' auto (TPU + fit + measured-win 'sgns' group), 0 off, force "
          "on even off-TPU (interpret-mode tests)",
          choices=("", "0", "false", "False", "force"))

# low-precision plane (ops/lowprec.py + etl/calibrate.py)
_register("DL4J_TPU_QUANT", "", "enum",
          "calibrated int8 serving: '' auto (quantize when the model zip "
          "carries quant.json AND the accuracy gate passes), 0 off, force "
          "(quantize even when the gate delta exceeds the bar — delta "
          "still measured and reported)",
          choices=("", "0", "off", "force"))
_register("DL4J_TPU_QUANT_MAX_DELTA", "0.05", "float",
          "int8 accuracy gate: max abs output delta vs the f32 record "
          "measured at registry load on the calibration gate sample; past "
          "it the record lands BROKEN (PR 8 isolation) and the serving "
          "default never moves")
_register("DL4J_TPU_BF16", "0", "bool",
          "bf16 master-weight training mode for the containers and "
          "TransformerLM/BertMLM: f32 master params + updater state, bf16 "
          "cast at the train-step boundary, dynamic loss scaling "
          "(halve-and-skip on non-finite grads)")
_register("DL4J_TPU_LOSS_SCALE", "", "str",
          "dynamic loss-scale policy 'init' or 'init:growth_interval' "
          "('' = 32768:2000: start at 2^15, double after 2000 clean "
          "steps, halve-and-skip on non-finite grads, floor 1)")
_register("DL4J_TPU_SERVE_KV_DTYPE", "", "enum",
          "paged-KV arena dtype: '' = the model's compute dtype, bf16 "
          "halves KV bytes (same DL4J_TPU_HBM_GB admits ~2x tokens), f32 "
          "forces full precision",
          choices=("", "bf16", "f32"))

# observability (obs/)
_register("DL4J_TPU_OBS", "0", "bool",
          "span tracer master switch (default OFF; obs off => training "
          "bit-exact)")
_register("DL4J_TPU_OBS_SPANS", "4096", "int",
          "span ring capacity per tracer")
_register("DL4J_TPU_OBS_JOURNAL", "", "path",
          "flight-recorder JSONL path; '' = .obs_journal[.pN].jsonl under "
          "cwd (N = fleet/multihost process id)")
_register("DL4J_TPU_OBS_JOURNAL_N", "4096", "int",
          "flight-recorder event-ring cap")
_register("DL4J_TPU_OBS_FLUSH_S", "5", "float",
          "flight-recorder periodic flush interval (seconds)")
_register("DL4J_TPU_OBS_PORT", "0", "int",
          "standalone MetricsExporter HTTP port (0 = ephemeral)")

# serving engine (serving/)
_register("DL4J_TPU_SERVE_MAX_BATCH", "64", "int",
          "dynamic-batcher max rows per dispatched batch")
_register("DL4J_TPU_SERVE_MAX_WAIT_MS", "10", "float",
          "dynamic-batcher admission window (ms)")
_register("DL4J_TPU_SERVE_QUEUE_CAP", "512", "int",
          "request queue cap; past it /predict answers 429")
_register("DL4J_TPU_SERVE_TIMEOUT_S", "60", "float",
          "per-request deadline; past it /predict answers 504")
_register("DL4J_TPU_SERVE_SLOTS", "4", "int",
          "continuous-batching KV slot-pool size for /generate")
_register("DL4J_TPU_SERVE_BATCH", "", "bool",
          "0 = naive per-request baseline instead of dynamic batching")
_register("DL4J_TPU_SERVE_CONTINUOUS", "", "bool",
          "0 = disable continuous-batching decode for /generate")
_register("DL4J_TPU_SERVE_BREAKER_FAILS", "5", "int",
          "consecutive inference failures that open a model's circuit "
          "breaker (0 disables)")
_register("DL4J_TPU_SERVE_WATCHDOG_S", "30", "float",
          "hung-inference watchdog wall deadline per dispatch (0 "
          "disables)")
_register("DL4J_TPU_SERVE_DRAIN_S", "20", "float",
          "graceful-drain deadline on stop()/SIGTERM")
_register("DL4J_TPU_SERVE_KV_BLOCK", "16", "int",
          "paged-KV block size in tokens for /generate (0 = fall back "
          "to the fixed slot pool)")
_register("DL4J_TPU_SERVE_KV_BLOCKS", "0", "int",
          "paged-KV arena size in blocks (0 = auto-size from "
          "DL4J_TPU_HBM_GB via ops/memory.kv_arena_blocks)")
_register("DL4J_TPU_SERVE_SLO_CLASSES", "", "str",
          "SLO scheduling classes 'name:deadline_s,...' highest "
          "priority first ('' = one default class at the request "
          "timeout)")
_register("DL4J_TPU_SERVE_TICK_K", "1", "int",
          "decode tokens per jitted tick (lax.scan inside one dispatch) "
          "for the fixed-slot and paged /generate pools; the worker "
          "adaptively drops to 1 whenever admissions are pending or any "
          "lane is within k tokens of its budget, so scheduling "
          "semantics are per-token while steady-state decode pays the "
          "~5ms dispatch overhead once per k tokens")
_register("DL4J_TPU_SERVE_SPEC", "", "str",
          "self-speculative decoding draft for greedy /generate on the "
          "paged pool: '' off, int8 = weight-quantized self-draft, "
          "layers[:m] = truncated-layer self-draft (m = draft depth, "
          "default half the target's layers)")
_register("DL4J_TPU_SERVE_SPEC_K", "4", "int",
          "draft tokens proposed per speculative round (the target "
          "verifies k+1 positions in one dispatch)")
_register("DL4J_TPU_SERVE_MESH", "0", "int",
          "serving-mesh device count for the paged /generate plane: "
          ">= 2 runs the decode tick TP-style under shard_map over that "
          "many devices (attention heads + KV arena head-sharded, "
          "serving/mesh.MeshPagedDecoder — byte-identical to the "
          "single-device tick); 0/'' = single-device decoders")
_register("DL4J_TPU_SERVE_ROLE", "", "enum",
          "serving replica role for prefill/decode disaggregation: "
          "prefill = own long-prompt prefill and export primed KV "
          "blocks (/prefill), decode = own the latency-critical decode "
          "tick, '' = both; published in the replica-<id>.addr JSON so "
          "the FleetRouter routes /generate by role",
          choices=("", "prefill", "decode"))
_register("DL4J_TPU_SERVE_FLEET_REPLICAS", "2", "int",
          "serving-fleet replica count (ServingFleet default)")
_register("DL4J_TPU_SERVE_ROUTER_PORT", "0", "int",
          "FleetRouter HTTP port (0 = ephemeral)")
_register("DL4J_TPU_SERVE_REPLICA_FAILS", "3", "int",
          "consecutive connect/5xx failures that eject a replica from "
          "the router (0 disables replica breakers)")
_register("DL4J_TPU_SERVE_SCALE_MIN", "1", "int",
          "autoscaler floor: never scale the fleet below this many "
          "replicas")
_register("DL4J_TPU_SERVE_SCALE_MAX", "4", "int",
          "autoscaler ceiling: never scale the fleet above this many "
          "replicas")
_register("DL4J_TPU_SERVE_SCALE_UP_QUEUE", "8", "float",
          "scale-up pressure: mean queued requests per ready replica "
          "at or above this votes up for the tick")
_register("DL4J_TPU_SERVE_SCALE_UP_P99_FRAC", "0.8", "float",
          "scale-up pressure: a class p99 at or above this fraction of "
          "its SLO deadline votes up for the tick")
_register("DL4J_TPU_SERVE_SCALE_UP_SHED", "1", "int",
          "scale-up pressure: at least this many new router sheds "
          "since the previous tick votes up (0 disables the shed vote)")
_register("DL4J_TPU_SERVE_SCALE_WINDOW", "3", "int",
          "consecutive ticks of one-sided pressure before the "
          "autoscaler acts (the sustained-evidence window)")
_register("DL4J_TPU_SERVE_SCALE_DOWN_QUEUE", "0", "float",
          "scale-down pressure: mean queued requests per ready replica "
          "at or below this (with zero sheds) votes down for the tick")
_register("DL4J_TPU_SERVE_SCALE_COOLDOWN", "5", "int",
          "ticks after any scale action before the next one (counted "
          "in TICKS, not wall-clock, so decisions replay bit-exact)")
_register("DL4J_TPU_SERVE_TENANT_QUOTAS", "", "str",
          "per-tenant token-bucket quotas 'name:rate_per_s[:burst],...'"
          " ('' = no tenant metering; unlisted tenants are unmetered)")

# resilience / checkpointing (resilience/)
_register("DL4J_TPU_CKPT_EVERY", "0", "int",
          "checkpoint every N steps (0 = off)")
_register("DL4J_TPU_CKPT_KEEP", "3", "int",
          "keep-last-k checkpoints")
_register("DL4J_TPU_CKPT_ASYNC", "1", "bool",
          "0 = synchronous checkpoint writes")

# ETL / input pipeline (etl/, datasets/)
_register("DL4J_TPU_PIPELINE_WORKERS", "0", "int",
          "InputPipeline worker threads (0 = off; >0 also opts "
          "fit_iterator into auto-wrapping plain iterators)")
_register("DL4J_TPU_PREFETCH", "2", "int",
          "staged-batch queue depth (shared with AsyncDataSetIterator)")
_register("DL4J_TPU_DATA_DIR", "", "path",
          "dataset cache dir; '' = ~/.deeplearning4j_tpu")
_register("DL4J_TPU_OFFLINE", "", "flag",
          "any non-empty value skips dataset downloads (synthetic "
          "fallbacks engage immediately)")

# multihost / fleet (parallel/)
_register("DL4J_TPU_COORDINATOR", "", "str",
          "jax.distributed coordinator address (host:port); unset = "
          "single-process")
_register("DL4J_TPU_NUM_PROCESSES", "", "int",
          "jax.distributed process count")
_register("DL4J_TPU_PROCESS_ID", "", "int",
          "this process's jax.distributed / fleet rank; also suffixes the "
          "default obs journal path")
_register("DL4J_TPU_FLEET_HEARTBEAT_S", "5.0", "float",
          "elastic-fleet failure-detection heartbeat timeout (seconds)")
_register("DL4J_TPU_FLEET_MIN_WORKERS", "1", "int",
          "elastic-fleet round blocks below this live-membership size")
_register("DL4J_TPU_FLEET_DIR", "", "path",
          "default fleet spool/file-membership transport dir")

# online learning (online/)
_register("DL4J_TPU_ONLINE_WATERMARK", "64", "int",
          "StreamSource backpressure high watermark: push() blocks while "
          "this many batches sit undelivered")
_register("DL4J_TPU_ONLINE_IDLE_S", "0.2", "float",
          "idle window (seconds with no arrival) that ends a StreamSource "
          "poll pass / ContinuousTrainer fit round (0 = block until close)")
_register("DL4J_TPU_ONLINE_SNAPSHOT_ROUNDS", "1", "int",
          "candidate-snapshot cadence in fit rounds for "
          "ContinuousTrainer.export_candidate paths (0 = off)")
_register("DL4J_TPU_ONLINE_DRIFT_Z", "3.0", "float",
          "DriftMonitor alarm threshold: max per-column "
          "|live_mean - base_mean| / base_std")
_register("DL4J_TPU_ONLINE_DRIFT_MIN", "64", "int",
          "minimum live rows before DriftMonitor.check() renders a "
          "verdict")
_register("DL4J_TPU_ONLINE_SHADOW_FRACTION", "1.0", "float",
          "fraction of answered /predict traffic mirrored to the shadow "
          "candidate (deterministic stride, not RNG)")
_register("DL4J_TPU_ONLINE_SHADOW_MIN", "32", "int",
          "minimum mirrored requests before ShadowPromoter.evaluate() "
          "will pass a candidate")
_register("DL4J_TPU_ONLINE_GATE_AGREE", "0.0", "float",
          "promotion gate: minimum shadow-vs-primary argmax agreement "
          "fraction (0 disables the agreement gate)")

# embedding & retrieval serving (retrieval/)
_register("DL4J_TPU_EMBED_LAYER", "", "int",
          "feed-forward embedding layer: int index into the MLN "
          "activations list ('' = -2, the last hidden layer); CG vertex "
          "selection is per-adapter, not env-driven")
_register("DL4J_TPU_EMBED_POOL", "mean", "str",
          "sequence pooling for BertMLM /embed contextual embeddings",
          choices=("mean", "cls", "max"))
_register("DL4J_TPU_ANN_ROWS", "0", "int",
          "vector-index arena capacity in rows (0 = auto-size from "
          "DL4J_TPU_HBM_GB via ops/memory.ann_arena_rows)")
_register("DL4J_TPU_ANN_CLUSTERS", "0", "int",
          "IVF coarse-quantizer cluster count (0 = auto ~= sqrt(rows))")
_register("DL4J_TPU_ANN_NPROBE", "8", "int",
          "IVF clusters probed per /search query (recall/qps dial; "
          "measured recall@k vs the exact oracle rides "
          "retrieval_stats.last_recall)")

# bench / examples harness (bench.py, examples/)
_register("DL4J_TPU_EXAMPLE_SMOKE", "", "flag",
          "any non-empty value shrinks every examples/*.py to smoke-tier "
          "shapes (the -m examples tier sets it)")
_register("DL4J_TPU_FORCE_CPU", "", "flag",
          "any non-empty value pins bench.py to the CPU substrate "
          "(honest fallback legs when the tunnel is down)")
_register("DL4J_TPU_W2V_CORPUS", "", "path",
          "real-text corpus for the word2vec bench leg ('' = synthetic, "
          "provenance-labelled)")
_register("DL4J_TPU_XPLANE_TRACE", "", "path",
          "per-leg xplane trace output dir (bench.py --trace)")


# ---------------------------------------------------------------------------
# readers — dynamic, registered-name-checked, default-on-garbage
# ---------------------------------------------------------------------------


def knob(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise KnobError(
            f"{name} is not a registered DL4J_TPU knob — add it to "
            "deeplearning4j_tpu/ops/env.py (and CLAUDE.md) or fix the "
            "typo") from None


def knob_names() -> Tuple[str, ...]:
    return tuple(sorted(KNOBS))


def is_registered(name: str) -> bool:
    return name in KNOBS


def raw(name: str, default: Optional[str] = None) -> str:
    """The raw env string, '' when unset and no default is given.

    ``default`` (when provided) overrides the table default — call sites
    with context-dependent fallbacks (e.g. CheckpointManager's explicit
    constructor args) pass their own."""
    k = knob(name)
    v = os.environ.get(name)
    if v is None or v == "":
        return default if default is not None else k.default
    return v


def get_str(name: str, default: Optional[str] = None) -> Optional[str]:
    v = raw(name, "" if default is None else default)
    return v if v != "" else default


def get_int(name: str, default: Optional[int] = None) -> Optional[int]:
    v = raw(name, "").strip()
    if v == "" and default is None:
        v = knob(name).default
    try:
        return int(v) if v != "" else default
    except ValueError:
        return default


def get_float(name: str, default: Optional[float] = None) -> Optional[float]:
    v = raw(name, "").strip()
    if v == "" and default is None:
        v = knob(name).default
    try:
        return float(v) if v != "" else default
    except ValueError:
        return default


_FALSY = ("0", "off", "false", "no")


def get_bool(name: str, default: Optional[bool] = None) -> bool:
    """The repo's bool convention: '0'/'off'/'false'/'no' => False, any
    other non-empty value => True, unset/empty => the table default (or
    the ``default`` override)."""
    v = raw(name, "").strip().lower()
    if v == "":
        if default is not None:
            return default
        v = knob(name).default.strip().lower()
        if v == "":
            return False
    return v not in _FALSY


def nonempty(name: str) -> bool:
    """``bool(os.environ.get(name))`` parity for flag knobs (OFFLINE,
    EXAMPLE_SMOKE, FORCE_CPU) — any non-empty value, '0' included, is
    truthy; kept for behavior-identical migration of those sites."""
    knob(name)
    return bool(os.environ.get(name))
