"""Pallas TPU kernels for hot ops.

The reference accelerates its hot layers with hand-written native kernels
(cuDNN helpers, SURVEY.md section 2.2; LSTMHelpers.java per-step gemm loop
:132,145). The XLA equivalent of most of that set is automatic fusion; the one
place a hand kernel still pays on TPU is the LSTM recurrence: a lax.scan
launches one XLA loop iteration per timestep, re-reading U/h/c from HBM each
step. The pallas kernel below runs the WHOLE scan in one kernel — U, the
peepholes, and the carried h/c stay resident in VMEM; only the per-step
input projection streams in and the per-step output streams out.

Scope & fallback policy:
  - pallas kernels for BOTH directions: the forward emits the cell-state
    sequence as a residual and a reverse-time kernel consumes it (gates
    recomputed from xproj + h_prev; U and the dh/dc carry VMEM-resident
    across the reverse sweep; dU/peephole grads accumulated in scratch).
    Shapes whose backward blocks exceed VMEM (lstm_bwd_fits) fall back to
    jax autodiff through the plain scan;
  - mask-free path (padded/masked sequences fall back to the scan);
  - the kernel engages per SHAPE CLASS only where the committed on-chip
    artifact proves a win (lstm_kernel_wins reads PALLAS_BENCH.json rows
    written by benchmarks/pallas_lstm_bench.py — the measured-win rent
    rule, ops/kernel_gate.py), AND the blocks fit VMEM (lstm_scan_fits);
    everything else falls back to the scan. Round-2 chip numbers: scan/
    pallas ratios 1.07 / 0.63 / 0.45 over (N32,T128,H128) /
    (N64,T256,H256) / (N128,T512,H512) — so the smallest class stays on
    the scan and the larger classes run the kernel. This is the
    reference's reflective cuDNN-helper slot (ConvolutionLayer.java:64-70)
    as a shape-gated backend registry. DL4J_TPU_PALLAS=0 disables
    everything; DL4J_TPU_PALLAS_FORCE=1 bypasses the win table (never the
    fit checks).
  - CPU tests run the same kernel under interpret=True.

Written per /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops import env as envknob

# VMEM is ~16MB/core; keep a conservative budget for U + h + c + one xproj
# block + one output block (floats).
_VMEM_BUDGET_FLOATS = 2_000_000


_DISABLE_OVERRIDE = 0  # >0 = pallas_disabled() contexts active


@contextlib.contextmanager
def pallas_disabled():
    """Context manager scoping a pallas-off override to the enclosed code
    (trace-time effect): the explicit alternative to mutating the
    process-global DL4J_TPU_PALLAS env var. Used by the strict-equivalence
    harness, which must compare backend MATH with identical kernels."""
    global _DISABLE_OVERRIDE
    _DISABLE_OVERRIDE += 1
    try:
        yield
    finally:
        _DISABLE_OVERRIDE -= 1


def pallas_enabled() -> bool:
    """Default ON for TPU (the kernel beats lax.scan on all measured
    shapes — see module docstring); DL4J_TPU_PALLAS=0 disables. The
    special value DL4J_TPU_PALLAS=force enables even off-TPU — only
    useful for tests that monkeypatch the kernel into interpret mode
    (compiling the TPU kernel on CPU/GPU fails)."""
    if _DISABLE_OVERRIDE:
        return False
    env = envknob.raw("DL4J_TPU_PALLAS")
    if env in ("0", "false", "False"):
        return False
    if env == "force":
        return True
    # honor jax.default_device(...) overrides (the equivalence harness runs
    # CPU legs this way while the process default backend stays TPU)
    dd = jax.config.jax_default_device
    if dd is not None:
        return getattr(dd, "platform", "") in ("tpu", "axon")
    return jax.default_backend() == "tpu"


# Mosaic double-buffers every streamed block, so the per-block budget must
# leave room for 2x the xproj block + 2x the output block + U + scratch
# inside ~16MB of VMEM.
_BLOCK_BUDGET_FLOATS = 500_000  # ~2MB per xproj block (x2 for double buffer)


def _time_chunk(t: int, n: int, four_h: int) -> int:
    """Timesteps per grid step: the largest divisor of T whose xproj block
    (ch * N * 4H floats) fits the VMEM block budget. Bigger chunks amortize
    pipeline overhead; the budget keeps big-model shapes compiling (a
    32-step block at N=128/H=512 is 33MB — over VMEM on its own)."""
    for cand in (32, 16, 8, 4, 2):
        if t % cand == 0 and cand * n * four_h <= _BLOCK_BUDGET_FLOATS:
            return cand
    return 1


def lstm_kernel_wins(n: int, h: int, t: int = 32) -> bool:
    """Measured-win SHAPE TABLE (VERDICT round-2 weak #8: the gate must be
    a measured win, not just VMEM fit): the nearest on-chip row of
    PALLAS_BENCH.json — by log-work distance over n*t*h — decides whether
    the kernel engages for this shape class. Rows where lax.scan won keep
    the kernel OFF for their class; no rows at all (fresh clone) keeps it
    OFF until benchmarks/pallas_lstm_bench.py runs on a chip. VMEM fit
    (lstm_scan_fits) stays a separate NECESSARY condition."""
    if envknob.raw("DL4J_TPU_PALLAS_FORCE") == "1":
        return True
    import math

    from deeplearning4j_tpu.ops.kernel_gate import _load

    rows = []
    data = _load()
    for row in data.get("lstm", {}).values():
        if (isinstance(row, dict) and "speedup" in row
                and row.get("backend") != "cpu"
                and not row.get("interpret")):
            rows.append((row["n"], row["t"], row["h"],
                         float(row["speedup"])))
    # legacy round-2 layout: top-level "cases" with scan_speedup_over_pallas
    # (>1 = scan faster, i.e. kernel speedup is the reciprocal)
    for c in data.get("cases", []):
        if (not c.get("pallas_interpret_mode", True)
                and "scan_speedup_over_pallas" in c):
            rows.append((c["n"], c["t"], c["h"],
                         1.0 / float(c["scan_speedup_over_pallas"])))
    if not rows:
        return False
    work = math.log(max(1, n * t * h))
    nearest = min(rows, key=lambda r: abs(
        math.log(max(1, r[0] * r[1] * r[2])) - work))
    return nearest[3] >= 1.0


def lstm_scan_fits(n: int, h: int, t: int = 32) -> bool:
    """VMEM guard for the ACTUAL block sizes the kernel uses: a ch-timestep
    xproj block (ch*n*4h, double-buffered) + hs output block (ch*n*h,
    ditto), U, h/c scratch + io. The cs residual block is counted only for
    shapes whose BACKWARD kernel fits (lstm_bwd_fits) — only those
    forwards emit it (_lstm_fwd); everything else backward-falls-back to
    scan autodiff and the forward stays residual-free."""
    ch = _time_chunk(t, n, 4 * h)
    need = h * 4 * h + 4 * n * h + 2 * ch * n * 4 * h + 2 * ch * n * h
    if lstm_bwd_fits(n, h, t):
        need += 2 * ch * n * h  # the double-buffered cs residual block
    return need <= _VMEM_BUDGET_FLOATS


# ---------------------------------------------------------------------------
# Fused LSTM forward scan
# ---------------------------------------------------------------------------


def _make_lstm_kernel(emit_cs: bool):
    """Grid = (T,), sequential. Time-major layout: block t sees
    xproj[t, :, :] and writes hs[t, :, :] — the block's trailing two dims
    are then (N, 4H)/(N, H), satisfying the TPU (8, 128) tiling rule.
    h/c live in VMEM scratch across iterations. With emit_cs the cell-state
    sequence is emitted as a residual for the backward kernel (it recomputes
    gates from xproj + h_prev but needs c_prev/c exactly, and re-running
    the whole forward recurrence in reverse would serialize twice); the
    no-grad primal uses the emit_cs=False variant so inference never pays
    the extra T*N*H HBM write (pallas outputs cannot be DCE'd)."""

    def kernel(xproj_ref, u_ref, p_ref, h0_ref, c0_ref, hs_ref, *rest):
        if emit_cs:
            cs_ref, hf_ref, cf_ref, h_scr, c_scr = rest
        else:
            hf_ref, cf_ref, h_scr, c_scr = rest
        t = pl.program_id(0)
        n_t = pl.num_programs(0)

        @pl.when(t == 0)
        def _():
            h_scr[:] = h0_ref[:]
            c_scr[:] = c0_ref[:]

        n_out = h_scr.shape[-1]
        chunk = xproj_ref.shape[0]
        u = u_ref[:]
        pi = p_ref[0, :]
        pf = p_ref[1, :]
        po = p_ref[2, :]

        def body(k, carry):
            h_prev, c_prev = carry
            # z: [N, 4H] = xproj_t + h_prev @ U  (MXU)
            z = xproj_ref[k, :, :] + jnp.dot(
                h_prev, u, preferred_element_type=jnp.float32
            )
            zi = z[:, 0 * n_out : 1 * n_out]
            zf = z[:, 1 * n_out : 2 * n_out]
            zo = z[:, 2 * n_out : 3 * n_out]
            zg = z[:, 3 * n_out : 4 * n_out]
            i = jax.nn.sigmoid(zi + pi * c_prev)
            f = jax.nn.sigmoid(zf + pf * c_prev)
            g = jnp.tanh(zg)
            c = f * c_prev + i * g
            o = jax.nn.sigmoid(zo + po * c)
            h = o * jnp.tanh(c)
            hs_ref[k, :, :] = h
            if emit_cs:
                cs_ref[k, :, :] = c
            return h, c

        h, c = jax.lax.fori_loop(0, chunk, body, (h_scr[:], c_scr[:]))
        h_scr[:] = h
        c_scr[:] = c

        @pl.when(t == n_t - 1)
        def _():
            hf_ref[:] = h
            cf_ref[:] = c

    return kernel


def _lstm_pallas_fwd_raw(xproj, u, p, h0, c0, *, interpret: bool,
                         emit_cs: bool = False):
    """xproj: [N, T, 4H] (input projection + bias, precomputed);
    returns (hs [N,T,H], cs_tm [T,N,H] residual or None, h_f, c_f)."""
    n, t, four_h = xproj.shape
    h_dim = four_h // 4
    ch = _time_chunk(t, n, four_h)
    grid = (t // ch,)
    blk_seq = pl.BlockSpec((ch, n, h_dim), lambda i: (i, 0, 0),
                           memory_space=pltpu.VMEM)
    blk_nh = pl.BlockSpec((n, h_dim), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    seq_shape = jax.ShapeDtypeStruct((t, n, h_dim), jnp.float32)
    nh_shape = jax.ShapeDtypeStruct((n, h_dim), jnp.float32)
    out_shape = ((seq_shape,) + ((seq_shape,) if emit_cs else ())
                 + (nh_shape, nh_shape))
    out_specs = ((blk_seq,) + ((blk_seq,) if emit_cs else ())
                 + (blk_nh, blk_nh))
    xproj_tm = jnp.swapaxes(xproj, 0, 1)  # time-major [T, N, 4H]
    outs = pl.pallas_call(
        _make_lstm_kernel(emit_cs),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ch, n, four_h), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h_dim, four_h), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, h_dim), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            blk_nh,
            blk_nh,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((n, h_dim), jnp.float32),
            pltpu.VMEM((n, h_dim), jnp.float32),
        ],
        interpret=interpret,
    )(xproj_tm.astype(jnp.float32), u.astype(jnp.float32),
      p.astype(jnp.float32), h0.astype(jnp.float32), c0.astype(jnp.float32))
    if emit_cs:
        hs_tm, cs_tm, h_f, c_f = outs
    else:
        (hs_tm, h_f, c_f), cs_tm = outs, None
    return jnp.swapaxes(hs_tm, 0, 1), cs_tm, h_f, c_f


def _lstm_scan_reference(xproj, u, p, h0, c0):
    """Plain lax.scan twin of the kernel (tanh activation) — the autodiff
    path for the custom VJP and the numerical oracle in tests."""

    def step(carry, xp_t):
        h_prev, c_prev = carry
        z = xp_t + h_prev @ u
        zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(zi + p[0] * c_prev)
        f = jax.nn.sigmoid(zf + p[1] * c_prev)
        g = jnp.tanh(zg)
        c = f * c_prev + i * g
        o = jax.nn.sigmoid(zo + p[2] * c)
        h = o * jnp.tanh(c)
        return (h, c), h

    (h_f, c_f), hs = jax.lax.scan(step, (h0, c0), jnp.swapaxes(xproj, 0, 1))
    return jnp.swapaxes(hs, 0, 1), h_f, c_f


# ---------------------------------------------------------------------------
# Fused LSTM backward scan (reverse-time pallas kernel)
# ---------------------------------------------------------------------------


def _lstm_bwd_kernel(xproj_ref, hprev_ref, cprev_ref, cs_ref, u_ref, p_ref,
                     dhs_ref, dhf_ref, dcf_ref,
                     dxproj_ref, du_ref, dp_ref, dh0_ref, dc0_ref,
                     dh_scr, dc_scr, du_scr, dp_scr):
    """Reverse-time twin of _lstm_kernel. The grid runs 0..n_t-1 but the
    index maps hand block i the (n_t-1-i)-th time chunk, so U and the
    carried dh/dc stay VMEM-resident across the whole reverse sweep while
    time blocks stream through. Gates are recomputed from xproj + h_prev
    (cheaper than storing 4 gate planes); c_prev/c come from the saved
    cell sequence. dU / peephole grads accumulate in VMEM scratch and are
    written once at the final program."""
    t = pl.program_id(0)
    n_t = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        dh_scr[:] = dhf_ref[:]          # cotangent of the FINAL h
        dc_scr[:] = dcf_ref[:]
        du_scr[:] = jnp.zeros_like(du_scr)
        dp_scr[:] = jnp.zeros_like(dp_scr)

    chunk = xproj_ref.shape[0]
    n_out = dh_scr.shape[-1]
    u = u_ref[:]
    pi = p_ref[0, :]
    pf = p_ref[1, :]
    po = p_ref[2, :]

    def body(k, carry):
        dh_c, dc_c, du_a, dpi_a, dpf_a, dpo_a = carry
        kk = chunk - 1 - k              # reverse order inside the block
        h_prev = hprev_ref[kk, :, :]
        c_prev = cprev_ref[kk, :, :]
        c = cs_ref[kk, :, :]
        z = xproj_ref[kk, :, :] + jnp.dot(
            h_prev, u, preferred_element_type=jnp.float32)
        i = jax.nn.sigmoid(z[:, 0 * n_out:1 * n_out] + pi * c_prev)
        f = jax.nn.sigmoid(z[:, 1 * n_out:2 * n_out] + pf * c_prev)
        o = jax.nn.sigmoid(z[:, 2 * n_out:3 * n_out] + po * c)
        g = jnp.tanh(z[:, 3 * n_out:4 * n_out])
        tc = jnp.tanh(c)

        dh = dhs_ref[kk, :, :] + dh_c
        do = dh * tc
        dzo = do * o * (1.0 - o)
        dc = dh * o * (1.0 - tc * tc) + dc_c + dzo * po
        dzi = dc * g * i * (1.0 - i)
        dzg = dc * i * (1.0 - g * g)
        dzf = dc * c_prev * f * (1.0 - f)
        dz = jnp.concatenate([dzi, dzf, dzo, dzg], axis=-1)
        dxproj_ref[kk, :, :] = dz
        du_a = du_a + jnp.dot(h_prev.T, dz,
                              preferred_element_type=jnp.float32)
        dpi_a = dpi_a + jnp.sum(dzi * c_prev, axis=0)
        dpf_a = dpf_a + jnp.sum(dzf * c_prev, axis=0)
        dpo_a = dpo_a + jnp.sum(dzo * c, axis=0)
        dh_c = jnp.dot(dz, u.T, preferred_element_type=jnp.float32)
        dc_c = dc * f + dzi * pi + dzf * pf
        return dh_c, dc_c, du_a, dpi_a, dpf_a, dpo_a

    zeros_h = jnp.zeros((n_out,), jnp.float32)
    dh_c, dc_c, du_a, dpi_a, dpf_a, dpo_a = jax.lax.fori_loop(
        0, chunk, body,
        (dh_scr[:], dc_scr[:], jnp.zeros_like(du_scr[:]),
         zeros_h, zeros_h, zeros_h),
    )
    dh_scr[:] = dh_c
    dc_scr[:] = dc_c
    du_scr[:] = du_scr[:] + du_a
    dp_scr[0, :] = dp_scr[0, :] + dpi_a
    dp_scr[1, :] = dp_scr[1, :] + dpf_a
    dp_scr[2, :] = dp_scr[2, :] + dpo_a

    @pl.when(t == n_t - 1)
    def _():
        du_ref[:] = du_scr[:]
        dp_ref[:] = dp_scr[:]
        dh0_ref[:] = dh_c
        dc0_ref[:] = dc_c


def lstm_bwd_fits(n: int, h: int, t: int = 32) -> bool:
    """VMEM guard for the backward kernel: U + dU + dp scratch + the six
    streamed time blocks (xproj, dxproj at 4H; hprev/cprev/cs/dhs at H),
    double-buffered."""
    ch = _time_chunk(t, n, 4 * h)
    need = (2 * h * 4 * h + 6 * h              # U, dU scratch, dp
            + 2 * (2 * ch * n * 4 * h)         # xproj + dxproj blocks
            + 4 * (2 * ch * n * h)             # hprev/cprev/cs/dhs blocks
            + 4 * n * h)                       # carries + dhf/dcf
    return need <= _VMEM_BUDGET_FLOATS


def _lstm_pallas_bwd_raw(xproj, u, p, h0, c0, cs_tm, hs, dhs, dh_f, dc_f,
                         *, interpret: bool):
    """All-pallas reverse pass. Returns (dxproj [N,T,4H], dU, dp, dh0, dc0)."""
    n, t, four_h = xproj.shape
    h_dim = four_h // 4
    ch = _time_chunk(t, n, four_h)
    n_blk = t // ch
    xproj_tm = jnp.swapaxes(xproj, 0, 1).astype(jnp.float32)
    hs_tm = jnp.swapaxes(hs, 0, 1).astype(jnp.float32)
    dhs_tm = jnp.swapaxes(dhs, 0, 1).astype(jnp.float32)
    # h_{t-1} / c_{t-1} streams: shift the saved sequences right by one
    hprev_tm = jnp.concatenate([h0.astype(jnp.float32)[None], hs_tm[:-1]], 0)
    cprev_tm = jnp.concatenate([c0.astype(jnp.float32)[None], cs_tm[:-1]], 0)

    rev = lambda i: (n_blk - 1 - i, 0, 0)
    fixed2 = lambda i: (0, 0)
    blk_t = lambda w: pl.BlockSpec((ch, n, w), rev, memory_space=pltpu.VMEM)
    blk_nh = pl.BlockSpec((n, h_dim), fixed2, memory_space=pltpu.VMEM)

    out_shape = (
        jax.ShapeDtypeStruct((t, n, four_h), jnp.float32),   # dxproj
        jax.ShapeDtypeStruct((h_dim, four_h), jnp.float32),  # dU
        jax.ShapeDtypeStruct((3, h_dim), jnp.float32),       # dp
        jax.ShapeDtypeStruct((n, h_dim), jnp.float32),       # dh0
        jax.ShapeDtypeStruct((n, h_dim), jnp.float32),       # dc0
    )
    dxproj_tm, du, dp, dh0, dc0 = pl.pallas_call(
        _lstm_bwd_kernel,
        grid=(n_blk,),
        in_specs=[
            blk_t(four_h),                                    # xproj
            blk_t(h_dim), blk_t(h_dim), blk_t(h_dim),         # hprev/cprev/cs
            pl.BlockSpec((h_dim, four_h), fixed2, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, h_dim), fixed2, memory_space=pltpu.VMEM),
            blk_t(h_dim),                                     # dhs
            blk_nh, blk_nh,                                   # dh_f, dc_f
        ],
        out_specs=(
            blk_t(four_h),
            pl.BlockSpec((h_dim, four_h), fixed2, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, h_dim), fixed2, memory_space=pltpu.VMEM),
            blk_nh, blk_nh,
        ),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((n, h_dim), jnp.float32),
            pltpu.VMEM((n, h_dim), jnp.float32),
            pltpu.VMEM((h_dim, four_h), jnp.float32),
            pltpu.VMEM((3, h_dim), jnp.float32),
        ],
        interpret=interpret,
    )(xproj_tm, hprev_tm, cprev_tm, cs_tm, u.astype(jnp.float32),
      p.astype(jnp.float32), dhs_tm, dh_f.astype(jnp.float32),
      dc_f.astype(jnp.float32))
    return jnp.swapaxes(dxproj_tm, 0, 1), du, dp, dh0, dc0


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def lstm_pallas_scan(xproj, u, p, h0, c0, interpret=False):
    """Fused LSTM scan: pallas kernels for BOTH directions (reverse-time
    backward kernel when the shape fits VMEM, scan-autodiff fallback
    otherwise). Gate order in the 4H axis is [i, f, o, g], identical to
    recurrent._lstm_step's z-split, so params are shared untouched."""
    hs, _, h_f, c_f = _lstm_pallas_fwd_raw(xproj, u, p, h0, c0,
                                           interpret=interpret)
    return hs, h_f, c_f


def _lstm_fwd(xproj, u, p, h0, c0, interpret):
    # emit the cell-state residual ONLY when the backward kernel will
    # consume it; otherwise the backward is scan-autodiff (which recomputes
    # its own forward) and the residual would be a pure HBM-write waste
    n, t, four_h = xproj.shape
    emit = lstm_bwd_fits(n, four_h // 4, t)
    hs, cs_tm, h_f, c_f = _lstm_pallas_fwd_raw(
        xproj, u, p, h0, c0, interpret=interpret, emit_cs=emit)
    return (hs, h_f, c_f), (xproj, u, p, h0, c0, cs_tm, hs)


def _lstm_bwd(interpret, res, grads):
    xproj, u, p, h0, c0, cs_tm, hs = res
    dhs, dh_f, dc_f = grads
    if cs_tm is not None:
        return _lstm_pallas_bwd_raw(xproj, u, p, h0, c0, cs_tm, hs,
                                    dhs, dh_f, dc_f, interpret=interpret)
    _, vjp = jax.vjp(
        lambda *args: _lstm_scan_reference(*args), xproj, u, p, h0, c0
    )
    return vjp(grads)


lstm_pallas_scan.defvjp(_lstm_fwd, _lstm_bwd)
