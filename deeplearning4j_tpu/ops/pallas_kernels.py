"""Pallas TPU kernels for hot ops.

The reference accelerates its hot layers with hand-written native kernels
(cuDNN helpers, SURVEY.md section 2.2; LSTMHelpers.java per-step gemm loop
:132,145). The XLA equivalent of most of that set is automatic fusion; the one
place a hand kernel still pays on TPU is the LSTM recurrence: a lax.scan
launches one XLA loop iteration per timestep, re-reading U/h/c from HBM each
step. The pallas kernel below runs the WHOLE scan in one kernel — U, the
peepholes, and the carried h/c stay resident in VMEM; only the per-step
input projection streams in and the per-step output streams out.

Scope & fallback policy:
  - forward only; the backward pass is jax autodiff through the plain scan
    (custom_vjp recomputes — same gradients, fwd at kernel speed);
  - mask-free path (padded/masked sequences fall back to the scan);
  - DEFAULT ON for TPU (disable with DL4J_TPU_PALLAS=0). Measured on a
    v5e chip with a sound completion fence (benchmarks/
    pallas_lstm_bench.py, PALLAS_BENCH.json): the kernel beats lax.scan
    on every tested shape — 1.09x at (N32,T128,H128), 1.25x at
    (N64,T256,H256), 1.75x at (N128,T512,H512). (Round 1 recorded "scan
    wins ~100x"; that measurement used jax.block_until_ready, which does
    not actually fence remote execution through the axon tunnel.) The
    kernel only engages when its blocks fit VMEM (lstm_scan_fits);
    everything else falls back to the scan. This is the reference's
    reflective cuDNN-helper slot (ConvolutionLayer.java:64-70) as a
    shape-gated backend registry.
  - CPU tests run the same kernel under interpret=True.

Written per /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# VMEM is ~16MB/core; keep a conservative budget for U + h + c + one xproj
# block + one output block (floats).
_VMEM_BUDGET_FLOATS = 2_000_000


def pallas_enabled() -> bool:
    """Default ON for TPU (the kernel beats lax.scan on all measured
    shapes — see module docstring); DL4J_TPU_PALLAS=0 disables. The
    special value DL4J_TPU_PALLAS=force enables even off-TPU — only
    useful for tests that monkeypatch the kernel into interpret mode
    (compiling the TPU kernel on CPU/GPU fails)."""
    env = os.environ.get("DL4J_TPU_PALLAS")
    if env in ("0", "false", "False"):
        return False
    if env == "force":
        return True
    # honor jax.default_device(...) overrides (the equivalence harness runs
    # CPU legs this way while the process default backend stays TPU)
    dd = jax.config.jax_default_device
    if dd is not None:
        return getattr(dd, "platform", "") in ("tpu", "axon")
    return jax.default_backend() == "tpu"


# Mosaic double-buffers every streamed block, so the per-block budget must
# leave room for 2x the xproj block + 2x the output block + U + scratch
# inside ~16MB of VMEM.
_BLOCK_BUDGET_FLOATS = 500_000  # ~2MB per xproj block (x2 for double buffer)


def _time_chunk(t: int, n: int, four_h: int) -> int:
    """Timesteps per grid step: the largest divisor of T whose xproj block
    (ch * N * 4H floats) fits the VMEM block budget. Bigger chunks amortize
    pipeline overhead; the budget keeps big-model shapes compiling (a
    32-step block at N=128/H=512 is 33MB — over VMEM on its own)."""
    for cand in (32, 16, 8, 4, 2):
        if t % cand == 0 and cand * n * four_h <= _BLOCK_BUDGET_FLOATS:
            return cand
    return 1


def lstm_scan_fits(n: int, h: int, t: int = 32) -> bool:
    """VMEM guard for the ACTUAL block sizes the kernel uses: a ch-timestep
    xproj block (ch*n*4h, double-buffered) + output block (ch*n*h, ditto),
    U, h/c scratch + io."""
    ch = _time_chunk(t, n, 4 * h)
    need = h * 4 * h + 4 * n * h + 2 * ch * n * 4 * h + 2 * ch * n * h
    return need <= _VMEM_BUDGET_FLOATS


# ---------------------------------------------------------------------------
# Fused LSTM forward scan
# ---------------------------------------------------------------------------


def _lstm_kernel(xproj_ref, u_ref, p_ref, h0_ref, c0_ref, hs_ref, hf_ref,
                 cf_ref, h_scr, c_scr):
    """Grid = (T,), sequential. Time-major layout: block t sees
    xproj[t, :, :] and writes hs[t, :, :] — the block's trailing two dims
    are then (N, 4H)/(N, H), satisfying the TPU (8, 128) tiling rule.
    h/c live in VMEM scratch across iterations."""
    t = pl.program_id(0)
    n_t = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    n_out = h_scr.shape[-1]
    chunk = xproj_ref.shape[0]
    u = u_ref[:]
    pi = p_ref[0, :]
    pf = p_ref[1, :]
    po = p_ref[2, :]

    def body(k, carry):
        h_prev, c_prev = carry
        # z: [N, 4H] = xproj_t + h_prev @ U  (MXU)
        z = xproj_ref[k, :, :] + jnp.dot(
            h_prev, u, preferred_element_type=jnp.float32
        )
        zi = z[:, 0 * n_out : 1 * n_out]
        zf = z[:, 1 * n_out : 2 * n_out]
        zo = z[:, 2 * n_out : 3 * n_out]
        zg = z[:, 3 * n_out : 4 * n_out]
        i = jax.nn.sigmoid(zi + pi * c_prev)
        f = jax.nn.sigmoid(zf + pf * c_prev)
        g = jnp.tanh(zg)
        c = f * c_prev + i * g
        o = jax.nn.sigmoid(zo + po * c)
        h = o * jnp.tanh(c)
        hs_ref[k, :, :] = h
        return h, c

    h, c = jax.lax.fori_loop(0, chunk, body, (h_scr[:], c_scr[:]))
    h_scr[:] = h
    c_scr[:] = c

    @pl.when(t == n_t - 1)
    def _():
        hf_ref[:] = h
        cf_ref[:] = c


def _lstm_pallas_fwd_raw(xproj, u, p, h0, c0, *, interpret: bool):
    """xproj: [N, T, 4H] (input projection + bias, precomputed);
    returns (hs [N,T,H], h_f, c_f)."""
    n, t, four_h = xproj.shape
    h_dim = four_h // 4
    ch = _time_chunk(t, n, four_h)
    grid = (t // ch,)
    out_shape = (
        jax.ShapeDtypeStruct((t, n, h_dim), jnp.float32),
        jax.ShapeDtypeStruct((n, h_dim), jnp.float32),
        jax.ShapeDtypeStruct((n, h_dim), jnp.float32),
    )
    xproj_tm = jnp.swapaxes(xproj, 0, 1)  # time-major [T, N, 4H]
    hs_tm, h_f, c_f = pl.pallas_call(
        _lstm_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((ch, n, four_h), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h_dim, four_h), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, h_dim), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n, h_dim), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n, h_dim), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((ch, n, h_dim), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n, h_dim), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n, h_dim), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((n, h_dim), jnp.float32),
            pltpu.VMEM((n, h_dim), jnp.float32),
        ],
        interpret=interpret,
    )(xproj_tm.astype(jnp.float32), u.astype(jnp.float32),
      p.astype(jnp.float32), h0.astype(jnp.float32), c0.astype(jnp.float32))
    return jnp.swapaxes(hs_tm, 0, 1), h_f, c_f


def _lstm_scan_reference(xproj, u, p, h0, c0):
    """Plain lax.scan twin of the kernel (tanh activation) — the autodiff
    path for the custom VJP and the numerical oracle in tests."""

    def step(carry, xp_t):
        h_prev, c_prev = carry
        z = xp_t + h_prev @ u
        zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(zi + p[0] * c_prev)
        f = jax.nn.sigmoid(zf + p[1] * c_prev)
        g = jnp.tanh(zg)
        c = f * c_prev + i * g
        o = jax.nn.sigmoid(zo + p[2] * c)
        h = o * jnp.tanh(c)
        return (h, c), h

    (h_f, c_f), hs = jax.lax.scan(step, (h0, c0), jnp.swapaxes(xproj, 0, 1))
    return jnp.swapaxes(hs, 0, 1), h_f, c_f


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def lstm_pallas_scan(xproj, u, p, h0, c0, interpret=False):
    """Fused LSTM forward scan: pallas kernel forward, scan-autodiff
    backward. Gate order in the 4H axis is [i, f, o, g], identical to
    recurrent._lstm_step's z-split, so params are shared untouched."""
    hs, h_f, c_f = _lstm_pallas_fwd_raw(xproj, u, p, h0, c0,
                                        interpret=interpret)
    return hs, h_f, c_f


def _lstm_fwd(xproj, u, p, h0, c0, interpret):
    out = lstm_pallas_scan(xproj, u, p, h0, c0, interpret)
    return out, (xproj, u, p, h0, c0)


def _lstm_bwd(interpret, res, grads):
    xproj, u, p, h0, c0 = res
    _, vjp = jax.vjp(
        lambda *args: _lstm_scan_reference(*args), xproj, u, p, h0, c0
    )
    return vjp(grads)


lstm_pallas_scan.defvjp(_lstm_fwd, _lstm_bwd)
