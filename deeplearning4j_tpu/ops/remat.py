"""Activation-rematerialization policy plane (``DL4J_TPU_REMAT``).

The flagship TransformerLM is memory-bound, not compute-bound, on the
target chip: BENCH_NOTES records d2048 L4 b16 as the best MFU row with
b32 exceeding usable HBM. The reference never had this problem because
its training loop was an op-by-op dispatch that fused nothing
(MultiLayerNetwork.java:1017 — every activation lived exactly as long as
the JVM held a reference); whole-step XLA compilation (ARCHITECTURE.md
decision #1) buys the dispatch win at the cost of every layer's residual
buffers staying live from forward until their backward use. Activation
rematerialization (Chen et al., "Training Deep Nets with Sublinear
Memory Cost") is the standard lever every production JAX stack ships:
trade recompute for HBM by checkpointing the layer boundary and
re-running the layer body in the backward pass.

One knob, a three-rung ladder (each rung strictly less HBM, strictly
more recompute):

  ``none``   store every activation (fastest; the pre-PR behavior)
  ``dots``   ``jax.checkpoint(policy=dots_saveable)``: keep matmul
             outputs (the MXU work), recompute elementwise ops — the
             cheap middle rung (recompute is VPU-only)
  ``block``  full per-block remat: store only the residual-stream carry
             between blocks, recompute the whole block body in the
             backward pass (sublinear activation memory in depth)

Resolution order: an explicit policy string wins; ``"auto"`` (the
config default everywhere) defers to the ``DL4J_TPU_REMAT`` env knob;
an unset knob means ``none``. The policy is read at TRACE time — the
same read-at-jit-construction discipline as the donation policy
(ops/dispatch.donation_enabled): flipping the env after a step has
compiled does not retroactively change it.

Consumed by: models/transformer.forward's block scan (train_step,
fit_batches, and the accum-path microbatch scan all trace through it),
models/bert.encode's block scan, and the containers' per-layer
``remat_apply`` (nn/common.apply_layer — the pre-existing
``gradient_checkpointing`` conf flag is this ladder's ``block`` rung,
now unified under the same knob). Measured evidence lives in the
``remat_memory`` bench leg + REMAT_MEMORY.json (AOT
``memory_analysis`` temp-bytes ladder — ops/memory.py).
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.ops import env as envknob

ENV_REMAT = "DL4J_TPU_REMAT"

# ladder order: increasing HBM savings, increasing backward recompute
POLICIES = ("none", "dots", "block")


def remat_policy(configured: Optional[str] = "auto") -> str:
    """Resolve the active remat policy.

    ``configured`` is the model/config-level request: a policy name pins
    it; ``"auto"`` (or None/empty) defers to the ``DL4J_TPU_REMAT`` env
    knob, whose absence means ``none``. Unknown names raise loudly — a
    typo'd policy must not silently train without remat and OOM on first
    tunnel contact (the exact failure the ladder exists to prevent)."""
    v = (configured or "auto").strip().lower()
    if v == "auto":
        v = envknob.raw(ENV_REMAT, "").strip().lower() or "none"
    if v not in POLICIES:
        raise ValueError(
            f"unknown remat policy {v!r} (known: {', '.join(POLICIES)}, "
            "or 'auto' to defer to DL4J_TPU_REMAT)")
    return v


def checkpoint_kwargs(policy: str) -> dict:
    """kwargs for ``jax.checkpoint`` implementing one active rung
    (``none`` is not an active rung — callers skip the wrap entirely)."""
    if policy == "block":
        return {}
    if policy == "dots":
        from jax.ad_checkpoint import checkpoint_policies

        return {"policy": checkpoint_policies.dots_saveable}
    raise ValueError(f"no checkpoint kwargs for policy {policy!r}")


def remat_wrap(fn, policy: Optional[str] = "auto", *,
               prevent_cse: bool = True):
    """Wrap a function (typically a ``lax.scan`` block body) per the
    resolved policy; ``none`` returns it untouched. ``prevent_cse=False``
    is for bodies that sit inside a scan — the loop boundary already
    blocks the CSE the checkpoint barriers guard against, so the default
    barriers would only cost fusion opportunities (the same rationale as
    nn/common.remat_apply's flag)."""
    pol = remat_policy(policy)
    if pol == "none":
        return fn
    import jax

    return jax.checkpoint(fn, prevent_cse=prevent_cse,
                          **checkpoint_kwargs(pol))
