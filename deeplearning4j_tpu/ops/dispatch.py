"""Dispatch-efficiency layer: buffer donation, shape bucketing, persistent
compile cache, and retrace/dispatch telemetry.

The reference's training entry points accept arbitrary batch shapes
(``MultiLayerNetwork.fit(DataSet)`` — MultiLayerNetwork.java:1017) and pay a
per-op JVM dispatch cost; under jax the cost model shifts but does not
vanish: a NEW batch shape is a full XLA retrace of the whole-step program,
and a jit without donated buffers copies params + optimizer state through
HBM on every step. On this chip a dispatch costs ~5ms and end-to-end MFU
tops out ~11% (BENCH_NOTES.md) — compile/dispatch amortization is the
single biggest lever left. This module concentrates the counter-measures
the containers (nn/multilayer.py, nn/graph.py), the Solver
(optimize/solvers.py), the parallel trainers (parallel/data_parallel.py)
and the flagship factories (models/transformer.py) all share:

  1. donation policy   — ``donation_enabled()`` / ``instrumented_jit(...,
     donate=...)``: donate ``params/states/upd_state`` into the step so the
     update is in-place on device. Default ON on accelerators, OFF on CPU
     (the test/equivalence substrate routinely re-reads params trees — the
     same rationale as models/transformer._donation_kwargs); the env knob
     ``DL4J_TPU_DONATE`` overrides both ways ("force" turns it on even on
     CPU, which this jax implements for real — tests use it to verify the
     call sites never re-read a donated buffer).
  2. shape bucketing   — ``bucket_size()`` pads ragged batches up to a
     small power-of-two-ish set so ``fit``/``fit_iterator``/``output``
     compile once per BUCKET instead of once per shape; the pad rows are
     masked out of the loss through the existing mask plumbing
     (nn/losses._masked_mean_per_example), which makes padding
     semantically free. Knob: ``DL4J_TPU_BUCKET_BATCHES`` (default on).
  3. compile cache     — ``enable_compile_cache()`` wires jax's persistent
     XLA compilation cache (``jax_compilation_cache_dir``) so round
     restarts and bench subprocess legs warm-start instead of recompiling.
     Knob: ``DL4J_TPU_COMPILE_CACHE`` (path | "0" to disable; default
     ``.jax_cache/`` under the cwd; an explicit
     ``JAX_COMPILATION_CACHE_DIR`` wins — that is jax's own env var, which
     the bench watcher already exports to every child).
  4. telemetry         — ``DispatchStats``: per-network counters of traces
     (XLA compiles), dispatches (calls; calls - traces = compiled-cache
     hits), donated-vs-copied steps and padded batches, surfaced through
     the listener chain (optimize/listeners.DispatchStatsListener) and the
     ``dispatch_overhead`` bench leg.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.obs import trace as obs_trace
from deeplearning4j_tpu.ops import env as envknob

ENV_DONATE = "DL4J_TPU_DONATE"
ENV_BUCKET = "DL4J_TPU_BUCKET_BATCHES"
ENV_CACHE = "DL4J_TPU_COMPILE_CACHE"
ENV_FUSE = "DL4J_TPU_FUSE"

_OFF = ("0", "off", "false", "no")
_ON = ("1", "on", "true", "yes", "force")



# ---------------------------------------------------------------------------
# donation policy
# ---------------------------------------------------------------------------


def donation_enabled() -> bool:
    """Should train-step jits donate their params/states/upd_state buffers?

    Read at jit-CONSTRUCTION time (the containers cache jits, so flipping
    the env after a net has compiled does not retro-actively change it).

    Default: donate on accelerators, skip on CPU — CPU runs are the
    test/equivalence substrate where callers routinely hold one initial
    params tree across several step functions (the serial-vs-distributed
    pattern), which donation would poison. The decision reads the
    ``jax_platforms`` CONFIG, never ``jax.default_backend()`` — the latter
    initializes the axon TPU plugin, which hangs on a dead tunnel and locks
    the platform before the caller could still choose CPU (CLAUDE.md).
    """
    v = envknob.raw(ENV_DONATE, "").strip().lower()
    if v in _OFF:
        return False
    if v in _ON:
        return True
    platforms = jax.config.jax_platforms
    return not (platforms and platforms.split(",")[0] == "cpu")


def arena_jit(fn, donate: Sequence[int] = ()):
    """jit for SINGLE-OWNER accumulator buffers — donated by default
    even on CPU.

    donation_enabled() defaults off on CPU because equivalence tests
    hold one params tree across several step functions; that caveat does
    not apply to a buffer with exactly one owner who always rebinds the
    result and never re-reads the input — the paged-KV serving arena
    (serving/paged.py), where an un-donated tick would copy the whole
    arena per generated token. An explicit ``DL4J_TPU_DONATE=0`` still
    wins (the knob's 'never' contract covers every donating jit)."""
    v = envknob.raw(ENV_DONATE, "").strip().lower()
    if v in _OFF or not donate:
        return jax.jit(fn)
    return jax.jit(fn, donate_argnums=tuple(donate))


# ---------------------------------------------------------------------------
# fusion policy (fit_batches' scan-of-steps)
# ---------------------------------------------------------------------------


def fusion_enabled(scanned_conv: bool = False) -> bool:
    """Should fit_batches fuse K steps into one lax.scan program?

    Fusion is the dispatch-amortization win everywhere EXCEPT scanned
    conv programs on XLA:CPU, which the backend pessimizes ~15x vs the
    per-step program (measured, BENCH_NOTES round-6 — the CPU-for-CPU
    lenet5 row quotes the per-step number for exactly this reason). The
    containers pass ``scanned_conv=True`` when the net has conv/
    subsampling layers; on the CPU substrate that falls back to per-step
    fits (recorded in ``DispatchStats.fused_fallbacks``). The env knob
    ``DL4J_TPU_FUSE`` overrides: ``force`` (or any _ON value) always
    fuses — the equivalence tests and the lenet5_cpu leg pin the fused
    program with it — and ``0`` never does. Reads the
    ``jax_platforms`` CONFIG, never the backend (the donation-policy
    rationale: jax.default_backend() would initialize the axon plugin,
    which hangs on a dead tunnel)."""
    v = envknob.raw(ENV_FUSE, "").strip().lower()
    if v in _ON:  # "force" and its _ON siblings ("1"/"on"/...) all pin fusion
        return True
    if v in _OFF:
        return False
    if not scanned_conv:
        return True
    platforms = jax.config.jax_platforms
    return not (platforms and platforms.split(",")[0] == "cpu")


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------


class DispatchStats:
    """Per-network dispatch-efficiency counters.

    The reference has nothing like this because its failure mode (per-op
    dispatch) is uniform; under jax the pathologies are *episodic* (a
    ragged batch triggering a silent 30s retrace) and need a counter to be
    visible at all.

      traces[name]   python-level traces of the named jit == XLA compiles
                     (a retrace on a new shape increments it again)
      calls[name]    dispatches of the named jit; calls - traces is the
                     compiled-program cache-hit count
      donated_steps / copied_steps
                     steps executed with / without buffer donation
      padded_batches / padded_examples
                     shape-bucketing activity (fit calls that padded, and
                     the total pad rows fed)
      trace_seconds[name]
                     wall-seconds spent in calls that TRACED (trace +
                     XLA compile + the first dispatch per shape) — the
                     compile-time ledger for tunnel-window triage: a
                     short contact window budgeted against these numbers
                     knows which programs it can afford to warm
      fused_fallbacks
                     fit_batches calls that fell back to per-step fits
                     under the fusion policy (fusion_enabled: the
                     XLA:CPU scan-of-conv pessimization guard)
      loss_scale_skips
                     bf16 loss-scaled training (DL4J_TPU_BF16 /
                     ops/lowprec.py): optimizer steps SKIPPED on
                     non-finite grads (the halve-and-skip half of
                     dynamic loss scaling). Refreshed at explicit sync
                     points (training_state() / net.loss_scale), never
                     per step — reading it per step would be a hidden
                     device sync.
      decode_ticks / decode_tokens
                     continuous-decode dispatch amortization (ISSUE 16:
                     serving/decode.py + serving/paged.py multi-token
                     ticks): jitted decode dispatches and the tokens
                     they produced, summed over every lane. The derived
                     ``tokens_per_dispatch`` in snapshot() is the number
                     the ~5ms-per-dispatch overhead divides by — 1.0 is
                     the single-token baseline, k*lanes the scanned
                     ceiling.
    """

    def __init__(self) -> None:
        self.traces: Dict[str, int] = defaultdict(int)
        self.calls: Dict[str, int] = defaultdict(int)
        self.trace_seconds: Dict[str, float] = defaultdict(float)
        self.donated_steps = 0
        self.copied_steps = 0
        self.padded_batches = 0
        self.padded_examples = 0
        self.fused_fallbacks = 0
        self.loss_scale_skips = 0
        self.decode_ticks = 0
        self.decode_tokens = 0

    def cache_hits(self, name: Optional[str] = None) -> int:
        if name is not None:
            return self.calls.get(name, 0) - self.traces.get(name, 0)
        return sum(self.calls.values()) - sum(self.traces.values())

    def snapshot(self) -> Dict[str, Any]:
        return {
            "traces": dict(self.traces),
            "calls": dict(self.calls),
            "cache_hits": {n: self.cache_hits(n) for n in self.calls},
            "trace_seconds": {n: round(s, 3)
                              for n, s in self.trace_seconds.items()},
            "donated_steps": self.donated_steps,
            "copied_steps": self.copied_steps,
            "padded_batches": self.padded_batches,
            "padded_examples": self.padded_examples,
            "fused_fallbacks": self.fused_fallbacks,
            "loss_scale_skips": self.loss_scale_skips,
            "decode_ticks": self.decode_ticks,
            "decode_tokens": self.decode_tokens,
            "tokens_per_dispatch": (
                round(self.decode_tokens / self.decode_ticks, 4)
                if self.decode_ticks else None),
        }


def instrumented_jit(fn, name: str, stats: DispatchStats, *,
                     donate: Sequence[int] = (),
                     static_argnums=None, step: bool = False,
                     mem_stats=None):
    """``jax.jit`` with retrace/dispatch telemetry and policy-gated donation.

    ``donate``: argnums to donate WHEN the donation policy is on; the
    caller guarantees those arguments are re-bound from the return value
    and never re-read (the containers' ``self.params, ... = step(...)``
    discipline). Call sites that DO re-read an argument — the Solver's
    line-search oracle re-probes the same flat param vector — must pass
    ``donate=()``.

    ``step=True`` marks a training step for the donated/copied counters.

    ``mem_stats``: an ops/memory.MemoryStats to receive AOT byte
    accounting; the wrapper's ``.measure_memory(*args)`` lowers +
    compiles WITHOUT executing and records the analysis under ``name``
    (the memory plane beside this dispatch plane — never paid implicitly
    on the hot path).

    The returned wrapper exposes ``.lower`` (bench cost-analysis uses it)
    and ``.donated_argnums`` (tests assert the policy). Calls that trace
    also accrue wall-seconds into ``stats.trace_seconds[name]`` (trace +
    compile + first dispatch — the compile-time triage ledger).
    """
    enable_compile_cache()
    donated: Tuple[int, ...] = tuple(donate) if (
        donate and donation_enabled()) else ()
    kw: Dict[str, Any] = {}
    if donated:
        kw["donate_argnums"] = donated
    if static_argnums is not None:
        kw["static_argnums"] = static_argnums

    counting = [True]  # AOT .lower() re-traces for analysis, not dispatch
    span_name = f"dispatch.{name}"  # hoisted off the per-call hot path

    def traced(*args, **kwargs):
        if counting[0]:
            stats.traces[name] += 1
        return fn(*args, **kwargs)

    jfn = jax.jit(traced, **kw)

    def wrapper(*args, **kwargs):
        stats.calls[name] += 1
        if step:
            if donated:
                stats.donated_steps += 1
            else:
                stats.copied_steps += 1
        before = stats.traces[name]
        t0 = time.perf_counter()
        # obs span (DL4J_TPU_OBS, default off -> shared null context):
        # HOST-side dispatch timing only — the jit returns async, so the
        # span never adds a device sync (the listener-chain bulk-readback
        # rule). Attrs distinguish trace vs compiled-cache-hit dispatch.
        with obs_trace.span(span_name, donated=bool(donated),
                            step=step) as sp:
            out = jfn(*args, **kwargs)
            if stats.traces[name] > before:
                # this call traced: its wall time is dominated by
                # trace+XLA compile (dispatch itself returns async) — the
                # per-trace compile-cost ledger the DispatchStatsListener
                # and the dispatch_overhead leg surface for tunnel-window
                # triage
                stats.trace_seconds[name] += time.perf_counter() - t0
                sp.set_attr("traced", True)
        return out

    def lower(*args, **kwargs):
        # cost-analysis lowering (bench legs) must not skew the
        # traces-vs-calls cache-hit arithmetic: it traces without
        # dispatching, which would read as a phantom retrace
        counting[0] = False
        try:
            return jfn.lower(*args, **kwargs)
        finally:
            counting[0] = True

    def measure_memory(*args, **kwargs):
        from deeplearning4j_tpu.ops import memory as memory_mod

        analysis = memory_mod.analyze_lowered(lower(*args, **kwargs))
        if mem_stats is not None and analysis is not None:
            mem_stats.record(name, analysis)
        return analysis

    wrapper.lower = lower
    wrapper.measure_memory = measure_memory
    wrapper.donated_argnums = donated
    wrapper._jitted = jfn
    wrapper.__name__ = f"jit_{name}"
    return wrapper


# ---------------------------------------------------------------------------
# shape bucketing
# ---------------------------------------------------------------------------


def bucketing_mode() -> str:
    """Bucketing policy, read at CALL time (per fit) so tests can toggle.

      "off"    — never pad (DL4J_TPU_BUCKET_BATCHES=0)
      "always" — every fit() buckets (DL4J_TPU_BUCKET_BATCHES=1)
      "auto"   — the default: bucket inside fit_iterator (the hot loop
                 where ragged tails and shape drift actually occur) and in
                 inference (output), but leave DIRECT fit(features, labels)
                 calls byte-exact — the repo's equivalence contracts
                 (fit_batches == K serial fits, distributed == serial)
                 compare direct-fit trajectories at tight tolerance, and
                 padding legitimately reassociates float32 reductions and
                 reshapes dropout draws.
    """
    v = envknob.raw(ENV_BUCKET, "").strip().lower()
    if v in _OFF:
        return "off"
    if v in _ON:
        return "always"
    return "auto"


def bucket_size(n: int) -> int:
    """Smallest power-of-two-ish size >= n.

    The bucket set is {1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, ...}
    — powers of two and 1.5x powers of two — so padding waste stays under
    50% (worst case sits just above a power of two) and a stream of
    arbitrary batch sizes compiles O(log n) programs instead of one per
    distinct size (the reference's fit(DataSet) accepts any shape because
    a JVM op re-dispatch is cheap; an XLA retrace is not)."""
    if n <= 2:
        return max(n, 1)
    p = 1
    while p < n:
        p <<= 1
    mid = (p >> 1) + (p >> 2)  # 1.5 * (p/2), sits between p/2 and p
    return mid if (p >= 4 and n <= mid) else p


def pad_axis0(a, target: int):
    """Zero-pad axis 0 up to ``target`` rows (no-op when already there)."""
    a = jnp.asarray(a)
    if a.shape[0] == target:
        return a
    pad = [(0, target - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad)


def inference_bucket(stats: DispatchStats, n: int) -> Optional[int]:
    """Inference-side bucketing decision shared by both containers'
    output(): the padded size to use (recording the activity in
    ``stats``), or None when no padding applies. Inference padding is
    unconditionally safe — BN uses running stats and dropout is off — so
    the only gates are the mode knob and n already being a bucket."""
    if bucketing_mode() == "off":
        return None
    target = bucket_size(n)
    if target == n:
        return None
    stats.padded_batches += 1
    stats.padded_examples += target - n
    return target


def pad_rows(stats: DispatchStats, target: int, arrays):
    """Pad each array (None entries pass through) along axis 0 to
    ``target`` and record the bucketing activity ONCE in ``stats`` — the
    single home of the pad-and-count discipline both containers' fit hooks
    share. Call only when padding is actually needed (target > batch)."""
    n = next(a for a in arrays if a is not None).shape[0]
    stats.padded_batches += 1
    stats.padded_examples += target - n
    return [None if a is None else pad_axis0(a, target) for a in arrays]


# memoized host-side masks: the mask is a pure function of
# (n_real, n_padded, time_steps), and building it eagerly with jnp ops
# would cost per-fit device dispatches (~5ms each through the remote-TPU
# tunnel) on the exact hot path this module exists to thin out. A numpy
# array rides the jit call's normal argument transfer instead.
_ROW_MASKS: Dict[Tuple[int, int, Optional[int]], "np.ndarray"] = {}


def row_validity_mask(n_real: int, n_padded: int,
                      time_steps: Optional[int] = None):
    """1.0 for real rows, 0.0 for pad rows — fed as the label mask so the
    masked-mean loss (nn/losses._masked_mean_per_example) divides by the
    REAL example count. For an unpadded batch this is all-ones, and
    sum(loss * 1) / sum(ones) is bit-identical to the plain mean — which is
    why the containers attach it even when no padding happened: every
    bucket then shares ONE jit signature instead of splitting into
    padded/unpadded variants of the same shape."""
    key = (n_real, n_padded, time_steps)
    m = _ROW_MASKS.get(key)
    if m is None:
        m = (np.arange(n_padded) < n_real).astype(np.float32)
        if time_steps is not None:
            m = np.broadcast_to(m[:, None], (n_padded, time_steps))
        _ROW_MASKS[key] = m
    return m


# ---------------------------------------------------------------------------
# persistent compile cache
# ---------------------------------------------------------------------------

_CACHE_LOCK = threading.Lock()
_CACHE_WIRED: Optional[str] = None


def compile_cache_dir() -> Optional[str]:
    """Resolve the cache directory from the env knobs (None = disabled)."""
    v = envknob.raw(ENV_CACHE, "").strip()
    if v.lower() in _OFF:
        return None
    if v:
        return v
    # jax's own env var: the bench watcher exports it to every child, and
    # an operator setting it explicitly should win over our default
    native = os.environ.get("JAX_COMPILATION_CACHE_DIR", "").strip()
    if native:
        return native
    return os.path.join(os.getcwd(), ".jax_cache")


def enable_compile_cache(cache_dir: Optional[str] = None,
                         min_compile_secs: float = 1.0) -> Optional[str]:
    """Wire jax's persistent XLA compilation cache (idempotent).

    Round restarts and bench subprocess legs re-jit the same programs; with
    the cache on disk the re-compile is a file read — a compile paid in one
    tunnel contact window is FREE in the next. Explicit ``cache_dir``
    always re-wires (tests point it at a tmpdir with
    ``min_compile_secs=0`` to force tiny compiles into the cache);
    otherwise the env-resolved directory is wired once per process.
    Returns the active directory, or None when disabled/unsupported."""
    global _CACHE_WIRED
    with _CACHE_LOCK:
        if envknob.raw(ENV_CACHE, "").strip().lower() in _OFF:
            return None  # the off-switch beats even an explicit cache_dir
        d = cache_dir or compile_cache_dir()
        if d is None:
            return None
        if cache_dir is None and _CACHE_WIRED is not None:
            return _CACHE_WIRED
        try:
            jax.config.update("jax_compilation_cache_dir", d)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_compile_secs))
        except Exception:  # noqa: BLE001 — cache is an optimization, never a crash
            return None
        _CACHE_WIRED = d
        return d
