"""AOT memory-accounting plane: the HBM ledger beside ops/dispatch's
dispatch ledger.

XLA's ahead-of-time path reports, per compiled program, exactly how many
bytes of arguments, outputs and temporaries (activations + workspace)
the executable will touch — ``jit(f).lower(args).compile()
.memory_analysis()`` — WITHOUT executing anything and on whatever
backend compiled it. That makes the memory cost of a training step
*provable without the tunnel* (VERDICT r5's structural ask): the CPU
build of the d512 L8 step shows the remat ladder's temp-bytes reduction
on this host today, and the same call against the chip reports real HBM
when the tunnel next opens.

Three surfaces:

  1. ``MemoryStats`` + ``analyze_jit`` — per-program byte accounting,
     exposed as ``net.memory_stats`` beside ``net.dispatch_stats`` on
     both containers and the flagship models (populated on demand via
     ``measure_memory``: AOT lowering is a compile, not a step, so it is
     never paid implicitly on the hot path).
  2. ``transformer_preflight`` — the OOM guard for the MFU-chase bench
     leg (bench.transformer_hbm_preflight delegates here): exact
     params/optimizer/grads via ``jax.eval_shape`` on the real inits,
     remat- and accum-aware analytic activation model for the
     bf16+flash regime, plus MEASURED AOT numbers merged in whenever the
     config is small enough to compile cheaply on the CPU substrate.
  3. ``auto_fit_transformer`` — given ``DL4J_TPU_HBM_GB``, pick the
     largest (batch, accum_steps, remat policy) triple that fits:
     largest batch first, then the cheapest way to afford it (no accum
     before accum, weakest remat rung before strongest — every rung down
     the ladder costs backward recompute).

The reference has no analog: its memory ceiling was JVM heap and its
failure mode an ``OutOfMemoryError`` mid-fit (SURVEY §3.1); here an OOM
on first tunnel contact wastes the round's one capture window, so the
guard must be computable offline.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.ops import env as envknob

ENV_HBM = "DL4J_TPU_HBM_GB"
# configs whose batch*seq*d_model element count is at or under this are
# cheap enough to AOT-compile on the CPU substrate for measured numbers
# (the d512 L8 b8 s256 evidence config compiles in ~2s on this host)
ENV_MEASURE_ELEMS = "DL4J_TPU_MEM_MEASURE_ELEMS"
_MEASURE_ELEMS_DEFAULT = 2_000_000


def hbm_budget_gb(default: float = 16.0) -> float:
    """The per-chip HBM budget the sizers fit against (env-overridable —
    BENCH_NOTES records this chip's usable HBM as ~16GB)."""
    try:
        return float(envknob.raw(ENV_HBM, "") or default)
    except ValueError:
        return default


class MemoryStats:
    """Per-program AOT memory accounting (bytes), keyed by the same
    program names DispatchStats uses (``train_step``, ``fit_batches``,
    ``output``) so the two ledgers line up row for row."""

    def __init__(self) -> None:
        self.programs: Dict[str, Dict[str, Any]] = {}

    def record(self, name: str, analysis: Dict[str, Any]) -> None:
        self.programs[name] = dict(analysis)

    def snapshot(self) -> Dict[str, Any]:
        return {k: dict(v) for k, v in self.programs.items()}


def analyze_compiled(compiled) -> Optional[Dict[str, Any]]:
    """Byte accounting of one compiled XLA executable, or None when the
    backend doesn't expose memory stats (the accounting is evidence,
    never a crash — same posture as dispatch.enable_compile_cache)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return None
    if ma is None:
        return None
    out = {
        "temp_bytes": int(ma.temp_size_in_bytes),
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    # live-at-once upper bound: args + temps + non-aliased outputs (a
    # donated step aliases outputs onto inputs, so alias_bytes nets out)
    out["peak_bytes"] = (out["argument_bytes"] + out["temp_bytes"]
                         + max(0, out["output_bytes"] - out["alias_bytes"]))
    return out


def analyze_lowered(lowered) -> Optional[Dict[str, Any]]:
    try:
        return analyze_compiled(lowered.compile())
    except Exception:  # noqa: BLE001
        return None


def analyze_jit(fn, *args, **kwargs) -> Optional[Dict[str, Any]]:
    """AOT memory accounting for a jitted callable (accepts plain
    ``jax.jit`` results and dispatch.instrumented_jit wrappers — both
    expose ``.lower``; instrumented wrappers suppress the phantom-retrace
    count themselves). Args may be real arrays or ShapeDtypeStructs —
    lowering never executes."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    try:
        return analyze_lowered(lower(*args, **kwargs))
    except Exception:  # noqa: BLE001
        return None


def measure(stats: Optional[MemoryStats], name: str, fn, *args,
            **kwargs) -> Optional[Dict[str, Any]]:
    """analyze_jit + record into a MemoryStats (when given)."""
    analysis = analyze_jit(fn, *args, **kwargs)
    if stats is not None and analysis is not None:
        stats.record(name, analysis)
    return analysis


# ---------------------------------------------------------------------------
# transformer training-step sizing (the flagship's OOM guard + auto-fit)
# ---------------------------------------------------------------------------


def _tree_bytes(tree) -> int:
    import jax

    return sum(int(np.prod(leaf.shape)) * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(tree))


def _cpu_substrate() -> bool:
    """True when jax is pinned to CPU via config — the only platform the
    measured path may compile on implicitly. Reads the CONFIG, never the
    backend (jax.default_backend() initializes the axon plugin, which
    hangs on a dead tunnel — CLAUDE.md)."""
    import jax

    platforms = jax.config.jax_platforms
    return bool(platforms) and platforms.split(",")[0] == "cpu"


def transformer_preflight(cfg, batch: int, *, accum_steps: int = 1,
                          remat: Optional[str] = None,
                          hbm_gb: Optional[float] = None,
                          measure_aot: Optional[bool] = None,
                          ) -> Tuple[bool, Dict[str, Any]]:
    """HBM estimate for one TransformerLM training step under a remat
    policy and gradient-accumulation factor. Returns (fits, report).

    Params, optimizer state and gradients are EXACT
    (``jax.eval_shape`` on the real init_params/init_opt_state — zero
    allocation, works without the chip). Activations are an analytic
    per-layer residual count for the bf16+flash regime, scaled by the
    remat rung:

      none   every layer's residuals stay live for the backward
             (q/k/v/attn-out/mlp-in/x ~6 [B,S,D] buffers + 2 [B,S,F]
             gelu buffers + flash o/lse, per layer)
      dots   per layer only the dot OUTPUTS stay (5 [B,S,D] + 1 [B,S,F]),
             plus one layer's full residual set as the recompute peak
      block  per layer only the [B,S,D] residual carry stays, plus one
             layer's full residual set as the recompute peak (Chen et
             al. sublinear memory)

    accum_steps > 1 sizes activations/logits per MICROBATCH (batch/A)
    and doubles the gradient tree (accumulator + current microbatch
    grads — models/transformer._build_step's scan). Logits count
    [mb, S, V] f32 x2 (fwd + softmax residual); 1.25x slack for XLA
    temps.

    When the config is small enough to compile cheaply and jax is pinned
    to the CPU substrate (or ``measure_aot=True``), the ACTUAL step is
    AOT-lowered and ``memory_analysis`` numbers are merged into the
    report (``measured`` sub-dict) — measured-where-possible, analytic
    everywhere else; the fits verdict stays with the analytic total,
    whose activation model is the flash/TPU program (the CPU build
    materializes dense [B,H,T,T] scores the chip never allocates)."""
    import jax

    from deeplearning4j_tpu.models.transformer import (
        init_opt_state,
        init_params,
    )
    from deeplearning4j_tpu.ops.remat import remat_policy

    policy = remat_policy(remat if remat is not None else cfg.remat)
    if batch % accum_steps:
        raise ValueError(f"batch {batch} not divisible by accum_steps "
                         f"{accum_steps}")
    from deeplearning4j_tpu.ops import lowprec

    budget_gb = hbm_budget_gb() if hbm_gb is None else float(hbm_gb)
    seq = cfg.max_len
    # bf16 activations under the performance dtype policy OR bf16
    # master-weight training (DL4J_TPU_BF16 casts at the step boundary,
    # so the residuals the backward keeps are bf16 either way)
    bf16_acts = cfg.dtype_policy == "performance" or lowprec.train_policy()
    ib = 2 if bf16_acts else 4
    L = cfg.n_layers

    p_shapes = jax.eval_shape(lambda: init_params(cfg))
    param_b = _tree_bytes(p_shapes)
    opt_b = _tree_bytes(jax.eval_shape(init_opt_state, p_shapes))
    # accum materializes the zero accumulator tree ALONGSIDE the current
    # microbatch's grads; the plain step holds one grad tree
    grad_b = param_b * (2 if accum_steps > 1 else 1)

    mb = batch // accum_steps
    bsd = mb * seq * cfg.d_model
    ff = mb * seq * cfg.d_ff
    layer_full = 6 * bsd + 2 * ff + bsd + 2 * mb * seq
    if policy == "none":
        act_b = L * layer_full * ib
    elif policy == "dots":
        act_b = (L * (5 * bsd + ff) + layer_full) * ib
    else:  # block
        act_b = (L * bsd + layer_full) * ib
    logit_b = 2 * mb * seq * cfg.vocab_size * 4
    total = (param_b + opt_b + grad_b + act_b + logit_b) * 1.25

    report = {
        "params_gb": round(param_b / 2**30, 2),
        "opt_gb": round(opt_b / 2**30, 2),
        "grads_gb": round(grad_b / 2**30, 2),
        "activations_gb_est": round(act_b / 2**30, 2),
        "logits_gb": round(logit_b / 2**30, 2),
        "total_gb_est": round(total / 2**30, 2),
        "hbm_gb": budget_gb,
        "batch": batch,
        "accum_steps": accum_steps,
        "remat": policy,
        "train_dtype": "bf16" if bf16_acts else "f32",
        "estimate": "analytic",
    }

    limit = int(envknob.raw(ENV_MEASURE_ELEMS, "")
                or _MEASURE_ELEMS_DEFAULT)
    do_measure = (measure_aot if measure_aot is not None
                  else (_cpu_substrate() and batch * seq * cfg.d_model
                        <= limit))
    if do_measure:
        measured = _measure_train_step(cfg, batch, accum_steps, policy,
                                       p_shapes)
        if measured is not None:
            report["measured"] = measured
            report["estimate"] = "analytic+measured"

    return total <= budget_gb * 2**30, report


def _measure_train_step(cfg, batch, accum_steps, policy, p_shapes):
    """AOT-compile the REAL train step (no execution, no allocation
    beyond the compile) and return its memory_analysis bytes."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import transformer as tfm

    cfg2 = dataclasses.replace(cfg, remat=policy, accum_steps=accum_steps)
    opt_shapes = jax.eval_shape(tfm.init_opt_state, p_shapes)
    toks = jax.ShapeDtypeStruct((batch, cfg.max_len), jnp.int32)
    analysis = analyze_jit(tfm.make_train_step(cfg2), p_shapes, opt_shapes,
                           toks, toks)
    if analysis is None:
        return None
    return {
        "temp_gb": round(analysis["temp_bytes"] / 2**30, 3),
        "argument_gb": round(analysis["argument_bytes"] / 2**30, 3),
        "output_gb": round(analysis["output_bytes"] / 2**30, 3),
        "peak_gb": round(analysis["peak_bytes"] / 2**30, 3),
        "note": ("AOT memory_analysis of the step as compiled on THIS "
                 "substrate (a CPU build materializes dense attention "
                 "scores the flash/TPU program streams through VMEM)"),
    }


def auto_fit_transformer(cfg, *, batches=(32, 16, 8, 4),
                         accum_steps=(1, 2, 4),
                         policies=None,
                         hbm_gb: Optional[float] = None,
                         ) -> Optional[Dict[str, Any]]:
    """Pick the largest (batch, accum_steps, remat) triple whose
    preflight fits the HBM budget (``DL4J_TPU_HBM_GB`` unless given).

    Preference order: largest global batch first; within a batch the
    CHEAPEST way to afford it — accum_steps ascending (each extra
    microbatch is another sequential pass), remat rungs weakest-first
    (each rung down the ladder buys HBM with backward recompute). The
    bench MFU-chase leg (bench.bench_transformer_big) calls this with
    accum pinned to 1; training scripts can let all three axes float.

    Returns {"batch", "accum_steps", "remat", "report"} or None when
    nothing fits."""
    from deeplearning4j_tpu.ops.remat import POLICIES

    if policies is None:
        policies = POLICIES
    for b in sorted(set(batches), reverse=True):
        for a in sorted(set(accum_steps)):
            if b % a:
                continue
            for p in policies:
                fits, rep = transformer_preflight(
                    cfg, b, accum_steps=a, remat=p, hbm_gb=hbm_gb)
                if fits:
                    return {"batch": b, "accum_steps": a, "remat": p,
                            "report": rep}
    return None


# ---------------------------------------------------------------------------
# paged-KV arena sizing (the serving-side twin of auto_fit_transformer)
# ---------------------------------------------------------------------------


def kv_block_bytes(cfg, block_tokens: int, dtype=None,
                   devices: int = 1) -> int:
    """PER-DEVICE bytes of ONE paged KV block across all layers: K and
    V, [n_layers, block_tokens, n_heads/devices, head_dim] each, in the
    arena dtype (serving/paged.py's layout). ``dtype=None`` resolves
    through ops/lowprec.kv_dtype — the model's compute dtype unless
    ``DL4J_TPU_SERVE_KV_DTYPE`` overrides it (bf16 halves KV bytes, so
    the same HBM budget admits ~2x tokens). ``devices`` is the serving
    mesh width (serving/mesh.py head-shards the arena, so each device
    holds only its n_heads/devices slice of every block); closed-form
    AOT arithmetic, no device touch (tunnel-free)."""
    from deeplearning4j_tpu.ops import lowprec

    if dtype is None:
        dtype = lowprec.kv_dtype(cfg)
    devices = max(1, int(devices))
    hd = cfg.d_model // cfg.n_heads
    heads_local = -(-cfg.n_heads // devices)  # ceil: honest off-grid
    itemsize = np.dtype(dtype).itemsize
    return 2 * cfg.n_layers * int(block_tokens) * heads_local * hd \
        * itemsize


def kv_arena_blocks(cfg, block_tokens: int, *, params=None,
                    hbm_gb: Optional[float] = None,
                    kv_fraction: float = 0.5,
                    max_blocks: int = 4096, dtype=None,
                    devices: int = 1) -> int:
    """How many KV blocks the arena can afford under ``DL4J_TPU_HBM_GB``
    (interpreted PER DEVICE when ``devices`` > 1).

    Budget = HBM minus twice the parameter bytes (weights resident plus
    one transient copy for dispatch headroom; the serving mesh
    REPLICATES params — projections are column-sliced at trace time —
    so param bytes are NOT divided by ``devices``), times
    ``kv_fraction`` (the rest stays free for prefill temporaries and
    the serving batcher's bucket programs), divided by
    :func:`kv_block_bytes` at that device count — head-sharding drops
    per-device block bytes to 1/devices, so capacity scales ~linearly
    with the mesh. Clamped to [one max_len sequence + 1, max_blocks] so
    a tiny budget still yields a decoder that can serve a single
    request and a huge one doesn't balloon the tick's gather. This
    replaces the fixed pool's ``slots * max_len`` over-allocation with
    sizing from the accounting plane (ISSUE 11 satellite; ``devices``
    is the ISSUE 18 mesh-serving satellite)."""
    budget = (hbm_gb if hbm_gb is not None else hbm_budget_gb()) * 2.0**30
    if params is not None:
        budget -= 2.0 * _tree_bytes(params)
    per_block = kv_block_bytes(cfg, block_tokens, dtype, devices)
    blocks = int(max(0.0, budget) * float(kv_fraction) / per_block)
    floor = cfg.max_len // int(block_tokens) + 1
    return max(floor, min(int(max_blocks), blocks))


# ---------------------------------------------------------------------------
# ANN vector-arena sizing (the retrieval-side twin of kv_arena_blocks)
# ---------------------------------------------------------------------------


def ann_row_bytes(dim: int, dtype=np.float32) -> int:
    """Device bytes of ONE index row: a [dim] vector in the arena dtype."""
    return int(dim) * np.dtype(dtype).itemsize


def ann_arena_rows(dim: int, *, params=None,
                   hbm_gb: Optional[float] = None,
                   ann_fraction: float = 0.25,
                   max_rows: int = 1 << 20,
                   min_rows: int = 1024, dtype=np.float32) -> int:
    """How many vector rows the retrieval arena can afford under
    ``DL4J_TPU_HBM_GB`` — the AOT sizing behind ``DL4J_TPU_ANN_ROWS=0``
    (retrieval/store.VectorStore), pure closed-form arithmetic, no
    device touch (tunnel-free, the kv_arena_blocks discipline).

    Budget = HBM minus twice the encoder parameter bytes (weights
    resident plus a transient dispatch copy), times ``ann_fraction``
    (the serving KV arena and batcher programs own the rest), divided by
    three row copies (published snapshot + staging arena + one transient
    publish clone — the generation-swap publish keeps two arenas live
    plus the copy in flight), clamped to [min_rows, max_rows]."""
    budget = (hbm_gb if hbm_gb is not None else hbm_budget_gb()) * 2.0**30
    if params is not None:
        budget -= 2.0 * _tree_bytes(params)
    per_row = 3 * ann_row_bytes(dim, dtype)
    rows = int(max(0.0, budget) * float(ann_fraction) / per_row)
    return max(int(min_rows), min(int(max_rows), rows))


# ---------------------------------------------------------------------------
# model resident-bytes pricing (the placement plane's bin-packing input)
# ---------------------------------------------------------------------------

# the same buffer attrs the serving registry walks when it deletes a
# retired model's device buffers (serving/registry._delete_device_buffers)
# — what unload frees is exactly what residency must price
MODEL_BUFFER_ATTRS = ("params", "states", "updater_state", "opt")


def model_resident_bytes(model) -> int:
    """Device bytes a loaded model keeps RESIDENT: its params /
    batch-norm states / updater / optimizer pytrees, priced as pure
    shape x itemsize arithmetic over the tree leaves — never a device
    read, so it answers tunnel-free (the kv_arena_blocks discipline).
    This is the per-model input to HBM bin-packing
    (serving/placement.py) and the /replicas utilization report."""
    total = 0
    for attr in MODEL_BUFFER_ATTRS:
        tree = getattr(model, attr, None)
        if tree is not None:
            total += _tree_bytes(tree)
    return total
