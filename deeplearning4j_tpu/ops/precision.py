"""Strict-precision conv without the compile hang: bf16x3 decomposition.

The north-star bar (BASELINE.json north_star; utils/equivalence.py) wants
float32-strict math on both backends. On the axon remote-TPU compile
helper, `jax.default_matmul_precision('float32')` makes XLA compile convs
at HIGHEST precision and that compilation WEDGES (reproduced round 2:
LeNet strict conv compile >9 min, never completes; matmul-only models
compile strict in ~80s). Round-2's fallback ran the accel conv leg at
default precision — so the conv north-star was never strict.

This module is the fix (VERDICT round-2 next-step #2, option "precision-
scoped"): split each f32 conv operand into EXACT bf16 high/low parts
(x = hi + lo with hi = bf16(x); both parts round-trip bf16 losslessly)
and take three DEFAULT-precision convs:

    conv(x, w) ~= conv(hi_x, hi_w) + conv(hi_x, lo_w) + conv(lo_x, hi_w)

Each pass multiplies exactly-representable bf16 values on the MXU with
f32 accumulation, so the only dropped term is lo*lo ~ 2^-16 * 2^-16
relative — f32-class accuracy through the FAST conv compile path. This is
the same decomposition XLA's own HIGHEST conv uses; spelling it out as
three DEFAULT-precision HLOs sidesteps whatever the remote helper chokes
on. Applied on BOTH equivalence legs so the curves compare backend
numerics (accumulation order), not decomposition error.
"""

from __future__ import annotations

import contextlib
from functools import partial

from deeplearning4j_tpu.ops import env as envknob

import jax.numpy as jnp
from jax import lax

_STRICT_CONV = 0


@contextlib.contextmanager
def strict_conv_3pass():
    """Scope (trace-time) in which conv layers run the bf16x3 strict
    decomposition instead of one default-precision conv. Mirrors
    ops/pallas_kernels.pallas_disabled's override pattern."""
    global _STRICT_CONV
    _STRICT_CONV += 1
    try:
        yield
    finally:
        _STRICT_CONV -= 1


def strict_conv_active() -> bool:
    return _STRICT_CONV > 0 or (
        envknob.raw("DL4J_TPU_STRICT_CONV") == "3pass")


def _split_bf16(a):
    hi = a.astype(jnp.bfloat16).astype(jnp.float32)
    lo = (a - hi).astype(jnp.bfloat16).astype(jnp.float32)
    return hi, lo


def conv_f32_3pass(x, w, **conv_kwargs):
    """f32-class-accurate conv via three DEFAULT-precision passes (module
    docstring). The explicit precision argument overrides any ambient
    `jax.default_matmul_precision('float32')`, keeping the conv on the
    fast compile path even inside a globally-strict region."""
    conv = partial(lax.conv_general_dilated,
                   precision=lax.Precision.DEFAULT, **conv_kwargs)
    xh, xl = _split_bf16(jnp.asarray(x, jnp.float32))
    wh, wl = _split_bf16(jnp.asarray(w, jnp.float32))
    return conv(xh, wh) + conv(xh, wl) + conv(xl, wh)
