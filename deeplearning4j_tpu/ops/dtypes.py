"""Global dtype / numerics policy.

The reference runs float32 (ND4J default) with op-by-op eager semantics. On
TPU the MXU wants bfloat16 inputs with float32 accumulation; for the
correctness bar ("CPU-equivalent loss curves", BASELINE.md) we need a strict
float32 mode with highest-precision matmuls and deterministic reductions.

Two modes:
  - ``performance``: params float32, compute bfloat16, matmul precision default.
  - ``strict``: everything float32, ``jax.default_matmul_precision('highest')``
    applied by the training loop via :func:`float32_strict`.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class DtypePolicy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32
    # 'default' | 'float32' | 'highest' — passed to jax.default_matmul_precision
    matmul_precision: str = "highest"

    def cast_compute(self, x):
        return jnp.asarray(x, self.compute_dtype)

    def cast_param(self, x):
        return jnp.asarray(x, self.param_dtype)

    def cast_output(self, x):
        return jnp.asarray(x, self.output_dtype)


STRICT = DtypePolicy()
PERFORMANCE = DtypePolicy(compute_dtype=jnp.bfloat16, matmul_precision="default")

_policy: DtypePolicy = STRICT


def get_policy() -> DtypePolicy:
    return _policy


def set_policy(policy: DtypePolicy) -> None:
    global _policy
    _policy = policy


def softmax_dtype(dtype):
    """Accumulation dtype for softmax / log-softmax upcasts: AT LEAST
    float32, never less — and never a DOWNcast.

    The model code's ``astype(float32)`` before attention/loss softmaxes
    guards bf16 (an exp/sum over thousands of keys loses mass below f32),
    but a hard cast also demotes float64, which silently quantizes the
    loss under the x64 gradient-check substrate: a central difference
    smaller than one f32 ULP of the loss reads back as exactly zero
    (observed: BERT MLM numeric grads of 0.0 against analytic 1e-4).
    Promote, don't pin: bf16 -> f32, f32 -> f32, f64 -> f64."""
    return jnp.promote_types(dtype, jnp.float32)


@contextlib.contextmanager
def float32_strict():
    """Context for reference-equivalent numerics (the BASELINE north-star bar)."""
    prev = _policy
    set_policy(STRICT)
    try:
        with jax.default_matmul_precision("highest"):
            yield
    finally:
        set_policy(prev)
