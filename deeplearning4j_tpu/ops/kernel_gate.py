"""Measured-win gate for pallas kernels (the CLAUDE.md rent rule made
mechanical — VERDICT round-2 weak #8 asked for exactly this: default-on
decided by the committed on-chip artifact, not just VMEM fit).

PALLAS_BENCH.json (repo root) is written by the on-chip benches
(benchmarks/pallas_lstm_bench.py, bench.py ring/flash legs). A kernel may
engage BY DEFAULT only when the artifact records it beating its XLA twin;
VMEM-fit checks remain a necessary condition on top. Explicit opt-in
(use_flash=True, DL4J_TPU_PALLAS_FORCE=1) bypasses the win check but never
the fit check.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from deeplearning4j_tpu.ops import env as envknob

_ARTIFACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "..", "..", "PALLAS_BENCH.json")
_lock = threading.Lock()
_cache: Optional[dict] = None


def _load() -> dict:
    global _cache
    with _lock:
        if _cache is None:
            try:
                with open(_ARTIFACT) as f:
                    _cache = json.load(f)
            except (OSError, ValueError):
                _cache = {}
        return _cache


def reload() -> None:
    """Drop the cached artifact (tests; after a bench writes new rows)."""
    global _cache
    with _lock:
        _cache = None


def measured_win(group: str, name: str, *, min_speedup: float = 1.0,
                 default: bool = False) -> bool:
    """True when PALLAS_BENCH.json records `group.name.speedup` >=
    min_speedup on a real chip. `default` is the answer when no row exists
    (fresh clone / chip never reachable): new kernels ship default-OFF
    until the artifact proves them."""
    if envknob.raw("DL4J_TPU_PALLAS_FORCE") == "1":
        return True
    row = _load().get(group, {}).get(name)
    if not isinstance(row, dict) or "speedup" not in row:
        return default
    if row.get("backend") == "cpu" or row.get("interpret"):
        return default  # only real-chip rows count as proof
    return float(row["speedup"]) >= min_speedup


def _merge(mutate) -> None:
    """Atomic read-mutate-replace of the artifact under the module lock."""
    with _lock:
        try:
            with open(_ARTIFACT) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        mutate(data)
        tmp = _ARTIFACT + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, _ARTIFACT)
    reload()


def record_win(group: str, name: str, row: dict) -> None:
    """Merge one bench result into PALLAS_BENCH.json, preserving unrelated
    groups/rows."""
    _merge(lambda data: data.setdefault(group, {}).__setitem__(name, row))


def record_verdict(group: str, text: str) -> None:
    """Record a per-kernel-group verdict under the artifact's ``verdicts``
    dict. Replaces the legacy single top-level ``verdict`` (which
    round-boundary archiving would overwrite with whichever kernel bench
    ran last) — each group keeps its own default-on note."""
    _merge(lambda data: data.setdefault("verdicts", {}).__setitem__(
        group, text))


def merge_top_level(updates: dict) -> None:
    """Merge top-level keys (the legacy round-1/2 schema: backend / cases /
    verdict) into the artifact without touching kernel groups. Kept for
    archived-artifact tooling; live benches write group rows + verdicts."""
    _merge(lambda data: data.update(updates))
