"""Pallas TPU fused SGNS (skip-gram negative sampling) step.

W2V_SCATTER_PREANALYSIS.json quantifies the target: the XLA SGNS step is
67% scatter at 10k vocab and 92% at 253k — the gather -> dot/sigmoid ->
scatter-add chain is memory-bound on the two [V, D] embedding tables,
and chip reality (~5ms per dispatch, BENCH_NOTES.md) argues for one
fused program instead of XLA's gather + einsum + two scatter dispatches.
This kernel IS that one program, the embedding-plane twin of
nlp/word2vec._neg_body (SkipGram.java:214-252 semantics — see that
docstring for the reference provenance):

  phase 1 (all reads at STALE values, exactly XLA's gather-before-
  scatter): per batch element, DMA the context row of syn0 and the K+1
  target rows of syn1neg HBM->VMEM, compute dot, the MAX_EXP-saturated
  gradient coefficient g, and neu1e = g . s1, parking l1/g/neu1e in VMEM;

  phase 2 (read-modify-write scatter): per batch element, DMA each
  destination row in, add its contribution, DMA it back. The grid-free
  sequential loop makes colliding rows accumulate exactly like
  ``.at[].add()``, and the 1/sqrt(k) collision mean-scale
  (word2vec._mean_scale) is precomputed OUTSIDE the kernel — the
  histogram is a cheap [V] scatter; the [V, D] row traffic is what the
  kernel fuses.

Scope & fallback policy (the kernel-rent convention, CLAUDE.md):
  - engages only behind ``DL4J_TPU_PALLAS_SGNS``: '' auto = pallas
    enabled + VMEM fit (sgns_fits) + a real-chip measured win in
    PALLAS_BENCH.json's ``sgns`` group (the armed on-chip W2V profile
    writes it on next tunnel contact); 0 = never; force = on even
    off-TPU (interpret mode);
  - fallback is word2vec._neg_body (the XLA step), selected at trace
    time through the epoch scan's static args;
  - CPU tests run this kernel under interpret=True, including the f64
    equivalence gradcheck (tests/test_pallas_sgns.py, quick tier).

Written per /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops import env as envknob
from deeplearning4j_tpu.ops.pallas_kernels import pallas_enabled

MAX_EXP = 6.0  # must match nlp/word2vec.MAX_EXP (SkipGram.java saturation)

# VMEM scratch: l1 + neu1e caches [B, D], g cache [B, K+1], one staged
# [K+1, D] target block and a [1, D] RMW row — budget leaves headroom
# for the coefficient inputs and Mosaic padding inside ~16MB/core
_VMEM_BUDGET_FLOATS = 2_000_000


def sgns_fits(batch: int, k1: int, dim: int) -> bool:
    """VMEM gate: the per-batch caches must fit the scratch budget."""
    return (2 * batch * dim + 2 * batch * k1 + (k1 + 1) * dim + batch
            <= _VMEM_BUDGET_FLOATS)


def _tpu_backend() -> bool:
    dd = jax.config.jax_default_device
    if dd is not None:
        return getattr(dd, "platform", "") in ("tpu", "axon")
    return jax.default_backend() == "tpu"


def sgns_kernel_enabled(batch: int, k1: int, dim: int) -> bool:
    """Trace-time gate for the fused SGNS kernel: knob 0 = never, force =
    fit only (interpret off-TPU), '' = pallas + fit + the measured-win
    ``sgns`` group row (real-chip, non-interpret — ops/kernel_gate.py)."""
    knob = envknob.raw("DL4J_TPU_PALLAS_SGNS")
    if knob in ("0", "false", "False"):
        return False
    if not sgns_fits(batch, k1, dim):
        return False
    if knob == "force":
        return True
    from deeplearning4j_tpu.ops.kernel_gate import measured_win

    return pallas_enabled() and measured_win("sgns", "fused_step")


def sgns_interpret() -> bool:
    """Interpret mode off-TPU (the Mosaic kernel only compiles on chip)."""
    return not _tpu_backend()


def _sgns_kernel(ctx_ref, tgt_ref, labels_ref, gmul_ref, ts_ref, cs_ref,
                 syn0_in, syn1_in, syn0_out, syn1_out,
                 l1_buf, neu1e_buf, g_buf, s1_blk, row, sem,
                 *, batch: int, k1: int):
    """Two-phase fused step (see module docstring). Scalar-prefetch:
    ctx_ref [B], tgt_ref [B, K+1] (SMEM row indices). VMEM coefficient
    inputs: labels/gmul/ts [B, K+1], cs [B, 1]. syn0/syn1 stay in HBM
    (memory_space ANY, input-output aliased) and move row-by-row through
    explicit DMA — the kernel never materializes a [B, K+1, D] gather."""

    def fetch(src, dst):
        cp = pltpu.make_async_copy(src, dst, sem)
        cp.start()
        cp.wait()

    def phase1(i, _):
        ci = ctx_ref[i]
        fetch(syn0_in.at[pl.ds(ci, 1)], row)
        l1_buf[pl.ds(i, 1), :] = row[...]
        l1 = row[...]                                   # [1, D]

        def gather_tgt(k, _):
            fetch(syn1_in.at[pl.ds(tgt_ref[i, k], 1)],
                  s1_blk.at[pl.ds(k, 1)])
            return 0

        lax.fori_loop(0, k1, gather_tgt, 0)
        s1 = s1_blk[...]                                # [K+1, D]
        dot = lax.dot_general(l1, s1, (((1,), (1,)), ((), ())))  # [1, K+1]
        labels = labels_ref[pl.ds(i, 1), :]
        # saturation semantics (SkipGram.java:234-246), keyed on dot like
        # the XLA twin: dot > MAX_EXP -> labels-1, dot < -MAX_EXP ->
        # labels, else labels - sigmoid(dot)
        base = jnp.where(dot > MAX_EXP, labels - 1.0,
                         jnp.where(dot < -MAX_EXP, labels,
                                   labels - jax.nn.sigmoid(dot)))
        g = base * gmul_ref[pl.ds(i, 1), :]             # [1, K+1]
        g_buf[pl.ds(i, 1), :] = g
        neu1e_buf[pl.ds(i, 1), :] = lax.dot_general(
            g, s1, (((1,), (0,)), ((), ())))            # [1, D]
        return 0

    def phase2(i, _):
        ci = ctx_ref[i]
        fetch(syn0_out.at[pl.ds(ci, 1)], row)
        row[...] = (row[...] + cs_ref[pl.ds(i, 1), :]
                    * neu1e_buf[pl.ds(i, 1), :])
        fetch(row, syn0_out.at[pl.ds(ci, 1)])

        def scatter_tgt(k, _):
            t = tgt_ref[i, k]
            fetch(syn1_out.at[pl.ds(t, 1)], row)
            coef = (g_buf[pl.ds(i, 1), pl.ds(k, 1)]
                    * ts_ref[pl.ds(i, 1), pl.ds(k, 1)])  # [1, 1]
            row[...] = row[...] + coef * l1_buf[pl.ds(i, 1), :]
            fetch(row, syn1_out.at[pl.ds(t, 1)])
            return 0

        lax.fori_loop(0, k1, scatter_tgt, 0)
        return 0

    lax.fori_loop(0, batch, phase1, 0)
    lax.fori_loop(0, batch, phase2, 0)


def sgns_fused_step(syn0, syn1neg, contexts, targets, labels, live, alpha,
                    *, interpret: bool = False):
    """Drop-in fused twin of word2vec._neg_body: syn0/syn1neg [V, D]
    (donated through input-output aliasing), contexts [B] i32, targets
    [B, K+1] i32, labels/live [B, K+1], alpha scalar -> (syn0', syn1neg').

    Math identical to the XLA step up to fp association order in the
    colliding-row accumulation (tests pin f64 agreement at 1e-9)."""
    from deeplearning4j_tpu.nlp.word2vec import _mean_scale

    b, k1 = targets.shape
    v, d = syn0.shape
    dt = syn0.dtype
    live = live.astype(dt)
    t_scale = _mean_scale(syn1neg.shape[0], targets, live)
    ctx_live = (live.sum(axis=1) > 0).astype(dt)
    ctx_scale = _mean_scale(v, contexts, ctx_live)
    gmul = (alpha * live).astype(dt)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((b, k1), lambda i, ctx, tgt: (0, 0)),
            pl.BlockSpec((b, k1), lambda i, ctx, tgt: (0, 0)),
            pl.BlockSpec((b, k1), lambda i, ctx, tgt: (0, 0)),
            pl.BlockSpec((b, 1), lambda i, ctx, tgt: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, d), dt),          # l1 cache
            pltpu.VMEM((b, d), dt),          # neu1e cache
            pltpu.VMEM((b, k1), dt),         # g cache
            pltpu.VMEM((k1, d), dt),         # staged target rows
            pltpu.VMEM((1, d), dt),          # DMA / RMW row
            pltpu.SemaphoreType.DMA,
        ],
    )
    # input indices for aliasing count the scalar-prefetch operands:
    # (ctx, tgt, labels, gmul, ts, cs, syn0, syn1) -> syn0 is 6, syn1 is 7
    out = pl.pallas_call(
        functools.partial(_sgns_kernel, batch=b, k1=k1),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((v, d), dt),
                   jax.ShapeDtypeStruct((syn1neg.shape[0], d), dt)],
        input_output_aliases={6: 0, 7: 1},
        interpret=interpret,
    )(contexts.astype(jnp.int32), targets.astype(jnp.int32),
      labels.astype(dt), gmul, t_scale.astype(dt),
      ctx_scale.astype(dt)[:, None], syn0, syn1neg)
    return out[0], out[1]
