"""Pallas TPU paged-decode attention kernel.

The paged serving tick (serving/paged.py paged_decode_step) gathers every
lane's KV blocks into a dense contiguous copy per generated token —
``ck[tables].reshape(S, T, H, hd)`` materializes S * max_len * H * hd
floats of HBM traffic each tick even though a lane typically occupies a
handful of blocks. This kernel is the vLLM PagedAttention move (Kwon et
al., 2023) fused with the flash-attention online softmax (Dao et al.,
2022; same recipe as ops/pallas_attention.py): the grid walks each lane's
BLOCK TABLE via scalar prefetch, Mosaic streams exactly the referenced
arena blocks HBM->VMEM (the table entry IS the block index map), and a
running (max, denominator, accumulator) triple in VMEM scratch folds each
block into the softmax without ever materializing the gathered window.

Mask contract (byte-for-byte the gather path's): a token at global
position t = j * block_tokens + offset is visible iff ``t <= pos[lane]``
— the same ``arange <= pos`` predicate that keeps the trash block
(physical block 0, where inactive lanes and unallocated table entries
point) invisible: trash content can enter a score only at masked
positions, where the online softmax assigns it exp(-inf) = 0 weight
exactly.

Scope & fallback policy (the kernel-rent convention, CLAUDE.md):
  - engages only behind ``DL4J_TPU_PALLAS_PAGED``: '' auto = pallas
    enabled + VMEM/shape fit (paged_fits) + a real-chip measured win in
    PALLAS_BENCH.json's ``paged`` group (ops/kernel_gate.py); 0 = never;
    force = on even off-TPU (interpret mode — CPU equivalence tests);
  - fallback is serving/paged.py's existing gather path, selected at
    trace time (the tick cache keys on the resolved path);
  - CPU tests run this kernel under interpret=True
    (tests/test_pallas_paged.py, quick tier).

Written per /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops import env as envknob
from deeplearning4j_tpu.ops.pallas_kernels import pallas_enabled

# one k + one v arena block resident per grid step (double-buffered by
# Mosaic), plus q/o lane blocks and the running-stat scratch — keep well
# under the ~16MB/core VMEM like the other kernels' budgets
_VMEM_BUDGET_FLOATS = 1_000_000


def paged_fits(block_tokens: int, n_heads: int, head_dim: int) -> bool:
    """VMEM/alignment gate: the streamed (bt, H, hd) k/v blocks must fit
    the budget and the trailing (H, hd) dims must be Mosaic-tileable
    ((8, 128) lanes) — serving shapes like H=16, hd=128 qualify; the tiny
    CPU-test shapes run in interpret mode where alignment is free."""
    return (2 * block_tokens * n_heads * head_dim <= _VMEM_BUDGET_FLOATS
            and head_dim % 128 == 0 and n_heads % 8 == 0)


def _tpu_backend() -> bool:
    # honor jax.default_device(...) overrides, same as pallas_enabled
    dd = jax.config.jax_default_device
    if dd is not None:
        return getattr(dd, "platform", "") in ("tpu", "axon")
    return jax.default_backend() == "tpu"


def paged_kernel_enabled(n_heads: int, head_dim: int,
                         block_tokens: int) -> bool:
    """Trace-time gate for the paged-decode attention kernel. force
    bypasses the measured-win table AND the alignment half of the fit
    check (interpret mode has no Mosaic tiling), never the VMEM budget."""
    knob = envknob.raw("DL4J_TPU_PALLAS_PAGED")
    if knob in ("0", "false", "False"):
        return False
    if knob == "force":
        return (2 * block_tokens * n_heads * head_dim
                <= _VMEM_BUDGET_FLOATS)
    from deeplearning4j_tpu.ops.kernel_gate import measured_win

    return (pallas_enabled()
            and paged_fits(block_tokens, n_heads, head_dim)
            and measured_win("paged", "decode_attention"))


def paged_interpret() -> bool:
    """Interpret mode off-TPU (compiling the Mosaic kernel on CPU fails)."""
    return not _tpu_backend()


def _paged_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, block_tokens: int,
                  scale: float):
    """Grid (lane s, table slot j): fold arena block ``tables[s, j]`` into
    lane s's online softmax. q_ref/o_ref: [1, H, hd]; k_ref/v_ref:
    [1, bt, H, hd] (the block the index map fetched); m/l scratch:
    [H, 128] f32 (running max / denominator broadcast across lanes for
    Mosaic alignment); acc scratch: [H, hd] f32."""
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    q = q_ref[0].astype(jnp.float32) * scale          # [H, hd]
    k = k_ref[0].astype(jnp.float32)                  # [bt, H, hd]
    v = v_ref[0].astype(jnp.float32)

    # scores[h, t] = q[h] . k[t, h]; multiply-reduce keeps the layout
    # VPU-friendly (no per-head dot_general on a [bt, H, hd] operand)
    sc = jnp.sum(q[None, :, :] * k, axis=-1).T        # [H, bt]
    t_glob = j * block_tokens + lax.broadcasted_iota(
        jnp.int32, (1, block_tokens), 1)              # [1, bt]
    sc = jnp.where(t_glob <= pos_ref[s], sc, -jnp.inf)

    m_prev = m_scr[...]                               # [H, 128]
    blk_max = jnp.max(sc, axis=-1, keepdims=True)     # [H, 1]
    m_new = jnp.maximum(m_prev, blk_max)
    # a block past the lane's write position is fully masked: keep the
    # exp argument finite (exp(-inf - -inf) would be nan)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(sc - m_safe[:, :1])
    p = jnp.where(jnp.isfinite(sc), p, 0.0)           # [H, bt]
    corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
    m_scr[...] = m_new
    l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
    # acc[h] += sum_t p[h, t] * v[t, h]: broadcast-multiply-reduce again
    acc_scr[...] = (acc_scr[...] * corr[:, :1]
                    + jnp.sum(p.T[:, :, None] * v, axis=0))

    @pl.when(j == pl.num_programs(1) - 1)
    def _emit():
        l_safe = jnp.maximum(l_scr[...][:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)


def paged_attention(q, ck, cv, tables, pos, *, interpret: bool = False):
    """Block-table decode attention: q [S, H, hd] (any float dtype),
    ck/cv [n_blocks+1, bt, H, hd] arena (block 0 = trash), tables [S, m]
    int32, pos [S] int32 -> att [S, H, hd] float32.

    Numerically the gather path's f32 masked softmax-attention with the
    gather replaced by table-indexed block streaming; the causal
    ``t <= pos`` mask is applied per block at global token positions."""
    s, h, hd = q.shape
    bt = ck.shape[1]
    m = tables.shape[1]
    scale = 1.0 / float(np.sqrt(hd))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, m),
        in_specs=[
            pl.BlockSpec((1, h, hd), lambda s, j, tables, pos: (s, 0, 0)),
            pl.BlockSpec((1, bt, h, hd),
                         lambda s, j, tables, pos: (tables[s, j], 0, 0, 0)),
            pl.BlockSpec((1, bt, h, hd),
                         lambda s, j, tables, pos: (tables[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, hd),
                               lambda s, j, tables, pos: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, block_tokens=bt, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, h, hd), jnp.float32),
        interpret=interpret,
    )(tables.astype(jnp.int32), pos.astype(jnp.int32), q, ck, cv)
