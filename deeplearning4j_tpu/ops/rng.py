"""Deterministic RNG streams.

The reference seeds a single java RNG per configuration
(NeuralNetConfiguration.Builder#seed). JAX uses splittable counter-based keys;
we expose a small helper that derives named, per-layer, per-step streams so
that weight init, dropout, and samplers (RBM Gibbs sampling) are reproducible
and independent — designed early per SURVEY.md section 7 "Hard parts".
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Stable fold-in tags for the different stream kinds.
_KIND_TAGS = {
    "init": 0x1,
    "dropout": 0x2,
    "sample": 0x3,
    "data": 0x4,
    "noise": 0x5,
}


def key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def layer_key(base: jax.Array, layer_index: int, kind: str = "init") -> jax.Array:
    """Derive the stream for (layer, kind). Stable across runs and jit."""
    k = jax.random.fold_in(base, _KIND_TAGS[kind])
    return jax.random.fold_in(k, layer_index)


def step_key(base: jax.Array, step: jax.Array | int) -> jax.Array:
    """Per-iteration stream (dropout etc.); `step` may be a traced scalar."""
    return jax.random.fold_in(base, jnp.asarray(step, jnp.uint32))
