"""Server-renderable chart-component DSL.

Capability mirror of deeplearning4j-ui-components (SURVEY.md section 2.5):
Chart{Line,Histogram,Scatter,StackedArea,Timeline,HorizontalBar},
ComponentTable, ComponentText, chart styles, JSON round-trip, and the
standalone static-page export (reference …/ui/standalone/, staticpage.ftl +
dl4j-ui.js d3 renderer).

Here each component renders itself to inline SVG/HTML server-side (the
d3-renderer role), so exported pages are fully self-contained. Colors use a
CVD-validated categorical palette in fixed slot order (series identity never
depends on color alone: every chart carries a legend and value tooltips).
"""

from __future__ import annotations

import html
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Validated categorical palette (fixed slot order — assign, never cycle).
SERIES_COLORS = [
    "#2a78d6", "#eb6834", "#1baf7a", "#eda100",
    "#e87ba4", "#008300", "#4a3aa7", "#e34948",
]
SURFACE = "#fcfcfb"
TEXT_PRIMARY = "#0b0b0b"
TEXT_SECONDARY = "#52514e"
GRID = "#e4e3e0"

_REGISTRY: Dict[str, type] = {}


def _register(cls):
    _REGISTRY[cls.__name__] = cls
    return cls


@dataclass
class StyleChart:
    """Reference style/StyleChart.java: width/height/axis strokes."""

    width: int = 640
    height: int = 320
    margin_top: int = 28
    margin_bottom: int = 34
    margin_left: int = 52
    margin_right: int = 16
    stroke_width: float = 2.0

    def to_dict(self):
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


class Component:
    """Reference api/Component.java: typed, JSON-serializable."""

    title: str = ""

    def to_dict(self) -> Dict[str, Any]:
        raise NotImplementedError

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def render(self) -> str:
        """Server-side HTML/SVG."""
        raise NotImplementedError


def component_from_dict(d: Dict[str, Any]) -> Component:
    cls = _REGISTRY[d["component_type"]]
    return cls.from_dict(d)


def _plot_frame(style: StyleChart, title: str, x_min, x_max, y_min, y_max,
                body: str, legend: Sequence[str] = ()) -> str:
    """Shared SVG chrome: title, recessive grid, axis labels, legend."""
    w, h = style.width, style.height
    ml, mr = style.margin_left, style.margin_right
    mt, mb = style.margin_top, style.margin_bottom
    pw, ph = w - ml - mr, h - mt - mb
    grid_lines, labels = [], []
    for i in range(5):
        fy = mt + ph * i / 4
        val = y_max - (y_max - y_min) * i / 4
        grid_lines.append(
            f'<line x1="{ml}" y1="{fy:.1f}" x2="{w - mr}" y2="{fy:.1f}" '
            f'stroke="{GRID}" stroke-width="1"/>'
        )
        labels.append(
            f'<text x="{ml - 6}" y="{fy + 4:.1f}" text-anchor="end" '
            f'font-size="11" fill="{TEXT_SECONDARY}">{val:.3g}</text>'
        )
    for i in range(5):
        fx = ml + pw * i / 4
        val = x_min + (x_max - x_min) * i / 4
        labels.append(
            f'<text x="{fx:.1f}" y="{h - mb + 16}" text-anchor="middle" '
            f'font-size="11" fill="{TEXT_SECONDARY}">{val:.3g}</text>'
        )
    legend_items = []
    if len(legend) >= 2:  # single series: title names it, no legend box
        for i, name in enumerate(legend):
            lx = ml + i * 110
            legend_items.append(
                f'<rect x="{lx}" y="{h - 12}" width="10" height="10" rx="2" '
                f'fill="{SERIES_COLORS[i % len(SERIES_COLORS)]}"/>'
                f'<text x="{lx + 14}" y="{h - 3}" font-size="11" '
                f'fill="{TEXT_PRIMARY}">{html.escape(str(name))}</text>'
            )
    extra_h = 18 if legend_items else 0
    return (
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" '
        f'height="{h + extra_h}" style="background:{SURFACE}">'
        f'<text x="{ml}" y="16" font-size="13" font-weight="600" '
        f'fill="{TEXT_PRIMARY}">{html.escape(title)}</text>'
        + "".join(grid_lines) + "".join(labels) + body + "".join(legend_items)
        + "</svg>"
    )


def _scale(v, lo, hi, out_lo, out_hi):
    if hi == lo:
        return (out_lo + out_hi) / 2.0
    return out_lo + (v - lo) * (out_hi - out_lo) / (hi - lo)


@_register
@dataclass
class ChartLine(Component):
    """Reference chart/ChartLine.java: named (x, y) series."""

    title: str = ""
    series: List[Tuple[str, List[float], List[float]]] = field(default_factory=list)
    style: StyleChart = field(default_factory=StyleChart)

    def add_series(self, name: str, x: Sequence[float], y: Sequence[float]):
        self.series.append((name, [float(v) for v in x], [float(v) for v in y]))
        return self

    def to_dict(self):
        return {
            "component_type": type(self).__name__,
            "title": self.title,
            "series": [[n, x, y] for n, x, y in self.series],
            "style": self.style.to_dict(),
        }

    @classmethod
    def from_dict(cls, d):
        return cls(
            title=d["title"],
            series=[(n, x, y) for n, x, y in d["series"]],
            style=StyleChart.from_dict(d["style"]),
        )

    def _bounds(self):
        xs = [v for _, x, _ in self.series for v in x] or [0.0, 1.0]
        ys = [v for _, _, y in self.series for v in y] or [0.0, 1.0]
        return min(xs), max(xs), min(ys), max(ys)

    def render(self) -> str:
        st = self.style
        x0, x1, y0, y1 = self._bounds()
        ml, mt = st.margin_left, st.margin_top
        pw = st.width - ml - st.margin_right
        ph = st.height - mt - st.margin_bottom
        body = []
        for i, (name, xs, ys) in enumerate(self.series):
            pts = " ".join(
                f"{_scale(x, x0, x1, ml, ml + pw):.1f},"
                f"{_scale(y, y0, y1, mt + ph, mt):.1f}"
                for x, y in zip(xs, ys)
            )
            color = SERIES_COLORS[i % len(SERIES_COLORS)]
            body.append(
                f'<polyline points="{pts}" fill="none" stroke="{color}" '
                f'stroke-width="{st.stroke_width}">'
                f"<title>{html.escape(str(name))}</title></polyline>"
            )
        return _plot_frame(st, self.title, x0, x1, y0, y1, "".join(body),
                           [n for n, _, _ in self.series])


@_register
@dataclass
class ChartScatter(ChartLine):
    """Reference chart/ChartScatter.java."""

    def render(self) -> str:
        st = self.style
        x0, x1, y0, y1 = self._bounds()
        ml, mt = st.margin_left, st.margin_top
        pw = st.width - ml - st.margin_right
        ph = st.height - mt - st.margin_bottom
        body = []
        for i, (name, xs, ys) in enumerate(self.series):
            color = SERIES_COLORS[i % len(SERIES_COLORS)]
            for x, y in zip(xs, ys):
                cx = _scale(x, x0, x1, ml, ml + pw)
                cy = _scale(y, y0, y1, mt + ph, mt)
                body.append(
                    f'<circle cx="{cx:.1f}" cy="{cy:.1f}" r="4" fill="{color}" '
                    f'stroke="{SURFACE}" stroke-width="2">'
                    f"<title>{html.escape(str(name))}: ({x:.4g}, {y:.4g})"
                    f"</title></circle>"
                )
        return _plot_frame(st, self.title, x0, x1, y0, y1, "".join(body),
                           [n for n, _, _ in self.series])


@_register
@dataclass
class ChartHistogram(Component):
    """Reference chart/ChartHistogram.java: (lower, upper, count) bins."""

    title: str = ""
    lower: List[float] = field(default_factory=list)
    upper: List[float] = field(default_factory=list)
    counts: List[float] = field(default_factory=list)
    style: StyleChart = field(default_factory=StyleChart)

    def add_bin(self, lower: float, upper: float, count: float):
        self.lower.append(float(lower))
        self.upper.append(float(upper))
        self.counts.append(float(count))
        return self

    def to_dict(self):
        return {
            "component_type": type(self).__name__,
            "title": self.title,
            "lower": self.lower,
            "upper": self.upper,
            "counts": self.counts,
            "style": self.style.to_dict(),
        }

    @classmethod
    def from_dict(cls, d):
        return cls(title=d["title"], lower=d["lower"], upper=d["upper"],
                   counts=d["counts"], style=StyleChart.from_dict(d["style"]))

    def render(self) -> str:
        st = self.style
        if not self.counts:
            return _plot_frame(st, self.title, 0, 1, 0, 1, "")
        x0, x1 = min(self.lower), max(self.upper)
        y0, y1 = 0.0, max(self.counts)
        ml, mt = st.margin_left, st.margin_top
        pw = st.width - ml - st.margin_right
        ph = st.height - mt - st.margin_bottom
        body = []
        for lo, hi, c in zip(self.lower, self.upper, self.counts):
            bx0 = _scale(lo, x0, x1, ml, ml + pw)
            bx1 = _scale(hi, x0, x1, ml, ml + pw)
            by = _scale(c, y0, y1, mt + ph, mt)
            # 2px surface gap between adjacent fills; 4px rounded data end
            body.append(
                f'<rect x="{bx0 + 1:.1f}" y="{by:.1f}" '
                f'width="{max(0.5, bx1 - bx0 - 2):.1f}" '
                f'height="{max(0.0, mt + ph - by):.1f}" rx="4" '
                f'fill="{SERIES_COLORS[0]}">'
                f"<title>[{lo:.4g}, {hi:.4g}): {c:.6g}</title></rect>"
            )
        return _plot_frame(st, self.title, x0, x1, y0, y1, "".join(body))


@_register
@dataclass
class ChartStackedArea(ChartLine):
    """Reference chart/ChartStackedArea.java: series stacked bottom-up."""

    def render(self) -> str:
        st = self.style
        if not self.series:
            return _plot_frame(st, self.title, 0, 1, 0, 1, "")
        xs = self.series[0][1]
        acc = [0.0] * len(xs)
        stacks = []
        for name, _, ys in self.series:
            new_acc = [a + y for a, y in zip(acc, ys)]
            stacks.append((name, list(acc), list(new_acc)))
            acc = new_acc
        x0, x1 = min(xs), max(xs)
        y0, y1 = 0.0, max(acc) if acc else 1.0
        ml, mt = st.margin_left, st.margin_top
        pw = st.width - ml - st.margin_right
        ph = st.height - mt - st.margin_bottom
        body = []
        for i, (name, base, top) in enumerate(stacks):
            fwd = [
                f"{_scale(x, x0, x1, ml, ml + pw):.1f},"
                f"{_scale(t, y0, y1, mt + ph, mt):.1f}"
                for x, t in zip(xs, top)
            ]
            back = [
                f"{_scale(x, x0, x1, ml, ml + pw):.1f},"
                f"{_scale(b, y0, y1, mt + ph, mt):.1f}"
                for x, b in reversed(list(zip(xs, base)))
            ]
            color = SERIES_COLORS[i % len(SERIES_COLORS)]
            body.append(
                f'<polygon points="{" ".join(fwd + back)}" fill="{color}" '
                f'fill-opacity="0.85" stroke="{SURFACE}" stroke-width="2">'
                f"<title>{html.escape(str(name))}</title></polygon>"
            )
        return _plot_frame(st, self.title, x0, x1, y0, y1, "".join(body),
                           [n for n, _, _ in self.series])


@_register
@dataclass
class ChartHorizontalBar(Component):
    """Reference chart/ChartHorizontalBar.java: labeled values."""

    title: str = ""
    labels: List[str] = field(default_factory=list)
    values: List[float] = field(default_factory=list)
    style: StyleChart = field(default_factory=StyleChart)

    def add_bar(self, label: str, value: float):
        self.labels.append(label)
        self.values.append(float(value))
        return self

    def to_dict(self):
        return {
            "component_type": type(self).__name__,
            "title": self.title,
            "labels": self.labels,
            "values": self.values,
            "style": self.style.to_dict(),
        }

    @classmethod
    def from_dict(cls, d):
        return cls(title=d["title"], labels=d["labels"], values=d["values"],
                   style=StyleChart.from_dict(d["style"]))

    def render(self) -> str:
        st = self.style
        if not self.values:
            return _plot_frame(st, self.title, 0, 1, 0, 1, "")
        v0, v1 = min(0.0, min(self.values)), max(self.values)
        ml, mt = st.margin_left + 40, st.margin_top
        pw = st.width - ml - st.margin_right
        n = len(self.values)
        bh = max(6.0, (st.height - mt - st.margin_bottom) / max(1, n) - 2)
        body = []
        for i, (lab, v) in enumerate(zip(self.labels, self.values)):
            y = mt + i * (bh + 2)
            x_end = _scale(v, v0, v1, ml, ml + pw)
            body.append(
                f'<rect x="{ml}" y="{y:.1f}" width="{max(0.5, x_end - ml):.1f}" '
                f'height="{bh:.1f}" rx="4" fill="{SERIES_COLORS[0]}">'
                f"<title>{html.escape(str(lab))}: {v:.6g}</title></rect>"
                f'<text x="{ml - 6}" y="{y + bh / 2 + 4:.1f}" text-anchor="end" '
                f'font-size="11" fill="{TEXT_PRIMARY}">'
                f"{html.escape(str(lab))}</text>"
            )
        return (
            f'<svg xmlns="http://www.w3.org/2000/svg" width="{st.width}" '
            f'height="{st.height}" style="background:{SURFACE}">'
            f'<text x="{st.margin_left}" y="16" font-size="13" '
            f'font-weight="600" fill="{TEXT_PRIMARY}">'
            f"{html.escape(self.title)}</text>" + "".join(body) + "</svg>"
        )


@_register
@dataclass
class ChartTimeline(Component):
    """Reference chart/ChartTimeline.java: lanes of [start, end, label]."""

    title: str = ""
    lanes: List[Tuple[str, List[Tuple[float, float, str]]]] = field(
        default_factory=list
    )
    style: StyleChart = field(default_factory=StyleChart)

    def add_lane(self, name: str, entries: Sequence[Tuple[float, float, str]]):
        self.lanes.append(
            (name, [(float(a), float(b), str(l)) for a, b, l in entries])
        )
        return self

    def to_dict(self):
        return {
            "component_type": type(self).__name__,
            "title": self.title,
            "lanes": [[n, [list(e) for e in es]] for n, es in self.lanes],
            "style": self.style.to_dict(),
        }

    @classmethod
    def from_dict(cls, d):
        return cls(
            title=d["title"],
            lanes=[(n, [tuple(e) for e in es]) for n, es in d["lanes"]],
            style=StyleChart.from_dict(d["style"]),
        )

    def render(self) -> str:
        st = self.style
        alls = [e for _, es in self.lanes for e in es]
        if not alls:
            return _plot_frame(st, self.title, 0, 1, 0, 1, "")
        t0 = min(e[0] for e in alls)
        t1 = max(e[1] for e in alls)
        ml, mt = st.margin_left + 30, st.margin_top
        pw = st.width - ml - st.margin_right
        body = []
        lane_h = 24
        for li, (name, entries) in enumerate(self.lanes):
            y = mt + li * (lane_h + 4)
            body.append(
                f'<text x="{ml - 6}" y="{y + 16}" text-anchor="end" '
                f'font-size="11" fill="{TEXT_PRIMARY}">'
                f"{html.escape(str(name))}</text>"
            )
            for si, (a, b, lab) in enumerate(entries):
                xa = _scale(a, t0, t1, ml, ml + pw)
                xb = _scale(b, t0, t1, ml, ml + pw)
                color = SERIES_COLORS[si % len(SERIES_COLORS)]
                body.append(
                    f'<rect x="{xa:.1f}" y="{y}" '
                    f'width="{max(1.0, xb - xa):.1f}" height="{lane_h}" rx="4" '
                    f'fill="{color}" stroke="{SURFACE}" stroke-width="2">'
                    f"<title>{html.escape(lab)}: {a:.6g}-{b:.6g}</title></rect>"
                )
        h = mt + len(self.lanes) * (lane_h + 4) + 8
        return (
            f'<svg xmlns="http://www.w3.org/2000/svg" width="{st.width}" '
            f'height="{h}" style="background:{SURFACE}">'
            f'<text x="{st.margin_left}" y="16" font-size="13" '
            f'font-weight="600" fill="{TEXT_PRIMARY}">'
            f"{html.escape(self.title)}</text>" + "".join(body) + "</svg>"
        )


@_register
@dataclass
class ComponentTable(Component):
    """Reference table/ComponentTable.java."""

    title: str = ""
    header: List[str] = field(default_factory=list)
    rows: List[List[str]] = field(default_factory=list)

    def to_dict(self):
        return {
            "component_type": type(self).__name__,
            "title": self.title,
            "header": self.header,
            "rows": self.rows,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(title=d["title"], header=d["header"], rows=d["rows"])

    def render(self) -> str:
        head = "".join(f"<th>{html.escape(str(h))}</th>" for h in self.header)
        body = "".join(
            "<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in row)
            + "</tr>"
            for row in self.rows
        )
        return (
            f'<div><h3 style="color:{TEXT_PRIMARY};font-size:13px">'
            f"{html.escape(self.title)}</h3>"
            f'<table style="border-collapse:collapse;font-size:12px;'
            f'color:{TEXT_PRIMARY}"><tr>{head}</tr>{body}</table></div>'
        )


@_register
@dataclass
class ComponentText(Component):
    """Reference text/ComponentText.java."""

    text: str = ""
    title: str = ""

    def to_dict(self):
        return {
            "component_type": type(self).__name__,
            "title": self.title,
            "text": self.text,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(title=d["title"], text=d["text"])

    def render(self) -> str:
        return (
            f'<p style="color:{TEXT_PRIMARY};font-size:13px">'
            f"{html.escape(self.text)}</p>"
        )


@_register
@dataclass
class ComponentImage(Component):
    """Inline raster image (the PlotFilters/ImageRender display role,
    reference plot/PlotFilters.java + ImageRender.java, rendered into the
    component DSL instead of an AWT window): carries base64 PNG bytes so
    exported pages stay fully self-contained."""

    png_base64: str = ""
    title: str = ""
    scale: int = 1  # integer upscale for small filter tiles (CSS pixels)
    width: int = 0   # source pixel dims (for the <img> size attributes)
    height: int = 0

    @classmethod
    def from_array(cls, image, title: str = "", scale: int = 1):
        """Build from a [H, W] / [H, W, 3/4] array ([0,1] floats or pixel
        values) via plot.filters.image_png_bytes."""
        import base64

        import numpy as np

        from deeplearning4j_tpu.plot.filters import image_png_bytes

        a = np.asarray(image)
        return cls(png_base64=base64.b64encode(
            image_png_bytes(a)).decode("ascii"),
            title=title, scale=scale,
            width=int(a.shape[1]), height=int(a.shape[0]))

    def to_dict(self):
        return {
            "component_type": type(self).__name__,
            "title": self.title,
            "png_base64": self.png_base64,
            "scale": self.scale,
            "width": self.width,
            "height": self.height,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(title=d["title"], png_base64=d["png_base64"],
                   scale=d.get("scale", 1), width=d.get("width", 0),
                   height=d.get("height", 0))

    def render(self) -> str:
        w = self.width * self.scale or ""
        h = self.height * self.scale or ""
        dims = (f' width="{w}" height="{h}"' if w and h else "")
        cap = (f'<div style="color:{TEXT_SECONDARY};font-size:12px">'
               f"{html.escape(self.title)}</div>" if self.title else "")
        return (f'{cap}<img src="data:image/png;base64,{self.png_base64}"'
                f'{dims} style="image-rendering:pixelated;'
                f'border:1px solid {GRID}" '
                f'alt="{html.escape(self.title or "image")}">')


def render_page(components: Sequence[Component], title: str = "DL4J-TPU") -> str:
    """Standalone static page (reference StaticPageUtil/staticpage.ftl) —
    fully self-contained, no external assets."""
    parts = "".join(
        f'<div class="comp">{c.render()}</div>' for c in components
    )
    return f"""<!doctype html><html><head><meta charset="utf-8">
<title>{html.escape(title)}</title><style>
body{{font-family:system-ui,sans-serif;background:{SURFACE};margin:1.5em}}
.comp{{display:inline-block;margin:10px;vertical-align:top;
border:1px solid {GRID};border-radius:6px;padding:8px}}
td,th{{border:1px solid {GRID};padding:3px 9px}}
</style></head><body><h2 style="color:{TEXT_PRIMARY}">{html.escape(title)}</h2>
{parts}</body></html>"""
