"""UI / monitoring — capability surface of deeplearning4j-ui-parent
(SURVEY.md section 2.5): the chart-component DSL with JSON serde and
standalone static-page export (deeplearning4j-ui-components), the training
UI server (UiServer + HistoryStorage), and the training listeners that
publish to it (HistogramIterationListener, FlowIterationListener,
ConvolutionalIterationListener).

TPU-era redesign: the reference's Dropwizard/Jetty + React + d3 stack
becomes a stdlib http.server plus SERVER-SIDE SVG rendering — zero JS/CDN
dependencies (this environment has no egress), same component model."""

from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartHorizontalBar,
    ChartLine,
    ChartScatter,
    ChartStackedArea,
    ChartTimeline,
    ComponentTable,
    ComponentImage,
    ComponentText,
    StyleChart,
    component_from_dict,
    render_page,
)
from deeplearning4j_tpu.ui.listeners import (
    ConvolutionalIterationListener,
    FlowIterationListener,
    HistogramIterationListener,
)
from deeplearning4j_tpu.ui.server import HistoryStorage, UiServer

__all__ = [
    "ChartHistogram",
    "ChartHorizontalBar",
    "ChartLine",
    "ChartScatter",
    "ChartStackedArea",
    "ChartTimeline",
    "ComponentTable",
    "ComponentImage",
    "ComponentText",
    "StyleChart",
    "component_from_dict",
    "render_page",
    "HistogramIterationListener",
    "FlowIterationListener",
    "ConvolutionalIterationListener",
    "HistoryStorage",
    "UiServer",
]
