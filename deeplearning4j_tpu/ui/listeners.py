"""UI-publishing iteration listeners.

Capability mirror of the reference training listeners (SURVEY.md 2.5):
  - HistogramIterationListener (…/ui/weights/HistogramIterationListener.java:33
    — binned param/gradient/score JSON posted to the UI every N iterations;
    wire bean CompactModelAndGradient);
  - FlowIterationListener (…/ui/flow/FlowIterationListener.java:46 — live
    topology + per-layer info beans LayerInfo/ModelInfo);
  - ConvolutionalIterationListener (…/ui/weights/
    ConvolutionalIterationListener.java:38 — conv activation grids).

Each listener can post to a UiServer (HTTP, the reference behavior) or just
accumulate locally (storage=...) for headless use / static export.
"""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.ui.server import HistoryStorage


def _flatten_params(model) -> Dict[str, np.ndarray]:
    out = {}
    params = model.params
    if isinstance(params, dict):  # ComputationGraph: name -> {pname: arr}
        for lname, ps in params.items():
            for pname, arr in (ps or {}).items():
                out[f"{lname}_{pname}"] = np.asarray(arr)
    else:  # MultiLayerNetwork: list of {pname: arr}
        for i, ps in enumerate(params or []):
            for pname, arr in (ps or {}).items():
                out[f"{i}_{pname}"] = np.asarray(arr)
    return out


class _PostingListener(IterationListener):
    def __init__(self, server_url: Optional[str] = None,
                 storage: Optional[HistoryStorage] = None):
        self.server_url = server_url
        self.storage = storage or (None if server_url else HistoryStorage())

    def _publish(self, payload: Dict[str, Any]) -> None:
        if self.server_url:
            try:
                req = urllib.request.Request(
                    self.server_url.rstrip("/") + "/train/update",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=5):
                    pass
            except (urllib.error.URLError, OSError) as e:
                # monitoring must never abort training — log and continue
                logger.warning("UI post failed (%s); continuing", e)
        if self.storage is not None:
            self.storage.put(payload["type"], payload)


class HistogramIterationListener(_PostingListener):
    """Bin every param tensor + score each N iterations."""

    def __init__(self, frequency: int = 10, num_bins: int = 20, **kw):
        super().__init__(**kw)
        self.frequency = max(1, frequency)
        self.num_bins = num_bins

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if iteration % self.frequency != 0:
            return
        params = {}
        for name, arr in _flatten_params(model).items():
            flat = arr.reshape(-1)
            counts, edges = np.histogram(flat, bins=self.num_bins)
            params[name] = {
                "lower": edges[:-1].tolist(),
                "upper": edges[1:].tolist(),
                "counts": counts.tolist(),
                "mean": float(flat.mean()),
                "std": float(flat.std()),
            }
        self._publish({
            "type": "histogram",
            "iteration": iteration,
            "score": float(score),
            "params": params,
        })
        self._publish({
            "type": "score", "iteration": iteration, "score": float(score),
        })


class FlowIterationListener(_PostingListener):
    """Topology + per-layer beans (LayerInfo/ModelInfo)."""

    def __init__(self, frequency: int = 10, **kw):
        super().__init__(**kw)
        self.frequency = max(1, frequency)

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if iteration % self.frequency != 0:
            return
        layers: List[Dict[str, Any]] = []
        conf = model.conf
        if hasattr(conf, "vertices"):  # graph
            for name in conf.topological_order():
                v = conf.vertices[name]
                ps = model.params.get(name, {}) if model.params else {}
                layers.append({
                    "name": name,
                    "layer_type": type(v).__name__,
                    "n_params": int(sum(np.asarray(a).size for a in ps.values())),
                    "inputs": list(conf.vertex_inputs.get(name, [])),
                })
        else:
            for i, lc in enumerate(conf.layers):
                ps = model.params[i] if model.params else {}
                layers.append({
                    "name": str(i),
                    "layer_type": type(lc).__name__,
                    "n_params": int(sum(np.asarray(a).size for a in ps.values())),
                    "inputs": [str(i - 1)] if i else [],
                })
        self._publish({
            "type": "flow",
            "iteration": iteration,
            "score": float(score),
            "layers": layers,
        })


class ConvolutionalIterationListener(_PostingListener):
    """Conv activation grids: stores per-channel [H, W] activation maps of
    the first example, normalized to [0, 1] (the reference renders these as
    image tiles; export via ui.components / render_page)."""

    def __init__(self, frequency: int = 10, max_channels: int = 16, **kw):
        super().__init__(**kw)
        self.frequency = max(1, frequency)
        self.max_channels = max_channels
        self._last_input = None

    def set_input(self, x) -> None:
        """Give the listener the minibatch to trace (the reference pulls
        activations from the layer workspace; functionally we re-run)."""
        self._last_input = np.asarray(x)

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if iteration % self.frequency != 0 or self._last_input is None:
            return
        acts = model.feed_forward(self._last_input[:1], train=False)
        grids: Dict[str, List[List[float]]] = {}
        seq = (
            acts if isinstance(acts, list)
            else [acts[k] for k in sorted(acts)]
        )
        for li, a in enumerate(seq):
            a = np.asarray(a)
            if a.ndim != 4:  # NHWC conv maps only
                continue
            for c in range(min(a.shape[-1], self.max_channels)):
                g = a[0, :, :, c]
                lo, hi = float(g.min()), float(g.max())
                norm = (g - lo) / (hi - lo) if hi > lo else g * 0
                grids[f"layer{li}_ch{c}"] = np.round(norm, 4).tolist()
        self._publish({
            "type": "activations",
            "iteration": iteration,
            "grids": grids,
        })
