"""Training UI server + storage.

Capability mirror of the reference UiServer (deeplearning4j-ui/.../ui/
UiServer.java:70 — Dropwizard web app with REST resources receiving listener
posts) and HistoryStorage (…/ui/storage/HistoryStorage.java — keyed
session history).

stdlib-only: http.server in a daemon thread; listeners POST JSON updates to
/train/update; GET / renders the dashboard server-side from the stored
history (score line, per-layer param histograms, topology table).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartLine,
    ComponentTable,
    ComponentText,
    render_page,
)


class HistoryStorage:
    """Keyed, bounded history of listener updates (HistoryStorage.java)."""

    def __init__(self, max_items_per_key: int = 2048):
        self._data: Dict[str, List[Any]] = {}
        self._lock = threading.Lock()
        self.max_items = max_items_per_key

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            items = self._data.setdefault(key, [])
            items.append(value)
            if len(items) > self.max_items:
                del items[: len(items) - self.max_items]

    def get(self, key: str) -> List[Any]:
        with self._lock:
            return list(self._data.get(key, []))

    def latest(self, key: str) -> Optional[Any]:
        with self._lock:
            items = self._data.get(key)
            return items[-1] if items else None

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._data)


class UiServer:
    """POST /train/update  {type: score|histogram|flow, ...}
    GET  /train/summary   JSON dump of latest state
    GET  /                server-rendered dashboard"""

    def __init__(self, port: int = 0, storage: Optional[HistoryStorage] = None):
        self.storage = storage or HistoryStorage()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path != "/train/update":
                    self._send(404, b"not found", "text/plain")
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(n))
                    key = payload.get("type", "unknown")
                    server.storage.put(key, payload)
                    self._send(200, b'{"ok":true}', "application/json")
                except (ValueError, KeyError) as e:
                    self._send(400, str(e).encode(), "text/plain")

            def do_GET(self):
                if self.path == "/train/summary":
                    out = {
                        k: server.storage.latest(k) for k in server.storage.keys()
                    }
                    self._send(
                        200, json.dumps(out).encode(), "application/json"
                    )
                elif self.path == "/":
                    self._send(
                        200, server.render_dashboard().encode(), "text/html"
                    )
                else:
                    self._send(404, b"not found", "text/plain")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "UiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- rendering --------------------------------------------------------
    def render_dashboard(self) -> str:
        comps = []
        scores = self.storage.get("score")
        if scores:
            chart = ChartLine(title="Score vs iteration")
            chart.add_series(
                "score",
                [s["iteration"] for s in scores],
                [s["score"] for s in scores],
            )
            comps.append(chart)
        hist = self.storage.latest("histogram")
        if hist:
            for name, h in hist.get("params", {}).items():
                c = ChartHistogram(title=f"param {name}")
                for lo, hi, cnt in zip(h["lower"], h["upper"], h["counts"]):
                    c.add_bin(lo, hi, cnt)
                comps.append(c)
        flow = self.storage.latest("flow")
        if flow:
            table = ComponentTable(
                title="Network topology",
                header=["layer", "type", "n_params"],
                rows=[
                    [l["name"], l["layer_type"], str(l["n_params"])]
                    for l in flow.get("layers", [])
                ],
            )
            comps.append(table)
        if not comps:
            comps = [ComponentText(text="no training data posted yet")]
        return render_page(comps, title="DL4J-TPU training")
