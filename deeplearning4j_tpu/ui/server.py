"""Training UI server + storage.

Capability mirror of the reference UiServer (deeplearning4j-ui/.../ui/
UiServer.java:70 — Dropwizard web app with REST resources receiving listener
posts) and HistoryStorage (…/ui/storage/HistoryStorage.java — keyed
session history).

stdlib-only: http.server in a daemon thread; listeners POST JSON updates to
/train/update; GET / renders the dashboard server-side from the stored
history (score line, per-layer param histograms, topology table).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartLine,
    ComponentTable,
    ComponentText,
    render_page,
)


class HistoryStorage:
    """Keyed, bounded history of listener updates (HistoryStorage.java)."""

    def __init__(self, max_items_per_key: int = 2048):
        self._data: Dict[str, List[Any]] = {}
        self._lock = threading.Lock()
        self.max_items = max_items_per_key

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            items = self._data.setdefault(key, [])
            items.append(value)
            if len(items) > self.max_items:
                del items[: len(items) - self.max_items]

    def get(self, key: str) -> List[Any]:
        with self._lock:
            return list(self._data.get(key, []))

    def latest(self, key: str) -> Optional[Any]:
        with self._lock:
            items = self._data.get(key)
            return items[-1] if items else None

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._data)


class UiServer:
    """POST /train/update      {type: score|histogram|flow, ...}
    GET  /train/summary        JSON dump of latest state
    GET  /                     server-rendered dashboard

    Explorer resources (reference ui/tsne/TsneResource.java and
    ui/nearestneighbors/word2vec/NearestNeighborsResource.java):
    POST /tsne/upload          {words:[...], vectors:[[...]]} -> run t-SNE
    POST /tsne/update          {words:[...], coords:[[x,y]...]} (precomputed)
    GET  /tsne/coords          stored 2-d coordinates as JSON
    GET  /tsne                 server-rendered scatter page
    POST /word2vec/upload      {words:[...], vectors:[[...]]} -> build VPTree
    GET  /word2vec/words       vocab list (reference /vocab)
    POST /word2vec/nearest     {word: w, k: n} | {vector: [...], k: n}"""

    def __init__(self, port: int = 0, storage: Optional[HistoryStorage] = None):
        self.storage = storage or HistoryStorage()
        # explorer state (uploaded embeddings / computed coordinates)
        # explorer state published as single atomic tuples — handler
        # threads snapshot once so words/coords (and words/vectors/tree)
        # can never be observed mid-replacement
        self._tsne_state: tuple = ([], [])  # (words, coords)
        self._nn_state = None  # (words, vectors, VPTree) | None
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, obj) -> None:
                self._send(code, json.dumps(obj).encode(), "application/json")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(n))
                    if self.path == "/train/update":
                        key = payload.get("type", "unknown")
                        server.storage.put(key, payload)
                        self._send_json(200, {"ok": True})
                    elif self.path == "/tsne/upload":
                        count = server.tsne_upload(
                            payload["words"], payload["vectors"],
                            **{
                                k: payload[k]
                                for k in ("perplexity", "iterations")
                                if k in payload
                            },
                        )
                        self._send_json(200, {"ok": True, "points": count})
                    elif self.path == "/tsne/update":
                        server.tsne_update(payload["words"], payload["coords"])
                        self._send_json(200, {"ok": True})
                    elif self.path == "/word2vec/upload":
                        count = server.nn_upload(
                            payload["words"], payload["vectors"]
                        )
                        self._send_json(200, {"ok": True, "words": count})
                    elif self.path == "/word2vec/nearest":
                        self._send_json(200, server.nn_query(payload))
                    else:
                        self._send(404, b"not found", "text/plain")
                except (ValueError, KeyError, TypeError) as e:
                    self._send_json(400, {"error": f"{type(e).__name__}: {e}"})

            def do_GET(self):
                if self.path == "/train/summary":
                    out = {
                        k: server.storage.latest(k) for k in server.storage.keys()
                    }
                    self._send_json(200, out)
                elif self.path == "/tsne/coords":
                    words, coords = server._tsne_state
                    self._send_json(200, {"words": words, "coords": coords})
                elif self.path == "/tsne":
                    self._send(
                        200, server.render_tsne().encode(), "text/html"
                    )
                elif self.path == "/word2vec/words":
                    state = server._nn_state
                    self._send_json(
                        200, {"words": state[0] if state else []}
                    )
                elif self.path == "/":
                    self._send(
                        200, server.render_dashboard().encode(), "text/html"
                    )
                else:
                    self._send(404, b"not found", "text/plain")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- explorer backends -------------------------------------------------
    def tsne_upload(self, words, vectors, perplexity: float = 30.0,
                    iterations: int = 300) -> int:
        """Run t-SNE on uploaded embeddings and store the scatter coords
        (reference TsneResource.handleUpload -> Tsne pipeline)."""
        import numpy as np

        from deeplearning4j_tpu.plot.tsne import Tsne

        x = np.asarray(vectors, dtype=np.float32)
        if x.ndim != 2 or x.shape[0] != len(words):
            raise ValueError("vectors must be [len(words), dim]")
        perplexity = min(perplexity, max(2.0, (x.shape[0] - 1) / 3.0))
        coords = Tsne(
            n_components=2, perplexity=perplexity, max_iter=int(iterations)
        ).fit_transform(x)
        self.tsne_update(list(words), np.asarray(coords).tolist())
        return len(self._tsne_state[0])

    def tsne_update(self, words, coords) -> None:
        """Store precomputed coordinates (reference postCoordinates :72)."""
        if len(words) != len(coords):
            raise ValueError("words/coords length mismatch")
        # single atomic swap: handler threads read (words, coords) as a pair
        coords = [[float(c[0]), float(c[1])] for c in coords]
        self._tsne_state = (list(words), coords)

    def nn_upload(self, words, vectors) -> int:
        """Build the VPTree over uploaded word vectors (reference
        NearestNeighborsResource upload -> VPTree build)."""
        import numpy as np

        from deeplearning4j_tpu.clustering.vptree import VPTree

        x = np.asarray(vectors, dtype=np.float32)
        if x.ndim != 2 or x.shape[0] != len(words):
            raise ValueError("vectors must be [len(words), dim]")
        # build off to the side, publish as ONE tuple: concurrent nn_query
        # on the ThreadingHTTPServer must never see a new word list paired
        # with an old tree (index-out-of-range / wrong labels)
        self._nn_state = (list(words), x, VPTree(x, distance="cosine"))
        return len(words)

    def nn_query(self, payload) -> Dict[str, Any]:
        """k nearest neighbors by word or raw vector (reference
        NearestNeighborsResource.getWords)."""
        import numpy as np

        state = self._nn_state  # snapshot: words/vectors/tree stay coherent
        if state is None:
            raise ValueError("no word vectors uploaded")
        nn_words, nn_vectors, nn_tree = state
        k = int(payload.get("k", 10))
        if "word" in payload:
            word = payload["word"]
            if word not in nn_words:
                raise ValueError(f"unknown word {word!r}")
            qi = nn_words.index(word)
            q = nn_vectors[qi]
            skip = qi
        else:
            q = np.asarray(payload["vector"], np.float32)
            skip = -1
        hits = nn_tree.knn(q, k + (1 if skip >= 0 else 0))
        out = [
            {"word": nn_words[i], "distance": float(d)}
            for d, i in hits
            if i != skip
        ][:k]
        return {"neighbors": out}

    def render_tsne(self) -> str:
        from deeplearning4j_tpu.ui.components import ChartScatter

        tsne_words, tsne_coords = self._tsne_state
        if not tsne_coords:
            return render_page(
                [ComponentText(text="no t-SNE coordinates uploaded yet — "
                               "POST /tsne/upload or /tsne/update")],
                title="t-SNE explorer",
            )
        chart = ChartScatter(title=f"t-SNE ({len(tsne_words)} points)")
        xs = [c[0] for c in tsne_coords]
        ys = [c[1] for c in tsne_coords]
        chart.add_series("words", xs, ys)
        return render_page([chart], title="t-SNE explorer")

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "UiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- rendering --------------------------------------------------------
    def render_dashboard(self) -> str:
        comps = []
        scores = self.storage.get("score")
        if scores:
            chart = ChartLine(title="Score vs iteration")
            chart.add_series(
                "score",
                [s["iteration"] for s in scores],
                [s["score"] for s in scores],
            )
            comps.append(chart)
        hist = self.storage.latest("histogram")
        if hist:
            for name, h in hist.get("params", {}).items():
                c = ChartHistogram(title=f"param {name}")
                for lo, hi, cnt in zip(h["lower"], h["upper"], h["counts"]):
                    c.add_bin(lo, hi, cnt)
                comps.append(c)
        flow = self.storage.latest("flow")
        if flow:
            table = ComponentTable(
                title="Network topology",
                header=["layer", "type", "n_params"],
                rows=[
                    [l["name"], l["layer_type"], str(l["n_params"])]
                    for l in flow.get("layers", [])
                ],
            )
            comps.append(table)
        if not comps:
            comps = [ComponentText(text="no training data posted yet")]
        return render_page(comps, title="DL4J-TPU training")
