"""TransformProcess: schema-checked record transforms (DataVec parity).

The reference's ingest plane compiles a list of declarative steps over a
typed :class:`~deeplearning4j_tpu.etl.schema.Schema` into an executable
record function (DataVec ``TransformProcess`` — the component SURVEY.md
names as the capability the reference outsources and this framework must
provide). Step vocabulary kept to the 2016 DataVec core:

  remove_columns          drop columns
  math_op                 column <op> operand (named ops — serializable)
  map_column              arbitrary Python fn on one column (NOT
                          serializable; to_json rejects it loudly)
  derive                  new trailing column from named source columns
  categorical_to_integer  category -> its index
  one_hot                 category -> len(categories) 0/1 columns
  string_to_time          strptime -> epoch seconds (UTC, deterministic)
  condition_filter        DROP records matching a named condition
  filter_invalid          DROP records with unparseable numeric fields
  rolling_window          trailing column = windowed aggregate over the
                          last K records (time-window transform; stateful
                          across the record STREAM)

Every step maps input schema -> output schema, so a mis-typed pipeline
fails at build time, not mid-epoch. ``compile()`` folds all steps into a
single per-record function (record -> record-or-None); stateful steps
(rolling windows) get FRESH state per compile, so every execution pass is
independent and deterministic.

Pipeline split contract (``split_for_pipeline``): record-parallel workers
may only run steps whose output is independent of record ORDER and
COUNT. Filters change downstream batch boundaries and rolling windows
carry state across records, so everything up to and including the last
such step runs serially in the dispatcher; the stateless per-record
suffix runs in the workers. The split is semantics-preserving by
construction: serial(head) ∘ parallel(tail) == serial(head ∘ tail).
"""

from __future__ import annotations

import calendar
import json
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.etl.schema import ColumnSpec, ColumnType, Schema


def _to_number(v):
    """Numeric coercion matching the reader/iterator plane's float():
    str/int/float -> float; raises ValueError on junk."""
    return float(v)


# ---------------------------------------------------------------------------
# Steps
# ---------------------------------------------------------------------------


class Step:
    """One schema-checked record transform. ``compile`` returns
    fn(record)->record (or None to DROP the record — filters)."""

    #: filters drop records (change downstream batch boundaries)
    is_filter = False
    #: stateful steps carry state across the record stream (windows)
    is_stateful = False

    def output_schema(self, schema: Schema) -> Schema:
        raise NotImplementedError

    def compile(self, schema: Schema) -> Callable[[list], Optional[list]]:
        raise NotImplementedError

    def to_spec(self) -> Dict:
        raise NotImplementedError(
            f"{type(self).__name__} is not serializable")


class RemoveColumns(Step):
    def __init__(self, names: Sequence[str]):
        self.names = [str(n) for n in names]

    def output_schema(self, schema):
        drop = set(self.names)
        for n in self.names:
            schema.index_of(n)  # loud on unknown columns
        return Schema([c for c in schema.columns if c.name not in drop])

    def compile(self, schema):
        keep = [i for i, c in enumerate(schema.columns)
                if c.name not in set(self.names)]

        def fn(rec):
            return [rec[i] for i in keep]

        return fn

    def to_spec(self):
        return {"op": "remove_columns", "names": list(self.names)}


_MATH_OPS = {
    "add": lambda x, k: x + k,
    "sub": lambda x, k: x - k,
    "rsub": lambda x, k: k - x,
    "mul": lambda x, k: x * k,
    "div": lambda x, k: x / k,
    "rdiv": lambda x, k: k / x,
    "pow": lambda x, k: x ** k,
    "min": lambda x, k: min(x, k),
    "max": lambda x, k: max(x, k),
}
_MATH_UNARY = {
    "abs": abs,
    "neg": lambda x: -x,
    "log": __import__("math").log,
    "log1p": __import__("math").log1p,
    "sqrt": __import__("math").sqrt,
}


class MathOp(Step):
    """column <op> operand with a NAMED op (DataVec MathOpTransform /
    MathOp enum) — named ops keep the step JSON-serializable."""

    def __init__(self, column: str, op: str, operand: Optional[float] = None):
        if op in _MATH_OPS:
            if operand is None:
                raise ValueError(f"math op {op!r} needs an operand")
        elif op in _MATH_UNARY:
            operand = None
        else:
            raise ValueError(
                f"unknown math op {op!r}; binary: {sorted(_MATH_OPS)}, "
                f"unary: {sorted(_MATH_UNARY)}")
        self.column, self.op = str(column), str(op)
        self.operand = None if operand is None else float(operand)

    def output_schema(self, schema):
        spec = schema.column(self.column)
        cols = list(schema.columns)
        cols[schema.index_of(self.column)] = ColumnSpec(
            spec.name, ColumnType.NUMERIC)
        return Schema(cols)

    def compile(self, schema):
        i = schema.index_of(self.column)
        if self.op in _MATH_OPS:
            f, k = _MATH_OPS[self.op], self.operand

            def fn(rec):
                rec = list(rec)
                rec[i] = f(_to_number(rec[i]), k)
                return rec
        else:
            f = _MATH_UNARY[self.op]

            def fn(rec):
                rec = list(rec)
                rec[i] = f(_to_number(rec[i]))
                return rec

        return fn

    def to_spec(self):
        out = {"op": "math_op", "column": self.column, "math": self.op}
        if self.operand is not None:
            out["operand"] = self.operand
        return out


class MapColumn(Step):
    """Arbitrary Python fn over one column — the escape hatch DataVec
    lacks. Deliberately NOT serializable (to_spec raises): a closure has
    no stable wire form, and a checkpoint that silently dropped it would
    replay a DIFFERENT pipeline."""

    def __init__(self, column: str, fn: Callable,
                 output_type: str = ColumnType.NUMERIC):
        self.column, self.fn, self.output_type = str(column), fn, output_type

    def output_schema(self, schema):
        cols = list(schema.columns)
        i = schema.index_of(self.column)
        cols[i] = ColumnSpec(self.column, self.output_type,
                             cols[i].categories
                             if self.output_type == ColumnType.CATEGORICAL
                             else None)
        return Schema(cols)

    def compile(self, schema):
        i, f = schema.index_of(self.column), self.fn

        def fn(rec):
            rec = list(rec)
            rec[i] = f(rec[i])
            return rec

        return fn


_DERIVE_OPS = {
    "sum": sum,
    "mean": lambda vs: sum(vs) / len(vs),
    "min": min,
    "max": max,
    "product": lambda vs: __import__("functools").reduce(
        lambda a, b: a * b, vs),
    "diff": lambda vs: vs[0] - sum(vs[1:]),
}


class Derive(Step):
    """Append a numeric column computed from named source columns — a
    named aggregate (serializable) or an arbitrary fn(values)->value."""

    def __init__(self, new_name: str, columns: Sequence[str],
                 op="sum"):
        self.new_name = str(new_name)
        self.columns = [str(c) for c in columns]
        if callable(op):
            self.op, self.fn = None, op
        else:
            if op not in _DERIVE_OPS:
                raise ValueError(
                    f"unknown derive op {op!r}: {sorted(_DERIVE_OPS)}")
            self.op, self.fn = str(op), _DERIVE_OPS[op]

    def output_schema(self, schema):
        for c in self.columns:
            schema.index_of(c)
        return Schema(list(schema.columns)
                      + [ColumnSpec(self.new_name, ColumnType.NUMERIC)])

    def compile(self, schema):
        idx = [schema.index_of(c) for c in self.columns]
        f = self.fn

        def fn(rec):
            return list(rec) + [f([_to_number(rec[i]) for i in idx])]

        return fn

    def to_spec(self):
        if self.op is None:
            raise NotImplementedError(
                "Derive with a Python callable is not serializable; use a "
                f"named op ({sorted(_DERIVE_OPS)})")
        return {"op": "derive", "new_name": self.new_name,
                "columns": list(self.columns), "agg": self.op}


class CategoricalToInteger(Step):
    def __init__(self, column: str):
        self.column = str(column)

    def _categories(self, schema) -> List[str]:
        spec = schema.column(self.column)
        if spec.type != ColumnType.CATEGORICAL:
            raise ValueError(
                f"{self.column!r} is {spec.type}, not categorical")
        return list(spec.categories)

    def output_schema(self, schema):
        self._categories(schema)
        cols = list(schema.columns)
        cols[schema.index_of(self.column)] = ColumnSpec(
            self.column, ColumnType.INTEGER)
        return Schema(cols)

    def compile(self, schema):
        i = schema.index_of(self.column)
        lut = {c: k for k, c in enumerate(self._categories(schema))}

        def fn(rec):
            rec = list(rec)
            v = str(rec[i])
            if v not in lut:
                raise ValueError(
                    f"value {v!r} not in categories of {self.column!r} "
                    f"({sorted(lut)})")
            rec[i] = lut[v]
            return rec

        return fn

    def to_spec(self):
        return {"op": "categorical_to_integer", "column": self.column}


class CategoricalToOneHot(CategoricalToInteger):
    """Replace the column with len(categories) 0/1 numeric columns named
    ``col[cat]`` (DataVec CategoricalToOneHotTransform)."""

    def output_schema(self, schema):
        cats = self._categories(schema)
        i = schema.index_of(self.column)
        cols = (list(schema.columns[:i])
                + [ColumnSpec(f"{self.column}[{c}]", ColumnType.NUMERIC)
                   for c in cats]
                + list(schema.columns[i + 1:]))
        return Schema(cols)

    def compile(self, schema):
        i = schema.index_of(self.column)
        cats = self._categories(schema)
        lut = {c: k for k, c in enumerate(cats)}
        width = len(cats)

        def fn(rec):
            v = str(rec[i])
            if v not in lut:
                raise ValueError(
                    f"value {v!r} not in categories of {self.column!r} "
                    f"({cats})")
            hot = [0.0] * width
            hot[lut[v]] = 1.0
            return list(rec[:i]) + hot + list(rec[i + 1:])

        return fn

    def to_spec(self):
        return {"op": "one_hot", "column": self.column}


class StringToTime(Step):
    """strptime -> epoch SECONDS as float, evaluated against UTC
    (calendar.timegm, not mktime — host-timezone-independent, so the same
    records transform identically on every machine)."""

    def __init__(self, column: str, fmt: str):
        self.column, self.fmt = str(column), str(fmt)

    def output_schema(self, schema):
        cols = list(schema.columns)
        cols[schema.index_of(self.column)] = ColumnSpec(
            self.column, ColumnType.TIME)
        return Schema(cols)

    def compile(self, schema):
        i, fmt = schema.index_of(self.column), self.fmt

        def fn(rec):
            rec = list(rec)
            rec[i] = float(calendar.timegm(time.strptime(str(rec[i]), fmt)))
            return rec

        return fn

    def to_spec(self):
        return {"op": "string_to_time", "column": self.column,
                "format": self.fmt}


_CONDITIONS = {
    "lt": lambda v, k: v < k,
    "le": lambda v, k: v <= k,
    "gt": lambda v, k: v > k,
    "ge": lambda v, k: v >= k,
    "eq": lambda v, k: v == k,
    "ne": lambda v, k: v != k,
    "in": lambda v, k: v in k,
    "not_in": lambda v, k: v not in k,
}


class ConditionFilter(Step):
    """DROP records where column <condition> value holds (DataVec
    ConditionFilter semantics: the condition selects what is REMOVED).
    Numeric conditions coerce both sides to float; eq/ne/in fall back to
    string comparison when coercion fails."""

    is_filter = True

    def __init__(self, column: str, condition: str, value):
        if condition not in _CONDITIONS:
            raise ValueError(
                f"unknown condition {condition!r}: {sorted(_CONDITIONS)}")
        self.column, self.condition, self.value = (
            str(column), str(condition), value)

    def output_schema(self, schema):
        schema.index_of(self.column)
        return schema

    def compile(self, schema):
        i = schema.index_of(self.column)
        cond = _CONDITIONS[self.condition]
        val = self.value

        def fn(rec):
            v = rec[i]
            try:
                matched = cond(_to_number(v),
                               [float(x) for x in val]
                               if isinstance(val, (list, tuple))
                               else float(val))
            except (TypeError, ValueError):
                matched = cond(str(v),
                               [str(x) for x in val]
                               if isinstance(val, (list, tuple))
                               else str(val))
            return None if matched else rec

        return fn

    def to_spec(self):
        val = (list(self.value) if isinstance(self.value, (list, tuple))
               else self.value)
        return {"op": "condition_filter", "column": self.column,
                "condition": self.condition, "value": val}


class FilterInvalid(Step):
    """DROP records whose numeric/integer/time columns fail float()
    (DataVec FilterInvalidValues) — the transform-plane replacement for
    the old reader behavior of exploding mid-assembly."""

    is_filter = True

    def __init__(self, columns: Optional[Sequence[str]] = None):
        self.columns = None if columns is None else [str(c) for c in columns]

    def output_schema(self, schema):
        for c in self.columns or []:
            schema.index_of(c)
        return schema

    def compile(self, schema):
        if self.columns is None:
            idx = [i for i, c in enumerate(schema.columns)
                   if c.type in (ColumnType.NUMERIC, ColumnType.INTEGER,
                                 ColumnType.TIME)]
        else:
            idx = [schema.index_of(c) for c in self.columns]

        def fn(rec):
            for i in idx:
                try:
                    _to_number(rec[i])
                except (TypeError, ValueError):
                    return None
            return rec

        return fn

    def to_spec(self):
        return {"op": "filter_invalid",
                "columns": None if self.columns is None
                else list(self.columns)}


_WINDOW_OPS = {
    "mean": lambda vs: sum(vs) / len(vs),
    "sum": sum,
    "min": min,
    "max": max,
}


class RollingWindow(Step):
    """Append ``col_<op><window>``: the aggregate over the last K records'
    values of ``col`` INCLUDING the current one (the time-window
    transform; shorter at the head of the stream — DataVec's sequence
    window ops restricted to the trailing-window case). Stateful across
    the record stream, so ``compile`` hands out FRESH state and
    ``split_for_pipeline`` keeps it out of record-parallel workers."""

    is_stateful = True

    def __init__(self, column: str, window: int, op: str = "mean"):
        if op not in _WINDOW_OPS:
            raise ValueError(
                f"unknown window op {op!r}: {sorted(_WINDOW_OPS)}")
        if int(window) < 1:
            raise ValueError("window must be >= 1")
        self.column, self.window, self.op = str(column), int(window), str(op)

    @property
    def new_name(self) -> str:
        return f"{self.column}_{self.op}{self.window}"

    def output_schema(self, schema):
        schema.index_of(self.column)
        return Schema(list(schema.columns)
                      + [ColumnSpec(self.new_name, ColumnType.NUMERIC)])

    def compile(self, schema):
        i = schema.index_of(self.column)
        agg = _WINDOW_OPS[self.op]
        buf: deque = deque(maxlen=self.window)

        def fn(rec):
            buf.append(_to_number(rec[i]))
            return list(rec) + [agg(list(buf))]

        return fn

    def to_spec(self):
        return {"op": "rolling_window", "column": self.column,
                "window": self.window, "agg": self.op}


_STEP_FROM_SPEC = {
    "remove_columns": lambda s: RemoveColumns(s["names"]),
    "math_op": lambda s: MathOp(s["column"], s["math"], s.get("operand")),
    "derive": lambda s: Derive(s["new_name"], s["columns"], s["agg"]),
    "categorical_to_integer":
        lambda s: CategoricalToInteger(s["column"]),
    "one_hot": lambda s: CategoricalToOneHot(s["column"]),
    "string_to_time": lambda s: StringToTime(s["column"], s["format"]),
    "condition_filter":
        lambda s: ConditionFilter(s["column"], s["condition"], s["value"]),
    "filter_invalid": lambda s: FilterInvalid(s.get("columns")),
    "rolling_window":
        lambda s: RollingWindow(s["column"], s["window"], s["agg"]),
}


# ---------------------------------------------------------------------------
# TransformProcess
# ---------------------------------------------------------------------------


class TransformProcess:
    """Ordered steps over an initial schema, compiled into ONE executable
    record function (DataVec ``TransformProcess`` parity). Builder-style:
    every step method appends and returns self."""

    def __init__(self, schema: Schema):
        self.initial_schema = schema
        self.steps: List[Step] = []

    # -- builder surface ---------------------------------------------------
    def _add(self, step: Step) -> "TransformProcess":
        step.output_schema(self.final_schema())  # validate NOW, loudly
        self.steps.append(step)
        return self

    def remove_columns(self, *names: str) -> "TransformProcess":
        return self._add(RemoveColumns(names))

    def math_op(self, column: str, op: str,
                operand: Optional[float] = None) -> "TransformProcess":
        return self._add(MathOp(column, op, operand))

    def map_column(self, column: str, fn: Callable,
                   output_type: str = ColumnType.NUMERIC
                   ) -> "TransformProcess":
        return self._add(MapColumn(column, fn, output_type))

    def derive(self, new_name: str, columns: Sequence[str],
               op="sum") -> "TransformProcess":
        return self._add(Derive(new_name, columns, op))

    def categorical_to_integer(self, column: str) -> "TransformProcess":
        return self._add(CategoricalToInteger(column))

    def one_hot(self, column: str) -> "TransformProcess":
        return self._add(CategoricalToOneHot(column))

    def string_to_time(self, column: str, fmt: str) -> "TransformProcess":
        return self._add(StringToTime(column, fmt))

    def condition_filter(self, column: str, condition: str,
                         value) -> "TransformProcess":
        return self._add(ConditionFilter(column, condition, value))

    def filter_invalid(self, columns: Optional[Sequence[str]] = None
                       ) -> "TransformProcess":
        return self._add(FilterInvalid(columns))

    def rolling_window(self, column: str, window: int,
                       op: str = "mean") -> "TransformProcess":
        return self._add(RollingWindow(column, window, op))

    # -- execution ---------------------------------------------------------
    def final_schema(self) -> Schema:
        schema = self.initial_schema
        for step in self.steps:
            schema = step.output_schema(schema)
        return schema

    def compile(self) -> Callable[[list], Optional[list]]:
        """ONE fn(record)->record-or-None folding every step (fresh
        stateful-step state: call once per execution pass)."""
        fns = []
        schema = self.initial_schema
        for step in self.steps:
            fns.append(step.compile(schema))
            schema = step.output_schema(schema)

        def fn(rec):
            for f in fns:
                rec = f(rec)
                if rec is None:
                    return None
            return rec

        return fn

    def execute(self, records):
        """Transform an iterable of records; filtered records are dropped
        from the output stream."""
        fn = self.compile()
        for rec in records:
            out = fn(rec)
            if out is not None:
                yield out

    @property
    def is_record_parallel_safe(self) -> bool:
        """True when NO step filters or carries stream state — such a
        process may run per-record in parallel workers without changing
        batch boundaries or windowed values."""
        return not any(s.is_filter or s.is_stateful for s in self.steps)

    def split_for_pipeline(self):
        """(head, tail): head = everything up to and INCLUDING the last
        filter/stateful step (must run serially, in stream order), tail =
        the pure stateless suffix (safe for record-parallel workers).
        Either part may be None when empty."""
        cut = 0
        for k, step in enumerate(self.steps):
            if step.is_filter or step.is_stateful:
                cut = k + 1
        head = tail = None
        if cut:
            head = TransformProcess(self.initial_schema)
            head.steps = self.steps[:cut]
        if cut < len(self.steps):
            mid_schema = self.initial_schema
            for step in self.steps[:cut]:
                mid_schema = step.output_schema(mid_schema)
            tail = TransformProcess(mid_schema)
            tail.steps = self.steps[cut:]
        return head, tail

    # -- serde -------------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "schema": json.loads(self.initial_schema.to_json()),
            "steps": [s.to_spec() for s in self.steps],
        })

    @staticmethod
    def from_json(s: str) -> "TransformProcess":
        data = json.loads(s)
        tp = TransformProcess(Schema.from_json(json.dumps(data["schema"])))
        for spec in data["steps"]:
            op = spec.get("op")
            if op not in _STEP_FROM_SPEC:
                raise ValueError(f"unknown transform step {op!r}")
            tp._add(_STEP_FROM_SPEC[op](spec))
        return tp


class TransformProcessRecordReader:
    """A RecordReader that applies a TransformProcess to a base reader's
    stream (DataVec TransformProcessRecordReader) — the bridge that lets
    the existing ``datasets.records.RecordReaderDataSetIterator`` consume
    transformed records unchanged. Fresh compile per pass, so stateful
    steps (rolling windows) restart with the stream."""

    def __init__(self, reader, transform: TransformProcess):
        self.reader = reader
        self.transform = transform

    def __iter__(self):
        return self.transform.execute(iter(self.reader))

    def reset(self) -> None:
        if hasattr(self.reader, "reset"):
            self.reader.reset()
