"""InputPipeline: overlapped, deterministic, checkpointable input staging.

The reference's training loop pulls each minibatch through
``AsyncDataSetIterator.java:30`` — ONE background thread, no transform
plane, no order guarantee beyond the base iterator's. This runtime is the
L5 subsystem around that idea, sized for the TPU regime where every
training-thread millisecond spent parsing records is a millisecond the
chip starves:

  dispatcher thread   reads the SOURCE in stream order (records from a
                      reader, or DataSets from a wrapped iterator),
                      applies the order/count-sensitive TransformProcess
                      head (filters, rolling windows) serially, chunks
                      into batches, shards for multi-process DP
                      (``parallel/multihost`` env contract — each process
                      keeps every ``shard_count``-th batch), and hands
                      sequence-numbered work to the pool;
  N worker threads    the record-parallel part: the stateless transform
                      tail, VECTORIZED batch assembly (one C-level
                      float64 parse of the whole chunk — byte-identical
                      to the per-record ``float()`` path, measurably
                      faster), and the fitted normalizer;
  reorder buffer      bounded map keyed by sequence number: batches
                      re-enter STREAM ORDER no matter which worker
                      finished first — pipeline output is byte-identical
                      to direct iteration at ANY worker count;
  stager thread       double-buffered ``jax.device_put``: batch j+1's
                      host->device copy overlaps the trainer's step on
                      batch j (the ``prefetch`` queue bounds device-side
                      batches in flight).

Telemetry rides in :class:`~deeplearning4j_tpu.etl.stats.PipelineStats`
(``pipeline.pipeline_stats`` — adopted onto the training containers as
``net.pipeline_stats`` beside ``dispatch_stats``/``memory_stats``).

Resilience: the pipeline implements the resumable-iterator protocol
(``datasets/iterator.DataSetIterator.state``) counting batches DELIVERED
— the dispatcher runs ahead, so the cursor snapshots travel WITH each
batch through the pool, exactly like ``AsyncDataSetIterator``'s
delivered-not-prefetched rule — which keeps ``ResilientTrainer``
kill-at-step-k + resume bit-exact through the pipeline.

Env knobs: ``DL4J_TPU_PIPELINE_WORKERS`` (worker count; also the opt-in
for ``fit_iterator`` auto-wrapping via :func:`maybe_wrap`),
``DL4J_TPU_PREFETCH`` (staged-batch queue depth, shared with
``AsyncDataSetIterator``).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.iterator import DataSet, DataSetIterator
from deeplearning4j_tpu.etl.stats import PipelineStats, dataset_nbytes
from deeplearning4j_tpu.obs import trace as obs_trace
from deeplearning4j_tpu.ops import env as envknob

WORKERS_ENV = "DL4J_TPU_PIPELINE_WORKERS"
PREFETCH_ENV = "DL4J_TPU_PREFETCH"

_SENTINEL = object()
_NO_PENDING = object()

#: reshard() value for a member that LEFT the fleet: the pipeline owns
#: nothing from the boundary on (None would mean "own everything")
DROP_SHARD = "drop"


def _env_int(name: str, default: int) -> int:
    return envknob.get_int(name, default)


def default_prefetch() -> int:
    """Staged-batch queue depth: DL4J_TPU_PREFETCH, default 2 (double
    buffering — one batch on device under compute, one staging)."""
    return max(1, _env_int(PREFETCH_ENV, 2))


def _auto_shard() -> Optional[Tuple[int, int]]:
    """(process_id, num_processes) from the multihost env contract —
    env-first so the query NEVER initializes a jax backend (the
    dead-tunnel rule, parallel/multihost.is_primary)."""
    from deeplearning4j_tpu.parallel.multihost import (
        NUM_PROCESSES_ENV,
        PROCESS_ID_ENV,
    )

    pid = envknob.get_str(PROCESS_ID_ENV)
    count = envknob.get_str(NUM_PROCESSES_ENV)
    if pid is None or count is None or int(count) <= 1:
        return None
    return int(pid), int(count)


# ---------------------------------------------------------------------------
# Vectorized batch assembly (byte-identical to the per-record path)
# ---------------------------------------------------------------------------


def assemble_batch(records: List, label_index: Optional[int],
                   num_possible_labels: int, regression: bool,
                   label_index_to: Optional[int]) -> DataSet:
    """Records -> DataSet with ``RecordReaderDataSetIterator`` semantics
    (datasets/records.py:167 ``_split``/``_make``) but ONE vectorized
    parse: the whole chunk goes through numpy's C float64 parser and is
    cast to float32 afterwards — the same double-rounding as
    ``float(v)`` per element then ``np.asarray(..., np.float32)``, so the
    output is BYTE-identical while parsing ~2x faster (the measured
    1-core win the ``input_pipeline`` bench leg commits). Falls back to
    the per-record path for chunks numpy cannot batch-parse (ragged or
    non-numeric leftovers)."""
    try:
        arr = np.asarray(records, dtype=np.float64)
        if arr.ndim != 2:
            raise ValueError("not a flat record chunk")
    except (ValueError, TypeError):
        return _assemble_per_record(records, label_index,
                                    num_possible_labels, regression,
                                    label_index_to)
    if label_index is None:
        x = arr.astype(np.float32)
        return DataSet(features=x, labels=x)  # AE pretrain: y is x
    li = label_index if label_index >= 0 else arr.shape[1] + label_index
    if label_index_to is not None:
        hi = label_index_to + 1
        y = arr[:, li:hi].astype(np.float32)
        x = np.concatenate([arr[:, :li], arr[:, hi:]], axis=1).astype(
            np.float32)
        return DataSet(features=x, labels=y)
    x = np.concatenate([arr[:, :li], arr[:, li + 1:]], axis=1).astype(
        np.float32)
    if regression or num_possible_labels <= 0:
        return DataSet(features=x, labels=arr[:, li:li + 1].astype(
            np.float32))
    idx = arr[:, li].astype(np.int64)  # truncation == int(label_val)
    y = np.zeros((arr.shape[0], num_possible_labels), np.float32)
    y[np.arange(arr.shape[0]), idx] = 1.0
    return DataSet(features=x, labels=y)


def _assemble_per_record(records, label_index, num_possible_labels,
                         regression, label_index_to) -> DataSet:
    from deeplearning4j_tpu.datasets.records import (
        RecordReaderDataSetIterator,
    )

    proto = RecordReaderDataSetIterator(
        reader=None, batch_size=len(records), label_index=label_index,
        num_possible_labels=num_possible_labels, regression=regression,
        label_index_to=label_index_to)
    feats, labels = [], []
    for rec in records:
        f, l = proto._split(rec)
        feats.append(f)
        labels.append(l)
    return proto._make(feats, labels)


# ---------------------------------------------------------------------------
# Shared coordination state
# ---------------------------------------------------------------------------


class _Coordination:
    """The reorder buffer plus the end-of-stream/error handshake all four
    thread roles share. ``buf`` maps LOCAL (post-shard, dense) batch
    index -> finished payload; ``total`` is the local batch count, known
    once the dispatcher exhausts the source."""

    def __init__(self, capacity: int):
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.buf: Dict[int, Any] = {}
        self.capacity = max(1, int(capacity))
        self.next_needed = 0
        self.total: Optional[int] = None
        self.workers_done = 0
        self.error: Optional[BaseException] = None

    def fail(self, exc: BaseException) -> None:
        with self.cond:
            if self.error is None:
                self.error = exc
            self.cond.notify_all()


class InputPipeline(DataSetIterator):
    """See module docstring. Two source modes:

      * ``InputPipeline(iterator, ...)`` wraps any DataSetIterator (or
        MultiDataSet iterator): assembly already happened in the source;
        the pipeline moves it off the training thread and adds the
        normalizer, ordering, staging, telemetry and resume planes.
      * ``InputPipeline.from_reader(reader, batch_size, ...)`` builds
        batches straight from a RecordReader (+ optional
        TransformProcess), with assembly vectorized in the workers —
        equivalent to ``RecordReaderDataSetIterator`` over a
        ``TransformProcessRecordReader``, byte for byte.
    """

    def __init__(self, source, *, workers: Optional[int] = None,
                 prefetch: Optional[int] = None, normalizer=None,
                 device_put: bool = True, shard="auto",
                 _reader_cfg: Optional[dict] = None):
        self.source = source
        self.workers = max(1, workers if workers is not None
                           else _env_int(WORKERS_ENV, 2))
        self.prefetch = max(1, prefetch if prefetch is not None
                            else default_prefetch())
        self.normalizer = normalizer
        self.device_put = device_put
        self.shard: Optional[Tuple[int, int]] = (
            _auto_shard() if shard == "auto" else shard)
        if self.shard is not None:
            idx, count = self.shard
            if not 0 <= idx < count:
                raise ValueError(f"shard index {idx} outside [0, {count})")
        # live resharding plane (ISSUE 6): a schedule of (at_seq, shard)
        # entries over ABSOLUTE batch sequence numbers — the elastic
        # fleet re-partitions the multihost shard selection on a
        # membership epoch bump, at a boundary every member agrees on,
        # so the union of the survivors' pipelines still covers every
        # batch exactly once. Guarded by _shard_lock (the dispatcher
        # thread reads it per batch).
        self._shard_lock = threading.Lock()
        self._shard_schedule: List[Tuple[int, Any]] = [(0, self.shard)]
        self._pending_shard: Any = _NO_PENDING
        self._dispatch_seq = 0  # ownership decided for seqs below this
        self._consumed_seq = 0  # high-water mark of the last pass
        self._pass_active = False
        self._reader_cfg = _reader_cfg
        if _reader_cfg is not None:
            head, tail = (None, None)
            tp = _reader_cfg.get("transform")
            if tp is not None:
                head, tail = tp.split_for_pipeline()
            self._tp_head, self._tp_tail = head, tail
        # graftlint: disable=ledger-registration -- adopted + registered by the container at fit time (nn/multilayer.py:688 re-adopts the ingest ledger through register_net)
        self.pipeline_stats = PipelineStats(
            workers=self.workers, queue_capacity=self.prefetch)
        # resume plane (delivered-batch cursor; see state()/restore_state)
        self._last_state: Optional[dict] = None
        self._resume: Optional[dict] = None

    # -- construction ------------------------------------------------------
    @classmethod
    def from_reader(cls, reader, batch_size: int, *,
                    label_index: Optional[int] = None,
                    num_possible_labels: int = -1,
                    regression: bool = False,
                    label_index_to: Optional[int] = None,
                    transform=None, **kw) -> "InputPipeline":
        """Pipeline straight off a RecordReader: dispatcher applies the
        TransformProcess head + batch chunking, workers run the stateless
        transform tail + vectorized assembly (label semantics exactly
        ``RecordReaderDataSetIterator``'s)."""
        cfg = {"batch_size": int(batch_size), "label_index": label_index,
               "num_possible_labels": int(num_possible_labels),
               "regression": bool(regression),
               "label_index_to": label_index_to, "transform": transform}
        return cls(reader, _reader_cfg=cfg, **kw)

    @classmethod
    def from_native(cls, features, labels, batch: int, *, epochs: int = 1,
                    seed: int = 0, capacity: int = 4, **kw
                    ) -> "InputPipeline":
        """The native C++ host feeder (``native/`` prefetch ring) as the
        pipeline source — shuffle + minibatch slicing in native code, the
        transform/normalizer/staging planes on top."""
        return cls(_NativeSource(features, labels, batch, epochs=epochs,
                                 seed=seed, capacity=capacity), **kw)

    # -- DataSetIterator surface ------------------------------------------
    def batch_size(self) -> int:
        if self._reader_cfg is not None:
            return int(self._reader_cfg["batch_size"])
        return self.source.batch_size()

    def total_examples(self) -> int:
        return self.source.total_examples()

    def reset(self) -> None:
        self._last_state = None
        self._resume = None
        if hasattr(self.source, "reset"):
            self.source.reset()

    # -- live resharding ---------------------------------------------------
    def reshard(self, shard, *, at_seq: Optional[int] = None) -> None:
        """Re-partition the multihost shard selection LIVE (the elastic
        fleet's membership-epoch hook). ``shard`` is ``(index, count)``,
        ``None`` (no sharding — own every batch), or :data:`DROP_SHARD`
        (a departed member: own nothing from the boundary on).

        ``at_seq`` anchors the change to an ABSOLUTE batch sequence
        number — every member must pass the same boundary (the agreed
        first batch of the next membership epoch), which is what keeps
        the union of the fleet's pipelines covering every batch exactly
        once, deterministically, with the delivered-batch cursor
        semantics intact (batches below the boundary keep the old
        partition; `state()` snapshots the schedule so a kill/resume
        replays the identical ownership). Raises when the dispatcher
        already decided ownership past the boundary — a retroactive
        reshard could double- or zero-own an in-flight batch.

        ``at_seq=None`` defers the change to the start of the NEXT pass
        (the between-epochs form)."""
        if shard is not None and shard != DROP_SHARD:
            idx, count = shard
            if not 0 <= idx < count:
                raise ValueError(f"shard index {idx} outside [0, {count})")
            shard = (int(idx), int(count))
        with self._shard_lock:
            if at_seq is None:
                self._pending_shard = shard
                return
            at_seq = int(at_seq)
            if self._pass_active and at_seq < self._dispatch_seq:
                raise ValueError(
                    f"reshard boundary {at_seq} already passed (dispatcher "
                    f"at {self._dispatch_seq}) — a retroactive reshard "
                    "would drop or double-own in-flight batches; pick a "
                    "boundary ahead of the stream")
            self._shard_schedule = (
                [(s, sh) for s, sh in self._shard_schedule if s < at_seq]
                + [(at_seq, shard)])

    def _owns(self, abs_seq: int) -> bool:
        """Shard ownership of batch `abs_seq` under the live schedule
        (last entry at or below the sequence number wins)."""
        with self._shard_lock:
            self._dispatch_seq = max(self._dispatch_seq, abs_seq + 1)
            shard = self._shard_schedule[0][1]
            for s, sh in self._shard_schedule:
                if s <= abs_seq:
                    shard = sh
                else:
                    break
        if shard == DROP_SHARD:
            return False
        return shard is None or abs_seq % shard[1] == shard[0]

    def _begin_pass(self, resumed: bool) -> None:
        """Fresh passes compact the boundaries the PREVIOUS pass consumed
        (they must not re-fire at the restarted sequence numbers) down to
        their final effective shard, while boundaries scheduled ahead of
        the stream stay armed; a pending next-pass reshard lands now.
        Resumed passes keep the restored schedule verbatim — ownership
        must replay identically."""
        with self._shard_lock:
            if not resumed:
                if self._pending_shard is not _NO_PENDING:
                    self._shard_schedule = [(0, self._pending_shard)]
                    self._pending_shard = _NO_PENDING
                else:
                    cut = self._consumed_seq
                    past = [e for e in self._shard_schedule if e[0] <= cut]
                    future = [e for e in self._shard_schedule if e[0] > cut]
                    self._shard_schedule = [(0, past[-1][1])] + future
            self._dispatch_seq = 0
            self._pass_active = True

    def _shard_schedule_snapshot(self) -> list:
        with self._shard_lock:
            return [[s, list(sh) if isinstance(sh, tuple) else sh]
                    for s, sh in self._shard_schedule]

    def _restore_shard_schedule(self, snap) -> None:
        with self._shard_lock:
            self._shard_schedule = [
                (int(s), tuple(sh) if isinstance(sh, list) else sh)
                for s, sh in snap]

    # -- resume protocol ---------------------------------------------------
    def state(self) -> Optional[dict]:
        """Cursor of the last batch DELIVERED to the consumer (never the
        dispatcher's read-ahead position — those batches would be
        silently skipped on resume). Two forms: ``source`` rides the
        wrapped iterator's own exact cursor; ``replay`` (readers and
        stateless sources) re-reads the stream and skips the delivered
        prefix — deterministic either way."""
        if self._last_state is not None:
            out = dict(self._last_state)
        elif self._resume is not None:  # restored but not yet iterated
            out = dict(self._resume)
        elif self._reader_cfg is None and hasattr(self.source, "state"):
            # pass not started: defer to a resumable source's own cursor
            snap = self.source.state()
            out = ({"mode": "source", "source": snap, "next_seq": 0}
                   if snap is not None
                   else {"mode": "replay", "next_seq": 0})
        else:
            out = {"mode": "replay", "next_seq": 0}
        # the live shard schedule rides the cursor: resumed ownership
        # must replay identically across a membership-epoch reshard —
        # including a deferred (next-pass) reshard not yet applied.
        # ONE lock acquisition for both reads: a reshard landing between
        # two acquisitions would leave the cursor missing a boundary the
        # surviving pipelines applied
        with self._shard_lock:
            out["shard_schedule"] = [
                [s, list(sh) if isinstance(sh, tuple) else sh]
                for s, sh in self._shard_schedule]
            if self._pending_shard is not _NO_PENDING:
                sh = self._pending_shard
                out["pending_shard"] = (list(sh) if isinstance(sh, tuple)
                                        else sh)
        return out

    def restore_state(self, state: dict) -> None:
        self._resume = dict(state)
        self._last_state = None
        self.pipeline_stats.record_restore()
        if state.get("shard_schedule"):
            self._restore_shard_schedule(state["shard_schedule"])
        if "pending_shard" in state:
            sh = state["pending_shard"]
            with self._shard_lock:
                self._pending_shard = (tuple(sh) if isinstance(sh, list)
                                       else sh)
        if (state.get("mode") == "source"
                and state.get("source") is not None):
            self.source.restore_state(state["source"])

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        resume, self._resume = self._resume, None
        self._begin_pass(resumed=resume is not None)
        seq_base = 0
        skip_below = 0
        if resume is not None:
            if resume.get("mode") == "source":
                # source already repositioned (restore_state); keep the
                # absolute sequence numbering so sharding stays aligned
                seq_base = int(resume.get("next_seq", 0))
            else:
                skip_below = int(resume.get("next_seq", 0))
            # a resumed pass that delivers ZERO batches (an idle live
            # stream — the poll window closed empty) must keep answering
            # the restored position from state(), not fall back to a
            # next_seq-0 snapshot; keep only the cursor keys — the shard
            # schedule/pending reshard are re-read LIVE by state()
            self._last_state = {k: resume[k]
                                for k in ("mode", "next_seq", "source")
                                if k in resume}
        stats = self.pipeline_stats
        stats.start_pass()
        coord = _Coordination(self.prefetch + self.workers)
        stop = threading.Event()
        work_q: "queue.Queue" = queue.Queue(maxsize=2 * self.workers)
        out_q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        threads = [threading.Thread(
            target=self._dispatcher, name="etl-dispatch",
            args=(coord, stop, work_q, seq_base, skip_below), daemon=True)]
        threads += [threading.Thread(
            target=self._worker, name=f"etl-worker-{k}",
            args=(coord, stop, work_q), daemon=True)
            for k in range(self.workers)]
        threads.append(threading.Thread(
            target=self._stager, name="etl-stage",
            args=(coord, stop, out_q), daemon=True))
        for t in threads:
            t.start()
        delivered_clean = False
        try:
            while True:
                waited = 0.0  # consumer-side wait for THIS delivery
                while True:
                    t0 = time.perf_counter()
                    try:
                        item = out_q.get(timeout=0.5)
                    except queue.Empty:
                        waited += time.perf_counter() - t0
                        stats.add_consumer_stall(time.perf_counter() - t0)
                        if coord.error is not None:
                            raise coord.error
                        continue
                    waited += time.perf_counter() - t0
                    stats.add_consumer_stall(time.perf_counter() - t0)
                    break
                if item is _SENTINEL:
                    if coord.error is not None:
                        raise coord.error
                    delivered_clean = True
                    break
                ds, cursor, nbytes, n = item
                self._last_state = cursor
                stats.record_delivered(nbytes, n, out_q.qsize())
                # staging-wait span: how long the TRAINING thread starved
                # before this batch arrived — the per-delivery view of
                # pipeline_stats.stall_seconds (recorded after the fact so
                # the hot loop keeps its shape; obs off = no-op)
                obs_trace.record_span("etl.wait", waited,
                                      seq=cursor.get("next_seq"),
                                      bytes=nbytes, records=n)
                yield ds
        finally:
            stop.set()
            with coord.cond:
                coord.cond.notify_all()
            for t in threads:
                t.join(timeout=5.0)
            with self._shard_lock:
                self._pass_active = False
                self._consumed_seq = self._dispatch_seq
            stats.end_pass()
        if delivered_clean and hasattr(self.source, "reset") \
                and self._reader_cfg is not None:
            self.source.reset()

    # -- thread roles ------------------------------------------------------
    def _local_batches(self, seq_base: int, skip_below: int):
        """(local_idx, abs_seq, payload, cursor) for every batch this
        process owns. Reads the SOURCE serially — the only stream-order-
        dependent stage — and snapshots the resume cursor per batch.
        Ownership consults the LIVE shard schedule per batch (reshard)."""
        local = 0
        if self._reader_cfg is not None:
            cfg = self._reader_cfg
            bs = cfg["batch_size"]
            head_fn = (self._tp_head.compile()
                       if self._tp_head is not None else None)
            chunk: list = []
            abs_seq = seq_base

            def emit(chunk, abs_seq, local):
                cursor = {"mode": "replay", "next_seq": abs_seq + 1}
                return (local, abs_seq, chunk, cursor)

            for rec in self.source:
                if head_fn is not None:
                    rec = head_fn(rec)
                    if rec is None:
                        continue
                chunk.append(rec)
                if len(chunk) == bs:
                    if self._owns(abs_seq) and abs_seq >= skip_below:
                        yield emit(chunk, abs_seq, local)
                        local += 1
                    abs_seq += 1
                    chunk = []
            if chunk:
                if self._owns(abs_seq) and abs_seq >= skip_below:
                    yield emit(chunk, abs_seq, local)
        else:
            abs_seq = seq_base
            can_state = hasattr(self.source, "state")
            for ds in self.source:
                snap = self.source.state() if can_state else None
                if self._owns(abs_seq) and abs_seq >= skip_below:
                    if snap is not None:
                        cursor = {"mode": "source", "source": snap,
                                  "next_seq": abs_seq + 1}
                    else:
                        cursor = {"mode": "replay", "next_seq": abs_seq + 1}
                    yield (local, abs_seq, ds, cursor)
                    local += 1
                abs_seq += 1

    def _dispatcher(self, coord, stop, work_q, seq_base, skip_below):
        stats = self.pipeline_stats
        count = 0
        try:
            for item in self._local_batches(seq_base, skip_below):
                if stop.is_set():
                    return
                t0 = time.perf_counter()
                while not stop.is_set():
                    try:
                        work_q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                else:
                    return
                stats.add_producer_stall(time.perf_counter() - t0)
                count += 1
            with coord.cond:
                coord.total = count
                coord.cond.notify_all()
        except BaseException as e:  # noqa: BLE001 — surfaced to consumer
            coord.fail(e)
        finally:
            for _ in range(self.workers):
                while not stop.is_set():
                    try:
                        work_q.put(_SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

    def _process(self, payload):
        """The record-parallel stage: transform tail + assembly (reader
        mode) or normalizer passthrough (wrap mode). Returns the finished
        HOST-side batch plus its byte/record counts (counted before
        device staging)."""
        if self._reader_cfg is not None:
            cfg = self._reader_cfg
            records = payload
            if self._tp_tail is not None:
                tail_fn = self._tp_tail.compile()  # stateless: fresh is free
                records = [tail_fn(r) for r in records]
            ds = assemble_batch(records, cfg["label_index"],
                                cfg["num_possible_labels"],
                                cfg["regression"], cfg["label_index_to"])
        else:
            ds = payload
        if self.normalizer is not None:
            ds = self._normalized_copy(ds)
        return ds, dataset_nbytes(ds), self._num_examples(ds)

    @staticmethod
    def _num_examples(ds) -> int:
        try:
            return int(ds.num_examples())
        except Exception:  # noqa: BLE001 — telemetry only
            return 0

    def _normalized_copy(self, ds):
        """PURE normalizer application: wrapped sources often yield VIEWS
        of their backing arrays (ListDataSetIterator slices); in-place
        transform would corrupt the source for later epochs."""
        norm = self.normalizer
        if hasattr(ds, "features_list"):  # MultiDataSet: features only
            from deeplearning4j_tpu.datasets.iterator import MultiDataSet

            return MultiDataSet(
                [norm.transform_array(f) for f in ds.features_list],
                list(ds.labels_list), ds.features_masks, ds.labels_masks)
        labels = ds.labels
        if norm._fit_labels and labels is not None:
            labels = norm.transform_array(labels, labels=True)
        return DataSet(norm.transform_array(ds.features), labels,
                       ds.features_mask, ds.labels_mask)

    def _worker(self, coord, stop, work_q):
        stats = self.pipeline_stats
        try:
            while not stop.is_set():
                try:
                    item = work_q.get(timeout=0.1)
                except queue.Empty:
                    continue
                if item is _SENTINEL:
                    break
                local_idx, abs_seq, payload, cursor = item
                ds, nbytes, n = self._process(payload)
                t0 = time.perf_counter()
                with coord.cond:
                    # the batch the stager needs next must always get in
                    # (capacity back-pressure would deadlock otherwise)
                    while (len(coord.buf) >= coord.capacity
                           and local_idx != coord.next_needed
                           and not stop.is_set() and coord.error is None):
                        coord.cond.wait(timeout=0.1)
                    if stop.is_set() or coord.error is not None:
                        return
                    coord.buf[local_idx] = (ds, cursor, nbytes, n)
                    coord.cond.notify_all()
                stats.add_producer_stall(time.perf_counter() - t0)
        except BaseException as e:  # noqa: BLE001
            coord.fail(e)
        finally:
            with coord.cond:
                coord.workers_done += 1
                coord.cond.notify_all()

    def _stage(self, ds):
        """Host->device staging (the double-buffering half: the copy of
        batch j+1 runs while the trainer computes on batch j)."""
        if not self.device_put:
            return ds
        import jax

        with obs_trace.span("etl.stage"):
            return self._device_put(ds, jax.device_put)

    def _device_put(self, ds, put):
        opt = lambda a: None if a is None else put(a)
        if hasattr(ds, "features_list"):
            from deeplearning4j_tpu.datasets.iterator import MultiDataSet

            return MultiDataSet(
                [put(f) for f in ds.features_list],
                [put(l) for l in ds.labels_list],
                None if ds.features_masks is None
                else [opt(m) for m in ds.features_masks],
                None if ds.labels_masks is None
                else [opt(m) for m in ds.labels_masks])
        return DataSet(put(ds.features), put(ds.labels),
                       opt(ds.features_mask), opt(ds.labels_mask))

    def _stager(self, coord, stop, out_q):
        stats = self.pipeline_stats
        try:
            while not stop.is_set():
                with coord.cond:
                    while (coord.next_needed not in coord.buf
                           and not stop.is_set() and coord.error is None
                           and not (coord.total is not None
                                    and coord.next_needed >= coord.total
                                    and coord.workers_done >= self.workers)):
                        coord.cond.wait(timeout=0.1)
                    if stop.is_set() or coord.error is not None:
                        return
                    if coord.next_needed not in coord.buf:
                        return  # stream complete
                    ds, cursor, nbytes, n = coord.buf.pop(coord.next_needed)
                    coord.next_needed += 1
                    coord.cond.notify_all()
                staged = self._stage(ds)
                t0 = time.perf_counter()
                while not stop.is_set():
                    try:
                        out_q.put((staged, cursor, nbytes, n), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                else:
                    return
                stats.add_producer_stall(time.perf_counter() - t0)
        except BaseException as e:  # noqa: BLE001
            coord.fail(e)
        finally:
            # the consumer's end-of-pass signal, errors included (it
            # re-raises coord.error on receipt)
            while True:
                try:
                    out_q.put(_SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    if stop.is_set():
                        break


class _NativeSource(DataSetIterator):
    """The native C++ prefetch ring (``native.NativePrefetchIterator``)
    adapted to the DataSet contract, so the pipeline can ride the
    native feeder's shuffle/slice plane (optional source)."""

    def __init__(self, features, labels, batch: int, *, epochs: int = 1,
                 seed: int = 0, capacity: int = 4):
        from deeplearning4j_tpu.native import NativePrefetchIterator

        self._it = NativePrefetchIterator(
            np.asarray(features), np.asarray(labels), batch,
            epochs=epochs, seed=seed, capacity=capacity)

    def __iter__(self):
        for x, y in self._it:
            yield DataSet(features=x, labels=y)

    def batch_size(self) -> int:
        return self._it.batch

    def total_examples(self) -> int:
        return int(len(self._it.features)) * self._it.epochs


def maybe_wrap(iterator):
    """``fit_iterator`` adoption hook: when ``DL4J_TPU_PIPELINE_WORKERS``
    opts in (> 0), wrap a plain iterator in an :class:`InputPipeline`;
    staged iterators (anything already exposing ``pipeline_stats`` —
    pipelines, AsyncDataSetIterator) and non-iterables pass through.
    With the env unset this is the identity, so the containers'
    equivalence contracts are untouched by default.

    ``shard=None`` on purpose: a plain iterator handed to
    ``fit_iterator`` is already the stream THIS process should train on
    (the multihost DP contract is process-local feeding), so auto-shard
    would silently drop every other batch of an already-local stream.
    Sharding is only sound when a pipeline is explicitly constructed
    over a GLOBAL stream (``InputPipeline(..., shard="auto")``)."""
    n = _env_int(WORKERS_ENV, 0)
    if n <= 0:
        return iterator
    if getattr(iterator, "pipeline_stats", None) is not None:
        return iterator
    if not hasattr(iterator, "__iter__"):
        return iterator
    return InputPipeline(iterator, workers=n, shard=None)
