"""Pipeline telemetry: the ingest-side sibling of ``ops/dispatch.DispatchStats``.

The reference's ingest plane (Canova/DataVec record readers behind
``AsyncDataSetIterator.java:30``) is a black box: when the training loop
stalls between iterations nothing records whether the time went to record
parsing, batch assembly, host->device transfer, or genuine device compute.
``PipelineStats`` makes the input side observable the same way
``dispatch_stats``/``memory_stats`` made the dispatch side observable:
every delivered batch is counted (batches / records / bytes), both kinds
of waiting are accounted separately —

  ``stall_seconds``           the CONSUMER (training thread) blocked
                              waiting for a staged batch: the input
                              pipeline is the bottleneck;
  ``producer_stall_seconds``  the PRODUCERS blocked on full buffers: the
                              trainer is the bottleneck (healthy — the
                              pipeline keeps up);

and the snapshot derives the throughput rates the bench leg commits
(``bench.py --only=input_pipeline``).

Shared by ``etl/pipeline.InputPipeline`` and
``datasets/iterator.AsyncDataSetIterator`` (one stats shape for every
staged iterator, so ``net.pipeline_stats`` reads the same regardless of
which staging wrapper fed the fit).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np


def dataset_nbytes(ds) -> int:
    """Host bytes of one delivered minibatch (features + labels + masks;
    MultiDataSet lists included). Counts the HOST-side payload the
    pipeline moved — device placement does not change it."""
    total = 0

    def add(a):
        nonlocal total
        if a is not None:
            total += int(np.asarray(a).nbytes)

    if hasattr(ds, "features_list"):  # MultiDataSet
        for a in ds.features_list:
            add(a)
        for a in ds.labels_list:
            add(a)
        for group in (ds.features_masks, ds.labels_masks):
            for a in group or []:
                add(a)
    else:
        add(getattr(ds, "features", None))
        add(getattr(ds, "labels", None))
        add(getattr(ds, "features_mask", None))
        add(getattr(ds, "labels_mask", None))
    return total


def dataset_num_examples(ds) -> int:
    try:
        return int(ds.num_examples())
    except Exception:  # noqa: BLE001 — telemetry must never break delivery
        return 0


class PipelineStats:
    """Thread-safe ingest counters. Producers (dispatcher/worker/stager
    threads) and the consumer update concurrently; ``snapshot()`` is the
    read surface (JSON-able, like ``DispatchStats.snapshot``)."""

    def __init__(self, workers: int = 0, queue_capacity: int = 0) -> None:
        self._lock = threading.Lock()
        self.workers = int(workers)
        self.queue_capacity = int(queue_capacity)
        self.batches = 0
        self.records = 0
        self.bytes = 0
        self.stall_seconds = 0.0
        self.producer_stall_seconds = 0.0
        self.wall_seconds = 0.0
        self.queue_depth = 0  # staged batches ready at the last delivery
        self.epochs = 0  # completed passes
        self.restores = 0  # restore_state() calls (resilience resumes)
        self._pass_start: Optional[float] = None

    # -- producer/consumer hooks -----------------------------------------
    def start_pass(self) -> None:
        with self._lock:
            self._pass_start = time.perf_counter()

    def end_pass(self) -> None:
        with self._lock:
            if self._pass_start is not None:
                self.wall_seconds += time.perf_counter() - self._pass_start
                self._pass_start = None
            self.epochs += 1

    def record_delivered(self, nbytes: int, records: int,
                         queue_depth: int = 0) -> None:
        """One batch reached the consumer. ``nbytes``/``records`` are
        measured on the HOST-side arrays BEFORE device staging (counting
        a staged jax array would force a device->host readback — the
        telemetry must never add a sync point to the hot path)."""
        with self._lock:
            self.batches += 1
            self.records += int(records)
            self.bytes += int(nbytes)
            self.queue_depth = int(queue_depth)

    def add_consumer_stall(self, seconds: float) -> None:
        with self._lock:
            self.stall_seconds += float(seconds)

    def add_producer_stall(self, seconds: float) -> None:
        with self._lock:
            self.producer_stall_seconds += float(seconds)

    def record_restore(self) -> None:
        with self._lock:
            self.restores += 1

    # -- read surface ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            wall = self.wall_seconds
            if self._pass_start is not None:  # mid-pass snapshot stays live
                wall += time.perf_counter() - self._pass_start
            out = {
                "workers": self.workers,
                "queue_capacity": self.queue_capacity,
                "batches": self.batches,
                "records": self.records,
                "bytes": self.bytes,
                "epochs": self.epochs,
                "restores": self.restores,
                "queue_depth": self.queue_depth,
                "wall_seconds": round(wall, 6),
                "stall_seconds": round(self.stall_seconds, 6),
                "producer_stall_seconds": round(
                    self.producer_stall_seconds, 6),
            }
        out["batches_per_sec"] = (
            round(out["batches"] / wall, 3) if wall > 0 else 0.0)
        out["records_per_sec"] = (
            round(out["records"] / wall, 1) if wall > 0 else 0.0)
        out["mb_per_sec"] = (
            round(out["bytes"] / 1e6 / wall, 3) if wall > 0 else 0.0)
        # fraction of the pass the TRAINING thread spent waiting on input
        # — the number the ROADMAP's "as fast as the hardware allows" cares
        # about (0.0 = the accelerator never starved)
        out["stall_fraction"] = (
            round(out["stall_seconds"] / wall, 4) if wall > 0 else 0.0)
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"PipelineStats({self.snapshot()})"
