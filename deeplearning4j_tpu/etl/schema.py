"""Typed column schema for the transform plane (DataVec ``Schema`` parity).

The reference outsources ingest typing to DataVec: a ``Schema`` is an
ordered list of typed columns and every ``TransformProcess`` step maps an
input schema to an output schema, so the pipeline's record layout is
checkable BEFORE any data flows (SURVEY.md section 2.1, the
``datasets/canova|datavec`` bridge note — the record-transform plane the
new framework "must therefore provide").

Kept deliberately small: the five column kinds the 2016-era readers
actually produce (numeric / integer / categorical / string / time), a
builder mirroring DataVec's ``Schema.Builder`` idiom, and JSON serde so a
fitted pipeline's schema can ride a checkpoint zip next to the normalizer
statistics (``utils/serialization.py``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence


class ColumnType:
    NUMERIC = "numeric"
    INTEGER = "integer"
    CATEGORICAL = "categorical"
    STRING = "string"
    TIME = "time"

    ALL = (NUMERIC, INTEGER, CATEGORICAL, STRING, TIME)


@dataclass
class ColumnSpec:
    """One typed column; ``categories`` is the closed label set for
    CATEGORICAL columns (DataVec ``CategoricalMetaData`` role — one-hot
    needs the full set up front, not whatever values a pass happened to
    see)."""

    name: str
    type: str = ColumnType.NUMERIC
    categories: Optional[List[str]] = field(default=None)

    def __post_init__(self):
        if self.type not in ColumnType.ALL:
            raise ValueError(f"unknown column type {self.type!r}")
        if self.type == ColumnType.CATEGORICAL and not self.categories:
            raise ValueError(
                f"categorical column {self.name!r} needs its category list")

    def to_spec(self) -> Dict:
        out = {"name": self.name, "type": self.type}
        if self.categories is not None:
            out["categories"] = list(self.categories)
        return out

    @staticmethod
    def from_spec(spec: Dict) -> "ColumnSpec":
        return ColumnSpec(spec["name"], spec.get("type", ColumnType.NUMERIC),
                          spec.get("categories"))


class Schema:
    """Ordered, name-indexed column list. Immutable by convention: the
    transform steps derive NEW schemas (``TransformProcess`` folds them
    left-to-right), never mutate one in place."""

    def __init__(self, columns: Sequence[ColumnSpec]):
        self.columns: List[ColumnSpec] = list(columns)
        self._index: Dict[str, int] = {}
        for i, c in enumerate(self.columns):
            if c.name in self._index:
                raise ValueError(f"duplicate column name {c.name!r}")
            self._index[c.name] = i

    # -- queries -----------------------------------------------------------
    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def num_columns(self) -> int:
        return len(self.columns)

    def has_column(self, name: str) -> bool:
        return name in self._index

    def index_of(self, name: str) -> int:
        if name not in self._index:
            raise KeyError(
                f"no column {name!r}; schema has {self.names()}")
        return self._index[name]

    def column(self, name: str) -> ColumnSpec:
        return self.columns[self.index_of(name)]

    # -- serde -------------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({"columns": [c.to_spec() for c in self.columns]})

    @staticmethod
    def from_json(s: str) -> "Schema":
        data = json.loads(s)
        return Schema([ColumnSpec.from_spec(c) for c in data["columns"]])

    def __eq__(self, other) -> bool:
        return (isinstance(other, Schema)
                and [c.to_spec() for c in self.columns]
                == [c.to_spec() for c in other.columns])

    def __repr__(self) -> str:
        return f"Schema({[(c.name, c.type) for c in self.columns]})"

    # -- builder (DataVec Schema.Builder idiom) ----------------------------
    @staticmethod
    def builder() -> "SchemaBuilder":
        return SchemaBuilder()


class SchemaBuilder:
    def __init__(self) -> None:
        self._columns: List[ColumnSpec] = []

    def add_numeric_column(self, *names: str) -> "SchemaBuilder":
        for n in names:
            self._columns.append(ColumnSpec(n, ColumnType.NUMERIC))
        return self

    def add_integer_column(self, *names: str) -> "SchemaBuilder":
        for n in names:
            self._columns.append(ColumnSpec(n, ColumnType.INTEGER))
        return self

    def add_categorical_column(self, name: str,
                               categories: Sequence[str]) -> "SchemaBuilder":
        self._columns.append(
            ColumnSpec(name, ColumnType.CATEGORICAL,
                       [str(c) for c in categories]))
        return self

    def add_string_column(self, *names: str) -> "SchemaBuilder":
        for n in names:
            self._columns.append(ColumnSpec(n, ColumnType.STRING))
        return self

    def add_time_column(self, *names: str) -> "SchemaBuilder":
        for n in names:
            self._columns.append(ColumnSpec(n, ColumnType.TIME))
        return self

    def build(self) -> Schema:
        return Schema(self._columns)
