"""Post-training int8 calibration: streaming activation statistics over a
calibration iterator, the fitted-stats discipline of etl/normalize.py
(reference DataVec's fit-then-serialize normalizer flow,
NormalizerStandardize.java fit(DataSetIterator)) applied to QUANTIZATION
scales instead of feature moments.

:class:`QuantCalibrator` drives the net's ``feed_forward`` over the
calibration batches and accumulates, per layer input, a streaming
``[n, sum, sumsq, absmax]`` accumulator (the NormalizerStandardize
``_acc_one`` idiom — exact single-pass merge, no activation retained).
``absmax / 127`` becomes the per-tensor symmetric activation scale
(Jacob et al., CVPR 2018); the mean/std ride along for audit so a
saturated calibration (absmax >> std) is visible in the serialized spec.

The fitted :class:`QuantSpec` serializes into the ModelSerializer zip as
``quant.json`` exactly like ``normalizer.json`` (utils/serialization), and
carries a small GATE SAMPLE of calibration rows so ``ModelRegistry.load``
can measure the int8-vs-f32 output delta self-contained at load time.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["QuantSpec", "QuantCalibrator", "quant_spec_from_json"]

_SPEC_VERSION = 1
_GATE_SAMPLE_ROWS = 32


class QuantSpec:
    """Fitted calibration artifact: per-layer activation scales + audit
    moments + the gate sample. Serde mirrors DataNormalization.state_dict
    (class-tagged JSON, arrays as lists) so the zip entry stays
    human-readable beside normalizer.json."""

    def __init__(self, act_scales: List[Optional[float]],
                 sample: Optional[np.ndarray] = None,
                 audit: Optional[List[Optional[Dict[str, float]]]] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self.act_scales = list(act_scales)
        self.sample = None if sample is None else np.asarray(
            sample, np.float32)
        self.audit = list(audit) if audit is not None else [None] * len(
            self.act_scales)
        self.meta = dict(meta or {})
        self.meta.setdefault("version", _SPEC_VERSION)

    def state_dict(self) -> dict:
        return {
            "class": type(self).__name__,
            "act_scales": [None if s is None else float(s)
                           for s in self.act_scales],
            "sample": None if self.sample is None else self.sample.tolist(),
            "sample_shape": None if self.sample is None
            else list(self.sample.shape),
            "audit": self.audit,
            "meta": self.meta,
        }

    def to_json(self) -> str:
        return json.dumps(self.state_dict(), sort_keys=True)

    @classmethod
    def from_state_dict(cls, state: dict) -> "QuantSpec":
        sample = state.get("sample")
        if sample is not None:
            sample = np.asarray(sample, np.float32)
            shape = state.get("sample_shape")
            if shape:
                sample = sample.reshape(shape)
        return cls(state.get("act_scales") or [], sample,
                   state.get("audit"), state.get("meta"))


def quant_spec_from_json(payload: str) -> QuantSpec:
    state = json.loads(payload)
    if state.get("class") not in (None, "QuantSpec"):
        raise ValueError(f"not a QuantSpec payload: {state.get('class')!r}")
    return QuantSpec.from_state_dict(state)


class QuantCalibrator:
    """Streaming calibration pass: ``fit(net, batches)`` feeds every
    calibration batch through the net's inference forward and folds each
    layer INPUT activation into an exact single-pass accumulator
    (etl/normalize.NormalizerStandardize._acc_one shape: n/sum/sumsq,
    plus absmax). Activations are reduced per batch and discarded —
    calibration memory is O(layers), not O(rows).

    Reference role: the DataVec normalizer fit loop
    (NormalizerStandardize.java fit) repurposed for quantization scales.
    """

    def __init__(self, sample_rows: int = _GATE_SAMPLE_ROWS):
        self.sample_rows = int(sample_rows)
        self._acc: Optional[List[List[float]]] = None  # [n,sum,sumsq,absmax]
        self._sample: Optional[np.ndarray] = None
        self._layers = 0

    # -- streaming accumulation -------------------------------------------
    def _fold(self, i: int, x: np.ndarray) -> None:
        x64 = np.asarray(x, np.float64)
        acc = self._acc[i]
        acc[0] += float(x64.size)
        acc[1] += float(x64.sum())
        acc[2] += float(np.square(x64).sum())
        acc[3] = max(acc[3], float(np.abs(x64).max()) if x64.size else 0.0)

    def fit_batch(self, net, features) -> "QuantCalibrator":
        """Fold one calibration batch. Layer i's scale is computed from
        its INPUT activation acts[i] (feed_forward returns [input, layer0
        out, ...]); absmax is reshape-invariant, so the pre-preprocessor
        view is exact for the flatten/reshape preprocessors between conv
        and dense stacks."""
        feats = np.asarray(features)
        acts = net.feed_forward(feats, train=False)
        n_layers = len(acts) - 1
        if self._acc is None:
            self._acc = [[0.0, 0.0, 0.0, 0.0] for _ in range(n_layers)]
            self._layers = n_layers
        for i in range(n_layers):
            self._fold(i, np.asarray(acts[i]))
        if self._sample is None or self._sample.shape[0] < self.sample_rows:
            have = 0 if self._sample is None else self._sample.shape[0]
            take = np.asarray(feats[: self.sample_rows - have], np.float32)
            self._sample = take if self._sample is None else np.concatenate(
                [self._sample, take], axis=0)
        return self

    def fit(self, net, data) -> "QuantCalibrator":
        """``data``: a DataSetIterator-style iterable (objects with
        ``.features``), plain arrays, or an iterable of arrays."""
        batches = [data] if hasattr(data, "ndim") else data
        for b in batches:
            feats = getattr(b, "features", b)
            self.fit_batch(net, feats)
        if hasattr(data, "reset"):
            data.reset()
        return self

    # -- finalize ----------------------------------------------------------
    def spec(self, net=None) -> QuantSpec:
        if self._acc is None:
            raise RuntimeError("QuantCalibrator.spec() before fit()")
        scales: List[Optional[float]] = []
        audit: List[Optional[Dict[str, float]]] = []
        for n, s, sq, absmax in self._acc:
            if n <= 0 or absmax <= 0.0:
                scales.append(None)
                audit.append(None)
                continue
            mean = s / n
            var = max(sq / n - mean * mean, 0.0)
            scales.append(absmax / 127.0)
            audit.append({"absmax": absmax, "mean": mean,
                          "std": float(np.sqrt(var)), "rows": n})
        meta: Dict[str, Any] = {"version": _SPEC_VERSION,
                                "layers": self._layers}
        if net is not None:
            meta["net_layers"] = len(getattr(net, "layers", []) or [])
        return QuantSpec(scales, self._sample, audit, meta)
