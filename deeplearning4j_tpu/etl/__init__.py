"""DataVec-parity ETL subsystem (L5): transform plane, fitted
normalizers, and the overlapped InputPipeline runtime.

The reference outsources its whole ingest plane to Canova/DataVec
(SURVEY.md section 2.1 — record readers, record->minibatch assembly,
transforms; ~7.5k LoC the framework "must therefore provide"). The thin
readers live in ``datasets/``; this package is the plane ABOVE them:

  schema/transforms   typed columns + TransformProcess compiled to one
                      executable record function (DataVec parity);
  normalize           fitted DataNormalization (standardize / min-max /
                      image scaler) with fit/transform/revert and
                      checkpoint-zip serde;
  pipeline            InputPipeline: parallel transform + vectorized
                      assembly off the training thread, deterministic
                      order, double-buffered device staging,
                      checkpointable delivered-batch cursor;
  stats               PipelineStats telemetry (net.pipeline_stats).
"""

from deeplearning4j_tpu.etl.normalize import (
    DataNormalization,
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    normalizer_from_json,
)
from deeplearning4j_tpu.etl.pipeline import InputPipeline, maybe_wrap
from deeplearning4j_tpu.etl.schema import ColumnType, Schema
from deeplearning4j_tpu.etl.stats import PipelineStats
from deeplearning4j_tpu.etl.transforms import TransformProcess

__all__ = [
    "ColumnType",
    "DataNormalization",
    "ImagePreProcessingScaler",
    "InputPipeline",
    "NormalizerMinMaxScaler",
    "NormalizerStandardize",
    "PipelineStats",
    "Schema",
    "TransformProcess",
    "maybe_wrap",
    "normalizer_from_json",
]
