"""Fitted dataset normalizers (DataNormalization parity).

The reference delegates dataset statistics to nd4j's DataNormalization
family — ``NormalizerStandardize`` / ``NormalizerMinMaxScaler`` /
``ImagePreProcessingScaler`` — with the ``fit(iterator)`` /
``transform(dataset)`` / ``revert`` lifecycle: statistics are fitted ONCE
over the training stream and then applied identically at train, eval,
serving and resume time. (The per-batch ``DataSet`` utilities in
``datasets/iterator.py`` — normalizeZeroMeanZeroUnitVariance etc. —
normalize each batch by ITS OWN statistics, which silently changes the
model's input distribution batch to batch; the fitted family is the
correct production surface.)

Statistics accumulate STREAMING (count/sum/sumsq, running min/max) in
float64 over the final axis — one pass over any iterator, no
materialization — so fitting over a 10M-row reader costs O(columns)
memory. ``transform`` mutates a DataSet in place (the reference
preProcess contract) and preserves an existing floating dtype (the
forced-x64 test regime rule, ``datasets/iterator._float_dtype_of``);
``transform_array`` is the PURE variant serving uses (a shared request
buffer must never be normalized in place).

Serde: ``to_json``/``normalizer_from_json`` round-trip every fitted
statistic; ``utils/serialization.py`` writes it into the ModelSerializer
zip as the optional ``normalizer.json`` section so serving and resume
apply the SAME statistics the model was trained under.
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np


def _float_dtype_of(a) -> np.dtype:
    dt = np.asarray(a).dtype
    return dt if np.issubdtype(dt, np.floating) else np.dtype(np.float32)


def _column_stats_axes(x: np.ndarray):
    """Statistics per FINAL-axis column, accumulated over every leading
    axis: [N,F] -> per-feature, [N,T,F] -> per-feature over all timesteps,
    [N,H,W,C] -> per-channel (the reference's columnwise contract extended
    to the layouts the containers actually feed)."""
    return tuple(range(x.ndim - 1))


class DataNormalization:
    """fit / transform / revert lifecycle. Also usable as a DataSet
    pre-processor (``pre_process`` alias — the reference attaches
    normalizers to iterators via setPreProcessor)."""

    _FIELDS = ()  # fitted statistics, in serde order (ndarray or None)

    def __init__(self, fit_labels: bool = False):
        self._fit_labels = bool(fit_labels)

    # -- configuration -----------------------------------------------------
    def fit_label(self, fit_labels: bool = True) -> "DataNormalization":
        """Also fit/transform the LABELS (regression targets — the
        reference's fitLabel(true))."""
        self._fit_labels = bool(fit_labels)
        return self

    @property
    def is_fit(self) -> bool:
        raise NotImplementedError

    # -- fitting -----------------------------------------------------------
    def fit(self, data) -> "DataNormalization":
        """Accumulate statistics over a DataSetIterator (one full pass,
        reset() after), a single DataSet, or a bare feature array."""
        if hasattr(data, "features"):  # DataSet
            self._accumulate(np.asarray(data.features),
                             np.asarray(data.labels)
                             if self._fit_labels else None)
        elif hasattr(data, "__iter__") and not hasattr(data, "shape"):
            for ds in data:
                self._accumulate(np.asarray(ds.features),
                                 np.asarray(ds.labels)
                                 if self._fit_labels else None)
            if hasattr(data, "reset"):
                data.reset()
        else:
            self._accumulate(np.asarray(data), None)
        self._finalize()
        return self

    def _accumulate(self, features: np.ndarray,
                    labels: Optional[np.ndarray]) -> None:
        raise NotImplementedError

    def _finalize(self) -> None:
        pass

    # -- application -------------------------------------------------------
    def transform(self, ds):
        """Normalize a DataSet IN PLACE (returns it), or return the
        normalized copy of a bare array."""
        if hasattr(ds, "features"):
            ds.features = self.transform_array(ds.features)
            if self._fit_labels and ds.labels is not None:
                ds.labels = self.transform_array(ds.labels, labels=True)
            return ds
        return self.transform_array(ds)

    # the DataSetPreProcessor role (reference preProcess(DataSet))
    def pre_process(self, ds):
        return self.transform(ds)

    def transform_array(self, x, labels: bool = False) -> np.ndarray:
        """PURE normalization of a bare array (serving/predict path)."""
        self._require_fit()
        x = np.asarray(x)
        out = self._apply(np.asarray(x, np.float64), labels=labels)
        return out.astype(_float_dtype_of(x))

    def revert(self, ds):
        """Inverse transform (reference revert/revertFeatures) — DataSet
        in place, or a bare array copy."""
        if hasattr(ds, "features"):
            ds.features = self.revert_array(ds.features)
            if self._fit_labels and ds.labels is not None:
                ds.labels = self.revert_array(ds.labels, labels=True)
            return ds
        return self.revert_array(ds)

    def revert_array(self, x, labels: bool = False) -> np.ndarray:
        self._require_fit()
        x = np.asarray(x)
        out = self._unapply(np.asarray(x, np.float64), labels=labels)
        return out.astype(_float_dtype_of(x))

    def _apply(self, x64: np.ndarray, labels: bool) -> np.ndarray:
        raise NotImplementedError

    def _unapply(self, x64: np.ndarray, labels: bool) -> np.ndarray:
        raise NotImplementedError

    def _require_fit(self) -> None:
        if not self.is_fit:
            raise RuntimeError(
                f"{type(self).__name__} used before fit() — fitted "
                "statistics are the whole point (per-batch statistics "
                "drift; see datasets.DataSet utilities for that)")

    # -- serde -------------------------------------------------------------
    def state_dict(self) -> dict:
        out = {"class": type(self).__name__,
               "fit_labels": self._fit_labels}
        for f in self._FIELDS:
            v = getattr(self, f)
            out[f] = None if v is None else np.asarray(v).tolist()
        return out

    def load_state_dict(self, state: dict) -> "DataNormalization":
        self._fit_labels = bool(state.get("fit_labels", False))
        for f in self._FIELDS:
            v = state.get(f)
            setattr(self, f,
                    None if v is None else np.asarray(v, np.float64))
        return self

    def to_json(self) -> str:
        return json.dumps(self.state_dict())


class NormalizerStandardize(DataNormalization):
    """Per-column zero-mean/unit-variance by the FITTED statistics
    (reference NormalizerStandardize). Streaming count/sum/sumsq;
    population std; zero-variance columns divide by 1."""

    _FIELDS = ("mean", "std", "label_mean", "label_std")

    def __init__(self, fit_labels: bool = False):
        super().__init__(fit_labels)
        self.mean = self.std = None
        self.label_mean = self.label_std = None
        self._acc = None  # (n, sum, sumsq) per stream
        self._lacc = None

    @property
    def is_fit(self) -> bool:
        return self.mean is not None

    @staticmethod
    def _acc_one(acc, x: np.ndarray):
        x64 = np.asarray(x, np.float64)
        axes = _column_stats_axes(x64)
        n = int(np.prod([x64.shape[a] for a in axes])) if axes else 1
        s = x64.sum(axis=axes)
        sq = np.square(x64).sum(axis=axes)
        if acc is None:
            return [n, s, sq]
        acc[0] += n
        acc[1] += s
        acc[2] += sq
        return acc

    def _accumulate(self, features, labels):
        self._acc = self._acc_one(self._acc, features)
        if labels is not None:
            self._lacc = self._acc_one(self._lacc, labels)

    @staticmethod
    def _fin_one(acc):
        n, s, sq = acc
        mean = s / n
        var = np.maximum(sq / n - np.square(mean), 0.0)
        std = np.sqrt(var)
        return mean, np.where(std == 0, 1.0, std)

    def _finalize(self):
        self.mean, self.std = self._fin_one(self._acc)
        if self._lacc is not None:
            self.label_mean, self.label_std = self._fin_one(self._lacc)

    def _stats(self, labels: bool):
        if labels:
            if self.label_mean is None:
                raise RuntimeError("labels were not fitted "
                                   "(fit_label(True) before fit)")
            return self.label_mean, self.label_std
        return self.mean, self.std

    def _apply(self, x64, labels):
        mean, std = self._stats(labels)
        return (x64 - mean) / std

    def _unapply(self, x64, labels):
        mean, std = self._stats(labels)
        return x64 * std + mean


class NormalizerMinMaxScaler(DataNormalization):
    """Per-column scale into [lo, hi] (default [0, 1]) by the FITTED
    min/max (reference NormalizerMinMaxScaler); constant columns map to
    lo."""

    _FIELDS = ("feature_min", "feature_max", "label_min", "label_max")

    def __init__(self, lo: float = 0.0, hi: float = 1.0,
                 fit_labels: bool = False):
        super().__init__(fit_labels)
        self.lo, self.hi = float(lo), float(hi)
        self.feature_min = self.feature_max = None
        self.label_min = self.label_max = None

    @property
    def is_fit(self) -> bool:
        return self.feature_min is not None

    def _accumulate(self, features, labels):
        def upd(cur_min, cur_max, x):
            x64 = np.asarray(x, np.float64)
            axes = _column_stats_axes(x64)
            mn, mx = x64.min(axis=axes), x64.max(axis=axes)
            if cur_min is None:
                return mn, mx
            return np.minimum(cur_min, mn), np.maximum(cur_max, mx)

        self.feature_min, self.feature_max = upd(
            self.feature_min, self.feature_max, features)
        if labels is not None:
            self.label_min, self.label_max = upd(
                self.label_min, self.label_max, labels)

    def _stats(self, labels: bool):
        if labels:
            if self.label_min is None:
                raise RuntimeError("labels were not fitted "
                                   "(fit_label(True) before fit)")
            lo, hi = self.label_min, self.label_max
        else:
            lo, hi = self.feature_min, self.feature_max
        span = hi - lo
        return lo, np.where(span == 0, 1.0, span)

    def _apply(self, x64, labels):
        mn, span = self._stats(labels)
        return (x64 - mn) / span * (self.hi - self.lo) + self.lo

    def _unapply(self, x64, labels):
        mn, span = self._stats(labels)
        return (x64 - self.lo) / (self.hi - self.lo) * span + mn

    def state_dict(self) -> dict:
        out = super().state_dict()
        out["lo"], out["hi"] = self.lo, self.hi
        return out

    def load_state_dict(self, state: dict):
        super().load_state_dict(state)
        self.lo = float(state.get("lo", 0.0))
        self.hi = float(state.get("hi", 1.0))
        return self


class ImagePreProcessingScaler(DataNormalization):
    """Pixel scaler: [0, 2^bits - 1] -> [lo, hi] (reference
    ImagePreProcessingScaler, default 8-bit -> [0, 1]). The statistics
    are CLOSED-FORM — fit() is a no-op kept for lifecycle uniformity."""

    _FIELDS = ()

    def __init__(self, lo: float = 0.0, hi: float = 1.0,
                 max_bits: int = 8):
        super().__init__(fit_labels=False)
        self.lo, self.hi = float(lo), float(hi)
        self.max_bits = int(max_bits)

    @property
    def is_fit(self) -> bool:
        return True

    def fit(self, data) -> "ImagePreProcessingScaler":
        return self  # closed-form; nothing to accumulate

    def _accumulate(self, features, labels):  # pragma: no cover
        pass

    @property
    def _max_val(self) -> float:
        return float(2 ** self.max_bits - 1)

    def _apply(self, x64, labels):
        return x64 / self._max_val * (self.hi - self.lo) + self.lo

    def _unapply(self, x64, labels):
        return (x64 - self.lo) / (self.hi - self.lo) * self._max_val

    def state_dict(self) -> dict:
        out = super().state_dict()
        out.update(lo=self.lo, hi=self.hi, max_bits=self.max_bits)
        return out

    def load_state_dict(self, state: dict):
        self.lo = float(state.get("lo", 0.0))
        self.hi = float(state.get("hi", 1.0))
        self.max_bits = int(state.get("max_bits", 8))
        return self


_NORMALIZER_CLASSES = {
    c.__name__: c for c in (NormalizerStandardize, NormalizerMinMaxScaler,
                            ImagePreProcessingScaler)
}


def normalizer_from_json(s: str) -> DataNormalization:
    """Restore any normalizer from its ``to_json`` form (dispatches on the
    recorded class — the ``normalizer.json`` zip-section reader)."""
    state = json.loads(s)
    cls = state.get("class")
    if cls not in _NORMALIZER_CLASSES:
        raise ValueError(f"unknown normalizer class {cls!r} "
                         f"(known: {sorted(_NORMALIZER_CLASSES)})")
    return _NORMALIZER_CLASSES[cls]().load_state_dict(state)
