"""Standalone training-side metrics exporter (stdlib HTTP).

The serving engine already has an HTTP surface to hang /metrics on; the
TRAINING side (fit/fleet runs) had none — its five ledgers were only
reachable from inside the process. This exporter is the training-side
sibling of the reference UI server (deeplearning4j-ui/.../UiServer.java
— same stdlib-http-in-a-daemon-thread shape as ui/server.py, same
atomic-snapshot discipline: each GET renders from ONE consistent
snapshot taken at request time, handler threads never observe
mid-update state):

  GET /metrics        Prometheus text exposition (format 0.0.4) of the
                      default MetricsRegistry — first-class metrics plus
                      every registered ledger view in one scrape
  GET /metrics.json   the same registry as a JSON dump
  GET /journal        the flight-recorder ring as JSONL (live view; the
                      on-disk file is for post-mortem)
  GET /health         liveness

Knob: ``DL4J_TPU_OBS_PORT`` (default 0 = OS-assigned ephemeral port —
the examples/tests read ``exporter.port``; a production run pins it).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deeplearning4j_tpu.ops import env as envknob

ENV_PORT = "DL4J_TPU_OBS_PORT"

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _env_port(default: int = 0) -> int:
    return envknob.get_int(ENV_PORT, default)


class MetricsExporter:
    """See module docstring. ``registry``/``journal`` default to the
    process-wide singletons so `MetricsExporter().start()` beside any
    fit loop exports everything the process registered."""

    def __init__(self, registry=None, journal=None,
                 port: Optional[int] = None):
        if registry is None:
            from deeplearning4j_tpu.obs.registry import default_registry

            registry = default_registry()
        if journal is None:
            from deeplearning4j_tpu.obs.journal import default_journal

            journal = default_journal()
        self.registry = registry
        self.journal = journal
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if self.path.split("?")[0] == "/metrics":
                        self._send(200,
                                   exporter.registry.render_prometheus()
                                   .encode(),
                                   PROMETHEUS_CONTENT_TYPE)
                    elif self.path == "/metrics.json":
                        self._send(200,
                                   json.dumps(exporter.registry.snapshot(),
                                              default=str).encode(),
                                   "application/json")
                    elif self.path == "/journal":
                        body = "".join(
                            json.dumps(e, default=str) + "\n"
                            for e in exporter.journal.events())
                        self._send(200, body.encode(),
                                   "application/x-ndjson")
                    elif self.path == "/health":
                        self._send(200, b'{"ok": true}',
                                   "application/json")
                    else:
                        self._send(404, b"not found", "text/plain")
                except Exception as e:  # noqa: BLE001 — export boundary
                    self._send(500, f"{type(e).__name__}: {e}".encode(),
                               "text/plain")

        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", _env_port() if port is None else int(port)),
            Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "MetricsExporter":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="obs-exporter")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
