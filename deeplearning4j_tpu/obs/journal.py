"""Flight-recorder journal: the last-N-events timeline a dead run leaves.

A preempted or crashed training process takes its in-memory telemetry
with it — the five ledgers, the span ring, the listener snapshots all
die with the interpreter. The reference's answer is the Spark stats
timeline persisted through the StateTracker (SURVEY: stats storage,
dl4j-spark training stats); ours is this module: a bounded in-memory
ring of JSONL-able events that is

  * CHEAP to append (lock + deque append; no IO on the hot path),
  * periodically flushed (at most every ``DL4J_TPU_OBS_FLUSH_S``
    seconds, piggybacked on appends — an idle process writes nothing),
  * FSYNC'd on preemption through the existing SIGTERM path
    (resilience/trainer.ResilientTrainer checkpoints-before-death and
    flushes this journal in the same breath),

so the post-mortem of a dead run starts from a readable timeline: the
last N spans, checkpoint commits, membership epochs, preemption marker.

Writes are atomic (tmp + rename, the resilience/checkpoint.py
discipline) and flush-serialized: a crash mid-flush leaves the previous
journal, never a torn one. The file is the RING, rewritten whole each
flush — bounded size by construction (``DL4J_TPU_OBS_JOURNAL_N`` events,
default 4096, plus a small pinned side ring). Rare MARKER events
(checkpoint commits, membership epochs, preempt/resume — any non-span
kind) are pinned in that side ring so a flood of per-dispatch spans
cannot evict the anchors a post-mortem timeline needs.

Gated like the tracer: :func:`event` is a no-op unless ``DL4J_TPU_OBS``
is on, so instrumented modules call it unconditionally.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.ops import env as envknob

ENV_JOURNAL = "DL4J_TPU_OBS_JOURNAL"
ENV_JOURNAL_N = "DL4J_TPU_OBS_JOURNAL_N"
ENV_FLUSH_S = "DL4J_TPU_OBS_FLUSH_S"


def default_journal_path() -> str:
    """Env path wins verbatim; the default gains a per-process suffix
    when this process is a multihost/fleet member (the multihost env
    contract's process id — read through the jax-free knob table,
    ops/env.py): N OS-process workers sharing one cwd must not
    last-writer-wins clobber the coordinator's checkpoint/membership/
    preempt timeline with their own span-only rings."""
    v = envknob.raw(ENV_JOURNAL, "").strip()
    if v:
        return v
    pid = envknob.raw("DL4J_TPU_PROCESS_ID", "").strip()
    suffix = f".p{pid}" if pid else ""
    return os.path.join(os.getcwd(), f".obs_journal{suffix}.jsonl")


class FlightRecorder:
    """Bounded event ring + crash-safe JSONL persistence."""

    def __init__(self, path: Optional[str] = None,
                 capacity: Optional[int] = None,
                 flush_interval_s: Optional[float] = None):
        self.path = path or default_journal_path()
        self.capacity = (capacity if capacity is not None
                         else max(16, envknob.get_int(ENV_JOURNAL_N, 4096)))
        self.flush_interval_s = (
            flush_interval_s if flush_interval_s is not None
            else envknob.get_float(ENV_FLUSH_S, 5.0))
        self._lock = threading.Lock()
        # serializes the tmp-write+rename: concurrent flushes (a periodic
        # background flush racing the preemption fsync) share one tmp
        # path per pid — unserialized they would truncate each other's
        # half-written file and install a torn journal at the exact
        # moment it matters
        self._flush_lock = threading.Lock()
        self._bg_pending = False
        self._ring: deque = deque(maxlen=self.capacity)
        # non-span MARKER events (checkpoint commits, membership epochs,
        # preempt/resume) ride a pinned side ring: per-dispatch spans
        # enter at hundreds/sec and would turn the main ring over in
        # under a minute, evicting exactly the rare events a post-mortem
        # needs to anchor the timeline
        self._markers: deque = deque(
            maxlen=min(self.capacity, max(16, self.capacity // 16)))
        self._seq = 0
        self._dirty = False
        self._last_flush = time.monotonic()
        self.flushes = 0

    # -- recording --------------------------------------------------------
    def record(self, kind: str, **fields) -> Dict[str, Any]:
        """Append one event to the ring. ``t`` is wall-clock (timeline
        correlation with external logs), ``mono`` the monotonic clock
        (durations across events of one process)."""
        ev = {"seq": None, "kind": kind, "t": round(time.time(), 6),
              "mono": round(time.perf_counter(), 6)}
        ev.update(fields)
        self.append(ev)
        return ev

    def append(self, ev: Dict[str, Any]) -> None:
        """Light-path append for PRE-stamped events — the tracer's
        finished spans already carry ``t_wall``/``t_mono``, so re-reading
        both clocks and merging a second dict would be pure hot-path
        waste. Assigns ``seq`` and rings; same flush policy as record."""
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
            if ev.get("kind") != "span":
                self._markers.append(ev)
            self._dirty = True
            due = (time.monotonic() - self._last_flush
                   >= self.flush_interval_s and not self._bg_pending)
            if due:
                self._bg_pending = True
        if due:
            # periodic persistence runs on a short-lived daemon thread —
            # the recording thread (a training step, a batcher worker)
            # must never pay the multi-ms JSONL rewrite; only the
            # explicit preemption/exit flush is synchronous
            try:
                threading.Thread(target=self._bg_flush, daemon=True,
                                 name="obs-journal-flush").start()
            except RuntimeError:
                # interpreter teardown / thread exhaustion: journaling
                # is evidence, never a crash — and the pending flag must
                # not wedge shut or periodic flushing dies for good
                with self._lock:
                    self._bg_pending = False

    def _bg_flush(self) -> None:
        try:
            self.flush()
        finally:
            with self._lock:
                self._bg_pending = False

    # -- persistence ------------------------------------------------------
    def flush(self, fsync: bool = False) -> Optional[str]:
        """Rewrite the journal file from the ring (tmp + rename, optional
        fsync — the preemption path passes ``fsync=True`` so the timeline
        survives the power-off semantics of a pod eviction). Returns the
        path written, or None when there was nothing new."""
        with self._flush_lock:
            # ring snapshot INSIDE the flush lock: two racing flushes
            # must not let an older snapshot land after a newer one
            # (the file would regress to a stale timeline)
            with self._lock:
                if not self._dirty and not fsync:
                    return None
                events = self._merged_locked()
                self._dirty = False
                self._last_flush = time.monotonic()
            tmp = f"{self.path}.tmp-{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    for ev in events:
                        f.write(json.dumps(ev, default=str) + "\n")
                    f.flush()
                    if fsync:
                        os.fsync(f.fileno())
                os.replace(tmp, self.path)
                if fsync:
                    try:
                        fd = os.open(os.path.dirname(self.path) or ".",
                                     os.O_RDONLY)
                        try:
                            os.fsync(fd)
                        finally:
                            os.close(fd)
                    except OSError:
                        pass
            except OSError:
                # journaling is evidence, never a crash; no tmp litter
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return None
        with self._lock:
            self.flushes += 1
        return self.path

    def _merged_locked(self) -> List[Dict[str, Any]]:
        """Main ring + pinned markers, seq-ordered and deduped (a recent
        marker sits in both rings) — the one timeline every read surface
        and every flush presents."""
        merged = {e["seq"]: e for e in self._markers}
        merged.update({e["seq"]: e for e in self._ring})
        return [merged[s] for s in sorted(merged)]

    # -- reading ----------------------------------------------------------
    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = self._merged_locked()
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        return out

    @staticmethod
    def load(path: str) -> List[Dict[str, Any]]:
        """Read a journal back (post-mortem). Tolerates a torn final line
        (should not happen under the atomic flush, but a journal is the
        one file you read AFTER something already went wrong)."""
        out = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            return []
        return out


_DEFAULT: Optional[FlightRecorder] = None
_DEFAULT_LOCK = threading.Lock()


def default_journal() -> FlightRecorder:
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = FlightRecorder()
    return _DEFAULT


def event(kind: str, **fields) -> None:
    """Gated event append: no-op unless DL4J_TPU_OBS is on, so the
    instrumented seams (checkpoint commit, membership epoch, preemption)
    call it unconditionally."""
    from deeplearning4j_tpu.obs.trace import obs_enabled

    if obs_enabled():
        default_journal().record(kind, **fields)


def flush(fsync: bool = False) -> Optional[str]:
    """Gated flush — the SIGTERM path's one-liner."""
    from deeplearning4j_tpu.obs.trace import obs_enabled

    if obs_enabled():
        return default_journal().flush(fsync=fsync)
    return None
