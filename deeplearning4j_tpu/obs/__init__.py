"""Unified observability plane: spans, one metrics registry, flight
recorder, Prometheus export.

The reference's observability spine is the IterationListener chain
(deeplearning4j-core/.../optimize/api/IterationListener.java) feeding
the UI/stats plane (deeplearning4j-ui-parent, dl4j-spark stats). This
package is its TPU-native growth: the five existing telemetry ledgers
(dispatch/memory/pipeline/resilience/serving) register into ONE
:class:`MetricsRegistry`; a default-off span tracer (``DL4J_TPU_OBS``)
correlates them across subsystems; a bounded flight-recorder journal
survives preemption; Prometheus text exposition is served by both the
serving engine's ``/metrics`` and the standalone training
:class:`MetricsExporter`.

Everything here is host-side and stdlib-only — no jax import, no device
syncs (the listener-chain bulk-readback rule).
"""

from deeplearning4j_tpu.obs.exporter import MetricsExporter
from deeplearning4j_tpu.obs.journal import (
    FlightRecorder,
    default_journal,
    default_journal_path,
)
from deeplearning4j_tpu.obs.registry import (
    MetricsRegistry,
    default_registry,
    register_net,
)
from deeplearning4j_tpu.obs.trace import (
    ENV_OBS,
    Span,
    Tracer,
    obs_enabled,
    record_span,
    set_enabled,
    span,
    tracer,
)

__all__ = [
    "ENV_OBS",
    "FlightRecorder",
    "MetricsExporter",
    "MetricsRegistry",
    "Span",
    "Tracer",
    "default_journal",
    "default_journal_path",
    "default_registry",
    "obs_enabled",
    "record_span",
    "register_net",
    "set_enabled",
    "span",
    "tracer",
]
