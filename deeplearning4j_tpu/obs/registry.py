"""MetricsRegistry: one schema over the repo's five telemetry ledgers.

The reproduction grew five disjoint stats planes — ``net.dispatch_stats``
(ops/dispatch.DispatchStats), ``net.memory_stats`` (ops/memory),
``net.pipeline_stats`` (etl/stats), ``net.resilience_stats``
(resilience/trainer + parallel/fleet) and the serving counters
(serving/telemetry.ServingStats) — each with its own snapshot dict and no
shared export surface. The reference, by contrast, funnels everything
through one listener/stats spine into the UI plane
(deeplearning4j-ui-parent, dl4j-spark/.../stats/StatsUtils.java:65).

This registry is that spine: the existing ledgers REGISTER here (they
keep their types and their in-place update paths — zero hot-path change)
and become *views* the registry flattens into one counter/gauge/histogram
sample space at scrape time. First-class counters/gauges/histograms exist
for metrics born here (span durations, serving latency buckets).

Export: :meth:`render_prometheus` emits text exposition format 0.0.4
(label escaping, cumulative histogram buckets with ``+Inf``, ``_total``
counter naming) — served by the serving engine's ``/metrics`` (content
negotiation) and the standalone training exporter (obs/exporter.py).

Scrape-time discipline: ``collect()`` snapshots each ledger through its
own lock (``snapshot()``) and never mutates it — a scrape can race a
training step freely. Ledger owners are held by WEAK reference so a
test constructing hundreds of throwaway nets cannot grow the registry
without bound; dead owners are pruned at collect time.
"""

from __future__ import annotations

import math
import threading
import weakref
from typing import Any, Dict, Iterable, List, Optional, Tuple

# serving latency / span duration ladder (seconds): sub-ms to 10s covers
# a cache-hit CPU dispatch through a tunnel-window XLA compile
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _sanitize(segment: str) -> str:
    out = []
    for ch in str(segment):
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    s = "".join(out)
    return s if (s and not s[0].isdigit()) else "_" + s


def escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, double-quote and
    newline (exposition format spec, in this order — escaping the quote
    first would double-escape the backslashes it introduces)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(labels: _LabelKey, extra: Optional[str] = None) -> str:
    parts = [f'{k}="{escape_label_value(v)}"' for k, v in labels]
    if extra is not None:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Histogram:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out


class _LedgerEntry:
    __slots__ = ("owner_ref", "owner_label", "name", "ledger")

    def __init__(self, owner_ref, owner_label: str, name: str, ledger):
        self.owner_ref = owner_ref
        self.owner_label = owner_label
        self.name = name
        self.ledger = ledger


class MetricsRegistry:
    """See module docstring. Thread-safe; one instance is the process
    default (:func:`default_registry`) that nets, trainers and serving
    engines register into, so ONE scrape covers the whole process."""

    def __init__(self) -> None:
        # RLock, not Lock: weakref.finalize callbacks (_drop_owner) can
        # fire during a gc triggered by an allocation INSIDE a locked
        # section on the same thread — a plain Lock would self-deadlock
        self._lock = threading.RLock()
        self._counters: Dict[Tuple[str, _LabelKey], float] = {}
        self._gauges: Dict[Tuple[str, _LabelKey], float] = {}
        self._hists: Dict[Tuple[str, _LabelKey], _Histogram] = {}
        self._help: Dict[str, str] = {}
        # (id(owner), ledger name) -> entry; owner held weakly
        self._ledgers: Dict[Tuple[int, str], _LedgerEntry] = {}
        self._owner_labels: Dict[int, str] = {}
        self._owner_seq: Dict[str, int] = {}

    # -- first-class metrics ----------------------------------------------
    def counter(self, name: str, value: float = 1.0, **labels) -> None:
        """Increment a monotonic counter (negative increments raise — the
        monotonicity contract the Prometheus scraper depends on)."""
        if value < 0:
            raise ValueError(f"counter {name} increment must be >= 0")
        key = (name, _labels_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + float(value)

    def gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[(name, _labels_key(labels))] = float(value)

    def histogram(self, name: str, value: float,
                  buckets: Optional[Iterable[float]] = None,
                  **labels) -> None:
        key = (name, _labels_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = _Histogram(tuple(buckets) if buckets is not None
                               else DEFAULT_BUCKETS)
                self._hists[key] = h
            h.observe(value)

    def set_help(self, name: str, text: str) -> None:
        with self._lock:
            self._help[name] = text

    # -- ledger adoption ---------------------------------------------------
    def register_ledger(self, owner, name: str, ledger) -> None:
        """Adopt an existing stats ledger (anything with ``snapshot()``
        or a plain dict) as a registry view. Idempotent per (owner,
        name); re-registering replaces the ledger object (the containers
        re-adopt ``pipeline_stats`` per fit_iterator)."""
        with self._lock:
            oid = id(owner)
            label = self._owner_labels.get(oid)
            if label is None:
                cls = type(owner).__name__
                seq = self._owner_seq.get(cls, 0)
                self._owner_seq[cls] = seq + 1
                label = f"{cls}#{seq}"
                self._owner_labels[oid] = label
                # prune the label map when the owner dies (id() values
                # are reused after gc — a stale entry would mislabel the
                # next object allocated at the same address)
                try:
                    weakref.finalize(owner, self._drop_owner, oid)
                except TypeError:
                    pass  # non-weakrefable owners just stay keyed by id
            try:
                ref = weakref.ref(owner)
            except TypeError:
                ref = lambda _o=owner: _o  # noqa: E731 — strong fallback
            self._ledgers[(oid, name)] = _LedgerEntry(ref, label, name,
                                                      ledger)

    def _drop_owner(self, oid: int) -> None:
        with self._lock:
            self._owner_labels.pop(oid, None)
            for key in [k for k in self._ledgers if k[0] == oid]:
                del self._ledgers[key]

    def register_net(self, net) -> None:
        """Register every ``*_stats`` ledger currently attached to a
        container — the one adoption hook the containers/trainers call so
        a NEW ledger following the naming convention is picked up without
        touching this module (tests/test_obs.py asserts the convention
        holds, so a ledger added WITHOUT the re-register call fails
        loudly there)."""
        for attr, val in list(vars(net).items()):
            if attr.endswith("_stats") and val is not None:
                self.register_ledger(net, attr, val)

    def ledgers(self, owner=None) -> Dict[str, Any]:
        """name -> ledger for one owner (or 'label/name' -> ledger for
        all) — the registration-assertion surface for tests."""
        with self._lock:
            if owner is not None:
                return {e.name: e.ledger for (oid, _), e in
                        self._ledgers.items() if oid == id(owner)}
            return {f"{e.owner_label}/{e.name}": e.ledger
                    for e in self._ledgers.values()}

    # -- collection --------------------------------------------------------
    @staticmethod
    def _ledger_snapshot(ledger) -> Dict[str, Any]:
        if hasattr(ledger, "snapshot"):
            return ledger.snapshot()
        return dict(ledger)

    @staticmethod
    def _flatten(prefix: str, obj, out: List[Tuple[str, float]]) -> None:
        """Numeric leaves of a snapshot dict -> (metric_name, value),
        path segments sanitized and joined with '_'. Strings/None and
        other non-numerics are dropped (provenance labels ride the JSON
        surface, not the sample space)."""
        if isinstance(obj, bool):
            out.append((prefix, 1.0 if obj else 0.0))
        elif isinstance(obj, (int, float)):
            out.append((prefix, float(obj)))
        elif isinstance(obj, dict):
            for k, v in obj.items():
                MetricsRegistry._flatten(f"{prefix}_{_sanitize(k)}", v, out)

    def collect_ledger_samples(self) -> List[Tuple[str, _LabelKey, float]]:
        with self._lock:
            entries = list(self._ledgers.items())
        out: List[Tuple[str, _LabelKey, float]] = []
        dead: List[Tuple[int, str]] = []
        for key, e in entries:
            if e.owner_ref() is None:
                dead.append(key)
                continue
            base = e.name[:-len("_stats")] if e.name.endswith("_stats") \
                else e.name
            flat: List[Tuple[str, float]] = []
            try:
                self._flatten(f"dl4j_{_sanitize(base)}",
                              self._ledger_snapshot(e.ledger), flat)
            except Exception:  # noqa: BLE001 — a scrape must never crash training
                continue
            labels = _labels_key({"owner": e.owner_label})
            out.extend((name, labels, v) for name, v in flat)
        if dead:
            with self._lock:
                for key in dead:
                    self._ledgers.pop(key, None)
        return out

    # -- export ------------------------------------------------------------
    def render_prometheus(self) -> str:
        """Text exposition format 0.0.4. One atomic pass: first-class
        metrics are copied under the lock, ledger views snapshot through
        their own locks — the rendered page is internally consistent per
        metric family."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (h.buckets, h.cumulative(), h.sum, h.count)
                     for k, h in self._hists.items()}
            helps = dict(self._help)
        lines: List[str] = []

        def emit_meta(name: str, mtype: str) -> None:
            if name in helps:
                text = helps[name].replace("\\", "\\\\").replace("\n",
                                                                 "\\n")
                lines.append(f"# HELP {name} {text}")
            lines.append(f"# TYPE {name} {mtype}")

        by_name: Dict[str, List[Tuple[_LabelKey, float]]] = {}
        for (name, labels), v in sorted(counters.items()):
            by_name.setdefault(name, []).append((labels, v))
        for name in sorted(by_name):
            emit_meta(name, "counter")
            for labels, v in by_name[name]:
                lines.append(f"{name}_total{_render_labels(labels)} "
                             f"{_fmt(v)}")

        by_name = {}
        for (name, labels), v in sorted(gauges.items()):
            by_name.setdefault(name, []).append((labels, v))
        for name in sorted(by_name):
            emit_meta(name, "gauge")
            for labels, v in by_name[name]:
                lines.append(f"{name}{_render_labels(labels)} {_fmt(v)}")

        by_hist: Dict[str, List[Tuple[_LabelKey, tuple]]] = {}
        for (name, labels), data in sorted(hists.items()):
            by_hist.setdefault(name, []).append((labels, data))
        for name in sorted(by_hist):
            emit_meta(name, "histogram")
            for labels, (buckets, cum, total, count) in by_hist[name]:
                for b, c in zip(buckets, cum[:-1]):
                    le = _render_labels(labels, f'le="{_fmt(b)}"')
                    lines.append(f"{name}_bucket{le} {c}")
                le = _render_labels(labels, 'le="+Inf"')
                lines.append(f"{name}_bucket{le} {cum[-1]}")
                lines.append(f"{name}_sum{_render_labels(labels)} "
                             f"{_fmt(total)}")
                lines.append(f"{name}_count{_render_labels(labels)} "
                             f"{count}")

        ledger_by_name: Dict[str, List[Tuple[_LabelKey, float]]] = {}
        for name, labels, v in self.collect_ledger_samples():
            ledger_by_name.setdefault(name, []).append((labels, v))
        for name in sorted(ledger_by_name):
            # ledger views export as gauges: the underlying dicts hold
            # both monotone counts and level values (queue_depth), and a
            # ledger replaced mid-run (fit_iterator re-adoption) may
            # legitimately reset — gauge is the honest type claim
            emit_meta(name, "gauge")
            for labels, v in sorted(ledger_by_name[name]):
                lines.append(f"{name}{_render_labels(labels)} {_fmt(v)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able full dump (the exporter's /metrics.json surface)."""
        with self._lock:
            counters = {name: {"+".join(f"{k}={v}" for k, v in labels)
                               or "_": val
                               for (n2, labels), val in
                               self._counters.items() if n2 == name}
                        for name in {n for n, _ in self._counters}}
            gauges = {name: {"+".join(f"{k}={v}" for k, v in labels)
                             or "_": val
                             for (n2, labels), val in self._gauges.items()
                             if n2 == name}
                      for name in {n for n, _ in self._gauges}}
            hists = {}
            for (name, labels), h in self._hists.items():
                hists.setdefault(name, {})[
                    "+".join(f"{k}={v}" for k, v in labels) or "_"] = {
                        "buckets": list(h.buckets),
                        "counts": list(h.counts),
                        "sum": h.sum,
                        "count": h.count,
                }
            entries = list(self._ledgers.values())
        ledgers: Dict[str, Dict[str, Any]] = {}
        for e in entries:
            if e.owner_ref() is None:
                continue
            try:
                snap = self._ledger_snapshot(e.ledger)
            except Exception:  # noqa: BLE001 — scrape never crashes training
                continue
            ledgers.setdefault(e.owner_label, {})[e.name] = snap
        return {"counters": counters, "gauges": gauges,
                "histograms": hists, "ledgers": ledgers}


_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = MetricsRegistry()
    return _DEFAULT


def register_net(net) -> None:
    """Module-level convenience the containers call (nn/multilayer.py,
    nn/graph.py __init__ + the ledger-attach points)."""
    default_registry().register_net(net)
