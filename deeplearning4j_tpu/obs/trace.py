"""Structured span tracer: the always-available, default-off timeline.

The reference threads one observability spine through every training
loop — the ``IterationListener`` chain invoked per optimizer iteration
(deeplearning4j-core/.../optimize/api/IterationListener.java, fired from
StochasticGradientDescent.java:66-67) feeding the UI/stats plane
(deeplearning4j-ui-parent). Our reproduction grew five disjoint ledgers
instead; this module is the correlation layer those ledgers lack: a
Dapper-style span tracer (PAPERS.md — always-on, low-overhead tracing
built in before the production story needs it) over the hot seams the
repo already owns:

  dispatch.<jit>   train-step dispatch (trace vs cache-hit vs execute)
                   — ops/dispatch.instrumented_jit
  etl.wait/stage   input-pipeline staging waits — etl/pipeline.py
  ckpt.*           checkpoint snapshot/write/commit — resilience/
  fleet.round/split, membership epochs — parallel/fleet.py
  serve.request/batch  request -> coalesced batch -> jit dispatch, with
                   a request id threading through the batcher

Spans are HOST-SIDE events only: a span around a jit call measures the
(async) dispatch, never a device sync — the same bulk-readback rule the
listener chain follows (a per-step ``block_until_ready`` would serialize
the pipeline this tracer exists to observe). Timing uses the monotonic
clock (``time.perf_counter``); ids are process-local integers.

Gate: ``DL4J_TPU_OBS`` (default OFF). Disabled, :func:`span` returns a
shared null context — one env lookup and one branch per call site, no
allocation of Span objects, no ring writes — and training is bit-exact
vs a build without the tracer (tests/test_obs.py proves it).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.ops import env as envknob

ENV_OBS = "DL4J_TPU_OBS"
ENV_SPANS = "DL4J_TPU_OBS_SPANS"

_ON = ("1", "on", "true", "yes")

# programmatic override (tests and the bench leg toggle without relying
# on env mutation ordering): None = defer to the env
_forced: Optional[bool] = None


def obs_enabled() -> bool:
    """The observability gate, read at CALL time (per span) so a single
    process can measure with-vs-without honestly (the ``obs_overhead``
    bench leg does exactly that)."""
    if _forced is not None:
        return _forced
    return envknob.raw(ENV_OBS, "").strip().lower() in _ON


def set_enabled(value: Optional[bool]) -> None:
    """Force the gate on/off programmatically; ``None`` restores the env
    decision."""
    global _forced
    _forced = value


class Span:
    """One timed operation: name, id, parent id, monotonic start/end,
    free-form attributes. Mutable only through :meth:`set_attr` while
    open; finished spans live in the tracer ring as plain dicts."""

    __slots__ = ("name", "span_id", "parent_id", "start", "end", "attrs",
                 "wall")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 attrs: Dict[str, Any]):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.wall = time.time()  # correlation with external logs only
        self.start = time.perf_counter()
        self.end: Optional[float] = None

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_wall": round(self.wall, 6),
            "t_mono": round(self.start, 6),
            "duration_s": (None if self.end is None
                           else round(self.end - self.start, 6)),
            "attrs": dict(self.attrs),
        }


class _NullSpan:
    """The disabled-path span: every mutator is a no-op so call sites
    keep ONE code path (``with span(...) as sp: ... sp.set_attr(...)``)
    whether obs is on or off."""

    __slots__ = ()
    name = None
    span_id = None
    parent_id = None

    def set_attr(self, key, value):
        pass


NULL_SPAN = _NullSpan()


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return NULL_SPAN

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _SpanCtx:
    """Context manager for one live span; pushes/pops the thread-local
    parent stack so nested spans parent automatically (a serving batch
    span opened in the batcher worker thread becomes the parent of the
    jit dispatch span the model call opens on that same thread)."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._tracer._stack().append(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        sp = self._span
        sp.end = time.perf_counter()
        if exc_type is not None:
            sp.attrs["error"] = exc_type.__name__
        stack = self._tracer._stack()
        if stack and stack[-1] is sp:
            stack.pop()
        self._tracer._finish(sp)
        return False


class Tracer:
    """Span factory + bounded ring of finished spans.

    Finished spans fan out to the flight-recorder journal (obs/journal)
    and a duration histogram in the metrics registry (obs/registry) —
    one instrumentation point, three read surfaces (ring for tests/
    debugging, journal for post-mortem timelines, histogram for export).
    """

    def __init__(self, capacity: Optional[int] = None, *,
                 registry=None, journal=None):
        self._lock = threading.Lock()
        self._ring: deque = deque(
            maxlen=capacity if capacity is not None
            else envknob.get_int(ENV_SPANS, 4096))
        self._local = threading.local()
        self._ids = itertools.count(1)
        self._registry = registry
        self._journal = journal

    # -- wiring (lazy: obs/__init__ connects the default singletons) ------
    def attach(self, *, registry=None, journal=None) -> None:
        if registry is not None:
            self._registry = registry
        if journal is not None:
            self._journal = journal

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    # -- recording --------------------------------------------------------
    def span(self, name: str, **attrs) -> _SpanCtx:
        parent = self._stack()[-1].span_id if self._stack() else None
        return _SpanCtx(self, Span(name, next(self._ids), parent, attrs))

    def record_span(self, name: str, seconds: float, **attrs) -> None:
        """A completed span recorded after the fact — for waits measured
        inline (the ETL consumer stall) where wrapping the wait in a
        context manager would restructure the hot loop."""
        sp = Span(name, next(self._ids), None, attrs)
        sp.start -= float(seconds)
        sp.wall -= float(seconds)
        sp.end = sp.start + float(seconds)
        self._finish(sp)

    def current_span(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def _finish(self, sp: Span) -> None:
        d = sp.to_dict()
        with self._lock:
            self._ring.append(d)
        journal = self._journal
        if journal is not None:
            # light-path append: the span dict is already timestamped
            journal.append(dict(d, kind="span"))
        registry = self._registry
        if registry is not None and sp.end is not None:
            registry.histogram("dl4j_span_seconds", sp.end - sp.start,
                               span=sp.name)

    # -- reading ----------------------------------------------------------
    def spans(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
        if name is not None:
            out = [s for s in out if s["name"] == name]
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_TRACER: Optional[Tracer] = None
_TRACER_LOCK = threading.Lock()


def tracer() -> Tracer:
    """The process-wide tracer, wired to the default registry/journal on
    first use (lazy so importing the instrumented modules never pays for
    the whole obs plane)."""
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                from deeplearning4j_tpu.obs import journal as journal_mod
                from deeplearning4j_tpu.obs import registry as registry_mod

                _TRACER = Tracer(
                    registry=registry_mod.default_registry(),
                    journal=journal_mod.default_journal())
    return _TRACER


def span(name: str, **attrs):
    """THE instrumentation entry point: a context manager yielding a Span
    when obs is enabled, the shared null context otherwise. The disabled
    path is one env read + one branch — cheap enough for the per-dispatch
    hot path this plane instruments."""
    if not obs_enabled():
        return _NULL_CTX
    return tracer().span(name, **attrs)


def record_span(name: str, seconds: float, **attrs) -> None:
    """Gated after-the-fact span recording (see Tracer.record_span)."""
    if obs_enabled():
        tracer().record_span(name, seconds, **attrs)
