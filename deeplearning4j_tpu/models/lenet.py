"""LeNet-5 for MNIST — BASELINE configs[0], the minimum end-to-end slice.

Built through the config DSL exactly as a user of the reference would build
it with NeuralNetConfiguration.Builder + ConvolutionLayerSetup
(dl4j-examples LenetMnistExample pattern; reference conv runtime:
deeplearning4j-core/.../nn/layers/convolution/ConvolutionLayer.java).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer,
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.preprocessors import CnnToFeedForwardPreProcessor
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

INPUT_SHAPE = (28, 28, 1)


def lenet5_conf(
    seed: int = 12345,
    learning_rate: float = 0.01,
    updater: str = "nesterovs",
    momentum: float = 0.9,
    l2: float = 5e-4,
):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(updater)
        .momentum(momentum)
        .l2(l2)
        .weight_init("xavier")
        .list()
        .layer(
            0,
            ConvolutionLayer(
                n_in=1, n_out=20, kernel_size=(5, 5), stride=(1, 1),
                activation="identity",
            ),
        )
        .layer(1, SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(
            2,
            ConvolutionLayer(
                n_in=20, n_out=50, kernel_size=(5, 5), stride=(1, 1),
                activation="identity",
            ),
        )
        .layer(3, SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(4, DenseLayer(n_in=4 * 4 * 50, n_out=500, activation="relu"))
        .layer(
            5,
            OutputLayer(
                n_in=500, n_out=10, activation="softmax", loss_function="mcxent"
            ),
        )
        .input_preprocessor(4, CnnToFeedForwardPreProcessor(4, 4, 50))
        .build()
    )


def build_lenet5(**kw) -> MultiLayerNetwork:
    net = MultiLayerNetwork(lenet5_conf(**kw))
    net.init(input_shape=INPUT_SHAPE)
    return net
