"""Deep Belief Network builders — the reference era's flagship model family.

DL4J 0.4's canonical examples are stacked-RBM DBNs with layerwise
contrastive-divergence pretraining followed by supervised fine-tuning
(reference: nn/layers/feedforward/rbm/RBM.java:101-137 contrastiveDivergence;
MultiLayerNetwork.pretrain :165-213; the classic MNIST DBN example shape
784-500-250-200-10). Here the same flow runs as jitted CD-k steps per layer
(MultiLayerNetwork.pretrain) and one jitted train step for fine-tuning.

Also provides the stacked denoising-autoencoder variant (reference
nn/layers/feedforward/autoencoder/AutoEncoder.java — corruption + MSE
reconstruction), the other pretraining-era stack.
"""

from __future__ import annotations

from typing import Sequence

from deeplearning4j_tpu.nn.conf import (
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers import RBM, AutoEncoder
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def dbn_conf(
    n_in: int = 784,
    hidden: Sequence[int] = (500, 250, 200),
    num_classes: int = 10,
    hidden_unit: str = "binary",
    visible_unit: str = "binary",
    k: int = 1,
    seed: int = 123,
    learning_rate: float = 0.1,
    updater: str = "sgd",
    activation: str = "sigmoid",
):
    """Stacked-RBM DBN: pretrain=True so fit() runs layerwise CD-k first
    (when invoked via pretrain()), then backprop fine-tunes end-to-end."""
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(updater)
        .weight_init("xavier")
        .list()
        .pretrain(True)
        .backprop(True)
    )
    sizes = [n_in, *hidden]
    for i in range(len(hidden)):
        b = b.layer(i, RBM(n_in=sizes[i], n_out=sizes[i + 1],
                           hidden_unit=hidden_unit, visible_unit=visible_unit,
                           k=k, activation=activation))
    b = b.layer(len(hidden), OutputLayer(n_in=sizes[-1], n_out=num_classes,
                                         activation="softmax",
                                         loss_function="negativeloglikelihood"))
    return b.build()


def build_dbn(**kwargs) -> MultiLayerNetwork:
    conf = dbn_conf(**kwargs)
    n_in = conf.layers[0].n_in
    return MultiLayerNetwork(conf).init(input_shape=(1, n_in))


def stacked_autoencoder_conf(
    n_in: int = 784,
    hidden: Sequence[int] = (500, 250),
    num_classes: int = 10,
    corruption_level: float = 0.3,
    seed: int = 123,
    learning_rate: float = 0.1,
    updater: str = "sgd",
):
    """Stacked denoising autoencoders + softmax head (the reference's
    AutoEncoder layer: corruption + sigmoid reconstruction, pretrained
    layerwise like the RBMs)."""
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(updater)
        .weight_init("xavier")
        .list()
        .pretrain(True)
        .backprop(True)
    )
    sizes = [n_in, *hidden]
    for i in range(len(hidden)):
        b = b.layer(i, AutoEncoder(n_in=sizes[i], n_out=sizes[i + 1],
                                   corruption_level=corruption_level,
                                   activation="sigmoid"))
    b = b.layer(len(hidden), OutputLayer(n_in=sizes[-1], n_out=num_classes,
                                         activation="softmax",
                                         loss_function="negativeloglikelihood"))
    return b.build()


def build_stacked_autoencoder(**kwargs) -> MultiLayerNetwork:
    conf = stacked_autoencoder_conf(**kwargs)
    n_in = conf.layers[0].n_in
    return MultiLayerNetwork(conf).init(input_shape=(1, n_in))
