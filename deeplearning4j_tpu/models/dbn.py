"""Deep Belief Network builders — the reference era's flagship model family.

DL4J 0.4's canonical examples are stacked-RBM DBNs with layerwise
contrastive-divergence pretraining followed by supervised fine-tuning
(reference: nn/layers/feedforward/rbm/RBM.java:101-137 contrastiveDivergence;
MultiLayerNetwork.pretrain :165-213; the classic MNIST DBN example shape
784-500-250-200-10). Here the same flow runs as jitted CD-k steps per layer
(MultiLayerNetwork.pretrain) and one jitted train step for fine-tuning.

Also provides the stacked denoising-autoencoder variant (reference
nn/layers/feedforward/autoencoder/AutoEncoder.java — corruption + MSE
reconstruction), the other pretraining-era stack.
"""

from __future__ import annotations

from typing import Callable, Sequence

from deeplearning4j_tpu.nn.conf import (
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers import RBM, AutoEncoder
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _pretrain_stack_conf(
    layer_factory: Callable[[int, int], object],
    n_in: int,
    hidden: Sequence[int],
    num_classes: int,
    seed: int,
    learning_rate: float,
    updater: str,
):
    """Shared scaffold for the two pretraining-era stacks: N pretrainable
    layers from `layer_factory(n_in, n_out)` + a softmax head, with
    pretrain=True so pretrain() runs layerwise before backprop fine-tune."""
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(updater)
        .weight_init("xavier")
        .list()
        .pretrain(True)
        .backprop(True)
    )
    sizes = [n_in, *hidden]
    for i in range(len(hidden)):
        b = b.layer(i, layer_factory(sizes[i], sizes[i + 1]))
    b = b.layer(len(hidden), OutputLayer(n_in=sizes[-1], n_out=num_classes,
                                         activation="softmax",
                                         loss_function="negativeloglikelihood"))
    return b.build()


def dbn_conf(
    n_in: int = 784,
    hidden: Sequence[int] = (500, 250, 200),
    num_classes: int = 10,
    hidden_unit: str = "binary",
    visible_unit: str = "binary",
    k: int = 1,
    seed: int = 123,
    learning_rate: float = 0.1,
    updater: str = "sgd",
    activation: str = "sigmoid",
):
    """Stacked-RBM DBN: CD-k pretraining then backprop fine-tune."""
    return _pretrain_stack_conf(
        lambda i, o: RBM(n_in=i, n_out=o, hidden_unit=hidden_unit,
                         visible_unit=visible_unit, k=k,
                         activation=activation),
        n_in, hidden, num_classes, seed, learning_rate, updater,
    )


def stacked_autoencoder_conf(
    n_in: int = 784,
    hidden: Sequence[int] = (500, 250),
    num_classes: int = 10,
    corruption_level: float = 0.3,
    seed: int = 123,
    learning_rate: float = 0.1,
    updater: str = "sgd",
):
    """Stacked denoising autoencoders + softmax head (the reference's
    AutoEncoder layer: corruption + sigmoid reconstruction, pretrained
    layerwise like the RBMs)."""
    return _pretrain_stack_conf(
        lambda i, o: AutoEncoder(n_in=i, n_out=o,
                                 corruption_level=corruption_level,
                                 activation="sigmoid"),
        n_in, hidden, num_classes, seed, learning_rate, updater,
    )


def _build(conf) -> MultiLayerNetwork:
    return MultiLayerNetwork(conf).init(input_shape=(1, conf.layers[0].n_in))


def build_dbn(**kwargs) -> MultiLayerNetwork:
    return _build(dbn_conf(**kwargs))


def build_stacked_autoencoder(**kwargs) -> MultiLayerNetwork:
    return _build(stacked_autoencoder_conf(**kwargs))
