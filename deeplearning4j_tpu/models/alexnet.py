"""AlexNet (Krizhevsky et al. 2012) through the config DSL.

The 2016-era reference ships no model-zoo module, but AlexNet is its
canonical big-CNN example shape (dl4j-examples AlexNet pattern built on
nn/conf/layers/{ConvolutionLayer,SubsamplingLayer,
LocalResponseNormalization}.java); this builder exercises the same layer
zoo — conv/LRN/max-pool/dense/dropout — as one MultiLayerNetwork conf.
Single-tower variant (modern form of the original's two GPU towers).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer,
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.layers import LocalResponseNormalization
from deeplearning4j_tpu.nn.conf.preprocessors import CnnToFeedForwardPreProcessor
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

INPUT_SHAPE = (227, 227, 3)


def alexnet_conf(
    num_classes: int = 1000,
    in_channels: int = 3,
    input_size: int = 227,
    seed: int = 42,
    learning_rate: float = 0.01,
    updater: str = "nesterovs",
    momentum: float = 0.9,
    l2: float = 5e-4,
    dropout: float = 0.5,
    dtype_policy: str = "strict",
    gradient_checkpointing: bool = False,
):
    # spatial sizes down the stack (input 227: 55 -> 27 -> 13 -> 13 -> 13 -> 6)
    s1 = (input_size - 11) // 4 + 1      # conv1 stride 4, valid
    p1 = (s1 - 3) // 2 + 1               # pool 3x3 /2
    s2 = p1                               # conv2 pad 2 keeps size
    p2 = (s2 - 3) // 2 + 1
    final = (p2 - 3) // 2 + 1            # pool5
    if final < 1:
        raise ValueError(
            f"input_size {input_size} too small for the AlexNet stack "
            f"(pool5 output would be {final}x{final}; minimum input is 67)")
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(updater)
        .momentum(momentum)
        .l2(l2)
        .weight_init("relu")
        .list()
        .dtype_policy(dtype_policy)
        .gradient_checkpointing(gradient_checkpointing)
        .layer(0, ConvolutionLayer(n_in=in_channels, n_out=96,
                                   kernel_size=(11, 11), stride=(4, 4),
                                   activation="relu"))
        .layer(1, LocalResponseNormalization())
        .layer(2, SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
        .layer(3, ConvolutionLayer(n_in=96, n_out=256, kernel_size=(5, 5),
                                   padding=(2, 2), activation="relu"))
        .layer(4, LocalResponseNormalization())
        .layer(5, SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
        .layer(6, ConvolutionLayer(n_in=256, n_out=384, kernel_size=(3, 3),
                                   padding=(1, 1), activation="relu"))
        .layer(7, ConvolutionLayer(n_in=384, n_out=384, kernel_size=(3, 3),
                                   padding=(1, 1), activation="relu"))
        .layer(8, ConvolutionLayer(n_in=384, n_out=256, kernel_size=(3, 3),
                                   padding=(1, 1), activation="relu"))
        .layer(9, SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
        .layer(10, DenseLayer(n_in=final * final * 256, n_out=4096,
                              activation="relu", dropout=dropout))
        .layer(11, DenseLayer(n_in=4096, n_out=4096, activation="relu",
                              dropout=dropout))
        .layer(12, OutputLayer(n_in=4096, n_out=num_classes,
                               activation="softmax", loss_function="mcxent"))
        .input_preprocessor(10, CnnToFeedForwardPreProcessor(final, final, 256))
    )
    return b.build()


def build_alexnet(input_size: int = 227, num_classes: int = 1000,
                  **kw) -> MultiLayerNetwork:
    conf = alexnet_conf(num_classes=num_classes, input_size=input_size, **kw)
    return MultiLayerNetwork(conf).init(
        input_shape=(input_size, input_size, conf.layers[0].n_in)
    )
