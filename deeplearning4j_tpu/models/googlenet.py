"""GoogLeNet / Inception-v1 on the ComputationGraph.

The 2014 architecture the reference's DAG machinery exists to express
(ComputationGraph.java + MergeVertex.java — concatenating parallel conv
towers is THE motivating example in the reference's graph docs): nine
Inception modules, each four towers (1x1 / 3x3-reduce+3x3 / 5x5-reduce+5x5
/ maxpool+1x1-proj) merged on the channel axis, LRN in the stem (2014,
pre-BatchNorm), and optionally the two auxiliary softmax heads — a
three-output graph trained through the SAME multi-output fit path the
reference drives (ComputationGraph.fit with one label array per output).

TPU notes: every tower is an independent lax.conv_general_dilated chain —
XLA schedules them in parallel onto the MXU and the channel concat is a
free layout operation; the whole fwd+bwd+update remains ONE jitted
program.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import MergeVertex
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    LocalResponseNormalization,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph

# (1x1, 3x3red, 3x3, 5x5red, 5x5, pool_proj) per module — the paper's
# Table 1 ("Going Deeper with Convolutions", Szegedy et al. 2014)
_INCEPTION = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


def _conv(gb, name, n_in, n_out, kernel, stride, padding, input_name):
    gb.add_layer(
        name,
        ConvolutionLayer(n_in=n_in, n_out=n_out, kernel_size=kernel,
                         stride=stride, padding=padding, activation="relu"),
        input_name,
    )
    return name


def _inception(gb, name, n_in, spec, input_name):
    c1, r3, c3, r5, c5, pp = spec
    t1 = _conv(gb, f"{name}_1x1", n_in, c1, (1, 1), (1, 1), (0, 0),
               input_name)
    r3n = _conv(gb, f"{name}_3x3r", n_in, r3, (1, 1), (1, 1), (0, 0),
                input_name)
    t3 = _conv(gb, f"{name}_3x3", r3, c3, (3, 3), (1, 1), (1, 1), r3n)
    r5n = _conv(gb, f"{name}_5x5r", n_in, r5, (1, 1), (1, 1), (0, 0),
                input_name)
    t5 = _conv(gb, f"{name}_5x5", r5, c5, (5, 5), (1, 1), (2, 2), r5n)
    gb.add_layer(
        f"{name}_pool",
        SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                         stride=(1, 1), padding=(1, 1)),
        input_name,
    )
    tp = _conv(gb, f"{name}_poolproj", n_in, pp, (1, 1), (1, 1), (0, 0),
               f"{name}_pool")
    gb.add_vertex(f"{name}_out", MergeVertex(), t1, t3, t5, tp)
    return f"{name}_out", c1 + c3 + c5 + pp


def _aux_head(gb, name, n_in, hw, num_classes, input_name):
    """Auxiliary classifier (paper section 5: avgpool5/3 -> 1x1 conv 128 ->
    fc 1024 -> dropout 0.7 -> softmax) — an extra OUTPUT of the graph."""
    # paper: 5x5/3 avg pool (14 -> 4 at 224px); clamped for small inputs
    k = min(5, hw)
    gb.add_layer(
        f"{name}_pool",
        SubsamplingLayer(pooling_type="avg", kernel_size=(k, k),
                         stride=(3, 3)),
        input_name,
    )
    _conv(gb, f"{name}_conv", n_in, 128, (1, 1), (1, 1), (0, 0),
          f"{name}_pool")
    out_hw = max(1, (hw - k) // 3 + 1)
    gb.add_layer(
        f"{name}_fc",
        DenseLayer(n_in=128 * out_hw * out_hw, n_out=1024,
                   activation="relu"),
        f"{name}_conv",
        preprocessor=CnnToFeedForwardPreProcessor(out_hw, out_hw, 128),
    )
    gb.add_layer(
        name,
        OutputLayer(n_in=1024, n_out=num_classes, activation="softmax",
                    loss_function="mcxent", dropout=0.7),
        f"{name}_fc",
    )
    return name


def googlenet_conf(input_size: int = 224, num_classes: int = 1000,
                   in_channels: int = 3, aux_heads: bool = False,
                   learning_rate: float = 0.01, updater: str = "nesterovs",
                   momentum: float = 0.9, l2: float = 2e-4, seed: int = 123):
    gb = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(updater)
        .momentum(momentum)
        .l2(l2)
        .weight_init("relu")
        .graph_builder()
        .add_inputs("in")
    )
    # stem: conv7/2 -> pool3/2 -> LRN -> 1x1 -> 3x3 -> LRN -> pool3/2
    _conv(gb, "stem1", in_channels, 64, (7, 7), (2, 2), (3, 3), "in")
    gb.add_layer("pool1", SubsamplingLayer(pooling_type="max",
                                           kernel_size=(3, 3), stride=(2, 2),
                                           padding=(1, 1)), "stem1")
    gb.add_layer("lrn1", LocalResponseNormalization(), "pool1")
    _conv(gb, "stem2a", 64, 64, (1, 1), (1, 1), (0, 0), "lrn1")
    _conv(gb, "stem2b", 64, 192, (3, 3), (1, 1), (1, 1), "stem2a")
    gb.add_layer("lrn2", LocalResponseNormalization(), "stem2b")
    gb.add_layer("pool2", SubsamplingLayer(pooling_type="max",
                                           kernel_size=(3, 3), stride=(2, 2),
                                           padding=(1, 1)), "lrn2")

    cur, n_in = "pool2", 192
    hw = input_size
    for _ in range(3):  # stem conv + 2 maxpools, each ceil-halving
        hw = (hw + 1) // 2
    outputs = []
    for mod, spec in _INCEPTION.items():
        cur, n_in = _inception(gb, f"inc{mod}", n_in, spec, cur)
        if mod in ("3b", "4e"):  # pool between stacks 3->4 and 4->5
            gb.add_layer(f"pool_{mod}",
                         SubsamplingLayer(pooling_type="max",
                                          kernel_size=(3, 3), stride=(2, 2),
                                          padding=(1, 1)), cur)
            cur = f"pool_{mod}"
            hw = (hw + 1) // 2
        if aux_heads and mod == "4a":
            outputs.append(_aux_head(gb, "aux1", n_in, hw, num_classes, cur))
        if aux_heads and mod == "4d":
            outputs.append(_aux_head(gb, "aux2", n_in, hw, num_classes, cur))

    hw = max(1, hw)
    gb.add_layer("avgpool",
                 SubsamplingLayer(pooling_type="avg", kernel_size=(hw, hw),
                                  stride=(hw, hw)), cur)
    gb.add_layer(
        "out",
        OutputLayer(n_in=n_in, n_out=num_classes, activation="softmax",
                    loss_function="mcxent", dropout=0.4),
        "avgpool",
        preprocessor=CnnToFeedForwardPreProcessor(1, 1, n_in),
    )
    # main output FIRST (ComputationGraph.output()[0] is the main head)
    return gb.set_outputs("out", *outputs).build()


def build_googlenet(input_size: int = 224, num_classes: int = 1000,
                    in_channels: int = 3, **kw) -> ComputationGraph:
    conf = googlenet_conf(input_size=input_size, num_classes=num_classes,
                          in_channels=in_channels, **kw)
    net = ComputationGraph(conf)
    net.init(input_shapes={"in": (input_size, input_size, in_channels)})
    return net
