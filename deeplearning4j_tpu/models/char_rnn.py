"""Character-level LSTM language model — BASELINE configs[1].

The reference-era canonical RNN workload (dl4j GravesLSTMCharModellingExample
pattern over the reference runtime: nn/layers/recurrent/GravesLSTM.java +
LSTMHelpers.java time loop; TBPTT MultiLayerNetwork.java:1162): stacked
GravesLSTM layers + RnnOutputLayer(MCXENT over the character softmax),
truncated BPTT, and rnnTimeStep-based sampling.

TPU notes: the LSTM time loop is lax.scan inside ONE jitted train step;
sampling streams through rnn_time_step carrying (h, c) state.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def char_rnn_conf(
    vocab_size: int,
    lstm_size: int = 200,
    num_layers: int = 2,
    seed: int = 12345,
    learning_rate: float = 0.1,
    updater: str = "rmsprop",
    tbptt_length: int = 50,
):
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(updater)
        .weight_init("xavier")
        .list()
    )
    n_in = vocab_size
    for i in range(num_layers):
        b = b.layer(i, GravesLSTM(n_in=n_in, n_out=lstm_size, activation="tanh"))
        n_in = lstm_size
    b = b.layer(
        num_layers,
        RnnOutputLayer(
            n_in=lstm_size, n_out=vocab_size, activation="softmax",
            loss_function="mcxent",
        ),
    )
    return (
        b.backprop_type("truncated_bptt")
        .t_bptt_forward_length(tbptt_length)
        .t_bptt_backward_length(tbptt_length)
        .build()
    )


class CharRnn:
    """Train on raw text; generate with temperature sampling."""

    def __init__(self, text: Optional[str] = None, chars: Optional[Sequence[str]] = None,
                 **conf_kw):
        if chars is None:
            assert text is not None, "need text or explicit char list"
            chars = sorted(set(text))
        self.chars: List[str] = list(chars)
        self.char_to_ix = {c: i for i, c in enumerate(self.chars)}
        self.vocab_size = len(self.chars)
        self.conf_kw = conf_kw
        self.net = MultiLayerNetwork(char_rnn_conf(self.vocab_size, **conf_kw))
        self.net.init(input_shape=(1, self.vocab_size))

    # -- data -------------------------------------------------------------
    def encode(self, text: str) -> np.ndarray:
        return np.array([self.char_to_ix[c] for c in text if c in self.char_to_ix],
                        np.int32)

    def batches(self, text: str, batch: int, seq_len: int):
        """Contiguous [B, T, V] one-hot minibatches with next-char labels
        (CharacterIterator in the reference example)."""
        ids = self.encode(text)
        usable = (len(ids) - 1) // (batch * seq_len) * (batch * seq_len)
        if usable <= 0:
            raise ValueError("text too short for requested batch/seq_len")
        xs = ids[:usable].reshape(batch, -1)
        ys = ids[1 : usable + 1].reshape(batch, -1)
        n_seq = xs.shape[1] // seq_len
        eye = np.eye(self.vocab_size, dtype=np.float32)
        for s in range(n_seq):
            sl = slice(s * seq_len, (s + 1) * seq_len)
            yield eye[xs[:, sl]], eye[ys[:, sl]]

    # -- training ---------------------------------------------------------
    def fit_text(self, text: str, epochs: int = 1, batch: int = 32,
                 seq_len: int = 100) -> List[float]:
        losses = []
        for _ in range(epochs):
            for x, y in self.batches(text, batch, seq_len):
                losses.append(float(self.net.fit(x, y)))
        return losses

    # -- generation -------------------------------------------------------
    def sample(self, prime: str, length: int = 200, temperature: float = 1.0,
               seed: int = 0, top_k: int = 0) -> str:
        """Stream generation via rnn_time_step (reference
        sampleCharactersFromNetwork pattern over rnnTimeStep :2152).
        top_k > 0 restricts each draw to the k most likely characters
        (the same filter surface as TransformerLM.generate)."""
        rng = np.random.default_rng(seed)
        self.net.rnn_clear_previous_state()
        eye = np.eye(self.vocab_size, dtype=np.float32)
        known_prime = [c for c in prime if c in self.char_to_ix]
        out = list(known_prime)
        # no known prime chars: start from the uniform distribution
        probs = np.full((1, self.vocab_size), 1.0 / self.vocab_size, np.float32)
        for c in known_prime:
            x = eye[self.char_to_ix[c]][None, None, :]
            probs = np.asarray(self.net.rnn_time_step(x))[0]
        for _ in range(length):
            p = probs.reshape(-1).astype(np.float64)
            if temperature != 1.0:
                logp = np.log(np.maximum(p, 1e-12)) / temperature
                p = np.exp(logp - logp.max())
            if top_k and top_k < p.size:
                # keep EXACTLY k entries even on probability ties at the
                # k-th value (lax.top_k semantics, matching the flagship's
                # TransformerLM._filter_logits)
                keep = np.argpartition(p, -top_k)[-top_k:]
                mask = np.zeros_like(p)
                mask[keep] = 1.0
                p = p * mask
            p /= p.sum()
            ci = int(rng.choice(self.vocab_size, p=p))
            out.append(self.chars[ci])
            x = eye[ci][None, None, :]
            probs = np.asarray(self.net.rnn_time_step(x))[0]
        return "".join(out)
