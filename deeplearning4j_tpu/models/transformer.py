"""Transformer language model — the multi-axis-parallel flagship.

The reference's sequence models top out at GravesLSTM/GRU char-RNNs
(reference nn/layers/recurrent/, models era 2016); this framework adds a
decoder-only transformer LM as the flagship for the parallelism stack,
because it is the model family whose scale actually NEEDS the mesh:

  data axis   ('data')  : batch sharded — GSPMD inserts the gradient
                          all-reduce (the ParallelWrapper/param-averaging
                          successor, SURVEY.md section 2.7).
  model axis  ('model') : Megatron column/row sharding of every attention
                          and MLP matrix (parallel/tensor_parallel.py has
                          the explicit shard_map formulation; HERE the same
                          layout is expressed as GSPMD sharding annotations
                          and XLA derives the identical psum schedule —
                          the scaling-book recipe: pick a mesh, annotate,
                          let the compiler insert collectives).
  expert axis ('expert'): optional MoE FFN blocks, experts sharded
                          (parallel/expert_parallel.py math, GSPMD layout).
  seq axis    ('seq')   : ring attention for sequences beyond one chip's
                          HBM (parallel/sequence_parallel.py), used by
                          `ring_forward`.

Everything under `train_step` is ONE jitted XLA program: forward, backward,
Adam update, with bf16 MXU matmuls when dtype_policy="performance".
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPELINE_AXIS,
    SEQUENCE_AXIS,
)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 128
    n_layers: int = 4
    n_heads: int = 4
    d_ff: int = 512
    max_len: int = 256
    moe_experts: int = 0          # 0 = dense FFN; >0 = MoE every block
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 1e-2
    dtype_policy: str = "strict"  # "strict" f32 | "performance" bf16 compute
    learning_rate: float = 3e-4
    # LR schedule (reference LearningRatePolicy role): linear warmup over
    # warmup_steps, then optional "cosine" decay to 0 at total_steps
    warmup_steps: int = 0
    lr_schedule: str = "none"     # "none" | "cosine"
    total_steps: int = 0
    # gradient accumulation: microbatches per optimizer step at 1/A the
    # activation memory. Dense: exact full-batch equivalence
    # (mean-of-means). MoE: the GROUPED objective (group = microbatch,
    # GShard/Switch semantics) — identical to PP with n_micro=A.
    accum_steps: int = 1
    seed: int = 0
    # flash-attention pallas kernel (ops/pallas_attention.py) on the
    # single-device path; the GSPMD-sharded path always uses dense XLA
    # attention (pallas custom calls don't auto-partition under GSPMD —
    # multi-chip attention goes through ring_forward instead)
    use_flash: bool = True
    # GPipe microbatch count used when TransformerLM is built on a mesh
    # with a 'pipe' axis (pipeline mode); must divide the fit() batch size
    pipeline_microbatches: int = 4
    # decoupled weight decay (AdamW, Loshchilov & Hutter): applied to
    # matrix params only (LN scales/biases and the position table exempt,
    # the standard LM recipe); 0 = plain Adam
    weight_decay: float = 0.0
    # global-norm gradient clipping before the optimizer update; 0 = off
    # (the reference's GradientNormalization ClipL2PerParamType role —
    # nn/conf/GradientNormalization.java — for the flagship)
    clip_grad_norm: float = 0.0
    # activation rematerialization for the block scan (ops/remat.py —
    # the Chen et al. sublinear-memory ladder): "auto" defers to the
    # DL4J_TPU_REMAT env knob (default none); "none" stores every
    # activation; "dots" keeps matmul outputs and recomputes elementwise
    # ops; "block" stores only the residual carry and recomputes the
    # whole block in the backward pass. Resolved at step-factory TRACE
    # time (the donation-policy discipline); composes with accum_steps
    # (remat shrinks per-microbatch activations, accum shrinks the
    # microbatch). Values are policy-invariant (remat==none is bit-exact
    # on the forward; grads agree to recompute-reassociation tolerance —
    # tests/test_remat.py).
    remat: str = "auto"

    @property
    def compute_dtype(self):
        return jnp.bfloat16 if self.dtype_policy == "performance" else jnp.float32


# ---------------------------------------------------------------------------
# Init + sharding layout
# ---------------------------------------------------------------------------


def init_params(cfg: TransformerConfig) -> Params:
    """Global-shaped params; block leaves stacked on a leading layer dim [L,...]
    so the forward is a lax.scan over layers (compile time O(1) in depth)."""
    key = jax.random.PRNGKey(cfg.seed)
    ks = jax.random.split(key, 10)
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers

    def norm(k, shape, scale):
        # float(scale): numpy f64 scalars are strongly typed and would
        # promote the whole tree to f64 under jax_enable_x64
        return jax.random.normal(k, shape, jnp.float32) * float(scale)

    def xavier(k, shape):
        return norm(k, shape, np.sqrt(2.0 / (shape[-2] + shape[-1])))

    def ones(shape):
        return jnp.ones(shape, jnp.float32)

    def zeros(shape):
        return jnp.zeros(shape, jnp.float32)

    blocks = {
        "ln1_g": ones((L, d)), "ln1_b": zeros((L, d)),
        "Wq": xavier(ks[0], (L, d, d)), "Wk": xavier(ks[1], (L, d, d)),
        "Wv": xavier(ks[2], (L, d, d)),
        # residual-branch output projections scaled down by depth (GPT-2 style)
        "Wo": norm(ks[3], (L, d, d), 0.02 / np.sqrt(2 * L)),
        "ln2_g": ones((L, d)), "ln2_b": zeros((L, d)),
    }
    if cfg.moe_experts:
        E = cfg.moe_experts
        blocks.update({
            "Wg": xavier(ks[4], (L, d, E)),
            "W1": xavier(ks[5], (L, E, d, f)), "b1": zeros((L, E, f)),
            "W2": norm(ks[6], (L, E, f, d), 0.02 / np.sqrt(2 * L)),
            "b2": zeros((L, E, d)),
        })
    else:
        blocks.update({
            "W1": xavier(ks[5], (L, d, f)), "b1": zeros((L, f)),
            "W2": norm(ks[6], (L, f, d), 0.02 / np.sqrt(2 * L)),
            "b2": zeros((L, d)),
        })
    return {
        "embed": norm(ks[7], (cfg.vocab_size, d), 0.02),
        "pos": norm(ks[8], (cfg.max_len, d), 0.01),
        "lnf_g": ones((d,)), "lnf_b": zeros((d,)),
        "blocks": blocks,
        # lm head tied to embed (reference EmbeddingLayer has no tying, but
        # tying is the modern default and halves the biggest matrix)
    }


def param_specs(cfg: TransformerConfig) -> Params:
    """Megatron PartitionSpecs (leading layer dim unsharded). Column-parallel
    weights shard the output dim over 'model'; row-parallel the input dim;
    MoE expert leaves additionally shard the expert dim over 'expert'."""
    col, row = P(None, None, MODEL_AXIS), P(None, MODEL_AXIS, None)
    blocks = {
        "ln1_g": P(), "ln1_b": P(),
        "Wq": col, "Wk": col, "Wv": col, "Wo": row,
        "ln2_g": P(), "ln2_b": P(),
    }
    if cfg.moe_experts:
        blocks.update({
            "Wg": P(),
            "W1": P(None, EXPERT_AXIS, None, MODEL_AXIS),
            "b1": P(None, EXPERT_AXIS, MODEL_AXIS),
            "W2": P(None, EXPERT_AXIS, MODEL_AXIS, None),
            "b2": P(None, EXPERT_AXIS, None),
        })
    else:
        blocks.update({"W1": col, "b1": P(None, MODEL_AXIS),
                       "W2": row, "b2": P()})
    return {
        "embed": P(None, MODEL_AXIS),
        "pos": P(),
        "lnf_g": P(), "lnf_b": P(),
        "blocks": blocks,
    }


def shard_params(params: Params, cfg: TransformerConfig, mesh: Mesh) -> Params:
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: isinstance(x, jnp.ndarray),
    )


def megatron_param_shardings(cfg: TransformerConfig, mesh: Mesh) -> Params:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))


def param_shardings_for_mesh(cfg: TransformerConfig, mesh: Mesh) -> Params:
    """THE single place that decides a mesh's param layout: depth-sharded
    (pipeline mode) when the mesh has a 'pipe' axis; Megatron/MoE GSPMD
    specs when it has a 'model'/'expert' axis; fully replicated otherwise
    (sequence-parallel and pure-DP meshes — activations shard, params
    don't). Training init, checkpoint restore and device_put all route
    through here so they can never diverge."""
    if PIPELINE_AXIS in mesh.shape:
        return pipeline_param_shardings(cfg, mesh)
    if MODEL_AXIS in mesh.shape or EXPERT_AXIS in mesh.shape:
        return megatron_param_shardings(cfg, mesh)
    rep = NamedSharding(mesh, P())
    shapes = jax.eval_shape(partial(init_params, cfg))
    return jax.tree_util.tree_map(lambda _: rep, shapes)


def shard_params_for_mesh(params: Params, cfg: TransformerConfig,
                          mesh: Mesh) -> Params:
    return jax.tree_util.tree_map(
        jax.device_put, params, param_shardings_for_mesh(cfg, mesh))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _ln(x, g, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def _attention(q, k, v, n_heads, use_flash=False):
    n, t, d = q.shape
    hd = d // n_heads
    q = q.reshape(n, t, n_heads, hd)
    k = k.reshape(n, t, n_heads, hd)
    v = v.reshape(n, t, n_heads, hd)
    if use_flash:
        # single dispatch policy lives in attention_auto (flash when the
        # pallas gate + VMEM fit allow, dense XLA otherwise)
        from deeplearning4j_tpu.ops.pallas_attention import attention_auto

        return attention_auto(q, k, v, causal=True).reshape(n, t, d)
    s = jnp.einsum("nqhd,nkhd->nhqk", q, k) / jnp.sqrt(
        jnp.asarray(hd, q.dtype))
    mask = jnp.tril(jnp.ones((t, t), bool))
    s = jnp.where(mask[None, None], s, jnp.asarray(-1e9, s.dtype))
    from deeplearning4j_tpu.ops.dtypes import softmax_dtype

    p = jax.nn.softmax(s.astype(softmax_dtype(s.dtype)),
                       axis=-1).astype(q.dtype)
    return jnp.einsum("nhqk,nkhd->nqhd", p, v).reshape(n, t, d)


def _dense_block_f32(bp, h, n_heads: int, attend=None, ffn=None,
                     cdt=jnp.float32):
    """One transformer block (no flash) — the block body shared by the
    sequence-parallel (ring_forward) and pipeline-parallel
    (pipeline_forward) paths; forward() keeps its own cast-aware variant
    for the mixed-precision/flash path. cdt: compute dtype — f32 by
    default (the name records the original scope); bf16 under
    dtype_policy='performance' (params cast per use like forward(), the
    residual stream h carried in cdt — which also halves the ring/pipe
    ppermute traffic). `attend` overrides the attention op
    ((q, k, v) [N,T,F] -> [N,T,F]) so the ring/Ulysses strategies plug
    in; `ffn` overrides the feed-forward (x_normed -> residual delta) so
    the MoE branch shares the attention-residual half too."""
    c = lambda a: a.astype(cdt)
    if attend is None:
        attend = lambda q, k, v: _attention(q, k, v, n_heads)
    x = _ln(h, c(bp["ln1_g"]), c(bp["ln1_b"]))
    q, k, v = x @ c(bp["Wq"]), x @ c(bp["Wk"]), x @ c(bp["Wv"])
    h = h + attend(q, k, v) @ c(bp["Wo"])
    x = _ln(h, c(bp["ln2_g"]), c(bp["ln2_b"]))
    if ffn is not None:
        return h + ffn(x)
    return (h + jax.nn.gelu(x @ c(bp["W1"]) + c(bp["b1"])) @ c(bp["W2"])
            + c(bp["b2"]))


def _moe_ffn(bp, h, cfg: TransformerConfig, capacity: int = 0):
    """MoE FFN: routing + expert math shared with parallel/expert_parallel
    (called inline, not through its shard_map, so GSPMD shards the expert
    dim via the param shardings; returns (out, aux_loss)). capacity=0 ->
    the standard formula; decode_step passes the NO-DROP capacity n*t so
    one routing/expert body serves both batch and streamed paths."""
    from deeplearning4j_tpu.parallel.expert_parallel import (
        _routing,
        aux_loss_from_gates,
        expert_mlp,
    )

    from deeplearning4j_tpu.ops.dtypes import softmax_dtype

    n, t, d = h.shape
    xt = h.reshape(n * t, d)
    scores = xt @ bp["Wg"]
    gates = jax.nn.softmax(scores.astype(softmax_dtype(scores.dtype)),
                           axis=-1)
    if not capacity:
        capacity = max(1, int(cfg.moe_capacity_factor * n * t * cfg.moe_top_k
                              / cfg.moe_experts))
    dispatch, combine = _routing(gates, cfg.moe_top_k, capacity)
    y = expert_mlp(bp["W1"], bp["b1"], bp["W2"], bp["b2"],
                   dispatch.astype(h.dtype), combine.astype(h.dtype), xt)
    return y.reshape(n, t, d), aux_loss_from_gates(gates)


def _moe_block(bp, h, cfg: TransformerConfig, *, attend=None, cdt,
               capacity: int = 0):
    """One transformer block with the MoE FFN: _dense_block_f32 with its
    ffn override wired to _moe_ffn, returning (h, aux). The SINGLE
    definition shared by the sequence-parallel (ring_forward), pipelined
    (stage_fn), and KV-cache prefill paths — one place to change MoE cast
    discipline or aux accounting."""
    bp16 = {kk: vv.astype(cdt) for kk, vv in bp.items()}
    cap = {}

    def ffn(x):
        y, cap["aux"] = _moe_ffn(bp16, x, cfg, capacity=capacity)
        return y

    h = _dense_block_f32(bp, h, cfg.n_heads, attend=attend, ffn=ffn,
                         cdt=cdt)
    return h, cap["aux"]


def forward(params: Params, tokens: jax.Array, cfg: TransformerConfig,
            ) -> Tuple[jax.Array, jax.Array]:
    """tokens [N, T] int32 -> (logits [N, T, V] f32, aux_loss scalar)."""
    cdt = cfg.compute_dtype
    n, t = tokens.shape
    h = params["embed"][tokens] + params["pos"][:t][None]
    h = h.astype(cdt)

    def block(carry, bp):
        h, aux = carry
        x = _ln(h, bp["ln1_g"].astype(cdt), bp["ln1_b"].astype(cdt))
        q, k, v = x @ bp["Wq"].astype(cdt), x @ bp["Wk"].astype(cdt), \
            x @ bp["Wv"].astype(cdt)
        h = h + _attention(q, k, v, cfg.n_heads,
                           use_flash=cfg.use_flash) @ bp["Wo"].astype(cdt)
        x = _ln(h, bp["ln2_g"].astype(cdt), bp["ln2_b"].astype(cdt))
        if cfg.moe_experts:
            bp16 = {kk: vv.astype(cdt) for kk, vv in bp.items()}
            y, a = _moe_ffn(bp16, x, cfg)
            h = h + y
            aux = aux + a
        else:
            inner = jax.nn.gelu(x @ bp["W1"].astype(cdt) + bp["b1"].astype(cdt))
            h = h + inner @ bp["W2"].astype(cdt) + bp["b2"].astype(cdt)
        return (h, aux), None

    from deeplearning4j_tpu.ops.remat import remat_wrap

    # remat policy ladder applied to the scan BODY (cfg.remat, resolved
    # at trace time): under autodiff the scan stores only what the
    # checkpoint policy saves per layer instead of every residual.
    # prevent_cse=False: the scan's loop boundary already blocks the CSE
    # the checkpoint barriers guard against (nn/common.remat_apply).
    block = remat_wrap(block, cfg.remat, prevent_cse=False)
    (h, aux), _ = lax.scan(block, (h, jnp.zeros((), jnp.float32)),
                           params["blocks"])
    h = _ln(h.astype(jnp.float32), params["lnf_g"], params["lnf_b"])
    logits = h @ params["embed"].T  # tied head
    return logits.astype(jnp.float32), aux / cfg.n_layers


def nll_loss(logits: jax.Array, targets: jax.Array, mask=None) -> jax.Array:
    """Mean next-token NLL — THE cross-entropy shared by the training
    losses (dense/pipeline/ring) and evaluate(), so objective and metric
    can never drift. mask ([N, T] 0/1): masked positions excluded from
    numerator AND denominator."""
    from deeplearning4j_tpu.ops.dtypes import softmax_dtype

    # at-least-f32 (bf16 logits upcast; f64 stays f64 for the gradchecks)
    dt = softmax_dtype(logits.dtype)
    logp = jax.nn.log_softmax(logits.astype(dt), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return nll.mean()
    m = mask.astype(dt)
    return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)


def loss_fn(params: Params, tokens: jax.Array, targets: jax.Array,
            cfg: TransformerConfig) -> jax.Array:
    logits, aux = forward(params, tokens, cfg)
    return nll_loss(logits, targets) + cfg.moe_aux_coef * aux


# ---------------------------------------------------------------------------
# Training (one jitted step; Adam)
# ---------------------------------------------------------------------------


def init_opt_state(params: Params) -> Params:
    from deeplearning4j_tpu.ops import lowprec

    z = lambda a: jnp.zeros_like(a)
    opt = {
        "m": jax.tree_util.tree_map(z, params),
        "v": jax.tree_util.tree_map(z, params),
        "t": jnp.zeros((), jnp.int32),
    }
    # bf16 loss-scaled training (DL4J_TPU_BF16): the dynamic loss-scale
    # state rides INSIDE the opt tree — step arity, the opt-only donation
    # contract and the save/load npz round-trip all stay unchanged
    if lowprec.train_policy():
        opt.update(lowprec.opt_scale_entries())
    return opt


def _clip_by_global_norm(grads, max_norm):
    """Global-norm clip (the standard LM recipe): ONE implementation — the
    framework's shared gradient-normalization path
    (optimize/updaters.normalize_gradients, reference
    GradientNormalization ClipL2 role) applied to the WHOLE param tree."""
    from deeplearning4j_tpu.optimize.updaters import (
        _global_norm,
        normalize_gradients,
    )

    return (normalize_gradients(grads, "clip_l2_per_layer", max_norm),
            _global_norm(grads))


def _decay_mask(params):
    """AdamW applies decay to weight MATRICES only (keys 'W*' and the tied
    embedding); LN scales/biases, biases and the position table are
    exempt. The decision is BY NAME — block leaves carry a leading [L]
    layer dim, so ndim alone cannot tell a stacked bias (L, f) from a
    matrix."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, _ in flat:
        last = path[-1]
        name = str(getattr(last, "key", last))
        out.append(name.startswith("W") or name == "embed")
    return jax.tree_util.tree_unflatten(treedef, out)


def _adam_update(params, grads, opt, lr, b1=0.9, b2=0.999, eps=1e-8,
                 weight_decay=0.0, clip_grad_norm=0.0):
    if clip_grad_norm:
        grads, _ = _clip_by_global_norm(grads, clip_grad_norm)
    t = opt["t"] + 1
    m = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                               opt["m"], grads)
    v = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                               opt["v"], grads)
    tf = t.astype(jnp.float32)
    corr = jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
    if weight_decay:
        mask = _decay_mask(params)
        new = jax.tree_util.tree_map(
            lambda p, m, v, d: p - lr * (corr * m / (jnp.sqrt(v) + eps)
                                         + (weight_decay * p if d else 0.0)),
            params, m, v, mask)
    else:
        new = jax.tree_util.tree_map(
            lambda p, m, v: p - lr * corr * m / (jnp.sqrt(v) + eps),
            params, m, v)
    return new, {"m": m, "v": v, "t": t}


def _donation_kwargs():
    """Donate the OPT buffers (Adam m/v — 2/3 of the training-state HBM)
    to the step: the moment updates become in-place on device. Params are
    deliberately NOT donated — the repo's serial-vs-distributed equivalence
    pattern passes one initial params tree to several step functions
    (tests, dryrun legs), which donation would poison on real chips.
    Optimizer state is always built fresh per run (init_opt_state), so its
    donation is safe by construction.

    The on/off decision is the shared policy in ops/dispatch
    (donation_enabled: CPU platforms skip donation, the DL4J_TPU_DONATE
    env knob overrides both ways; the check reads the jax_platforms CONFIG,
    never the backend — jax.default_backend() would initialize the axon
    plugin at factory-construction time, which hangs on a dead tunnel,
    CLAUDE.md)."""
    from deeplearning4j_tpu.ops import dispatch

    if not dispatch.donation_enabled():
        return {}
    return {"donate_argnums": (1,)}


def _reject_lowprec(path: str) -> None:
    """The ring/pipeline step factories drop unknown opt keys (they
    rebuild {'m','v','t'} from _adam_update), so bf16 loss scaling would
    silently degrade to ls-less f32 there — reject loudly instead (the
    accum_steps-under-PP pattern)."""
    from deeplearning4j_tpu.ops import lowprec

    if lowprec.train_policy():
        raise ValueError(
            f"DL4J_TPU_BF16 is not supported on the {path} training path "
            "yet — unset it (the dense and accum paths support it)")


def _validate_schedule(cfg: TransformerConfig) -> None:
    """Shared by the dense AND pipelined step factories — a cfg the dense
    path rejects loudly must never train silently through the pipeline."""
    if cfg.lr_schedule not in ("none", "cosine"):
        raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r} "
                         "(known: none, cosine)")
    if cfg.lr_schedule == "cosine" and cfg.total_steps <= 0:
        raise ValueError("lr_schedule='cosine' needs total_steps > 0 "
                         "(otherwise the decay is silently dropped)")


def _scheduled_lr(cfg: TransformerConfig, t):
    """LR at integer step t (1-based): optional linear warmup then optional
    cosine decay to zero over cfg.total_steps (standard LM schedule; the
    reference's LR-policy role — optimize/updaters.py — for the flagship)."""
    tf = t.astype(jnp.float32)
    lr = jnp.asarray(cfg.learning_rate, jnp.float32)
    if cfg.warmup_steps > 0:
        lr = lr * jnp.minimum(1.0, tf / cfg.warmup_steps)
    if cfg.lr_schedule == "cosine" and cfg.total_steps > 0:
        frac = jnp.clip((tf - cfg.warmup_steps)
                        / max(1, cfg.total_steps - cfg.warmup_steps), 0.0, 1.0)
        lr = lr * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
    return lr


def _build_step(cfg: TransformerConfig):
    """The pure (unjitted) optimizer step shared by make_train_step and
    the fused multi-step path; validates cfg combinations loudly."""
    accum_steps = cfg.accum_steps
    # accum_steps > 1 with MoE = the GROUPED objective (group = one
    # microbatch): per-group expert capacity + aux statistics, the same
    # GShard/Switch semantics as the pipelined path — accum A=k and
    # PP n_micro=k optimize the IDENTICAL loss on identical groups
    # (test_accum_moe_equals_pipelined_groups). Dense configs remain
    # exactly full-batch equivalent (mean-of-means).
    _validate_schedule(cfg)
    from deeplearning4j_tpu.ops import lowprec

    lp = lowprec.train_policy()

    def step(params, opt, tokens, targets):
        if lp:
            # bf16 master-weight mode (ops/lowprec.py): the scale rides
            # the opt tree; the backward pass runs on the SCALED loss of
            # the bf16-cast params, grads come back f32 via the cast's
            # transpose and are unscaled before Adam
            ls = lowprec.opt_scale_state(opt)
            base = {"m": opt["m"], "v": opt["v"], "t": opt["t"]}
            scale = ls["scale"]

            def grad_loss(p, x, y):
                return loss_fn(
                    lowprec.cast_tree(p), x, y, cfg
                ).astype(jnp.float32) * scale
        else:
            ls = None
            base = opt

            def grad_loss(p, x, y):
                return loss_fn(p, x, y, cfg)

        if accum_steps == 1:
            loss, grads = jax.value_and_grad(grad_loss)(
                params, tokens, targets)
        else:
            b = tokens.shape[0]
            if b % accum_steps != 0:
                raise ValueError(
                    f"batch {b} not divisible by accum_steps {accum_steps}")
            mb = b // accum_steps
            xs = tokens.reshape(accum_steps, mb, *tokens.shape[1:])
            ys = targets.reshape(accum_steps, mb, *targets.shape[1:])

            def micro(carry, xy):
                loss_a, grads_a = carry
                loss_i, grads_i = jax.value_and_grad(grad_loss)(
                    params, xy[0], xy[1])
                grads_a = jax.tree_util.tree_map(
                    lambda a, g: a + g / accum_steps, grads_a, grads_i)
                return (loss_a + loss_i / accum_steps, grads_a), None

            zero = jax.tree_util.tree_map(jnp.zeros_like, params)
            (loss, grads), _ = lax.scan(
                micro, (jnp.zeros((), jnp.float32), zero), (xs, ys))

        if lp:
            loss = loss / scale  # report the unscaled loss
            grads = lowprec.unscale(grads, scale)
            finite = lowprec.finite_tree(grads)
            lr = _scheduled_lr(cfg, base["t"] + 1)
            new_params, new_base = _adam_update(
                params, grads, base, lr,
                weight_decay=cfg.weight_decay,
                clip_grad_norm=cfg.clip_grad_norm)
            params = lowprec.select_trees(finite, new_params, params)
            # 't' is selected too: a skipped step must not advance the
            # LR schedule or the bias correction
            base = lowprec.select_trees(finite, new_base, base)
            ls = lowprec.advance_scale(ls, finite)
            return params, lowprec.opt_with_scale(base, ls), loss

        lr = _scheduled_lr(cfg, opt["t"] + 1)
        params, opt = _adam_update(params, grads, opt, lr,
                                   weight_decay=cfg.weight_decay,
                                   clip_grad_norm=cfg.clip_grad_norm)
        return params, opt, loss

    return step


def _mesh_shardings(cfg: TransformerConfig, mesh: Mesh):
    # param_shardings_for_mesh handles every mesh kind (Megatron when a
    # 'model'/'expert' axis exists, replicated for pure-DP meshes) — a
    # ('data',)-only mesh must not crash on a 'model' PartitionSpec
    from deeplearning4j_tpu.ops import lowprec

    pshard = param_shardings_for_mesh(cfg, mesh)
    oshard = {"m": pshard, "v": pshard, "t": NamedSharding(mesh, P())}
    if lowprec.train_policy():
        # the loss-scale scalars ride the opt tree replicated
        oshard.update({k: NamedSharding(mesh, P())
                       for k in lowprec.OPT_SCALE_KEYS})
    dshard = NamedSharding(
        mesh, P(DATA_AXIS) if DATA_AXIS in mesh.shape else P())
    return pshard, oshard, dshard


def make_train_step(cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """Returns step(params, opt, tokens, targets) -> (params, opt, loss),
    jitted. With a mesh: params carry Megatron/MoE shardings, the batch is
    sharded over 'data', and GSPMD derives the full DP x TP x EP collective
    schedule (gradient all-reduce over 'data'; the two per-block psums over
    'model'; expert all-to-alls over 'expert').

    cfg.accum_steps > 1 = gradient accumulation: the batch is split into A
    microbatches whose gradients are averaged in a lax.scan before ONE
    optimizer update — for dense configs numerically the full-batch step
    (the loss is a batch mean, so mean-of-microbatch-grads == full-batch
    grad) at 1/A the activation memory. MoE configs train the GROUPED
    objective (expert capacity + aux statistics per microbatch group —
    GShard/Switch semantics, identical to the pipelined path at
    n_micro=A; test_accum_moe_equals_pipelined_groups)."""
    step = _build_step(cfg)
    if mesh is None:
        return jax.jit(step, **_donation_kwargs())
    pshard, oshard, dshard = _mesh_shardings(cfg, mesh)
    return jax.jit(
        step,
        in_shardings=(pshard, oshard, dshard, dshard),
        out_shardings=(pshard, oshard, NamedSharding(mesh, P())),
        **_donation_kwargs(),
    )


def make_train_multi_step(cfg: TransformerConfig,
                          mesh: Optional[Mesh] = None):
    """K optimizer steps fused into ONE XLA program (the flagship's
    fit_batches — same role as MultiLayerNetwork.fit_batches): a lax.scan
    over stacked batches [K, N, T], removing the per-step dispatch
    round-trip (~5ms each through the remote-TPU tunnel). Serially
    equivalent to K fit() calls."""
    step = _build_step(cfg)
    multi = _multi_from_step(step)
    if mesh is None:
        return jax.jit(multi, **_donation_kwargs())
    pshard, oshard, dshard = _mesh_shardings(cfg, mesh)
    kshard = NamedSharding(mesh, P(None, DATA_AXIS))  # [K, N, T]
    return jax.jit(
        multi,
        in_shardings=(pshard, oshard, kshard, kshard),
        out_shardings=(pshard, oshard, NamedSharding(mesh, P())),
        **_donation_kwargs(),
    )


# ---------------------------------------------------------------------------
# Ring-attention (sequence-parallel) forward for long context
# ---------------------------------------------------------------------------


def ring_forward(params: Params, tokens: jax.Array, cfg: TransformerConfig,
                 mesh: Mesh, strategy: str = "ring",
                 return_aux: bool = False):
    """Forward with attention computed sequence-parallel over the 'seq'
    mesh axis (parallel/sequence_parallel.py): exact full attention for
    sequences sharded over devices. strategy='ring' rotates K/V shards via
    ppermute (memory-optimal for very long T); strategy='ulysses' uses two
    head<->sequence all_to_alls (fewer collectives; needs heads divisible
    by the axis size). Long-context inference/eval, and (via
    return_aux=True) the sequence-parallel TRAIN step: the MoE
    load-balance aux loss is accumulated per block so SP training
    optimizes the SAME objective as the serial step."""
    from deeplearning4j_tpu.parallel.sequence_parallel import (
        ring_attention_sharded,
        ulysses_attention_sharded,
    )

    if strategy not in ("ring", "ulysses"):
        raise ValueError(f"unknown sequence-parallel strategy {strategy!r}")
    sharded_att = (ring_attention_sharded if strategy == "ring"
                   else ulysses_attention_sharded)
    n, t = tokens.shape
    hd = cfg.d_model // cfg.n_heads
    # DP x SP composition: shard the batch over 'data' inside the attention
    # shard_map too — otherwise every data slice would all-gather the batch
    # and compute the full attention redundantly
    batch_ax = DATA_AXIS if DATA_AXIS in mesh.shape else None

    def attend(q, k, v):
        split = lambda a: a.reshape(n, t, cfg.n_heads, hd)
        out = sharded_att(split(q), split(k), split(v), mesh, causal=True,
                          batch_axis=batch_ax)
        return out.reshape(n, t, cfg.d_model)

    cdt = cfg.compute_dtype
    h = (params["embed"][tokens] + params["pos"][:t][None]).astype(cdt)
    L = params["blocks"]["Wq"].shape[0]
    aux_total = jnp.zeros((), jnp.float32)
    for i in range(L):
        bp = jax.tree_util.tree_map(lambda a, i=i: a[i], params["blocks"])
        if cfg.moe_experts:
            h, a = _moe_block(bp, h, cfg, attend=attend, cdt=cdt)
            aux_total = aux_total + a
        else:
            h = _dense_block_f32(bp, h, cfg.n_heads, attend=attend,
                                 cdt=cdt)
    h = _ln(h.astype(jnp.float32), params["lnf_g"], params["lnf_b"])
    logits = (h @ params["embed"].T).astype(jnp.float32)
    if return_aux:
        return logits, aux_total / cfg.n_layers
    return logits


# ---------------------------------------------------------------------------
# KV-cache decoding (autoregressive inference without the O(T^2)-per-token
# full-forward recompute; the reference's rnnTimeStep streaming idea —
# MultiLayerNetwork.rnnTimeStep :2152 carries h/c state — applied to
# attention: the carried state is each layer's K/V history)
# ---------------------------------------------------------------------------


def prefill_cache(params: Params, tokens: jax.Array, cfg: TransformerConfig,
                  ) -> Tuple[Params, jax.Array]:
    """Run the prompt through the model once, returning the per-layer K/V
    cache (leaves [L, N, max_len, H, hd]; positions beyond the prompt are
    garbage that decode's position mask never reads) plus the final hidden
    states [N, T, d] (f32, post-final-LN). Mirrors forward()'s block scan
    (same cast discipline), including the MoE FFN branch — the prompt
    routes with the standard capacity formula, so in the drop-free regime
    prefill+decode is exactly the full forward."""
    cdt = cfg.compute_dtype
    n, t = tokens.shape
    hd = cfg.d_model // cfg.n_heads
    h = (params["embed"][tokens] + params["pos"][:t][None]).astype(cdt)

    def block(h, bp):
        # the SHARED block body (_dense_block_f32); the attend override
        # both computes attention and CAPTURES this layer's K/V for the
        # cache (capture works because scan traces the body once and the
        # captured values are tracers feeding the scan outputs)
        captured = {}

        def attend(q, k, v):
            captured["k"], captured["v"] = k, v
            return _attention(q, k, v, cfg.n_heads, use_flash=cfg.use_flash)

        if cfg.moe_experts:
            h, _unused_aux = _moe_block(bp, h, cfg, attend=attend, cdt=cdt)
        else:
            h = _dense_block_f32(bp, h, cfg.n_heads, attend=attend,
                                 cdt=cdt)
        pad = ((0, 0), (0, cfg.max_len - t), (0, 0), (0, 0))
        kc = jnp.pad(captured["k"].reshape(n, t, cfg.n_heads, hd), pad)
        vc = jnp.pad(captured["v"].reshape(n, t, cfg.n_heads, hd), pad)
        return h, (kc, vc)

    h, (ks, vs) = lax.scan(block, h, params["blocks"])
    h = _ln(h.astype(jnp.float32), params["lnf_g"], params["lnf_b"])
    return {"k": ks, "v": vs}, h


def _moe_ffn_decode(bp, h, cfg: TransformerConfig) -> jax.Array:
    """MoE FFN for one decode step (h: [N, 1, d]): _moe_ffn with NO-DROP
    capacity — a streamed token only competes with the other N tokens of
    its own step (each token holds at most one slot per expert), so
    capacity = N makes decode drop-free. Matches the batch forward
    exactly whenever the batch run is itself drop-free (capacity-bound
    drops are inherently batch-vs-stream dependent — same boundary as any
    capacity-routed MoE)."""
    n, t, _ = h.shape
    return _moe_ffn(bp, h, cfg, capacity=n * t)[0]


def decode_step(params: Params, cache: Params, tok: jax.Array, pos,
                cfg: TransformerConfig) -> Tuple[Params, jax.Array]:
    """One autoregressive step: consume the token at position `pos`
    (writing its K/V into the cache) and return (updated cache, logits for
    position pos+1). tok: [N] int32; pos: traced scalar. Attention reads
    the full max_len cache under an `arange <= pos` mask — O(max_len) per
    token instead of the full forward's O(max_len^2). MoE blocks route
    through _moe_ffn_decode (no-drop capacity)."""
    cdt = cfg.compute_dtype
    n = tok.shape[0]
    hd = cfg.d_model // cfg.n_heads
    h = (params["embed"][tok] + params["pos"][pos])[:, None, :].astype(cdt)
    scale = 1.0 / float(np.sqrt(hd))
    visible = (jnp.arange(cfg.max_len) <= pos)[None, None, :]  # [1,1,T]

    def block(h, xs):
        bp, ck, cv = xs  # ck/cv: [N, T_max, H, hd]
        c = lambda a: a.astype(cdt)
        x = _ln(h, c(bp["ln1_g"]), c(bp["ln1_b"]))
        q = (x @ c(bp["Wq"])).reshape(n, cfg.n_heads, hd)
        k1 = (x @ c(bp["Wk"])).reshape(n, 1, cfg.n_heads, hd)
        v1 = (x @ c(bp["Wv"])).reshape(n, 1, cfg.n_heads, hd)
        ck = lax.dynamic_update_slice_in_dim(ck, k1.astype(ck.dtype), pos, 1)
        cv = lax.dynamic_update_slice_in_dim(cv, v1.astype(cv.dtype), pos, 1)
        s = jnp.einsum("nhd,nthd->nht", q.astype(jnp.float32),
                       ck.astype(jnp.float32)) * scale
        s = jnp.where(visible, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        att = jnp.einsum("nht,nthd->nhd", p,
                         cv.astype(jnp.float32)).reshape(n, 1, cfg.d_model)
        h = h + att.astype(cdt) @ c(bp["Wo"])
        x = _ln(h, c(bp["ln2_g"]), c(bp["ln2_b"]))
        if cfg.moe_experts:
            bp16 = {kk: c(vv) for kk, vv in bp.items()}
            h = h + _moe_ffn_decode(bp16, x, cfg)
        else:
            h = h + jax.nn.gelu(x @ c(bp["W1"]) + c(bp["b1"])) @ c(bp["W2"]) \
                + c(bp["b2"])
        return h, (ck, cv)

    h, (ks, vs) = lax.scan(block, h, (params["blocks"], cache["k"],
                                      cache["v"]))
    h = _ln(h[:, 0].astype(jnp.float32), params["lnf_g"], params["lnf_b"])
    return {"k": ks, "v": vs}, h @ params["embed"].T


# ---------------------------------------------------------------------------
# Sequence-parallel TRAINING (ring/Ulysses attention + loss + Adam in one
# jitted step over a ('seq',) or ('data', 'seq') mesh)
# ---------------------------------------------------------------------------


def make_ring_train_step(cfg: TransformerConfig, mesh: Mesh, *,
                         strategy: str = "ring"):
    """Long-context TRAINING step: the forward's attention runs
    sequence-parallel over the mesh's 'seq' axis (ring ppermute schedule or
    Ulysses all-to-alls — parallel/sequence_parallel.py), everything else
    (LN/FFN/embedding, elementwise over T) is sharded by GSPMD from the
    token sharding, and autodiff transposes the ring into the backward
    collective schedule. Params stay replicated; tokens/targets are
    sharded [batch -> 'data' when present, T -> 'seq'].

    This closes the axis that previously stopped at forward/eval
    (ring_forward's docstring said inference/eval): sequences longer than
    one chip's activation memory now take REAL optimizer steps.
    SP-train == serial-train is locked by tests/test_ring_training.py."""
    (ins, outs) = _ring_step_shardings(cfg, mesh)
    return jax.jit(_build_ring_step(cfg, mesh, strategy),
                   in_shardings=ins, out_shardings=outs,
                   **_donation_kwargs())


def _build_ring_step(cfg, mesh, strategy):
    # validated HERE so every sequence-parallel factory (single- and
    # multi-step) rejects the unsupported configs
    if cfg.accum_steps != 1:
        raise ValueError("cfg.accum_steps must be 1 under sequence-parallel "
                         "training (shard 'data' for more batch instead)")
    _reject_lowprec("sequence-parallel")
    _validate_schedule(cfg)

    def sp_loss(params, tokens, targets):
        # same objective as the serial loss_fn: NLL + the MoE aux term
        # (aux == 0 for dense configs) — SP-train == serial-train
        logits, aux = ring_forward(params, tokens, cfg, mesh,
                                   strategy=strategy, return_aux=True)
        return nll_loss(logits, targets) + cfg.moe_aux_coef * aux

    def step(params, opt, tokens, targets):
        loss, grads = jax.value_and_grad(sp_loss)(params, tokens, targets)
        lr = _scheduled_lr(cfg, opt["t"] + 1)
        params, opt = _adam_update(params, grads, opt, lr,
                                   weight_decay=cfg.weight_decay,
                                   clip_grad_norm=cfg.clip_grad_norm)
        return params, opt, loss

    return step


def _ring_step_shardings(cfg, mesh):
    rep = NamedSharding(mesh, P())
    # the SAME layout decision as __init__/restore (param_shardings_for_mesh:
    # replicated on pure seq/data meshes, Megatron if the mesh also has a
    # 'model'/'expert' axis) — step and placement can never disagree
    pshard = param_shardings_for_mesh(cfg, mesh)
    oshard = {"m": pshard, "v": pshard, "t": rep}
    data_ax = DATA_AXIS if DATA_AXIS in mesh.shape else None
    dshard = NamedSharding(mesh, P(data_ax, SEQUENCE_AXIS))
    return ((pshard, oshard, dshard, dshard), (pshard, oshard, rep))


def make_ring_train_multi_step(cfg: TransformerConfig, mesh: Mesh, *,
                               strategy: str = "ring"):
    """K sequence-parallel optimizer steps fused into one XLA program
    (stacked batches [K, N, T] — fit_batches dispatch amortization for the
    long-context mode)."""
    step = _build_ring_step(cfg, mesh, strategy)
    (pshard, oshard, dshard, _), (_, _, rep) = _ring_step_shardings(cfg,
                                                                    mesh)
    kshard = NamedSharding(mesh, P(None, *dshard.spec))
    return jax.jit(
        _multi_from_step(step),
        in_shardings=(pshard, oshard, kshard, kshard),
        out_shardings=(pshard, oshard, rep),
        **_donation_kwargs(),
    )


# ---------------------------------------------------------------------------
# Pipeline-parallel forward (depth sharded over the 'pipe' axis)
# ---------------------------------------------------------------------------


def pipeline_forward(params: Params, tokens: jax.Array,
                     cfg: TransformerConfig, mesh: Mesh, *,
                     n_micro: int, axis: str = PIPELINE_AXIS,
                     data_axis: Optional[str] = None,
                     return_aux: bool = False):
    """Forward with the LAYER STACK sharded over the mesh's 'pipe' axis
    (parallel/pipeline_parallel.py GPipe schedule): stage s holds layers
    [s*L/S, (s+1)*L/S); microbatches flow through the ring via ppermute.
    Embedding and the tied head run replicated outside the pipeline (they
    are a small fraction of the params). Differentiable — jax.grad gives
    the backward pipeline via the scan/ppermute transposes. data_axis:
    optional PP x DP composition — each microbatch additionally sharded
    over that mesh axis. MoE blocks route per group (see the stage_fn
    note below); return_aux=True also returns the grouped load-balance
    aux loss for the pipelined TRAIN objective."""
    from deeplearning4j_tpu.parallel.pipeline_parallel import pipeline_apply

    n_stages = mesh.shape[axis]
    L = cfg.n_layers
    if L % n_stages != 0:
        raise ValueError(f"n_layers {L} not divisible by {n_stages} stages")
    per = L // n_stages
    # restack block leaves [L, ...] -> [S, per, ...] (stage-major)
    stage_params = jax.tree_util.tree_map(
        lambda a: a.reshape((n_stages, per) + a.shape[1:]), params["blocks"])

    cdt = cfg.compute_dtype
    moe = bool(cfg.moe_experts)

    if moe:
        # MoE under GPipe routes PER GROUP (group = one microbatch, or one
        # microbatch x data-slice under PP x DP) — the GShard/Switch group
        # semantics: capacity and load-balance statistics are computed over
        # the tokens that are physically together. With n_micro=1 this is
        # exactly the serial batch objective; with n_micro>1 it is the
        # grouped objective deployed MoE systems train (drop-free logits
        # still match serial bit-for-bit).
        def stage_fn(sp, h):
            def block(carry, bp):
                h, aux = carry
                h, a = _moe_block(bp, h, cfg, cdt=cdt)
                return (h, aux + a), None

            # aux carried as [1]: a rank-0 float scan carry becomes a
            # rank-0 shard_map residual, which this jax's (0.4.x)
            # shard_map transpose mis-specs (see
            # parallel/pipeline_parallel._pipeline_body)
            (h, aux), _ = lax.scan(
                block, (h, jnp.zeros((1,), jnp.float32)), sp)
            return h, aux
    else:
        def stage_fn(sp, h):
            def block(h, bp):
                return _dense_block_f32(bp, h, cfg.n_heads, cdt=cdt), None

            h, _ = lax.scan(block, h, sp)
            return h

    n, t = tokens.shape
    # bf16 policy: the residual stream (the thing the ring ppermutes each
    # tick) is carried in the compute dtype — half the ICI traffic
    h = (params["embed"][tokens] + params["pos"][:t][None]).astype(cdt)
    out = pipeline_apply(stage_params, h, mesh, stage_fn=stage_fn,
                         n_micro=n_micro, axis=axis, data_axis=data_axis,
                         with_aux=moe)
    if moe:
        h, aux = out
        # mean aux per layer per group (serial forward's /L, M=1 => equal)
        aux = aux / (cfg.n_layers * n_micro)
    else:
        h, aux = out, jnp.zeros((), jnp.float32)
    h = _ln(h.astype(jnp.float32), params["lnf_g"], params["lnf_b"])
    logits = (h @ params["embed"].T).astype(jnp.float32)
    if return_aux:
        return logits, aux
    return logits


# ---------------------------------------------------------------------------
# Pipeline-parallel TRAINING (GPipe fwd + autodiff bwd pipeline + Adam,
# one jitted step over a ('pipe',) or ('pipe', 'data') mesh)
# ---------------------------------------------------------------------------


def pipeline_param_shardings(cfg: TransformerConfig, mesh: Mesh,
                             axis: str = PIPELINE_AXIS) -> Params:
    """NamedShardings for pipeline mode: every block leaf [L, ...] sharded
    over 'pipe' on the LAYER dim (layer-major == stage-major because
    pipeline_forward's [L]->[S, L/S] restack is contiguous), so each device
    holds exactly its own stage's layers — the model can be S x larger than
    one chip's HBM. Embedding/pos/final-LN are replicated (small)."""
    shapes = jax.eval_shape(partial(init_params, cfg))
    rep = NamedSharding(mesh, P())

    def of(a, pipe: bool):
        if pipe:
            return NamedSharding(mesh, P(axis, *(None,) * (a.ndim - 1)))
        return rep

    return {
        k: (jax.tree_util.tree_map(lambda a: of(a, True), v)
            if k == "blocks"
            else jax.tree_util.tree_map(lambda a: of(a, False), v))
        for k, v in shapes.items()
    }


def shard_params_pipeline(params: Params, cfg: TransformerConfig, mesh: Mesh,
                          axis: str = PIPELINE_AXIS) -> Params:
    return jax.tree_util.tree_map(
        jax.device_put, params, pipeline_param_shardings(cfg, mesh, axis))


def make_pipeline_train_step(cfg: TransformerConfig, mesh: Mesh, *,
                             n_micro: int, axis: str = PIPELINE_AXIS,
                             data_axis: Optional[str] = None):
    """Full pipelined TRAIN step: GPipe microbatch forward, backward
    pipeline from autodiff (scan/ppermute transposes — microbatch gradient
    accumulation falls out of the scan transpose), Adam update, all in ONE
    jitted XLA program. Returns step(params, opt, tokens, targets) ->
    (params, opt, loss), numerically the same optimizer step as the serial
    make_train_step on the same batch (PP-train == serial-train;
    tests/test_pipeline_training.py locks the loss curves together).

    The reference has no pipeline axis at all (SURVEY.md section 2.7); this
    is the beyond-reference leg that lets the flagship's depth exceed one
    chip's HBM while still taking real optimizer steps."""
    ins, outs = _pipeline_step_shardings(cfg, mesh, axis, data_axis)
    return jax.jit(_build_pipeline_step(cfg, mesh, n_micro, axis, data_axis),
                   in_shardings=ins, out_shardings=outs,
                   **_donation_kwargs())


def _build_pipeline_step(cfg, mesh, n_micro, axis, data_axis):
    # validated HERE so every pipelined factory (single- and multi-step)
    # rejects the unsupported configs, not just make_pipeline_train_step
    _reject_lowprec("pipelined")
    _validate_schedule(cfg)
    if cfg.accum_steps != 1:
        raise ValueError(
            "cfg.accum_steps must be 1 under pipelined training — n_micro "
            "IS the microbatch/accumulation count (the GPipe schedule)")

    def pp_loss(params, tokens, targets):
        # same shape as the serial loss_fn (NLL + aux; aux == 0 dense).
        # MoE aux is the GROUPED objective (group = microbatch): exactly
        # the serial objective at n_micro=1, the GShard/Switch grouped
        # objective at n_micro > 1.
        logits, aux = pipeline_forward(params, tokens, cfg, mesh,
                                       n_micro=n_micro, axis=axis,
                                       data_axis=data_axis, return_aux=True)
        return nll_loss(logits, targets) + cfg.moe_aux_coef * aux

    def step(params, opt, tokens, targets):
        loss, grads = jax.value_and_grad(pp_loss)(params, tokens, targets)
        lr = _scheduled_lr(cfg, opt["t"] + 1)
        params, opt = _adam_update(params, grads, opt, lr,
                                   weight_decay=cfg.weight_decay,
                                   clip_grad_norm=cfg.clip_grad_norm)
        return params, opt, loss

    return step


def _pipeline_step_shardings(cfg, mesh, axis, data_axis):
    pshard = pipeline_param_shardings(cfg, mesh, axis)
    oshard = {"m": pshard, "v": pshard, "t": NamedSharding(mesh, P())}
    dshard = NamedSharding(mesh,
                           P(data_axis) if data_axis is not None else P())
    return ((pshard, oshard, dshard, dshard),
            (pshard, oshard, NamedSharding(mesh, P())))


def make_pipeline_train_multi_step(cfg: TransformerConfig, mesh: Mesh, *,
                                   n_micro: int, axis: str = PIPELINE_AXIS,
                                   data_axis: Optional[str] = None):
    """K pipelined optimizer steps fused into one XLA program (lax.scan
    over stacked batches [K, N, T] — the fit_batches dispatch-amortization
    applied to the pipeline schedule)."""
    step = _build_pipeline_step(cfg, mesh, n_micro, axis, data_axis)
    (pshard, oshard, dshard, _), (_, _, lshard) = _pipeline_step_shardings(
        cfg, mesh, axis, data_axis)
    kshard = NamedSharding(
        mesh, P(None, *dshard.spec))
    return jax.jit(
        _multi_from_step(step),
        in_shardings=(pshard, oshard, kshard, kshard),
        out_shardings=(pshard, oshard, lshard),
        **_donation_kwargs(),
    )


def _multi_from_step(step):
    """Wrap a pure train step into a K-step lax.scan over stacked batches
    (shared by the dense, pipelined, and BERT-MLM multi-step factories —
    variadic so steps with any number of data stacks fit: (tokens,
    targets) here, (inputs, targets, weights) for the MLM)."""
    def multi(params, opt, *stacks):
        def body(carry, xs):
            params, opt = carry
            params, opt, loss = step(params, opt, *xs)
            return (params, opt), loss

        (params, opt), losses = lax.scan(body, (params, opt), stacks)
        return params, opt, losses

    return multi


# ---------------------------------------------------------------------------
# Convenience wrapper
# ---------------------------------------------------------------------------


class TransformerLM:
    """Flagship LM with the framework's fit/generate surface."""

    def __init__(self, cfg: TransformerConfig, mesh: Optional[Mesh] = None):
        self.cfg = cfg  # the user's config — persisted verbatim by save()
        # runtime config: flash is disabled under a mesh (pallas custom
        # calls don't auto-partition under GSPMD; multi-chip attention is
        # ring_forward's job) WITHOUT mutating cfg, so a mesh-trained
        # checkpoint reloaded on one device gets its flash path back
        self._run_cfg = (dataclasses.replace(cfg, use_flash=False)
                         if mesh is not None else cfg)
        self.mesh = mesh
        self.params = init_params(cfg)
        if mesh is not None:
            # pipeline mode (depth-sharded over 'pipe') or Megatron GSPMD,
            # decided by param_shardings_for_mesh
            self.params = shard_params_for_mesh(self.params, cfg, mesh)
        self.opt = init_opt_state(self.params)
        self._step = self._make_step()
        self._gen_cache: Dict[tuple, Any] = {}
        self.iteration = 0
        from deeplearning4j_tpu.ops.memory import MemoryStats

        # AOT memory ledger beside the containers' dispatch_stats
        # (ops/memory.py); populated on demand by measure_memory()
        self.memory_stats = MemoryStats()
        from deeplearning4j_tpu.obs.registry import register_net

        # ledger-registration convention (PR 7): every *_stats ledger
        # joins the central MetricsRegistry at its attach point — weakly
        # held, so short-lived models don't leak
        register_net(self)

    def _pipeline_mode(self) -> bool:
        return self.mesh is not None and PIPELINE_AXIS in self.mesh.shape

    def _pipeline_kwargs(self) -> Dict[str, Any]:
        return {
            "n_micro": self.cfg.pipeline_microbatches,
            "data_axis": (DATA_AXIS if DATA_AXIS in self.mesh.shape
                          else None),
        }

    def _sequence_mode(self) -> bool:
        return self.mesh is not None and SEQUENCE_AXIS in self.mesh.shape

    def _make_step(self):
        if self._pipeline_mode():
            return make_pipeline_train_step(self._run_cfg, self.mesh,
                                            **self._pipeline_kwargs())
        if self._sequence_mode():
            return make_ring_train_step(self._run_cfg, self.mesh)
        return make_train_step(self._run_cfg, self.mesh)

    @classmethod
    def from_state(cls, cfg: TransformerConfig, params: Params,
                   opt: Optional[Params] = None,
                   mesh: Optional[Mesh] = None) -> "TransformerLM":
        """Build an LM around EXISTING state without running (or paying
        for) a random init — the restore path for checkpoints whose params
        are already materialized/sharded (utils/sharded_checkpoint.py)."""
        lm = cls.__new__(cls)
        lm.cfg = cfg
        lm._run_cfg = (dataclasses.replace(cfg, use_flash=False)
                       if mesh is not None else cfg)
        lm.mesh = mesh
        lm.params = params
        lm.opt = opt if opt is not None else init_opt_state(params)
        lm._step = lm._make_step()
        lm._gen_cache = {}
        # the optimizer step count IS the training iteration — restoring it
        # keeps the listener iteration contract across checkpoint resumes
        lm.iteration = int(lm.opt["t"])
        from deeplearning4j_tpu.ops.memory import MemoryStats

        lm.memory_stats = MemoryStats()
        return lm

    def measure_memory(self, tokens: jax.Array,
                       targets: jax.Array) -> Optional[Dict[str, Any]]:
        """AOT memory accounting for the current train step on this batch
        shape (ops/memory.analyze_jit: lower + compile + memory_analysis,
        no execution) — recorded under 'train_step' in self.memory_stats.
        On the CPU substrate this measures the CPU build; against the
        chip it reports real HBM. Returns the byte dict, or None when the
        backend exposes no memory stats."""
        from deeplearning4j_tpu.ops import memory as memory_mod

        return memory_mod.measure(
            self.memory_stats, "train_step", self._step,
            self.params, self.opt, jnp.asarray(tokens, jnp.int32),
            jnp.asarray(targets, jnp.int32))

    def fit(self, tokens: jax.Array, targets: jax.Array) -> jax.Array:
        self.params, self.opt, loss = self._step(
            self.params, self.opt, tokens, targets)
        self.iteration += 1
        return loss

    def fit_batches(self, tokens_k: jax.Array,
                    targets_k: jax.Array) -> jax.Array:
        """K fused optimizer steps in one XLA program: tokens/targets
        stacked [K, N, T]. Returns the K per-step losses. Serially
        equivalent to K fit() calls (make_train_multi_step)."""
        if getattr(self, "_multi_step", None) is None:
            if self._pipeline_mode():
                self._multi_step = make_pipeline_train_multi_step(
                    self._run_cfg, self.mesh, **self._pipeline_kwargs())
            elif self._sequence_mode():
                self._multi_step = make_ring_train_multi_step(
                    self._run_cfg, self.mesh)
            else:
                self._multi_step = make_train_multi_step(self._run_cfg,
                                                         self.mesh)
        self.params, self.opt, losses = self._multi_step(
            self.params, self.opt, tokens_k, targets_k)
        self.iteration += int(tokens_k.shape[0])
        return losses

    def fit_iterator(self, iterator, num_epochs: int = 1,
                     listeners=()) -> "TransformerLM":
        """fit(DataSetIterator) parity for the flagship (reference
        MultiLayerNetwork.fit :1017 semantics): DataSets carry token ids as
        features [N, T] and next-token ids as labels [N, T]. Works with
        any framework iterator incl. AsyncDataSetIterator prefetch; the
        IterationListener chain (optimize/listeners.py) is invoked with a
        host readback only when listeners are present. The iteration
        counter persists across calls (self.iteration — same contract as
        MultiLayerNetwork :1017), so resumed training never re-emits
        earlier iteration numbers to the listeners."""
        for _ in range(num_epochs):
            for ds in iterator:
                loss = self.fit(jnp.asarray(ds.features, jnp.int32),
                                jnp.asarray(ds.labels, jnp.int32))
                if listeners:
                    score = float(loss)
                    for lst in listeners:
                        lst.iteration_done(self, self.iteration, score)
            if hasattr(iterator, "reset"):
                iterator.reset()
        return self

    def evaluate(self, iterator) -> Dict[str, float]:
        """Held-out evaluation: mean next-token cross-entropy and
        perplexity over an iterator of DataSets carrying token ids
        ([N, T] features, next-ids labels — the fit_iterator layout).
        The per-batch loss is jitted once and losses stay device-side
        until ONE bulk readback (the evaluate(DataSetIterator) role —
        reference MultiLayerNetwork.evaluate :2316 — for the flagship)."""
        if getattr(self, "_eval_loss", None) is None:
            cfg = self._run_cfg

            @jax.jit
            def eval_loss(params, tokens, targets, mask):
                logits, _ = forward(params, tokens, cfg)
                return nll_loss(logits, targets, mask)

            self._eval_loss = eval_loss
        losses, counts = [], []
        for ds in iterator:
            x = jnp.asarray(ds.features, jnp.int32)
            y = jnp.asarray(ds.labels, jnp.int32)
            # labels_mask (variable-length sequences): masked positions
            # count in neither the loss nor the token total
            m = ds.labels_mask if ds.labels_mask is not None \
                else ds.features_mask
            if m is None:
                m_arr = jnp.ones(x.shape, jnp.float32)
                counts.append(x.shape[0] * x.shape[1])
            else:
                m_arr = jnp.asarray(m, jnp.float32)
                counts.append(float(np.asarray(m).sum()))
            losses.append(self._eval_loss(self.params, x, y, m_arr))
        if hasattr(iterator, "reset"):
            iterator.reset()
        if not losses:
            return {"loss": float("nan"), "perplexity": float("nan"),
                    "tokens": 0}
        w = np.asarray(counts, np.float64)
        ls = np.asarray(jnp.stack(losses), np.float64)  # ONE bulk readback
        mean = float((ls * w).sum() / w.sum())
        return {"loss": mean, "perplexity": float(np.exp(mean)),
                "tokens": int(w.sum())}

    def logits(self, tokens: jax.Array) -> jax.Array:
        return forward(self.params, tokens, self._run_cfg)[0]

    def output(self, tokens) -> jax.Array:
        """Container-compatible inference surface (MultiLayerNetwork.output
        / streaming ModelServer.predict): token ids in, logits out."""
        return self.logits(jnp.asarray(tokens).astype(jnp.int32))

    def save(self, path: str) -> None:
        """Checkpoint in the framework's ModelSerializer zip layout
        (shared writer — utils/serialization.write_flagship_zip;
        reference ModelSerializer.java:70-110 three-part semantic:
        configuration + coefficients + updater)."""
        from deeplearning4j_tpu.utils.serialization import (
            write_flagship_zip,
        )

        write_flagship_zip(path, "TransformerLM", self.cfg, self.params,
                           self.opt)

    @classmethod
    def load(cls, path: str, mesh: Optional[Mesh] = None,
             load_updater: bool = True) -> "TransformerLM":
        from deeplearning4j_tpu.utils.serialization import (
            _npz_bytes_into_tree,
            read_flagship_zip,
        )

        cfg_dict, coeff, upd, _ = read_flagship_zip(path, "TransformerLM")
        cfg = TransformerConfig(**cfg_dict)
        lm = cls(cfg, mesh=mesh)
        lm.params = _npz_bytes_into_tree(coeff, lm.params)
        if load_updater and upd is not None:
            lm.opt = _npz_bytes_into_tree(upd, lm.opt)
            # optimizer step count IS the training iteration (same
            # contract as from_state): resumed runs must not re-emit
            # earlier iteration numbers to listeners
            lm.iteration = int(lm.opt["t"])
        if mesh is not None:
            lm.params = shard_params_for_mesh(lm.params, cfg, mesh)
        return lm

    def _sample_fn(self, n_new: int, top_k=None, has_top_p=False):
        """Jitted sampler, cached per n_new (a fresh @jax.jit closure per
        generate() call would recompile every time); temperature and key are
        traced args so they never force recompiles. The token buffer keeps
        the prompt at positions 0..t-1 (RIGHT-padded with zeros that causal
        masking makes invisible), so position embeddings match training —
        left-padding would condition sampling on a fake zero-token prefix."""
        cached = self._gen_cache.get((n_new, top_k, has_top_p))
        if cached is not None:
            return cached
        cfg = self._run_cfg
        filt = self._filter_logits

        @jax.jit
        def sample(params, buf, pos0, key, temperature, top_p):
            def one(carry, i):
                buf, key = carry
                logits, _ = forward(params, buf, cfg)
                pos = pos0 + i  # next write index; condition on pos-1
                last = jnp.take_along_axis(
                    logits, (pos - 1)[None, None, None].repeat(
                        buf.shape[0], 0), axis=1)[:, 0]
                key, sub = jax.random.split(key)
                tempered = last / jnp.maximum(temperature, 1e-6)
                nxt = jax.random.categorical(
                    sub, filt(tempered, top_k,
                              top_p if has_top_p else None))
                buf = lax.dynamic_update_slice_in_dim(
                    buf, nxt[:, None].astype(buf.dtype), pos, axis=1)
                return (buf, key), nxt

            (_, _), out = lax.scan(one, (buf, key), jnp.arange(n_new))
            return out.T  # [N, n_new]

        self._gen_cache[(n_new, top_k, has_top_p)] = sample
        return sample

    @staticmethod
    def _filter_logits(logits, top_k: Optional[int], top_p):
        """Top-k / nucleus (top-p) filtering of TEMPERED logits (callers
        scale by temperature first — the standard order, so the nucleus is
        computed on the distribution actually sampled). top_k is static
        (lax.top_k needs a static k; one compile per k); top_p is a TRACED
        scalar (or None to skip) — sweeping it never recompiles. Filters
        compose: k first, then the smallest set of remaining tokens whose
        cumulative probability reaches top_p (the top token always
        survives: its preceding cumulative mass is 0)."""
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p is not None:
            sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_desc, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_sorted = (cum - probs) < top_p  # cumprob BEFORE the token
            thresh = jnp.min(
                jnp.where(keep_sorted, sorted_desc, jnp.inf), axis=-1,
                keepdims=True)
            logits = jnp.where(logits < thresh, -jnp.inf, logits)
        return logits

    def _sample_kv_fn(self, n_new: int, top_k=None, has_top_p=False):
        """KV-cache sampler (prefill once, then one decode_step per token
        — O(max_len) each instead of a full O(max_len^2) forward). Cached
        per n_new; the prefill width max_len - n_new is static, so prompt
        length never forces a recompile (window right-padded; pad K/V
        entries are either overwritten before first read or masked)."""
        key_c = ("kv", n_new, top_k, has_top_p)
        cached = self._gen_cache.get(key_c)
        if cached is not None:
            return cached
        cfg = self._run_cfg
        filt = self._filter_logits

        @jax.jit
        def sample(params, buf, pos0, key, temperature, top_p):
            cache, _ = prefill_cache(params, buf, cfg)
            n = buf.shape[0]
            tok = jnp.take_along_axis(
                buf, (pos0 - 1)[None, None].repeat(n, 0), axis=1)[:, 0]

            def one(carry, i):
                cache, tok, key = carry
                cache, logits = decode_step(params, cache, tok,
                                            pos0 - 1 + i, cfg)
                key, sub = jax.random.split(key)
                tempered = logits / jnp.maximum(temperature, 1e-6)
                nxt = jax.random.categorical(
                    sub, filt(tempered, top_k,
                              top_p if has_top_p else None))
                return (cache, nxt.astype(buf.dtype), key), nxt

            _, out = lax.scan(one, (cache, tok, key), jnp.arange(n_new))
            return out.T  # [N, n_new]

        self._gen_cache[key_c] = sample
        return sample

    def generate(self, prompt: jax.Array, n_new: int, temperature: float = 1.0,
                 seed: int = 0, use_cache: Optional[bool] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None) -> jax.Array:
        """Sample n_new tokens after the prompt (static shapes throughout:
        one compile per n_new). prompt len + n_new must fit max_len; longer
        prompts keep their last (max_len - n_new) tokens. use_cache:
        KV-cache decoding (O(max_len) per token) — default on for DENSE
        single-device models; the full-forward sampler remains the
        default for mesh-sharded models and for MoE (where capacity-bound
        routing is batch-vs-stream dependent: KV decode routes each step
        as its own no-drop group, which matches the batch forward only in
        the drop-free regime — pass use_cache=True to opt in). Tensor-
        parallel ('model') meshes support use_cache=True: GSPMD shards
        prefill+decode on the head dim (equivalence-locked by
        test_tp_mesh_kv_decode_equals_serial)."""
        cfg = self._run_cfg
        if n_new >= cfg.max_len:
            raise ValueError(f"n_new {n_new} must be < max_len {cfg.max_len}")
        if top_k is not None and not 1 <= top_k <= cfg.vocab_size:
            raise ValueError(f"top_k {top_k} must be in [1, vocab_size]")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p {top_p} must be in (0, 1]")
        if use_cache is None:
            # MoE stays opt-in: flipping it on by default would silently
            # change sampled tokens for capacity-bound configs (the
            # default moe_capacity_factor=1.25 regime)
            use_cache = self.mesh is None and not cfg.moe_experts
        t = prompt.shape[1]
        keep = min(t, cfg.max_len - n_new)
        window = prompt[:, t - keep:]
        width = (cfg.max_len - n_new) if use_cache else cfg.max_len
        buf = jnp.pad(window, ((0, 0), (0, width - keep)))
        has_tp = top_p is not None
        fn = (self._sample_kv_fn(n_new, top_k, has_tp) if use_cache
              else self._sample_fn(n_new, top_k, has_tp))
        return fn(
            self.params, buf, jnp.asarray(keep, jnp.int32),
            jax.random.PRNGKey(seed), jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_p if has_tp else 1.0, jnp.float32))
